"""Pod-scale multihost fleet: one front door over N host-local fleets.

Everything below this module is per-host: the ``WeightStore`` keeps ONE
packed tree per process, ``DisaggCoordinator`` hands KV blocks between
pools on one machine, ``FleetAutoscaler`` scales one host's replicas. This
module stitches N of those hosts into a pod with three coordinated pieces:

- **Pod weight registry** (:class:`PodWeightRegistry`) — every host gossips
  which resident trees it holds (``weights.key_digest`` + refs + bytes), so
  the pod view proves the N_hosts×W property (one packed copy per host,
  aliased by all local replicas — never N_replicas×W) and a checkpoint
  retirement broadcast (``weights.teardown``) reaches every host's store.
- **Cross-host disagg handoff** (:class:`PodHandoff`) — the prefill host
  exports the ``KVPageBlock``, serializes it (``KVPageBlock.to_bytes``,
  checksummed), ships it through the ``pod.handoff`` fault site to the
  least-loaded remote decode host, and relays the remote pool's tokens
  back to the origin's client. The shipped block's host→device stage on
  the receiver rides ``ContinuousBatcher.stage_resume`` — dispatch-only,
  overlapped with the decode ticks already in flight (PRESERVE-style,
  arXiv:2501.08192). Every failure degrades exactly like the single-host
  contract: serve-in-place or blockless re-prefill, counted by kind,
  never a dropped stream.
- **Pod autoscaler** (:class:`PodAutoscaler`) — aggregates per-host
  ``FleetAutoscaler`` pressure (slot-weighted, ``fleet.aggregate_pressure``)
  and nudges spawn/drain on the right host against the pod-wide free list
  each heartbeat carries; a host whose heartbeat goes stale past the
  timeout is declared dead, its relayed sessions resume on the survivors
  via the existing token-exact migration path, and it leaves routing.

Transports: :class:`LoopbackTransport` is the in-process fabric (N
simulated hosts in one process — deterministic, fast, what the quick-tier
tests and the bench smoke drive); :class:`CollectiveTransport` is the real
one, riding ``parallel.multihost.PodControlPlane``'s symmetric allgather
over the same gloo/ICI substrate the SPMD control plane uses. Both speak
the same 4-call surface (publish / peers / send / handler), so every pod
component is transport-agnostic.

Run ``python -m mlx_sharding_tpu.pod --coordinator ...`` on two
processes for the acceptance demo: per-host weight trees, a cross-host
handoff bit-identical to monolithic serving, and a host-death drain with
zero dropped streams (see ``tests/test_pod_fleet.py``).
"""

from __future__ import annotations

import logging
import pickle
import queue
import threading
import time
import uuid
from collections import deque
from typing import Callable, Optional

import numpy as np

from mlx_sharding_tpu.analysis.runtime import make_lock
from mlx_sharding_tpu.fleet import aggregate_pressure
from mlx_sharding_tpu.kv_transfer import BlockIntegrityError, KVPageBlock
from mlx_sharding_tpu.resilience import ResumeState
from mlx_sharding_tpu.testing.faults import inject
from mlx_sharding_tpu.utils.clock import MONOTONIC, Clock
from mlx_sharding_tpu.weights import weight_store

logger = logging.getLogger(__name__)

# a peer whose heartbeat is older than this is dead: its sessions resume
# on the survivors and it leaves routing (override per-instance or via env)
HEARTBEAT_TIMEOUT_S = 10.0

# how long the origin waits for the next relayed token before declaring
# the remote leg dead and resuming locally (must exceed a worst-case
# remote decode tick + one transport tick)
RELAY_TIMEOUT_S = 30.0


class PodTransportError(RuntimeError):
    """A pod message could not be delivered (dead peer, closed fabric)."""


class PodHandoffFallback(Exception):
    """The cross-host leg failed; the origin continues on its local plan.

    ``kind`` is the counted fallback; ``tokens_relayed`` is how many tokens
    the remote pool already delivered to the client (the local resume must
    start AFTER them); ``keep_block`` means the origin's host copy of the
    block is still trustworthy (the failure happened before/instead of the
    remote import), so the local leg may import it instead of re-prefilling."""

    def __init__(self, kind: str, *, tokens_relayed: int = 0,
                 keep_block: bool = False):
        self.kind = kind
        self.tokens_relayed = tokens_relayed
        self.keep_block = keep_block
        super().__init__(f"pod handoff fallback: {kind}")


# --------------------------------------------------------------------------
# transports


class LoopbackHub:
    """In-process pod fabric: N simulated hosts in one interpreter.

    Delivery is synchronous push — ``send`` invokes the destination's
    handler on the calling thread (handlers that need concurrency spawn
    their own worker, exactly like the collective transport's tick thread
    would). ``kill(host)`` models SIGKILL: the host stops publishing and
    every message to or from it raises, so peers discover the death the
    same way they would for real — a stale heartbeat."""

    def __init__(self, clock: Clock = MONOTONIC):
        self.clock = clock
        self._lock = make_lock("LoopbackHub._lock")
        self._info: dict = {}      # host -> (info dict, published stamp)
        self._handlers: dict = {}  # host -> callable(src, kind, payload)
        self._dead: set = set()

    def register(self, host_id: int) -> "LoopbackTransport":
        with self._lock:
            self._info[host_id] = ({}, self.clock())
        return LoopbackTransport(self, host_id)

    def kill(self, host_id: int) -> None:
        """Simulated host death: heartbeats freeze, messages bounce."""
        with self._lock:
            self._dead.add(host_id)
            self._handlers.pop(host_id, None)

    def _publish(self, host_id: int, info: dict) -> None:
        with self._lock:
            if host_id in self._dead:
                return
            self._info[host_id] = (dict(info), self.clock())

    def _peers(self, host_id: int) -> dict:
        now = self.clock()
        with self._lock:
            return {
                h: {"info": dict(info), "age_s": now - stamp}
                for h, (info, stamp) in self._info.items()
                if h != host_id
            }

    def _send(self, src: int, dest: int, kind: str, payload: bytes) -> None:
        with self._lock:
            if src in self._dead or dest in self._dead:
                raise PodTransportError(f"host {dest} is unreachable")
            handler = self._handlers.get(dest)
        if handler is None:
            raise PodTransportError(f"host {dest} has no handler attached")
        handler(src, kind, payload)


class LoopbackTransport:
    """One simulated host's endpoint on a :class:`LoopbackHub`."""

    def __init__(self, hub: LoopbackHub, host_id: int):
        self.hub = hub
        self.host_id = host_id
        self._closed = False

    def set_handler(self, cb: Callable[[int, str, bytes], None]) -> None:
        with self.hub._lock:
            self.hub._handlers[self.host_id] = cb

    def publish(self, info: dict) -> None:
        if self._closed:
            raise PodTransportError("transport closed")
        self.hub._publish(self.host_id, info)

    def peers(self) -> dict:
        return self.hub._peers(self.host_id)

    def send(self, dest: int, kind: str, payload: bytes) -> None:
        if self._closed:
            raise PodTransportError("transport closed")
        self.hub._send(self.host_id, dest, kind, payload)

    def close(self) -> None:
        self._closed = True
        with self.hub._lock:
            self.hub._handlers.pop(self.host_id, None)


class CollectiveTransport:
    """The real pod fabric: every host contributes one fixed-shape buffer
    per tick through ``PodControlPlane.pod_exchange`` (a symmetric
    allgather) and receives everyone's. Heartbeats ARE the ticks; queued
    messages are framed into the tick blob, fragmented when larger than
    one blob so a multi-megabyte KV block ships across consecutive ticks
    while both hosts' decode loops keep running — the pod-scale version
    of the dispatch-only overlap discipline.

    A peer that stops arriving turns the collective into a timeout
    (``WorkerTimeoutError`` from the plane); the transport then reports
    every peer dead, and the local fleet degrades to single-host serving
    rather than wedging a request thread in a collective."""

    # blob framing: [4B n_msgs] then per message
    # [4B dest][4B kind_len][4B payload_len][kind][payload]; dest -1 = all
    _HDR = 12

    def __init__(self, *, interval_s: float = 0.05, plane=None,
                 clock: Clock = MONOTONIC):
        import jax

        from mlx_sharding_tpu.parallel.multihost import PodControlPlane

        self.plane = plane if plane is not None else PodControlPlane()
        self.host_id = jax.process_index()
        self.n_hosts = jax.process_count()
        self.interval_s = interval_s
        self.clock = clock
        self._lock = make_lock("CollectiveTransport._lock")
        self._outbox: deque = deque()   # framed (dest, kind, payload) bytes
        self._info: dict = {}
        self._peers: dict = {}          # host -> (info, stamp)
        self._handler: Optional[Callable] = None
        self._frags: dict = {}          # (src, msgid) -> {idx: part, ...}
        self._seq = 0
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- surface
    def set_handler(self, cb: Callable[[int, str, bytes], None]) -> None:
        self._handler = cb

    def publish(self, info: dict) -> None:
        with self._lock:
            self._info = dict(info)

    def peers(self) -> dict:
        now = self.clock()
        with self._lock:
            if self.plane.dead:
                # a dead plane means NO peer is provably alive: report every
                # known peer at infinite age so death detection fires
                return {
                    h: {"info": dict(info), "age_s": float("inf")}
                    for h, (info, stamp) in self._peers.items()
                }
            return {
                h: {"info": dict(info), "age_s": now - stamp}
                for h, (info, stamp) in self._peers.items()
            }

    def send(self, dest: int, kind: str, payload: bytes) -> None:
        if self._closed or self.plane.dead:
            raise PodTransportError("pod fabric is down")
        kb = kind.encode()
        # fragment anything that cannot ride one tick blob (leave header
        # room); reassembly is keyed by a random message id
        cap = self.plane.blob_bytes - 4 - self._HDR - len(kb) - 64
        if len(payload) <= cap:
            msgs = [(dest, kind, payload)]
        else:
            msgid = uuid.uuid4().bytes  # 16B
            msgs = []
            parts = [payload[i:i + cap] for i in range(0, len(payload), cap)]
            for i, part in enumerate(parts):
                head = msgid + np.asarray(
                    [i, len(parts), len(kb)], np.int32
                ).tobytes() + kb
                msgs.append((dest, "_frag", head + part))
        with self._lock:
            self._outbox.extend(msgs)

    # ---------------------------------------------------------------- loop
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mst-pod-transport", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        from mlx_sharding_tpu.parallel.multihost import WorkerTimeoutError

        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except WorkerTimeoutError:
                logger.error(
                    "pod collective timed out — peers presumed dead; "
                    "degrading to single-host serving"
                )
                return
            except Exception:  # noqa: BLE001 — the fabric must not die quietly
                logger.exception("pod transport tick failed")
                return

    def tick(self) -> None:
        """One pod exchange: frame as much of the outbox as fits, allgather,
        deliver every received message to the handler."""
        blob, n_msgs = self._drain_outbox()
        with self._lock:
            self._seq += 1
            seq = self._seq
        hdr = np.asarray(
            [seq, self.host_id, n_msgs, len(blob), 0, 0, 0, 0], np.int32
        )
        headers, blobs = self.plane.pod_exchange(
            hdr, np.frombuffer(blob, np.uint8)
        )
        now = self.clock()
        for h in range(headers.shape[0]):
            src = int(headers[h][1])
            if src == self.host_id:
                continue
            used = int(headers[h][3])
            with self._lock:
                info, _ = self._peers.get(src, ({}, now))
                self._peers[src] = (info, now)
            self._deliver(src, bytes(blobs[h][:used].tobytes()))

    def _drain_outbox(self) -> tuple:
        # heartbeat info rides every tick as message 0
        with self._lock:
            msgs = [(-1, "hb", pickle.dumps(self._info))]
            used = 4 + self._HDR + 2 + len(msgs[0][2])
            budget = self.plane.blob_bytes
            while self._outbox:
                dest, kind, payload = self._outbox[0]
                need = self._HDR + len(kind.encode()) + len(payload)
                if used + need > budget:
                    break
                used += need
                msgs.append(self._outbox.popleft())
        out = [np.asarray([len(msgs)], np.int32).tobytes()]
        for dest, kind, payload in msgs:
            kb = kind.encode()
            out.append(np.asarray(
                [dest, len(kb), len(payload)], np.int32
            ).tobytes())
            out.append(kb)
            out.append(payload)
        return b"".join(out), len(msgs)

    def _deliver(self, src: int, blob: bytes) -> None:
        if len(blob) < 4:
            return
        n = int(np.frombuffer(blob[:4], np.int32)[0])
        off = 4
        for _ in range(n):
            if off + self._HDR > len(blob):
                return
            dest, klen, plen = np.frombuffer(
                blob[off:off + self._HDR], np.int32
            )
            off += self._HDR
            kind = blob[off:off + klen].decode()
            off += int(klen)
            payload = blob[off:off + plen]
            off += int(plen)
            if dest not in (-1, self.host_id):
                continue
            if kind == "hb":
                try:
                    info = pickle.loads(payload)
                except Exception:  # noqa: BLE001 — a bad heartbeat is stale,
                    continue       # not fatal
                with self._lock:
                    self._peers[src] = (info, self.clock())
                continue
            if kind == "_frag":
                done = self._reassemble(src, payload)
                if done is None:
                    continue
                kind, payload = done
            if self._handler is not None:
                try:
                    self._handler(src, kind, payload)
                except Exception:  # noqa: BLE001 — one bad message must not
                    logger.exception("pod message handler failed")  # kill ticks

    def _reassemble(self, src: int, payload: bytes) -> Optional[tuple]:
        msgid = payload[:16]
        idx, total, klen = np.frombuffer(payload[16:28], np.int32)
        kind = payload[28:28 + klen].decode()
        part = payload[28 + int(klen):]
        with self._lock:
            parts = self._frags.setdefault((src, msgid), {})
            parts[int(idx)] = part
            if len(parts) < int(total):
                return None
            del self._frags[(src, msgid)]
        return kind, b"".join(parts[i] for i in range(int(total)))

    def close(self) -> None:
        self._closed = True
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)


# --------------------------------------------------------------------------
# weight registry


class PodWeightRegistry:
    """The multihost face of the per-host ``WeightStore``: gossips this
    host's resident trees every heartbeat and aggregates everyone's into
    the pod view behind ``mst_weight_store_*{host=}``. Build-once stays a
    HOST property (the store's lock arbitrates concurrent local spawns to
    one placement); what the pod adds is proof — the view shows exactly
    one tree per host per checkpoint, N_hosts×W — and coordinated
    teardown: ``request_teardown`` broadcasts a digest and every host's
    handler maps it back onto its local key."""

    def __init__(self, store=None):
        self.store = store if store is not None else weight_store()
        self._lock = make_lock("PodWeightRegistry._lock")
        self.teardowns_sent = 0
        self.teardowns_received = 0
        self._on_teardown: Optional[Callable] = None

    def local_info(self) -> dict:
        """This host's heartbeat entry (digest-keyed, wire-sized)."""
        st = self.store.stats()
        return {
            "trees": st["trees"],
            "refs": st["refs"],
            "bytes": st["bytes"],
            "digests": {
                e["digest"]: {"refs": e["refs"], "bytes": e["bytes"]}
                for e in st["entries"]
            },
        }

    def pod_view(self, peers: dict) -> dict:
        """Per-host weight occupancy from the latest gossip, local host
        included — the ``mst_weight_store_*{host=}`` source."""
        view = {}
        for host, entry in peers.items():
            w = entry.get("info", {}).get("weights")
            if w:
                view[host] = {
                    "trees": w.get("trees", 0),
                    "refs": w.get("refs", 0),
                    "bytes": w.get("bytes", 0),
                }
        return view

    def set_teardown_handler(self, cb: Callable) -> None:
        """``cb(key)`` runs when a teardown broadcast names a tree this
        host holds (the provider wires a drain of the replicas leasing it)."""
        self._on_teardown = cb

    def request_teardown(self, transport, digest: str) -> None:
        """Broadcast a checkpoint retirement to every live peer."""
        with self._lock:
            self.teardowns_sent += 1
        for host in list(transport.peers()):
            try:
                transport.send(host, "weights.teardown", digest.encode())
            except PodTransportError:
                pass  # a dead host has nothing left to tear down

    def handle_teardown(self, digest: str) -> Optional[object]:
        """Map a gossiped digest onto this host's store; returns the local
        WeightKey when found (after running the registered handler)."""
        with self._lock:
            self.teardowns_received += 1
        key = self.store.find(digest)
        if key is not None and self._on_teardown is not None:
            try:
                self._on_teardown(key)
            except Exception:  # noqa: BLE001 — teardown is advisory
                logger.exception("weight teardown handler failed")
        return key


# --------------------------------------------------------------------------
# cross-host handoff


class PodHandoff:
    """Ships a prefill host's ``ResumeState`` to a remote decode host and
    relays the remote stream back — the cross-host third phase of the
    disagg pipeline (``DisaggCoordinator.attach_pod``).

    Origin side: :meth:`pick_remote` prices the gossiped decode pools and
    returns a live host with free decode slots whose pressure beats the
    local pool's (None → serve locally, which is NOT a fallback);
    :meth:`serve_remote` runs the ``pod.handoff`` fault site, serializes
    the checksummed block, ships it, and yields relayed tokens. Receiver
    side: :meth:`attach_local` binds the local decode target; an incoming
    block is rebuilt (``KVPageBlock.from_bytes`` re-verifies the checksum),
    staged dispatch-only via ``stage_resume`` so its DMA overlaps the
    decode ticks in flight, and served through the ordinary
    ``generate_step(_resume=...)`` path — corrupt blocks fall into the
    scheduler's own re-prefill fallback, still token-exact.

    Fallback kinds (each counted, each landing on the origin's local plan,
    never a dropped stream): ``handoff_fault`` (injected control failure —
    serve in place, block intact), ``remote_unavailable`` (the chosen host
    died between pick and ship), ``serialize_error`` (block unserializable —
    local import still possible), ``transfer_fault`` (send failed mid-ship),
    ``remote_error`` (the remote pool failed before finishing),
    ``relay_timeout`` (the remote host went silent mid-stream — the
    host-death drain: the origin resumes after the last relayed token)."""

    def __init__(self, host_id: int, transport, *,
                 local_pressure: Optional[Callable[[], float]] = None,
                 heartbeat_timeout_s: float = HEARTBEAT_TIMEOUT_S,
                 relay_timeout_s: float = RELAY_TIMEOUT_S,
                 clock: Clock = MONOTONIC):
        self.host_id = host_id
        self.transport = transport
        self.local_pressure = local_pressure
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.relay_timeout_s = relay_timeout_s
        self.clock = clock
        self._lock = make_lock("PodHandoff._lock")
        self.shipped = 0
        self.bytes_shipped = 0
        self.received = 0
        self.relayed_tokens = 0
        self.fallbacks: dict = {}
        self._ms: deque = deque(maxlen=512)
        self._waiters: dict = {}     # rid -> queue.Queue of relay events
        self._target = None          # local decode target (receiver side)
        self._serve_kw_allow = None

    # ---------------------------------------------------------- accounting
    def _count(self, kind: str) -> None:
        with self._lock:
            self.fallbacks[kind] = self.fallbacks.get(kind, 0) + 1

    def stats(self) -> dict:
        with self._lock:
            ms = sorted(self._ms)
            n = len(ms)
            return {
                "shipped": self.shipped,
                "bytes_shipped": self.bytes_shipped,
                "received": self.received,
                "relayed_tokens": self.relayed_tokens,
                "fallbacks": dict(self.fallbacks),
                "ms_p50": ms[n // 2] if n else None,
                "ms_p99": ms[min(n - 1, int(round(0.99 * n)))] if n else None,
            }

    # ------------------------------------------------------------- routing
    def pick_remote(self) -> Optional[int]:
        """The least-pressured LIVE peer advertising free decode slots —
        and only when it genuinely beats the local pool (a tie ships
        nothing: the wire is never free). None means serve locally."""
        best, best_p = None, None
        try:
            peers = self.transport.peers()
        except Exception:  # noqa: BLE001 — no fabric, no remote
            return None
        for host, entry in peers.items():
            if entry.get("age_s", float("inf")) > self.heartbeat_timeout_s:
                continue
            decode = entry.get("info", {}).get("decode") or {}
            if int(decode.get("free", 0) or 0) <= 0:
                continue
            p = float(decode.get("pressure", 0.0) or 0.0)
            if best_p is None or p < best_p:
                best, best_p = host, p
        if best is None:
            return None
        if self.local_pressure is not None:
            try:
                if best_p >= self.local_pressure():
                    return None
            except Exception:  # noqa: BLE001 — price conservatively: local
                return None
        return best

    # ------------------------------------------------------------- origin
    def serve_remote(self, state: ResumeState, fwd_kw: dict):
        """Generator: ship ``state`` to the picked remote decode host and
        yield the relayed tokens. Raises :class:`PodHandoffFallback` on any
        failure; by the fault-site contract the injected ``pod.handoff``
        fires BEFORE any wire work, so that path leaves the block intact
        for the local serve-in-place."""
        nbytes = int(getattr(state.block, "nbytes", 0) or 0)
        try:
            inject("pod.handoff", n_bytes=nbytes)
        except Exception:
            self._count("handoff_fault")
            raise PodHandoffFallback("handoff_fault", keep_block=True) \
                from None
        dest = self.pick_remote()
        if dest is None:
            self._count("remote_unavailable")
            raise PodHandoffFallback("remote_unavailable", keep_block=True)
        data = b""
        if state.block is not None:
            try:
                data = state.block.to_bytes()
            except Exception:  # noqa: BLE001 — ship blockless? no: the local
                # import is strictly better than a remote re-prefill
                self._count("serialize_error")
                raise PodHandoffFallback("serialize_error", keep_block=True) \
                    from None
        rid = uuid.uuid4().hex
        wire = pickle.dumps({
            "rid": rid,
            "block": data,
            "prompt": np.asarray(state.prompt, np.int32),
            "history": [int(t) for t in (state.history or [])],
            "produced": int(state.produced),
            "resume_keys": None if state.block is not None
            else getattr(state, "resume_keys", None),
            "resume_recent": None if state.block is not None
            else getattr(state, "resume_recent", None),
            "kw": {k: v for k, v in fwd_kw.items()
                   if k in ("max_tokens", "temperature", "top_p", "seed",
                            "repetition_penalty", "repetition_context_size",
                            "logit_bias", "stall_timeout")},
        }, protocol=pickle.HIGHEST_PROTOCOL)
        q: queue.Queue = queue.Queue()
        with self._lock:
            self._waiters[rid] = q
        t0 = self.clock()
        relayed = 0
        try:
            try:
                self.transport.send(dest, "pod.block", wire)
            except Exception:  # noqa: BLE001 — the wire failed, block intact
                self._count("transfer_fault")
                raise PodHandoffFallback("transfer_fault", keep_block=True) \
                    from None
            with self._lock:
                self.shipped += 1
                self.bytes_shipped += len(wire)
            while True:
                try:
                    ev, item = q.get(timeout=self.relay_timeout_s)
                except queue.Empty:
                    # the remote host went silent mid-stream: host death.
                    # The origin owns the client stream, so it resumes
                    # locally AFTER the last relayed token — the token-exact
                    # drain of a dead host's session onto a survivor.
                    self._count("relay_timeout")
                    raise PodHandoffFallback(
                        "relay_timeout", tokens_relayed=relayed
                    ) from None
                if ev == "tok":
                    relayed += 1
                    with self._lock:
                        if relayed == 1:
                            self._ms.append((self.clock() - t0) * 1000.0)
                        self.relayed_tokens += 1
                    yield item
                elif ev == "end":
                    return
                else:  # "err": the remote pool failed before finishing
                    self._count("remote_error")
                    raise PodHandoffFallback(
                        "remote_error", tokens_relayed=relayed,
                        keep_block=relayed == 0,
                    )
        finally:
            # mst: allow(MST202): rid is a fresh uuid owned by this call; nothing else inserts or pops it between the two lock scopes
            with self._lock:
                self._waiters.pop(rid, None)

    # ----------------------------------------------------------- receiver
    def attach_local(self, target) -> None:
        """Bind the local decode target (anything with ``generate_step``
        supporting ``_resume``; ``stage_resume`` is used when present)."""
        self._target = target

    def handle(self, src: int, kind: str, payload: bytes) -> bool:
        """Transport-handler hook. Returns True when the message was a
        handoff-protocol message (consumed)."""
        if kind == "pod.block":
            threading.Thread(
                target=self._serve_shipped, args=(src, payload),
                name="mst-pod-serve", daemon=True,
            ).start()
            return True
        if kind in ("pod.tok", "pod.end", "pod.err"):
            try:
                rid, item = pickle.loads(payload)
            except Exception:  # noqa: BLE001 — undecodable relay event
                return True
            with self._lock:
                q = self._waiters.get(rid)
            if q is not None:
                q.put((kind.split(".")[1], item))
            return True
        return False

    def _serve_shipped(self, src: int, payload: bytes) -> None:
        """Receiver worker: rebuild the state, stage the block, serve on
        the local decode target, relay every token back to the origin."""
        rid = None
        try:
            msg = pickle.loads(payload)
            rid = msg["rid"]
            block = None
            if msg["block"]:
                try:
                    block = KVPageBlock.from_bytes(msg["block"])
                except BlockIntegrityError:
                    # corrupt in flight: the blockless fold re-prefills —
                    # same degradation as a failed local import
                    block = None
            state = ResumeState(
                prompt=msg["prompt"], history=list(msg["history"]),
                produced=int(msg["produced"]), block=block,
                resume_keys=msg.get("resume_keys"),
                resume_recent=msg.get("resume_recent"),
            )
            with self._lock:
                self.received += 1
            target = self._target
            if target is None:
                raise RuntimeError("no local decode target attached")
            stage = getattr(target, "stage_resume", None)
            if stage is not None and block is not None:
                # dispatch-only host→device stage, overlapped with the
                # decode ticks already in flight on this host
                stage(state)
            for item in target.generate_step(
                state.prompt, _resume=state, **msg.get("kw", {})
            ):
                self.transport.send(src, "pod.tok", pickle.dumps((rid, item)))
            self.transport.send(src, "pod.end", pickle.dumps((rid, None)))
        except Exception as e:  # noqa: BLE001 — report, origin falls back
            logger.exception("pod remote serve failed")
            if rid is not None:
                try:
                    self.transport.send(
                        src, "pod.err", pickle.dumps((rid, repr(e)[:200]))
                    )
                except Exception:  # noqa: BLE001 — origin's relay timeout
                    pass           # covers a dead return path


# --------------------------------------------------------------------------
# pod-federated prefix store

# how long a federated fetch waits for the owner's blob before degrading
# to plain prefill (a host-tier export + one transport round trip)
PREFIX_FETCH_TIMEOUT_S = 5.0

# how long a digest that missed pod-wide stays negative-cached, so a cold
# prefix doesn't re-probe the fabric on every admission
PREFIX_NEG_CACHE_S = 30.0


class PodPrefixFederation:
    """Federates the :class:`~mlx_sharding_tpu.prefix_store.PrefixStore`
    host tier across the pod, the same way weight digests federate: each
    host's heartbeat carries its prefix-digest inventory
    (``PrefixStore.host_inventory``), so a local prefix miss can consult
    the pod view and — on a remote hit — pull the owner's exported
    ``KVPageBlock`` (checksummed ``to_bytes`` wire format) into the LOCAL
    host tier, where the scheduler's ordinary staged-prefetch/demand-
    import path picks it up. Pod-wide, a hot prefix is prefilled ONCE.

    :meth:`fetch` runs strictly OFF the decode tick (the scheduler calls
    it from admission's store-consult slow path, never from ``_tick`` —
    mstcheck MST115 enforces this), fires the ``pod.prefix_fetch`` fault
    site requester-side before touching the wire, and degrades to plain
    prefill on EVERY failure, each counted by kind and none able to drop
    or corrupt a stream:

    - ``fetch_fault`` — injected control failure at the fault site;
    - ``miss`` — no live peer advertises the digest (negative-cached);
    - ``stale_inventory`` — only stale heartbeats advertise it, or the
      owner's tier evicted the block between gossip and fetch;
    - ``owner_dead`` — the send to the advertised owner failed;
    - ``timeout`` — the owner went silent past ``fetch_timeout_s``;
    - ``integrity`` — the blob failed its checksum, page geometry, or
      KV share-map layout check (kv_share.py) on arrival;
    - ``host_reject`` — the local tier refused the block (budget).
    """

    def __init__(self, host_id: int, transport, store, *,
                 heartbeat_timeout_s: float = HEARTBEAT_TIMEOUT_S,
                 fetch_timeout_s: float = PREFIX_FETCH_TIMEOUT_S,
                 neg_cache_s: float = PREFIX_NEG_CACHE_S,
                 clock: Clock = MONOTONIC):
        self.host_id = host_id
        self.transport = transport
        self.store = store
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.fetch_timeout_s = fetch_timeout_s
        self.neg_cache_s = neg_cache_s
        self.clock = clock
        self._lock = make_lock("PodPrefixFederation._lock")
        self._waiters: dict = {}   # rid -> queue.Queue of (ev, data)
        self._neg: dict = {}       # digest hex -> clock() expiry
        self.hits = 0              # pod-view consults that found an owner
        self.fetches = 0           # blobs imported into the local tier
        self.fetch_bytes = 0
        self.blobs_served = 0      # owner side: blobs exported to peers
        self.bytes_served = 0
        self.fallbacks: dict = {}
        self._ms: deque = deque(maxlen=512)

    # ---------------------------------------------------------- accounting
    def _count(self, kind: str) -> None:
        with self._lock:
            self.fallbacks[kind] = self.fallbacks.get(kind, 0) + 1

    def stats(self) -> dict:
        try:
            inventory = len(self.store.host_inventory())
        except Exception:  # noqa: BLE001 — a sick store reports nothing
            inventory = 0
        with self._lock:
            ms = sorted(self._ms)
            n = len(ms)
            return {
                "inventory_keys": inventory,
                "hits": self.hits,
                "fetches": self.fetches,
                "fetch_bytes": self.fetch_bytes,
                "blobs_served": self.blobs_served,
                "bytes_served": self.bytes_served,
                "fallbacks": dict(self.fallbacks),
                "fetch_ms_p50": ms[n // 2] if n else None,
                "fetch_ms_p99": (
                    ms[min(n - 1, int(round(0.99 * n)))] if n else None
                ),
            }

    # ----------------------------------------------------------- heartbeat
    def local_info(self) -> dict:
        """This host's prefix heartbeat entry: the host-tier digest
        inventory plus the geometry peers need to pre-judge compatibility
        (page size and KV share-map hash both ride the blob check anyway —
        advertising them just saves a doomed fetch)."""
        try:
            return {
                "keys": self.store.host_inventory(),
                "page_size": self.store.page_size,
                "share": self.store.share_hash,
                "compress": self.store.compress_hash,
            }
        except Exception:  # noqa: BLE001 — advertise nothing, not garbage
            return {}

    # ------------------------------------------------------------- routing
    def _owner_for(self, hexd: str):
        """(owner host, None) for the freshest LIVE peer advertising the
        digest; (None, fallback kind) otherwise."""
        try:
            peers = self.transport.peers()
        except Exception:  # noqa: BLE001 — no fabric, no federation
            return None, "miss"
        local = {
            "page_size": self.store.page_size,
            "share": self.store.share_hash,
            "compress": self.store.compress_hash,
        }
        best = None
        stale_only = False
        layout_only = False
        for host, entry in peers.items():
            info = (entry.get("info") or {}).get("prefix") or {}
            if hexd not in (info.get("keys") or ()):
                continue
            if info.get("page_size") != local["page_size"] \
                    or info.get("share") != local["share"] \
                    or info.get("compress") != local["compress"]:
                # incompatible geometry (page size / share map / compress
                # layout): the fetch would fail the blob check — skip
                # before any bytes move
                layout_only = True
                continue
            age = entry.get("age_s", float("inf"))
            if age > self.heartbeat_timeout_s:
                stale_only = True
                continue
            if best is None or age < best[0]:
                best = (age, host)
        if best is not None:
            return best[1], None
        if stale_only:
            return None, "stale_inventory"
        return None, ("layout_mismatch" if layout_only else "miss")

    # ------------------------------------------------------------ requester
    def _neg_cached(self, hexd: str) -> bool:
        """One lock scope: purge an expired entry, report a live one."""
        now = self.clock()
        with self._lock:
            exp = self._neg.get(hexd)
            if exp is None:
                return False
            if now < exp:
                return True
            del self._neg[hexd]
            return False

    def _neg_add(self, hexd: str) -> None:
        with self._lock:
            self._neg[hexd] = self.clock() + self.neg_cache_s

    def fetch(self, digest: bytes) -> bool:
        """Pull the prefix block for ``digest`` from its pod owner into
        the LOCAL host tier. True iff the block is now locally resident
        (the caller re-probes the store and rides the normal import path);
        False means plain prefill, with the reason counted. Blocking —
        call it from admission's store-consult slow path, NEVER from the
        decode tick."""
        hexd = digest.hex()
        if self._neg_cached(hexd):
            self._count("neg_cached")
            return False
        try:
            inject("pod.prefix_fetch", digest=hexd)
        except Exception:  # noqa: BLE001 — injected control failure
            self._count("fetch_fault")
            return False
        owner, why = self._owner_for(hexd)
        if owner is None:
            self._count(why)
            if why in ("miss", "layout_mismatch"):
                # a mismatched layout is as durable as a miss: the peer
                # would need a restart with new maps to become compatible
                self._neg_add(hexd)
            return False
        with self._lock:
            self.hits += 1
        rid = uuid.uuid4().hex
        q: queue.Queue = queue.Queue()
        with self._lock:
            self._waiters[rid] = q
        t0 = self.clock()
        try:
            try:
                self.transport.send(
                    owner, "prefix.fetch",
                    pickle.dumps({"rid": rid, "digest": digest},
                                 protocol=pickle.HIGHEST_PROTOCOL),
                )
            except Exception:  # noqa: BLE001 — the advertised owner died
                self._count("owner_dead")
                return False
            try:
                ev, data = q.get(timeout=self.fetch_timeout_s)
            except queue.Empty:
                self._count("timeout")
                return False
        finally:
            # mst: allow(MST202): rid is a fresh uuid owned by this call
            with self._lock:
                self._waiters.pop(rid, None)
        if ev != "blob" or not data:
            # the owner's tier evicted the block after the last heartbeat
            self._count("stale_inventory")
            self._neg_add(hexd)
            return False
        try:
            block = KVPageBlock.from_bytes(data)
        except BlockIntegrityError:
            self._count("integrity")
            return False
        if (self.store.page_size is not None
                and block.page_size != self.store.page_size) \
                or block.share_hash != self.store.share_hash:
            self._count("integrity")
            return False
        if block.compress_hash is not None \
                and block.compress_hash != self.store.compress_hash:
            # the owner lied (or re-calibrated) since its last heartbeat:
            # the latent layout cannot be reconstructed here
            self._count("layout_mismatch")
            return False
        if not self.store.host_put(digest, block):
            self._count("host_reject")
            return False
        with self._lock:
            self.fetches += 1
            self.fetch_bytes += len(data)
            self._ms.append((self.clock() - t0) * 1000.0)
        return True

    # ----------------------------------------------------------- receiver
    def handle(self, src: int, kind: str, payload: bytes) -> bool:
        """Transport-handler hook. Returns True when the message was a
        prefix-federation message (consumed)."""
        if kind == "prefix.fetch":
            # serve off the transport receive thread: to_bytes of a big
            # block must not stall the heartbeat loop (handoff discipline)
            threading.Thread(
                target=self._serve_fetch, args=(src, payload),
                name="mst-pod-prefix", daemon=True,
            ).start()
            return True
        if kind in ("prefix.blob", "prefix.miss"):
            try:
                rid, data = pickle.loads(payload)
            except Exception:  # noqa: BLE001 — undecodable reply
                return True
            with self._lock:
                q = self._waiters.get(rid)
            if q is not None:
                q.put(("blob" if kind == "prefix.blob" else "miss", data))
            return True
        return False

    def _serve_fetch(self, src: int, payload: bytes) -> None:
        rid = None
        blob = b""
        try:
            msg = pickle.loads(payload)
            rid = msg["rid"]
            blk = self.store.host_block(msg["digest"])
            if blk is not None:
                blob = blk.to_bytes()
        except Exception:  # noqa: BLE001 — a serve failure is the
            blob = b""     # requester's stale_inventory fallback
        if rid is None:
            return
        try:
            self.transport.send(
                src,
                "prefix.blob" if blob else "prefix.miss",
                pickle.dumps((rid, blob), protocol=pickle.HIGHEST_PROTOCOL),
            )
        except Exception:  # noqa: BLE001 — requester's fetch timeout
            return         # covers a dead return path
        if blob:
            with self._lock:
                self.blobs_served += 1
                self.bytes_served += len(blob)


# --------------------------------------------------------------------------
# pod autoscaler


class PodAutoscaler:
    """One control loop over the whole pod, run identically on every host.

    Decisions are deterministic functions of the shared gossip view, and
    each host only ever ACTS on itself — the host that the view says
    should spawn, spawns; everyone else concludes it shouldn't. No leader,
    no election, no races: disagreement is bounded by one heartbeat of
    staleness, and the per-host ``FleetAutoscaler`` bounds (min/max, the
    device-slice free list behind its factory) still gate every action.

    Host death: a peer whose heartbeat age passes ``heartbeat_timeout_s``
    is declared dead once, ``on_host_death`` fires (the fleet resumes its
    relayed sessions — see PodHandoff's relay timeout — and routing drops
    it), and the dead host's advertised capacity leaves the free list."""

    def __init__(self, host_id: int, transport, controllers=(), *,
                 scale_up_pressure: float = 0.75,
                 scale_down_pressure: float = 0.25,
                 heartbeat_timeout_s: float = HEARTBEAT_TIMEOUT_S,
                 on_host_death: Optional[Callable[[int], None]] = None,
                 clock: Clock = MONOTONIC):
        self.host_id = host_id
        self.transport = transport
        self.controllers = list(controllers)
        self.scale_up_pressure = scale_up_pressure
        self.scale_down_pressure = scale_down_pressure
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.on_host_death = on_host_death
        self.clock = clock
        self._lock = make_lock("PodAutoscaler._lock")
        self.dead_hosts: set = set()
        self.deaths_detected = 0
        self.spawns = 0
        self.drains = 0
        self.ticks = 0

    # ------------------------------------------------------------- signals
    def local_info(self) -> dict:
        """This host's autoscaler heartbeat entry: pressure + headroom."""
        pressure = 0.0
        spawnable = drainable = live = 0
        slots = 0
        for ctrl in self.controllers:
            try:
                pressure = max(pressure, ctrl.pressure())
                h = ctrl.headroom()
                spawnable += h["spawnable"]
                drainable += h["drainable"]
                live += h["live"]
                slots += ctrl.rs.stats()[0]
            except Exception:  # noqa: BLE001 — a sick controller reports
                continue       # nothing, not garbage
        return {
            "pressure": round(pressure, 4),
            "slots": slots,
            "live": live,
            "spawnable": spawnable,
            "drainable": drainable,
        }

    def _live_view(self) -> tuple:
        """(infos by host incl. self, newly dead hosts)."""
        infos = {self.host_id: self.local_info()}
        newly_dead = []
        with self._lock:
            known_dead = set(self.dead_hosts)
        for host, entry in self.transport.peers().items():
            if host in known_dead:
                continue
            if entry.get("age_s", float("inf")) > self.heartbeat_timeout_s:
                newly_dead.append(host)
                continue
            fl = entry.get("info", {}).get("fleet")
            if fl:
                infos[host] = fl
        return infos, newly_dead

    # ------------------------------------------------------------ decision
    def tick(self) -> dict:
        """One pod control decision on the current gossip view."""
        with self._lock:
            self.ticks += 1
        infos, newly_dead = self._live_view()
        for host in newly_dead:
            with self._lock:
                if host in self.dead_hosts:
                    continue
                self.dead_hosts.add(host)
                self.deaths_detected += 1
            logger.warning(
                "pod host %d heartbeat stale — declaring it dead; its "
                "relayed sessions resume on the survivors", host,
            )
            if self.on_host_death is not None:
                try:
                    self.on_host_death(host)
                except Exception:  # noqa: BLE001 — detection must not die
                    logger.exception("host-death handler failed")
        pod_pressure = aggregate_pressure(list(infos.values()))
        action = None
        mine = infos[self.host_id]
        if pod_pressure >= self.scale_up_pressure:
            # the least-loaded host WITH headroom spawns; that might be us
            cands = [
                (info.get("pressure", 0.0), host)
                for host, info in infos.items()
                if int(info.get("spawnable", 0) or 0) > 0
            ]
            if cands and min(cands)[1] == self.host_id:
                action = self._spawn_local()
        elif pod_pressure <= self.scale_down_pressure:
            # the MOST loaded drainable host sheds — it frees the most
            # contended hardware back to the pod free list
            cands = [
                (info.get("pressure", 0.0), host)
                for host, info in infos.items()
                if int(info.get("drainable", 0) or 0) > 0
            ]
            if cands and max(cands)[1] == self.host_id:
                action = self._drain_local()
        with self._lock:
            dead = sorted(self.dead_hosts)
        return {
            "pod_pressure": round(pod_pressure, 4),
            "hosts": len(infos),
            "dead": dead,
            "action": action,
            "local_pressure": mine.get("pressure", 0.0),
        }

    def _spawn_local(self) -> Optional[str]:
        for ctrl in self.controllers:
            try:
                out = ctrl.spawn_one()
            except Exception:  # noqa: BLE001 — controller's own quarantine
                continue
            if out == "spawn":
                with self._lock:
                    self.spawns += 1
                return out
        return None

    def _drain_local(self) -> Optional[str]:
        for ctrl in self.controllers:
            try:
                out = ctrl.drain_one()
            except Exception:  # noqa: BLE001
                continue
            if out == "drain":
                with self._lock:
                    self.drains += 1
                return out
        return None

    def state(self) -> dict:
        with self._lock:
            return {
                "ticks": self.ticks,
                "spawns": self.spawns,
                "drains": self.drains,
                "dead_hosts": sorted(self.dead_hosts),
                "deaths_detected": self.deaths_detected,
                "heartbeat_timeout_s": self.heartbeat_timeout_s,
            }


# --------------------------------------------------------------------------
# the front door


class PodFleet:
    """One host's membership in the pod: local fleet + weight registry +
    cross-host handoff + pod autoscaler, bound to one transport.

    ``generate_step`` delegates to the local generator (a
    ``DisaggCoordinator`` with the pod handoff attached serves the decode
    leg remotely when a remote pool is cheaper); :meth:`tick` publishes the
    heartbeat and runs the pod autoscaler — call it from a loop
    (:meth:`start`) in serving, or directly in tests."""

    def __init__(self, host_id: int, transport, local, *,
                 controllers=(), decode_pool=None, registry=None,
                 prefix_store=None,
                 heartbeat_timeout_s: float = HEARTBEAT_TIMEOUT_S,
                 relay_timeout_s: float = RELAY_TIMEOUT_S,
                 interval_s: float = 0.5,
                 clock: Clock = MONOTONIC):
        self.host_id = host_id
        self.transport = transport
        self.local = local
        self.interval_s = interval_s
        self.clock = clock
        self.registry = registry if registry is not None \
            else PodWeightRegistry()
        # the decode target remote prefill hosts ship into: an explicit
        # pool, the local coordinator's decode pool, or the generator itself
        target = decode_pool
        if target is None:
            target = getattr(local, "decode", local)
        self._decode_target = target
        self.handoff = PodHandoff(
            host_id, transport,
            local_pressure=self._local_decode_pressure,
            heartbeat_timeout_s=heartbeat_timeout_s,
            relay_timeout_s=relay_timeout_s, clock=clock,
        )
        self.handoff.attach_local(target)
        if hasattr(local, "attach_pod"):
            local.attach_pod(self.handoff)
        self.autoscaler = PodAutoscaler(
            host_id, transport, controllers,
            heartbeat_timeout_s=heartbeat_timeout_s,
            on_host_death=self._host_died, clock=clock,
        )
        self.prefix: Optional[PodPrefixFederation] = None
        if prefix_store is not None:
            self.attach_prefix_store(prefix_store)
        self.host_deaths = 0
        self._lock = make_lock("PodFleet._lock")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        transport.set_handler(self._on_message)

    def attach_prefix_store(self, store) -> "PodPrefixFederation":
        """Federate ``store``'s host tier over this pod: its digest
        inventory rides the heartbeat, and the federation handle lands on
        ``store.federation`` so the scheduler's store-consult slow path
        reaches :meth:`PodPrefixFederation.fetch` without knowing about
        the pod at all."""
        self.prefix = PodPrefixFederation(
            self.host_id, self.transport, store,
            heartbeat_timeout_s=self.autoscaler.heartbeat_timeout_s,
            clock=self.clock,
        )
        store.federation = self.prefix
        return self.prefix

    # ------------------------------------------------------------- serving
    def generate_step(self, prompt_tokens, **kw):
        return self.local.generate_step(prompt_tokens, **kw)

    def __getattr__(self, name):
        # stat surfaces (stats/fleet_stats/health/...) pass through to the
        # local generator so the server drives a PodFleet unchanged
        return getattr(self.local, name)

    def _local_decode_pressure(self) -> float:
        from mlx_sharding_tpu.fleet import pool_pressure

        slots, active, queued = self._decode_target.stats()
        return pool_pressure(slots, active, queued, 0)

    # ----------------------------------------------------------- heartbeat
    def _local_info(self) -> dict:
        decode = {}
        try:
            load = getattr(self._decode_target, "pool_load", None)
            if load is not None:
                decode = load()
            else:
                slots, active, queued = self._decode_target.stats()
                decode = {"slots": slots, "active": active,
                          "queued": queued, "free": max(0, slots - active)}
            decode["pressure"] = round(self._local_decode_pressure(), 4)
        except Exception:  # noqa: BLE001 — advertise nothing, not garbage
            decode = {}
        spec = None
        try:
            # speculation summary rides the heartbeat so pod placement can
            # see which hosts speculate and how well it pays (draft-engine
            # WEIGHT trees already gossip via the registry block above —
            # they live in the same WeightStore as the base)
            fn = getattr(self.local, "spec_stats", None)
            if fn is not None:
                st = fn()
                if st:
                    spec = {
                        "mode": st.get("mode"),
                        "accept_rate": round(
                            float(st.get("accept_rate", 0.0)), 4
                        ),
                        "rounds": st.get("rounds", 0),
                    }
        except Exception:  # noqa: BLE001 — advertise nothing, not garbage
            spec = None
        info = {
            "host": self.host_id,
            "fleet": self.autoscaler.local_info(),
            "decode": decode,
            "weights": self.registry.local_info(),
        }
        if spec is not None:
            info["spec"] = spec
        if self.prefix is not None:
            # prefix-digest inventory rides the same heartbeat the weight
            # digests do — a miss anywhere consults this pod view
            info["prefix"] = self.prefix.local_info()
        return info

    def tick(self) -> dict:
        """Publish the heartbeat, run one pod-autoscaler decision."""
        self.transport.publish(self._local_info())
        return self.autoscaler.tick()

    def start(self) -> None:
        if self._thread is not None:
            return
        if hasattr(self.transport, "start"):
            self.transport.start()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mst-pod-fleet", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the pod loop must outlive a
                logger.exception("pod fleet tick failed")  # bad tick

    # ------------------------------------------------------------ messages
    def _on_message(self, src: int, kind: str, payload: bytes) -> None:
        if self.handoff.handle(src, kind, payload):
            return
        if self.prefix is not None and self.prefix.handle(src, kind, payload):
            return
        if kind == "weights.teardown":
            self.registry.handle_teardown(payload.decode())
            return
        logger.debug("unrecognized pod message kind %r from %d", kind, src)

    def _host_died(self, host: int) -> None:
        with self._lock:
            self.host_deaths += 1

    # ------------------------------------------------------ observability
    def pod_stats(self) -> dict:
        """The /health ``pod`` block and the host-labeled metrics source:
        every known host's fleet/weights/heartbeat view plus the handoff
        and autoscaler counters."""
        hosts = {
            str(self.host_id): {
                "alive": True,
                "heartbeat_age_s": 0.0,
                "fleet": self.autoscaler.local_info(),
                "weights": self.registry.local_info(),
            }
        }
        try:
            peers = self.transport.peers()
        except Exception:  # noqa: BLE001 — a dead fabric still renders
            peers = {}
        dead = set(self.autoscaler.state()["dead_hosts"])
        with self._lock:
            host_deaths = self.host_deaths
        for host, entry in peers.items():
            info = entry.get("info", {})
            age = entry.get("age_s")
            hosts[str(host)] = {
                "alive": host not in dead and (
                    age is not None
                    and age <= self.autoscaler.heartbeat_timeout_s
                ),
                "heartbeat_age_s": (
                    None if age is None or age == float("inf")
                    else round(age, 3)
                ),
                "fleet": info.get("fleet", {}),
                "weights": info.get("weights", {}),
            }
        out = {
            "host_id": self.host_id,
            "hosts": hosts,
            "handoff": self.handoff.stats(),
            "autoscaler": self.autoscaler.state(),
            "host_deaths": host_deaths,
        }
        if self.prefix is not None:
            out["prefix"] = self.prefix.stats()
        return out

    def close(self, close_local: bool = True) -> None:
        """Stop the pod loop and transport. ``close_local`` follows the
        server's ownership (the PodFleet replaced the provider's generator,
        so tearing it down tears the chain); pass False when the local
        generator outlives this pod membership (tests, re-attachment)."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        try:
            self.transport.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        if close_local:
            close = getattr(self.local, "close", None)
            if close is not None:
                close()


# --------------------------------------------------------------------------
# gloo acceptance demo (``python -m mlx_sharding_tpu.pod``)


def _selftest_main(argv=None):  # pragma: no cover — driven by the slow test
    """Two-process CPU acceptance demo over real gloo collectives.

    Rank 0 runs a disagg coordinator (prefill + decode batchers aliasing
    ONE packed weight tree) with the pod attached; rank 1 runs a decode
    host (two batchers aliasing ONE tree, one pod-attached). The demo
    proves, in one deployment: (1) one weight tree per host with >= 2
    local refs, visible through the gossip view; (2) a cross-host
    prefill→decode handoff whose greedy stream is bit-identical to a
    monolithic batcher; (3) the ``pod.handoff`` fault and a real host
    death mid-relay both degrading to the local plan with zero dropped
    streams and counted fallbacks. Rank 0 prints one JSON document.
    """
    import argparse
    import json
    import os
    import sys

    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=4")
    os.environ.setdefault("MST_POD_TIMEOUT_S", "20")

    p = argparse.ArgumentParser()
    p.add_argument("--coordinator", required=True)
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--process-id", type=int, required=True)
    args = p.parse_args(argv)

    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — older/newer jax: best effort
            pass
    jax.distributed.initialize(args.coordinator,
                               num_processes=args.num_processes,
                               process_id=args.process_id)
    import jax.numpy as jnp

    from mlx_sharding_tpu.config import LlamaConfig
    from mlx_sharding_tpu.disagg import DisaggCoordinator
    from mlx_sharding_tpu.models.llama import LlamaModel
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import (
        PipelineEngine,
        place_weights,
    )
    from mlx_sharding_tpu.replicas import ReplicaSet
    from mlx_sharding_tpu.scheduler import ContinuousBatcher
    from mlx_sharding_tpu.testing import faults
    from mlx_sharding_tpu.weights import (
        WeightKey, aliased_spawn, weight_store,
    )

    host = jax.process_index()
    tiny = dict(vocab_size=256, hidden_size=32, intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2)
    model = LlamaModel(LlamaConfig(**tiny))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    mesh = make_mesh(pp=1, devices=jax.local_devices()[:1])
    key = WeightKey(checkpoint="pod-demo", stage_bounds=(("auto", 1),),
                    dtype="float32", quant="tp1",
                    placement=f"pod-host-{host}")
    store = weight_store()
    eng_kw = dict(microbatches=2, max_seq=64, cache_dtype=jnp.float32,
                  prefill_chunk=8, pool_pages=10, page_size=8)

    def aliased_batcher():
        def make(lease):
            eng = PipelineEngine(model, None, lease.weights.mesh,
                                 weights=lease.weights, **eng_kw)
            eng.on_close(lease.release)
            return ContinuousBatcher(eng, decode_block=3)

        return aliased_spawn(
            store, key, lambda: place_weights(model, params, mesh), make)

    transport = CollectiveTransport(interval_s=0.05)
    job = ([3, 17, 42], dict(max_tokens=24))

    if host == 0:
        # prefill + decode pools alias ONE local tree (trees=1, refs=2)
        co = DisaggCoordinator(
            ReplicaSet([aliased_batcher()], role="prefill"),
            ReplicaSet([aliased_batcher()], role="decode"),
        )
        fleet = PodFleet(host, transport, co, relay_timeout_s=5.0,
                         interval_s=0.1)
        # monolithic parity reference, built OUTSIDE the store so the
        # tree/ref gauges stay an exact statement about the fleet
        mono = ContinuousBatcher(
            PipelineEngine(model, params, mesh, **eng_kw), decode_block=3)
        ref = [t for t, _ in mono.generate_step(job[0], **job[1])]
        fleet.start()
        # price the local decode pool as hot so routing picks the remote
        fleet.handoff.local_pressure = lambda: 1.0
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            view = fleet.pod_stats()["hosts"]
            if "1" in view and (view["1"].get("weights") or {}).get("trees"):
                break
            time.sleep(0.2)
        report = {"hosts": fleet.pod_stats()["hosts"]}

        # ---- demo 2: cross-host handoff, bit-identical greedy stream
        got = [t for t, _ in co.generate_step(job[0], **job[1])]
        h = fleet.handoff.stats()
        report["handoff"] = {
            "match": got == ref, "shipped": h["shipped"],
            "bytes_shipped": h["bytes_shipped"],
            "relayed_tokens": h["relayed_tokens"],
            "ms_p50": h["ms_p50"], "ms_p99": h["ms_p99"],
        }

        # ---- demo 3: injected pod.handoff fault → serve-in-place parity
        faults.arm("pod.handoff", exc=faults.FaultError, times=1)
        got_fault = [t for t, _ in co.generate_step(job[0], **job[1])]
        faults.disarm()
        report["fault_sweep"] = {
            "match": got_fault == ref,
            "fallbacks": fleet.handoff.stats()["fallbacks"],
        }

        # ---- demo 4: real host death mid-relay → token-exact local drain
        transport.send(1, "demo.die", b"2")  # die after 2 relayed tokens
        time.sleep(0.5)
        got_death = [t for t, _ in co.generate_step(job[0], **job[1])]
        h = fleet.handoff.stats()
        report["host_death"] = {
            "match": got_death == ref,
            "fallbacks": h["fallbacks"],
            "dropped_streams": 0 if got_death == ref else 1,
        }
        report["ok"] = all((
            report["handoff"]["match"], report["handoff"]["shipped"] >= 1,
            report["fault_sweep"]["match"],
            report["fault_sweep"]["fallbacks"].get("handoff_fault") == 1,
            report["host_death"]["match"],
            (report["host_death"]["fallbacks"].get("relay_timeout", 0)
             + report["host_death"]["fallbacks"].get("remote_error", 0)
             + report["host_death"]["fallbacks"].get("transfer_fault", 0)
             >= 1),
            all((v.get("weights") or {}).get("trees") == 1
                and (v.get("weights") or {}).get("refs", 0) >= 2
                for v in report["hosts"].values()),
        ))
        print(json.dumps(report))
        sys.stdout.flush()
        os._exit(0 if report["ok"] else 1)
    else:
        # decode host: two batchers alias ONE tree; the first is the
        # pod-attached decode target, the second proves the aliasing
        b1 = aliased_batcher()
        _b2 = aliased_batcher()  # noqa: F841 — holds the second ref live
        die_after = [None]

        class _Mortal:
            """Decode target that can die mid-relay on command."""

            def __init__(self, inner):
                self.inner = inner

            def __getattr__(self, name):
                return getattr(self.inner, name)

            def generate_step(self, prompt, **kw):
                n = 0
                for item in self.inner.generate_step(prompt, **kw):
                    yield item
                    n += 1
                    if die_after[0] is not None and n >= die_after[0]:
                        os._exit(0)  # SIGKILL-grade: no goodbyes

        fleet = PodFleet(host, transport, _Mortal(b1), interval_s=0.1)
        inner_handler = transport._handler

        def handler(src, kind, payload):
            if kind == "demo.die":
                die_after[0] = int(payload or b"1")
                return
            inner_handler(src, kind, payload)

        transport.set_handler(handler)
        fleet.start()
        time.sleep(120)  # killed by demo 4 (or the test's timeout)


if __name__ == "__main__":
    # run the CANONICAL module's driver: under ``python -m`` this file is
    # imported twice (once as __main__, once as mlx_sharding_tpu.pod), and
    # the fallback exceptions must be the classes disagg.py catches
    from mlx_sharding_tpu.pod import _selftest_main as _canonical_main

    _canonical_main()
