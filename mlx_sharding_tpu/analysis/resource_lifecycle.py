"""MST40x: path-sensitive must-release verification.

Runs the resource registry (:mod:`.resources`) over per-function CFGs
(:mod:`.cfg`): every path from an acquire to a function exit is walked
with a tiny abstract interpreter tracking each handle variable through

    LIVE → RELEASED            (lease.release() / self._done(i, probe))
    LIVE → ESCAPED_STRONG      (stored on self/req/..., returned, yielded)
    LIVE → ESCAPED_WEAK        (passed as a plain call argument)

plus *None-refinement*: ``if lease is None: return`` kills the handle on
the true arm, so Optional acquires (``PrefixStore.acquire`` → ``None`` on
miss) don't flag their miss path.

Rules:

- **MST401 leak-on-exception-path** — a LIVE handle reaches the raise
  exit: some call between acquire and release can raise (the non-raising
  vocabulary in :mod:`.resources` filters counters/logging) and no
  ``try/finally`` puts the release on that unwind. The PR-3 probe-ticket
  bug, statically.
- **MST402 double-release** — a path releases the same handle twice
  ("released exactly once through drain/close/fault paths", PR 11).
- **MST403 release-of-escaped** — releasing a handle after ownership
  already transferred (stored on an object / returned): the new owner
  will release it again. Release after a *weak* escape (handle passed to
  a constructor that may or may not take ownership) is allowed — that is
  the ``aliased_spawn`` fault-cleanup idiom.
- **MST404 missing-release-arm** — a LIVE handle reaches the *normal*
  exit: a conditional release misses this early-``return`` arm (or the
  function simply never releases).

Interprocedural layer (module-local, two-pass): a function whose every
path either returns a freshly acquired handle or releases it becomes an
acquire-alias at its call sites; a function that releases a parameter on
all paths becomes a release-alias for the argument at that position.

Bounded: loops are walked 0 or 1 times (each CFG node at most twice per
path), with global path/step caps — best-effort on pathological
functions, exact on the acquire/release shapes this repo actually has.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Optional

from mlx_sharding_tpu.analysis import cfg as cfglib
from mlx_sharding_tpu.analysis import resources
from mlx_sharding_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    dotted_name,
    qualname_for_line,
)

# handle states
LIVE = "live"
RELEASED = "released"
STRONG = "escaped"        # ownership transferred (attr store / return)
WEAK = "escaped-weak"     # passed as a call argument

MAX_STEPS = 60_000        # traversal-step safety valve per function


@dataclass(frozen=True)
class Handle:
    kind: str       # resources kind ("weights.lease")
    status: str
    acq_line: int
    event_line: int  # line of the last status transition


@dataclass(frozen=True)
class FnSummary:
    """Module-local interprocedural facts for one function."""

    name: str
    returns_fresh: Optional[str] = None   # resource kind it hands out
    releases_param: Optional[int] = None  # 0-based index (self excluded)
    param_name: Optional[str] = None


def _bare(call: ast.Call) -> Optional[str]:
    name = dotted_name(call.func)
    return name.split(".")[-1] if name else None


def _receiver(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return dotted_name(call.func.value)
    return None


def _may_raise(stmt: ast.AST) -> bool:
    for n in ast.walk(stmt):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            bare = _bare(n)
            if bare is None or not resources.is_nonraising(bare):
                return True
    return False


def _expr_calls(node: ast.AST) -> list:
    calls = [n for n in ast.walk(node) if isinstance(n, ast.Call)]
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    return calls


def _names_in(node: ast.AST) -> list:
    return [n.id for n in ast.walk(node) if isinstance(n, ast.Name)]


# --------------------------------------------------------------- node ops
# op shapes:
#   ("acquire", var, kind, ast_node)
#   ("release", var, ast_node)          # receiver- or arg-style release
#   ("release_cm", var, ast_node)       # with __exit__: silent, idempotent
#   ("strong", var, ast_node)
#   ("weak", var, ast_node)
#   ("kill", var, None)
class _Ops:
    """Per-CFG-node effect extraction, with interprocedural extensions."""

    def __init__(self, summaries: dict):
        self.summaries = summaries  # bare fn name -> FnSummary
        self._cache: dict = {}

    def for_node(self, node) -> list:
        ops = self._cache.get(node.idx)
        if ops is None:
            ops = self._compute(node)
            self._cache[node.idx] = ops
        return ops

    # -- helpers -----------------------------------------------------
    def _acquire_kind(self, call: ast.Call) -> Optional[str]:
        bare = _bare(call)
        if bare is None:
            return None
        spec = resources.match_acquire(bare, _receiver(call))
        if spec is not None:
            return spec.kind
        s = self.summaries.get(bare)
        if s is not None and s.returns_fresh:
            return s.returns_fresh
        return None

    def _call_ops(self, call: ast.Call, ops: list, acquired_to: set):
        """Release/weak-escape effects of one call (acquire handled by
        the enclosing assignment, which knows the binding target)."""
        bare = _bare(call)
        released_here: set = set()
        if bare is not None:
            spec = resources.match_release(bare)
            if spec is not None:
                if spec.release_as_arg:
                    for arg in list(call.args) + [k.value for k in call.keywords]:
                        if isinstance(arg, ast.Name):
                            ops.append(("release", arg.id, call))
                            released_here.add(arg.id)
                elif isinstance(call.func, ast.Attribute) and isinstance(
                        call.func.value, ast.Name):
                    ops.append(("release", call.func.value.id, call))
                    released_here.add(call.func.value.id)
            s = self.summaries.get(bare)
            if s is not None and s.releases_param is not None:
                args = [a for a in call.args]
                if s.releases_param < len(args) and isinstance(
                        args[s.releases_param], ast.Name):
                    ops.append(("release", args[s.releases_param].id, call))
                    released_here.add(args[s.releases_param].id)
                for kw in call.keywords:
                    if kw.arg == s.param_name and isinstance(kw.value, ast.Name):
                        ops.append(("release", kw.value.id, call))
                        released_here.add(kw.value.id)
        # any other handle passed in is a weak escape
        for arg in list(call.args) + [k.value for k in call.keywords]:
            for name in _names_in(arg):
                if name not in released_here and name not in acquired_to:
                    ops.append(("weak", name, call))

    def _compute(self, node) -> list:
        ops: list = []
        stmt = node.stmt
        if stmt is None or node.kind == "dispatch":
            # dispatch nodes reference the whole ast.Try for location only —
            # the body/handler/finally statements are their own CFG nodes
            return ops

        if node.kind == "with_exit":
            if isinstance(stmt, ast.withitem) and isinstance(
                    stmt.optional_vars, ast.Name):
                ops.append(("release_cm", stmt.optional_vars.id, stmt))
            return ops

        if isinstance(stmt, ast.withitem):
            # the context-expression node of a `with`
            acquired_to: set = set()
            kind = (self._acquire_kind(stmt.context_expr)
                    if isinstance(stmt.context_expr, ast.Call) else None)
            if kind is not None and isinstance(stmt.optional_vars, ast.Name):
                acquired_to.add(stmt.optional_vars.id)
            for call in _expr_calls(stmt.context_expr):
                self._call_ops(call, ops, acquired_to)
            if kind is not None and isinstance(stmt.optional_vars, ast.Name):
                ops.append(("acquire", stmt.optional_vars.id, kind,
                            stmt.context_expr))
            elif isinstance(stmt.optional_vars, ast.Name):
                ops.append(("kill", stmt.optional_vars.id, None))
            return ops

        if isinstance(stmt, ast.ExceptHandler):
            if stmt.name:
                ops.append(("kill", stmt.name, None))
            return ops

        if isinstance(stmt, (ast.If, ast.While)):
            for call in _expr_calls(stmt.test):
                self._call_ops(call, ops, set())
            return ops

        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            for call in _expr_calls(stmt.iter):
                self._call_ops(call, ops, set())
            for name in _names_in(stmt.target):
                ops.append(("kill", name, None))
            return ops

        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                for call in _expr_calls(stmt.value):
                    self._call_ops(call, ops, set())
                for name in _names_in(stmt.value):
                    ops.append(("strong", name, stmt))
            return ops

        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    ops.append(("kill", t.id, None))
            return ops

        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            acquired_to: set = set()
            kind = (self._acquire_kind(value)
                    if isinstance(value, ast.Call) else None)
            bind_var = None
            if kind is not None:
                for t in targets:
                    if isinstance(t, ast.Name):
                        bind_var = t.id
                    elif isinstance(t, ast.Tuple):
                        bare = _bare(value)
                        spec = resources.match_acquire(bare, _receiver(value)) \
                            if bare else None
                        pos = spec.handle_pos if spec else None
                        if pos is not None and pos < len(t.elts) and \
                                isinstance(t.elts[pos], ast.Name):
                            bind_var = t.elts[pos].id
                if bind_var is not None:
                    acquired_to.add(bind_var)
            if value is not None:
                for call in _expr_calls(value):
                    self._call_ops(call, ops, acquired_to)
            # escapes / rebinds from the store targets
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    if value is not None:
                        for name in _names_in(value):
                            if name not in acquired_to:
                                ops.append(("strong", name, stmt))
                else:
                    for name in _names_in(t):
                        if name not in acquired_to:
                            ops.append(("kill", name, None))
            if bind_var is not None:
                ops.append(("acquire", bind_var, kind, stmt))
            return ops

        # generic statement (Expr, Assert, ...): calls + yield escapes
        has_yield = node.kind == "yield"
        for call in _expr_calls(stmt):
            self._call_ops(call, ops, set())
        if has_yield:
            for n in ast.walk(stmt):
                if isinstance(n, (ast.Yield, ast.YieldFrom)) and \
                        n.value is not None:
                    for name in _names_in(n.value):
                        ops.append(("strong", name, stmt))
        return ops


# ------------------------------------------------------ branch refinement
def _refine(test: ast.AST, arm: bool) -> list:
    """Variables that are known None/falsy (→ not a handle) on ``arm``."""
    kills: list = []

    def none_cmp(t) -> Optional[tuple]:
        # returns (var, is_none_on_true) for `x is None` / `x is not None`
        if isinstance(t, ast.Compare) and len(t.ops) == 1 and \
                isinstance(t.left, ast.Name) and \
                isinstance(t.comparators[0], ast.Constant) and \
                t.comparators[0].value is None:
            if isinstance(t.ops[0], ast.Is):
                return (t.left.id, True)
            if isinstance(t.ops[0], ast.IsNot):
                return (t.left.id, False)
        return None

    def visit(t, polarity: bool):
        # polarity: the value this subexpression is known to have on `arm`
        if isinstance(t, ast.UnaryOp) and isinstance(t.op, ast.Not):
            visit(t.operand, not polarity)
            return
        if isinstance(t, ast.BoolOp):
            # `a and b` true → both true; `a or b` false → both false
            if isinstance(t.op, ast.And) and polarity:
                for v in t.values:
                    visit(v, True)
            elif isinstance(t.op, ast.Or) and not polarity:
                for v in t.values:
                    visit(v, False)
            return
        nc = none_cmp(t)
        if nc is not None:
            var, none_when_true = nc
            if none_when_true == polarity:
                kills.append(var)
            return
        if isinstance(t, ast.Name) and not polarity:
            kills.append(t.id)  # falsy branch: var is None/empty

    visit(test, arm)
    return kills


# ------------------------------------------------------------ path engine
class _Engine:
    def __init__(self, fn, mod: ModuleInfo, ops: _Ops,
                 seed_params: Optional[dict] = None):
        self.fn = fn
        self.mod = mod
        self.ops = ops
        self.seed_params = seed_params or {}
        self.findings: dict = {}     # dedup key -> Finding
        self.fresh_returns: set = set()   # resource kinds returned LIVE
        self.seed_leaked = False     # a seeded param reached an exit LIVE
        self.seed_released = False
        self.truncated = False

    # -- finding emission --------------------------------------------
    def _emit(self, rule: str, line: int, col: int, msg: str, dedup: tuple):
        if dedup in self.findings:
            return
        self.findings[dedup] = Finding(
            rule, self.mod.display_path, line, col, msg,
            context=qualname_for_line(self.mod.tree, line))

    def _apply(self, op, state: dict, node) -> None:
        tag = op[0]
        var = op[1]
        if tag == "acquire":
            state[var] = Handle(op[2], LIVE, node.line, node.line)
            return
        h = state.get(var)
        if tag == "kill":
            state.pop(var, None)
            return
        if h is None:
            return
        line = getattr(op[2], "lineno", node.line) or node.line
        col = getattr(op[2], "col_offset", 0)
        if tag == "release":
            if h.status == LIVE or h.status == WEAK:
                state[var] = Handle(h.kind, RELEASED, h.acq_line, line)
                if var in self.seed_params:
                    self.seed_released = True
            elif h.status == RELEASED:
                self._emit(
                    "MST402", line, col,
                    f"double release of {h.kind} handle {var!r} "
                    f"(acquired line {h.acq_line}, already released line "
                    f"{h.event_line}) — a second owner frees it again",
                    ("MST402", var, h.acq_line, line))
            elif h.status == STRONG:
                self._emit(
                    "MST403", line, col,
                    f"release of escaped {h.kind} handle {var!r} — "
                    f"ownership transferred at line {h.event_line}, the "
                    "new owner will release it again",
                    ("MST403", var, h.acq_line, line))
        elif tag == "release_cm":
            if h.status in (LIVE, WEAK):
                state[var] = Handle(h.kind, RELEASED, h.acq_line, line)
        elif tag == "strong":
            if h.status == LIVE or h.status == WEAK:
                state[var] = Handle(h.kind, STRONG, h.acq_line, line)
                if isinstance(node.stmt, ast.Return):
                    self.fresh_returns.add(h.kind)
        elif tag == "weak":
            if h.status == LIVE:
                state[var] = Handle(h.kind, WEAK, h.acq_line, line)

    def _at_exit(self, state: dict, *, exceptional: bool, line: int,
                 genexit: bool):
        for var, h in state.items():
            # WEAK still counts: passing a handle to a call does not
            # discharge the release obligation (only store/return does)
            if h.status not in (LIVE, WEAK):
                continue
            if var in self.seed_params:
                self.seed_leaked = True
                continue
            if exceptional:
                how = ("the consumer closes the generator here"
                       if genexit else "an exception unwinds through here")
                self._emit(
                    "MST401", line, 0,
                    f"{h.kind} handle {var!r} (acquired line {h.acq_line}) "
                    f"leaks when {how} — no release on the unwind path; "
                    "wrap in try/finally",
                    ("MST401", var, h.acq_line))
            else:
                self._emit(
                    "MST404", line, 0,
                    f"{h.kind} handle {var!r} (acquired line {h.acq_line}) "
                    "is still live at this return — a conditional release "
                    "misses this exit arm",
                    ("MST404", var, h.acq_line))

    # -- traversal ----------------------------------------------------
    def run(self, graph: cfglib.CFG):
        """Worklist exploration of (node, handle-state) pairs.

        Not naive path enumeration: two paths reaching the same node with
        the same abstract state are indistinguishable from there on, so
        the second is cut. Branch diamonds that never touch a handle
        collapse to one state; loops terminate because the state space is
        finite. Path-sensitivity is fully preserved — distinct states are
        explored separately, never joined.
        """
        nodes = graph.nodes
        init = dict(self.seed_params)
        stack = [(graph.entry, init, 0, False)]
        seen: set = set()
        steps = 0
        while stack:
            steps += 1
            if steps > MAX_STEPS:
                self.truncated = True
                return
            idx, state, line, genexit = stack.pop()
            key = (idx, genexit, tuple(sorted(state.items())))
            if key in seen:
                continue
            seen.add(key)
            node = nodes[idx]
            if idx == graph.exit:
                self._at_exit(state, exceptional=False,
                              line=line or node.line, genexit=False)
                continue
            if idx == graph.raise_exit:
                self._at_exit(state, exceptional=True,
                              line=line or node.line, genexit=genexit)
                continue

            pre = state
            post = dict(state)
            for op in self.ops.for_node(node):
                self._apply(op, post, node)
            # exception mid-statement: effects may not have happened —
            # roll acquires back (the acquire itself is what raised), keep
            # releases (treating a raising release as done avoids noise)
            exc_state = None

            for dst, kind in node.succ:
                if kind == cfglib.EXC or kind == cfglib.GENEXIT:
                    if exc_state is None:
                        # mid-statement unwind: the acquire (probably what
                        # raised) didn't complete, and a return/yield/store
                        # escape didn't happen either. Releases and weak
                        # call-arg handoffs are kept — treating a raising
                        # release as done avoids pure noise.
                        exc_state = dict(pre)
                        for op in self.ops.for_node(node):
                            if op[0] not in ("acquire", "strong"):
                                self._apply(op, exc_state, node)
                    st = exc_state
                elif kind in (cfglib.TRUE, cfglib.FALSE) and \
                        node.kind in ("branch", "loop") and \
                        isinstance(node.stmt, (ast.If, ast.While)):
                    st = dict(post)
                    for var in _refine(node.stmt.test, kind == cfglib.TRUE):
                        st.pop(var, None)
                else:
                    st = post
                stack.append((dst, dict(st), node.line or line,
                              genexit or kind == cfglib.GENEXIT))


# ------------------------------------------------------------- module API
def _functions(tree: ast.Module):
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n


def _has_static_acquire(fn, summaries: dict) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)) \
                and n is not fn:
            continue
        if isinstance(n, ast.Call):
            bare = _bare(n)
            if bare is None:
                continue
            if resources.match_acquire(bare, _receiver(n)) is not None:
                return True
            s = summaries.get(bare)
            if s is not None and s.returns_fresh:
                return True
    return False


def _param_names(fn) -> list:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def _summarize(fn, mod: ModuleInfo, base_ops: _Ops) -> Optional[FnSummary]:
    """Pass-1 facts: does ``fn`` hand out fresh handles / consume a param?"""
    graph = cfglib.build_cfg(fn, may_raise=_may_raise)
    if graph is None:
        return None
    returns_fresh = None
    if _has_static_acquire(fn, {}):
        eng = _Engine(fn, mod, base_ops)
        eng.run(graph)
        if len(eng.fresh_returns) == 1 and not eng.findings:
            returns_fresh = next(iter(eng.fresh_returns))

    releases_param = param_name = None
    params = _param_names(fn)
    released = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            bare = _bare(n)
            if bare and resources.match_release(bare) and \
                    isinstance(n.func, ast.Attribute) and \
                    isinstance(n.func.value, ast.Name) and \
                    n.func.value.id in params:
                released.add(n.func.value.id)
    if len(released) == 1:
        var = next(iter(released))
        seed = {var: Handle("param", LIVE, fn.lineno, fn.lineno)}
        eng = _Engine(fn, mod, base_ops, seed_params=seed)
        eng.findings = {}
        eng.run(graph)
        if eng.seed_released and not eng.seed_leaked and not eng.truncated:
            releases_param = params.index(var)
            param_name = var
    if returns_fresh is None and releases_param is None:
        return None
    return FnSummary(fn.name, returns_fresh, releases_param, param_name)


def check_module(mod: ModuleInfo) -> list:
    """MST401–MST404 findings for one module."""
    base_ops = _Ops({})
    summaries: dict = {}
    for fn in _functions(mod.tree):
        if _has_static_acquire(fn, {}) or any(
                resources.match_release(_bare(n) or "")
                for n in ast.walk(fn) if isinstance(n, ast.Call)):
            s = _summarize(fn, mod, _Ops({}))
            if s is not None:
                summaries[s.name] = s

    findings: list = []
    for fn in _functions(mod.tree):
        if not _has_static_acquire(fn, summaries):
            continue
        graph = cfglib.build_cfg(fn, may_raise=_may_raise)
        if graph is None:
            continue
        eng = _Engine(fn, mod, _Ops(summaries))
        eng.run(graph)
        findings.extend(eng.findings.values())
    return findings
