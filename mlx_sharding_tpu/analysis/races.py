"""MST5xx cross-thread shared-state race rules.

Built on the role facts of :mod:`analysis.thread_roles` (which threads run
which functions) and the same lock vocabulary as the MST20x pass (node
names are ``ClassName.attr`` or the ``make_lock("...")`` literal). All
rules are cross-module: they run in the global pass over the per-file
facts, so the incremental cache stays sound.

- **MST501 unlocked-cross-role-write** — an attribute is written from ≥2
  thread roles and the accesses share no common lock, with at least one
  access holding no lock at all. The Eraser verdict: candidate lockset
  C(v) is empty because nobody locked.
- **MST502 empty-lockset-intersection** — every access is under *some*
  lock, but the intersection across roles is empty: two sides each locked
  a different lock (mutual exclusion in name only).
- **MST503 bare-container-publication** — a mutable dict/list/set built in
  ``__init__`` is mutated by one role and returned *bare* (no
  ``dict(...)``/``list(...)``/``.copy()``) from the public surface: the
  caller iterates a live container another thread mutates. Copy under the
  lock instead.
- **MST504 blocking-under-tick-lock** — a blocking call (lock acquire,
  queue ``get``, clock sleep, ``wait``/``join``) while holding a lock the
  tick role also takes: a stall there wedges the decode tick.

A single *concurrent* role (HTTP handlers, sim actors, pod-serve and
drain workers) counts as two writers — two threads of the same role race
each other just fine. The ``api`` role (public surface of a thread-owning
class) is not self-concurrent, and attributes bound to an internally
synchronized type (``queue.Queue``, ``threading.Event``, …) are exempt:
the object *is* the lock.
"""

from __future__ import annotations

from mlx_sharding_tpu.analysis.core import Finding
from mlx_sharding_tpu.analysis.thread_roles import CONCURRENT_ROLES, propagate

# attributes that are single-word flags by convention: benign
# single-writer stop/config flags the GIL keeps atomic are still flagged
# when *written* from 2 roles, but reads alone never count as a writer
_IGNORED_ATTR_PREFIXES = ("__",)
# construction/teardown methods whose accesses happen-before/after the
# threaded phase (threads are started after __init__ returns and joined
# by close); their accesses do not participate in lockset intersection
_EXEMPT_FUNCS = {"__init__", "__post_init__", "__del__", "__repr__"}


def _fmt_roles(roles: set) -> str:
    return "{" + ", ".join(sorted(roles)) + "}"


def _has_conflict(rsets: list, self_concurrent: frozenset) -> bool:
    """Two of these accesses can run concurrently on different threads.

    A function's role set lists the *alternative* drivers of that code
    path, so two accesses whose role sets are comparable (one a subset of
    the other) are the same driver reached two ways — e.g. the autoscaler
    loop's ``tick()`` is public (``{api, autoscaler}``) but nobody drives
    it externally *while* the thread runs it. A conflict needs either two
    accesses with incomparable role sets (genuinely different threads) or
    one access from a multi-instance role (two sim actors / two pod-serve
    workers race each other just fine)."""
    for i, a in enumerate(rsets):
        if a & self_concurrent:
            return True
        for b in rsets[i + 1:]:
            if not (a <= b or b <= a):
                return True
    return False


def global_check(facts_by_path: dict) -> tuple[list, dict]:
    """(findings, per-attr verdicts) over every file's role facts.

    The verdict table — ``"Cls.attr" -> {roles, lockset, verdict}`` — is
    what the dynamic lockset recorder's agreement test compares against:
    an attr observed shared-modified with an empty lockset at runtime must
    not carry a ``clean`` static verdict.
    """
    roles = propagate(facts_by_path)
    findings: list[Finding] = []
    verdicts: dict[str, dict] = {}

    # MST504 needs the fleet-wide set of locks the tick role acquires
    tick_locks: set = set()
    for (path, cls, func), rset in roles.items():
        if "tick" in rset:
            ff = facts_by_path[path]["classes"][cls]["funcs"].get(func)
            if ff:
                tick_locks.update(ff["locks_taken"])

    for path in sorted(facts_by_path):
        facts = facts_by_path[path]
        for cls in sorted(facts["classes"]):
            fcls = facts["classes"][cls]
            # a fresh RequestHandler instance per request: its OWN attrs
            # never alias across handler threads (shared state it calls
            # into is analyzed in the callee's class)
            self_concurrent = CONCURRENT_ROLES
            if any("RequestHandler" in b for b in fcls.get("bases", ())):
                self_concurrent = CONCURRENT_ROLES - {"http_handler"}
            safe_attrs = set(fcls.get("safe_attrs", ()))
            per_attr: dict[str, list] = {}
            returns_bare: dict[str, list] = {}  # attr -> [(line, roles)]
            for func, ff in sorted(fcls["funcs"].items()):
                rset = roles.get((path, cls, func), set())
                if func.split(".")[0] in _EXEMPT_FUNCS:
                    continue
                if rset:
                    for attr, write, line, held in ff["accesses"]:
                        if attr.startswith(_IGNORED_ATTR_PREFIXES) \
                                or attr in safe_attrs:
                            continue
                        per_attr.setdefault(attr, []).append(
                            (bool(write), line, frozenset(held), rset, func))
                    for kind, line, held in ff["blocking"]:
                        hot = sorted(set(held) & tick_locks)
                        if hot:
                            findings.append(Finding(
                                "MST504", path, line, 0,
                                f"{kind} while holding {hot[0]} — a lock "
                                f"the tick loop also takes; a stall in "
                                f"{cls}.{func}() (roles {_fmt_roles(rset)}) "
                                "wedges the decode tick",
                                context=f"{cls}.{func}"))
                if ff["public"]:
                    for attr, line in ff["returns_bare"]:
                        returns_bare.setdefault(attr, []).append(
                            (line, rset or {"api"}))

            for attr in sorted(per_attr):
                accs = per_attr[attr]
                writes = [a for a in accs if a[0]]
                write_roles: set = set()
                all_roles: set = set()
                for write, _line, _held, rset, _func in accs:
                    all_roles |= rset
                    if write:
                        write_roles |= rset
                # the Eraser candidate lockset, over writes (a racy read
                # of guarded state is MST201's beat, not this rule's)
                common = None
                for _write, _line, held, _rset, _func in writes:
                    common = held if common is None else (common & held)
                common = common or frozenset()
                key = f"{cls}.{attr}"
                racy = (_has_conflict([a[3] for a in writes],
                                      self_concurrent) and not common)
                verdict = ("racy" if racy else
                           "clean" if writes and len(all_roles) > 1
                           else "single-role")
                prev = verdicts.get(key)
                if prev is None or (verdict == "racy"
                                    and prev["verdict"] != "racy"):
                    verdicts[key] = {"roles": sorted(all_roles),
                                     "lockset": sorted(common),
                                     "verdict": verdict}
                if racy:
                    unlocked = sorted((ln, fn) for _w, ln, held, _r, fn
                                      in writes if not held)
                    if unlocked:
                        line, func = unlocked[0]
                        findings.append(Finding(
                            "MST501", path, line, 0,
                            f"'{attr}' is written from roles "
                            f"{_fmt_roles(write_roles)} with no common "
                            f"lock — this write in {cls}.{func}() holds "
                            "no lock at all",
                            context=f"{cls}.{attr}"))
                    else:
                        wsorted = sorted((ln, fn, held) for _w, ln, held,
                                         _r, fn in writes)
                        line, func, held = wsorted[0]
                        findings.append(Finding(
                            "MST502", path, line, 0,
                            f"'{attr}' is locked at every write but the "
                            f"lockset intersection across roles "
                            f"{_fmt_roles(write_roles)} is empty — "
                            f"{cls}.{func}() holds "
                            f"{_fmt_roles(set(held))}, other roles hold "
                            "different locks (mutual exclusion in name "
                            "only)",
                            context=f"{cls}.{attr}"))
                    continue  # 503 on the same attr would be noise

                if attr in fcls["containers"] and attr in returns_bare \
                        and writes:
                    for line, rroles in returns_bare[attr]:
                        rsets = [a[3] for a in writes] + [frozenset(rroles)]
                        if _has_conflict(rsets, self_concurrent):
                            findings.append(Finding(
                                "MST503", path, line, 0,
                                f"mutable container '{attr}' (mutated by "
                                f"roles {_fmt_roles(write_roles)}) is "
                                f"returned bare from {cls}'s public "
                                "surface — the caller iterates a live "
                                "container another thread mutates; "
                                "return a copy made under the lock",
                                context=f"{cls}.{attr}"))
                            break
    return findings, verdicts
