"""Trace-safety rules (MST10x): hazards inside or around jit-traced code.

- **MST101 trace-host-effect** — a host side effect inside a function that
  is (transitively) traced by ``jax.jit`` / ``jax.vmap`` / ``jax.lax.scan``
  etc.: wall clocks (``time.time``/``time_ns``/…), ``print``, the stdlib
  ``random`` module or ``np.random``, and ``global``-statement mutation.
  These run once at trace time and silently freeze into the compiled
  program (or recompile it), the classic "my timestamp never changes" bug.
- **MST102 sync-in-hot-path** — a blocking device synchronization
  (``.item()``, ``jax.device_get``, ``np.asarray``/``np.array``) inside a
  serving hot path: the continuous-batching scheduler tick and its helpers,
  plus any function annotated ``# mst: hot-path``. Every such call stalls
  the dispatch pipeline for a full device round trip; intentional,
  amortized sync points carry an inline ``# mst: allow(MST102): …``.
- **MST103 recompile-hazard** — a call to a jit-compiled callable passing a
  freshly built array whose shape derives from request data (``len(...)``,
  ``.size``, ``.shape[...]``) without going through a recognized bucketing
  helper. Data-dependent shapes recompile the program per distinct value —
  the scheduler's chunked prefill (``_chunk_at``) and the page-rounded pool
  exist precisely to avoid this.
- **MST104 double-harvest** — a SECOND ``jax.device_get`` inside one
  tick-hot function. The async scheduler pipeline is built around a single
  consolidated harvest point per tick (pass a tuple pytree and unpack);
  each extra ``device_get`` is an extra serialization of the dispatch
  stream that silently re-introduces the host-blocked gap the pipeline
  exists to hide. An MST102 suppression on the sync does NOT cover this
  rule — a second harvest needs its own justification.
- **MST105 dense-dequant-in-decode** — a ``dequantize(...)`` result bound
  to a name inside a decode-hot function (the packed-matmul dispatchers in
  ``quant.py``, plus anything annotated ``# mst: decode-hot``) or anything
  it transitively calls in the same file. Materializing the dense bf16
  weight tile in HBM re-pays the full 4x weight traffic the packed path
  exists to delete, once per decode step. Fused-kernel dequant is invisible
  to this rule (Pallas kernel bodies are passed to ``pallas_call``, never
  called by name, so the call-closure walk never enters them); a guarded
  fallback whose dense tile is transient carries an inline
  ``# mst: allow(MST105): …``.
- **MST106 sync-spill-in-tick** — a synchronous full-block pull
  (``jax.device_get`` / ``np.asarray`` / ``.to_host()``) of an exported KV
  page block (the result of ``export_block``/``export_pool_pages``) inside
  a tick-hot function. A spilled block is the largest single transfer the
  scheduler ever touches (a request's whole page chain); pulling it inline
  stalls every live slot's decode for the full device→host copy. The spill
  path must only DISPATCH the gather on the tick thread and leave the
  blocking copy to the spill tier's flusher thread (see
  ``kv_transfer.KVSpillTier``). An MST102 suppression on the same call does
  NOT cover this rule — a full-block pull needs its own justification.
- **MST108 block-migration-in-tick** — a KV page-block migration call
  (``export_block``/``import_block``) inside a tick-hot function. These are
  the disaggregation/spill handoff primitives: an export gathers a
  request's whole page chain and stamps sampler state, an import allocates
  pages, scatters the payload and verifies the checksum — each is a
  whole-request unit of work that belongs on the non-hot helpers
  (``_handoff_out``, ``_import_block`` at admission) or a flusher thread,
  never inline in the per-decode-block tick. MST106 catches the
  synchronous *pull* of an exported block; this rule catches the migration
  call itself, which stalls the tick even when dispatch-only (tree flatten
  + jit argument marshalling per page chain).
- **MST109 demand-paged-import-in-tick** — an upload call
  (``jax.device_put`` / ``jnp.asarray`` / ``jnp.array``) inside a tick-hot
  function whose argument touches a spilled block's host pages
  (``.k_pages``/``.v_pages``, or a name fetched from a spill tier via
  ``.take()``/``.peek()``). That is the demand-paged resume: the tick
  blocks while a request's whole page chain marshals host→device, stalling
  every live slot's decode for a copy that could have been in flight
  already. The residency discipline is PRESERVE-style: stage the block
  with ``KVPageBlock.prefetch()`` from the (non-hot) wake/admission policy
  pass when the slot is scheduled to rejoin — the copy overlaps the
  current decode block's compute — and keep demand import as a counted
  off-tick fallback. An MST102/MST106 suppression nearby does NOT cover
  this rule.
- **MST111 store-import-in-tick** — an upload call inside a tick-hot
  function whose argument touches a result fetched from a prefix KV store
  (a name assigned from ``<...store...>.lookup()`` / ``.host_block()``).
  The fleet-wide prefix store's host tier holds whole page-chain payloads;
  marshaling one host→device inline in the tick stalls every live slot's
  decode exactly like the MST109 demand-paged resume. The admission
  discipline is the same PRESERVE shape: the (non-hot) waiting-queue pass
  stages the block with ``KVPageBlock.prefetch()`` while decode runs
  (``_prefetch_store_waiting``), admission scatters the staged copy, and
  demand import stays a counted off-tick fallback. MST109 tracks the spill
  tier's ``take``/``peek``; this rule tracks the store's lookup surface —
  a suppression on one does NOT cover the other.
- **MST110 weight-upload-in-spawn** — a full param-tree placement
  (``jax.device_put`` / ``put_global`` / ``place_weights``) inside a
  spawn-hot function: the replica-spawn factories the autoscaler calls
  (``replica_factory``/``pool_factory``/``spawn_replica``, ``fleet._spawn``,
  plus anything annotated ``# mst: spawn-hot``). A spawn that re-uploads or
  re-shards the checkpoint stalls scale-out on checkpoint I/O and costs a
  second W of HBM the fleet was sized not to have — the spawn path must
  alias the host's resident tree through ``weights.WeightStore.acquire``
  (the store's builder does the one real upload, off the per-spawn path).
  Only a call whose argument subtree names param-ish data (param / weight /
  state_dict / checkpoint) fires, so KV staging in a factory stays clean.
- **MST112 unguarded-trace-in-tick** — request-lifecycle tracing work
  (span construction / serialization: a call through a trace-ish receiver
  such as ``tr.add(...)``, ``req._trace.point(...)``, ``tracing.bind(...)``)
  or ``time.time()`` timestamping inside a tick-hot function, outside the
  tracing no-op guard. The tracing contract is near-zero cost when off:
  hot paths bind the handle once (``tr = req._trace``) and gate every span
  on ``if tr is not None:`` (an attribute/None test that branches on a
  trace-ish identifier counts as the guard; ``time.perf_counter()`` is the
  sanctioned timestamp and is never flagged). An unguarded call runs its
  argument marshalling and lock traffic on every decode block even with
  ``--trace off`` — exactly the regression the ``trace_overhead`` bench
  phase exists to catch, caught here statically instead.
- **MST113 control-plane-in-tick** — a blocking control-plane collective
  (``<plane>.exchange(...)``, ``<plane>.heartbeat(...)``,
  ``<plane>.pod_exchange(...)``) inside a tick-hot function. A collective
  is a cross-host rendezvous: it completes when the slowest host arrives
  or after the plane timeout when one never does, so inline in the tick it
  wedges every live slot's decode behind the slowest peer — and a dead
  peer freezes the fleet for the full timeout. Collectives belong on the
  dedicated transport/heartbeat threads; the tick reads the gossiped
  snapshot. An intentional inline rendezvous carries its own
  ``# mst: allow(MST113): …``.
- **MST114 sync-in-spec-policy** — a blocking device sync
  (``jax.device_get`` / ``.item()``) inside the speculation policy surface:
  the per-round draft proposal and acceptance-tracker functions
  (``_dispatch_spec``/``_spec_plan`` in the scheduler,
  ``propose``/``observe``/``window`` on the proposer/tracker, plus anything
  annotated ``# mst: spec-hot``). These run once per speculative round on
  the tick thread and are host-side numpy BY DESIGN — the n-gram match
  reads the request's host history, the tracker's EWMA is a float — so
  they are deliberately NOT in the MST102 hot set (``np.asarray`` is their
  bread and butter). But a ``device_get``/``.item()`` there drains the
  dispatch pipe once per round to read a value the round's single
  consolidated harvest (``_harvest_spec``) already returns — exactly the
  per-round stall adaptive speculation exists to amortize away. An
  MST102 suppression nearby does NOT cover this rule.
- **MST115 prefix-federation-in-tick** — a pod prefix-federation call
  (``<...federation/prefix...>.fetch(...)`` / ``.local_info(...)``,
  ``host_inventory(...)``) or share-map calibration I/O
  (``calibrate_share_map`` / ``rank_layer_pairs`` /
  ``layer_kv_signatures`` / ``load_share_map``) inside a tick-hot
  function. A federation fetch blocks on a cross-host blob transfer
  bounded only by its timeout, and an inventory walk serializes against
  the store's flusher lock — either inline in the tick stalls every live
  slot's decode behind a peer. Calibration is worse still: dense
  prefills plus whole-KV host marshalling. The discipline: the
  (non-hot) waiting-queue pass ``_pod_fetch_waiting`` starts the fetch
  on its own daemon thread and admission only reads the per-request
  flag; calibration is OFFLINE (``cli/kv_share_calibrate.py``) and
  serving loads the saved artifact once at startup. An intentional
  inline consult carries its own ``# mst: allow(MST115): …``.
- **MST116 latent-reconstruct-in-tick** — a compressed-latent KV codec
  call (``reconstruct_block`` / ``reconstruct_pages`` /
  ``compress_pages``, kv_compress.py) inside a tick-hot function.
  Reconstruction materializes the dense per-head pages from rank-r
  latents — a ``(tokens, r) @ (r, H*D)`` up-projection over every page
  of every layer, in host numpy — and compression is its transpose;
  either inline in the tick stalls every live slot's decode behind one
  block's matmul. The discipline: compression runs inside
  ``KVPageBlock.to_host`` on the spill flusher / handoff threads, and
  reconstruction runs in ``prefetch``'s overlapped host→device stage or
  the consumer's (non-hot) import path — the tick only ever touches
  already-dense pages. An intentional inline reconstruction carries its
  own ``# mst: allow(MST116): …``.
- **MST107 wall-clock-deadline** — ``time.time()`` feeding deadline or
  timeout arithmetic (an expression whose identifiers mention deadline /
  timeout / expiry / until / budget / ttft / retry_after / lease). The wall
  clock steps and slews under NTP; a deadline computed from it can fire
  years early or never. Every serving deadline — request_timeout, TTFT,
  breaker half-open ETA, autoscaler cooldown, lease expiry — must be a
  ``time.monotonic()`` difference. Timestamps for humans (log lines, the
  OpenAI ``created`` field) are fine: they carry no deadline identifiers.
  The rule also covers the reverse drift: inside a class that carries an
  INJECTABLE clock (``self.clock`` / ``self._clock``, see
  ``utils/clock.py``), a raw ``time.monotonic()`` in deadline arithmetic
  is flagged too — it silently bypasses the injected time source, so
  virtual-clock tests and the fleet simulator pass against one clock while
  the shipped binary runs on another.
"""

from __future__ import annotations

import ast

from mlx_sharding_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    dotted_name,
    qualname_for_line,
)

# functions that register their callable argument(s) for tracing
TRACING_ENTRY_POINTS = {
    "jax.jit", "jit", "pjit", "jax.pjit",
    "jax.vmap", "vmap", "jax.pmap",
    "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat",
    "jax.lax.scan", "lax.scan",
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.cond", "lax.cond",
    "jax.lax.switch", "lax.switch",
    "jax.lax.map", "lax.map",
}

HOST_CLOCKS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
}
HOST_RANDOM_ROOTS = ("random.", "np.random.", "numpy.random.")

# serving hot paths checked by MST102 (beyond '# mst: hot-path' annotations):
# the scheduler tick and everything it runs per decode block
HOT_PATH_FUNCS = {
    "scheduler.py": {
        # the per-tick path only: _preempt/_release_pages etc. run on rare
        # events (pool pressure), not every decode block
        "_tick", "_tick_async", "_decode_once", "_dispatch_block",
        "_harvest", "_quiesce", "_decoding", "_growth_fits", "_spec_once",
        "_prefill_one_chunk", "_grow_for_decode", "_emit",
        # the speculative round's harvest side runs on every spec tick;
        # _dispatch_spec/_spec_plan are deliberately NOT here (host numpy
        # proposal work — np.asarray is their job) and are covered by the
        # stricter MST114 device-sync rule instead
        "_harvest_spec", "_spec_tick", "_harvest_any",
    },
}

SYNC_CALLS = {"jax.device_get", "np.asarray", "np.array", "numpy.asarray",
              "numpy.array"}

# calls whose result is an exported KV page block (or its raw page pytrees):
# the payload MST106 forbids pulling synchronously on the tick thread
SPILL_PRODUCER_PREFIXES = ("export_block", "export_pool_pages")

# the block-migration primitives MST108 keeps out of tick-hot functions:
# whole-request page-chain gathers/scatters (kv_transfer.py)
MIGRATION_CALLS = {"export_block", "import_block"}

# the blocking control-plane collectives MST113 keeps out of tick-hot
# functions: each is a cross-host rendezvous bounded only by the plane's
# timeout (multihost.py ControlPlane.exchange / PodControlPlane.pod_exchange,
# and the heartbeat wrappers over them)
CONTROL_PLANE_CALLS = {"exchange", "heartbeat", "pod_exchange"}

# the pod prefix-federation surface MST115 keeps out of tick-hot
# functions: fetch() blocks on a cross-host blob transfer (pod.py
# PodPrefixFederation), local_info()/host_inventory() walk the store's
# host tier under its lock. fetch/local_info only fire through a
# federation-ish receiver (dotted name mentioning "federation"/"prefix");
# host_inventory is distinctive enough to fire anywhere
PREFIX_FEDERATION_CALLS = {"fetch", "local_info"}
PREFIX_FEDERATION_HINTS = ("federation", "prefix")
PREFIX_INVENTORY_CALLS = {"host_inventory"}

# share-map calibration I/O MST115 also forbids in tick-hot functions:
# each runs dense prefills and/or whole-KV host marshalling (kv_share.py)
# — calibration is offline (cli/kv_share_calibrate.py); serving loads the
# saved artifact once at startup
SHARE_CALIBRATION_CALLS = {"calibrate_share_map", "rank_layer_pairs",
                           "layer_kv_signatures", "load_share_map"}

# the compressed-latent codec surface MST116 keeps out of tick-hot
# functions: each call is a dense (tokens, r) x (r, H*D) projection over
# every page of every layer in host numpy (kv_compress.KVCompressCodec)
LATENT_RECONSTRUCT_CALLS = {"reconstruct_block", "reconstruct_pages",
                            "compress_pages"}

# host→device upload calls MST109 polices in tick-hot functions when their
# argument is a spilled block's page payload (the demand-paged resume)
UPLOAD_CALLS = {"jax.device_put", "jnp.asarray", "jnp.array",
                "jax.numpy.asarray", "jax.numpy.array"}
# attribute names that identify a KVPageBlock's page payload, and the spill
# tier lookups whose results MST109 tracks as block-bearing names
BLOCK_PAGE_ATTRS = {"k_pages", "v_pages"}
TIER_LOOKUP_ATTRS = {"take", "peek"}

# prefix-store lookup surface MST111 tracks: a call ``<recv>.<attr>(...)``
# where the receiver's dotted name mentions "store" and the attr is one of
# these marks the assigned name as (potentially) host-block-bearing
STORE_LOOKUP_ATTRS = {"lookup", "host_block"}

# spawn-hot roots checked by MST110 (beyond '# mst: spawn-hot'
# annotations): the replica-spawn factories the fleet autoscaler calls
SPAWN_HOT_FUNCS = {
    "openai_api.py": {"replica_factory", "pool_factory", "spawn_replica"},
    "fleet.py": {"_spawn"},
}
# calls that place a param tree on device — the one real upload belongs in
# the WeightStore builder, never on the per-spawn path
WEIGHT_UPLOAD_CALLS = {"device_put", "put_global", "place_weights"}
# identifier fragments that mark a call's argument as a param tree (vs the
# KV staging a spawn legitimately does)
PARAM_TREE_HINTS = ("param", "weight", "state_dict", "checkpoint")

# speculation-policy roots checked by MST114 (beyond '# mst: spec-hot'
# annotations): the per-round draft proposal and acceptance-tracker surface.
# Host numpy is expected here (so MST102 does not apply); a device sync is
# the one thing that must never appear — it stalls the dispatch pipe once
# per draft round for a value the round's consolidated harvest already pulls
SPEC_HOT_FUNCS = {
    "scheduler.py": {"_dispatch_spec", "_spec_plan"},
    "speculative.py": {"propose", "observe", "window"},
}

# decode-hot roots checked by MST105 (beyond '# mst: decode-hot'
# annotations): every packed decode matmul funnels through these
DECODE_HOT_FUNCS = {
    "quant.py": {"linear", "_quant_matmul"},
}

# call names that materialize a dense weight tile from a packed triple
DEQUANT_CALLS = {"dequantize", "dequant"}

# shape expressions routed through these calls are considered bucketed
BUCKETING_FUNCS = {"_chunk_at", "_pages_needed", "round_up", "bucket",
                   "next_power_of_two"}

ARRAY_BUILDERS = {"zeros", "ones", "full", "empty", "arange"}


def _collect_functions(tree: ast.Module) -> dict[str, list[ast.AST]]:
    """name -> every FunctionDef/Lambda-holding def in the file (any scope)."""
    table: dict[str, list[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            table.setdefault(node.name, []).append(node)
    return table


def _callable_args(call: ast.Call) -> list[ast.AST]:
    """Positional args of a tracing entry point that name/define callables."""
    out = []
    for arg in call.args:
        if isinstance(arg, (ast.Lambda, ast.Name, ast.Attribute)):
            out.append(arg)
    return out


def _traced_roots(tree: ast.Module, table: dict) -> list[ast.AST]:
    """Function nodes handed to a tracing entry point anywhere in the file."""
    roots: list[ast.AST] = []

    def note(arg: ast.AST):
        if isinstance(arg, ast.Lambda):
            roots.append(arg)
        else:
            name = dotted_name(arg)
            if name is None:
                return
            bare = name.split(".")[-1]  # self._first_sample_fn -> method name
            roots.extend(table.get(bare, ()))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname in TRACING_ENTRY_POINTS:
                for arg in _callable_args(node):
                    note(arg)
            # functools.partial(jax.jit, ...) decorator form
            if fname in ("functools.partial", "partial") and node.args:
                inner = dotted_name(node.args[0])
                if inner in TRACING_ENTRY_POINTS:
                    pass  # the decorated function is traced; handled below
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                dname = dotted_name(dec)
                if dname in TRACING_ENTRY_POINTS:
                    roots.append(node)
                elif isinstance(dec, ast.Call):
                    cname = dotted_name(dec.func)
                    if cname in TRACING_ENTRY_POINTS:
                        roots.append(node)
                    elif cname in ("functools.partial", "partial") and dec.args:
                        if dotted_name(dec.args[0]) in TRACING_ENTRY_POINTS:
                            roots.append(node)
    return roots


def _traced_closure(roots: list[ast.AST], table: dict) -> list[ast.AST]:
    """Roots plus every same-file function they (transitively) call or
    define — host effects two frames down still run at trace time."""
    seen: list[ast.AST] = []
    work = list(roots)
    while work:
        fn = work.pop()
        if any(fn is s for s in seen):
            continue
        seen.append(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name is None:
                    continue
                bare = name.split(".")[-1]
                if bare in table:
                    work.extend(table[bare])
    return seen


def _check_host_effects(mod: ModuleInfo, traced: list[ast.AST]) -> list[Finding]:
    findings = []

    def flag(node, what):
        findings.append(Finding(
            "MST101", mod.display_path, node.lineno, node.col_offset,
            f"host effect in jit-traced code: {what} runs once at trace "
            "time, not per step",
            context=qualname_for_line(mod.tree, node.lineno),
        ))

    for fn in traced:
        globals_declared: set[str] = set()
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for node in ast.walk(ast.Module(body=list(body), type_ignores=[])):
            if isinstance(node, ast.Global):
                globals_declared.update(node.names)
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name is None:
                    continue
                if name in HOST_CLOCKS:
                    flag(node, f"{name}()")
                elif name == "print":
                    flag(node, "print() (use jax.debug.print for traced "
                         "values)")
                elif any(name.startswith(root) for root in HOST_RANDOM_ROOTS):
                    flag(node, f"{name}() (use jax.random with an explicit "
                         "key)")
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in globals_declared:
                        flag(node, f"mutation of global {t.id!r}")
    return findings


def _hot_functions(mod: ModuleInfo) -> list[ast.FunctionDef]:
    configured = HOT_PATH_FUNCS.get(mod.basename, set())
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        annotated = any(
            line in mod.hot_lines
            for line in (node.lineno, node.lineno - 1)
        )
        if node.name in configured or annotated:
            out.append(node)
    return out


def _check_hot_syncs(mod: ModuleInfo) -> list[Finding]:
    findings = []
    for fn in _hot_functions(mod):
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                break  # nested defs are jit bodies; not host hot-path code
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            what = None
            if name in SYNC_CALLS:
                what = f"{name}()"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item" and not node.args
            ):
                what = ".item()"
            if what:
                findings.append(Finding(
                    "MST102", mod.display_path, node.lineno, node.col_offset,
                    f"blocking device sync in hot path {fn.name}(): {what} "
                    "stalls the tick for a device round trip",
                    context=qualname_for_line(mod.tree, node.lineno),
                ))
    return findings


def _spec_hot_functions(mod: ModuleInfo) -> list[ast.FunctionDef]:
    configured = SPEC_HOT_FUNCS.get(mod.basename, set())
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        annotated = any(
            line in mod.spec_hot_lines
            for line in (node.lineno, node.lineno - 1)
        )
        if node.name in configured or annotated:
            out.append(node)
    return out


def _check_spec_policy_syncs(mod: ModuleInfo) -> list[Finding]:
    """MST114: a blocking device sync inside the speculation policy
    surface. Narrower than MST102 on purpose — proposal/tracker code is
    host numpy by design (``np.asarray`` over the request's history IS the
    n-gram match), so only the true device round trips fire:
    ``jax.device_get`` and argless ``.item()``."""
    findings = []
    for fn in _spec_hot_functions(mod):
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                break  # nested defs are jit bodies; not host policy code
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            what = None
            if name is not None and name.split(".")[-1] == "device_get":
                what = f"{name}()"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item" and not node.args
            ):
                what = ".item()"
            if what:
                findings.append(Finding(
                    "MST114", mod.display_path, node.lineno, node.col_offset,
                    f"device sync in speculation policy {fn.name}(): {what} "
                    "drains the dispatch pipe once per draft round — the "
                    "proposal/tracker surface reads host state only; device "
                    "results arrive at the round's consolidated harvest",
                    context=qualname_for_line(mod.tree, node.lineno),
                ))
    return findings


def _check_double_harvest(mod: ModuleInfo) -> list[Finding]:
    """MST104: more than one ``jax.device_get`` in a tick-hot function.
    The pipelined scheduler loop must keep exactly one harvest point —
    consolidate extra pulls into the first one's tuple pytree."""
    findings = []
    for fn in _hot_functions(mod):
        first = None
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                break  # nested defs are jit bodies; not host hot-path code
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) != "jax.device_get":
                continue
            if first is None:
                first = node
                continue
            findings.append(Finding(
                "MST104", mod.display_path, node.lineno, node.col_offset,
                f"second device_get in hot path {fn.name}() (first at line "
                f"{first.lineno}): consolidate into one harvest — pass a "
                "tuple pytree and unpack host-side",
                context=qualname_for_line(mod.tree, node.lineno),
            ))
    return findings


def _is_spill_producer(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    return bool(name) and name.split(".")[-1].startswith(
        SPILL_PRODUCER_PREFIXES
    )


def _check_sync_spill(mod: ModuleInfo) -> list[Finding]:
    """MST106: a synchronous pull of an exported KV page block inside a
    tick-hot function. Matches a ``SYNC_CALLS`` call (or ``.to_host()``)
    whose argument/receiver subtree is a spill-producer call or a name
    assigned from one earlier in the same function — the spill discipline
    is dispatch-the-gather-on-tick, copy-on-flusher (kv_transfer)."""
    findings = []
    for fn in _hot_functions(mod):
        block_names: set[str] = set()
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _is_spill_producer(node.value)):
                for t in node.targets:
                    tname = dotted_name(t)
                    if tname:
                        block_names.add(tname.split(".")[-1])
                    elif isinstance(t, ast.Tuple):
                        for elt in t.elts:
                            ename = dotted_name(elt)
                            if ename:
                                block_names.add(ename.split(".")[-1])
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                break  # nested defs are jit bodies; not host hot-path code
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in SYNC_CALLS:
                subjects = list(node.args)
                what = f"{name}()"
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "to_host" and not node.args):
                subjects = [node.func.value]
                what = ".to_host()"
            else:
                continue
            touches_block = any(
                (isinstance(sub, ast.Call) and _is_spill_producer(sub))
                or (isinstance(sub, ast.Name) and sub.id in block_names)
                for subject in subjects
                for sub in ast.walk(subject)
            )
            if touches_block:
                findings.append(Finding(
                    "MST106", mod.display_path, node.lineno, node.col_offset,
                    f"synchronous spill copy in hot path {fn.name}(): "
                    f"{what} pulls a full exported KV page block, stalling "
                    "every live slot's decode — dispatch the gather here "
                    "and leave the device→host copy to the spill tier's "
                    "flusher thread",
                    context=qualname_for_line(mod.tree, node.lineno),
                ))
    return findings


def _check_block_migration(mod: ModuleInfo) -> list[Finding]:
    """MST108: an ``export_block``/``import_block`` call inside a tick-hot
    function. The handoff/spill discipline parks the request on the tick
    and runs the migration from a non-hot helper (``_handoff_out``,
    admission-side ``_import_block``) or the spill flusher — a page-chain
    gather/scatter inline in the tick stalls every live slot's decode.
    An MST102/MST106 suppression on a nearby sync does NOT cover this
    rule; an intentional inline migration carries its own
    ``# mst: allow(MST108): …``."""
    findings = []
    for fn in _hot_functions(mod):
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                break  # nested defs are jit bodies; not host hot-path code
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None or name.split(".")[-1] not in MIGRATION_CALLS:
                continue
            findings.append(Finding(
                "MST108", mod.display_path, node.lineno, node.col_offset,
                f"KV block migration in hot path {fn.name}(): "
                f"{name.split('.')[-1]}() gathers/scatters a whole page "
                "chain per request — park the request on the tick and run "
                "the migration from a non-hot helper or the flusher thread",
                context=qualname_for_line(mod.tree, node.lineno),
            ))
    return findings


def _check_control_plane_in_tick(mod: ModuleInfo) -> list[Finding]:
    """MST113: a blocking control-plane collective (``exchange`` /
    ``heartbeat`` / ``pod_exchange``) inside a tick-hot function. A
    collective is a cross-host rendezvous: it returns when the SLOWEST
    host arrives, or after the plane's timeout (seconds to minutes) when
    one never does — so one call inline in the tick wedges every live
    slot's decode behind a peer's GC pause, and a dead peer freezes the
    whole fleet for the full timeout instead of one heartbeat thread. The
    pod discipline runs every collective on its own daemon thread
    (``mst-pod-transport``) and lets the tick read the gossiped snapshot;
    an intentional inline rendezvous carries its own
    ``# mst: allow(MST113): …``."""
    findings = []
    for fn in _hot_functions(mod):
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                break  # nested defs are jit bodies; not host hot-path code
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None or "." not in name:
                continue  # bare exchange()/heartbeat() locals are not the
                # plane surface — the collective always rides a plane object
            if name.split(".")[-1] not in CONTROL_PLANE_CALLS:
                continue
            findings.append(Finding(
                "MST113", mod.display_path, node.lineno, node.col_offset,
                f"blocking control-plane collective in hot path "
                f"{fn.name}(): {name}() is a cross-host rendezvous bounded "
                "only by the plane timeout — run it on the pod transport "
                "thread and let the tick read the gossiped snapshot",
                context=qualname_for_line(mod.tree, node.lineno),
            ))
    return findings


def _check_prefix_federation_in_tick(mod: ModuleInfo) -> list[Finding]:
    """MST115: a pod prefix-federation consult or share-map calibration
    I/O inside a tick-hot function. ``federation.fetch()`` blocks on a
    cross-host blob transfer bounded only by its timeout; an inventory
    walk serializes against the store's flusher lock; calibration runs
    dense prefills plus whole-KV host marshalling. The discipline: the
    non-hot waiting-queue pass (``_pod_fetch_waiting``) starts the fetch
    on its own daemon thread and admission only reads the per-request
    flag; calibration is offline (``cli/kv_share_calibrate.py``)."""
    findings = []
    for fn in _hot_functions(mod):
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                break  # nested defs are jit bodies; not host hot-path code
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None:
                continue
            last = name.split(".")[-1]
            if last in SHARE_CALIBRATION_CALLS:
                why = (f"share-map calibration I/O in hot path {fn.name}(): "
                       f"{name}() runs dense prefills / whole-KV host "
                       "marshalling — calibrate offline "
                       "(cli/kv_share_calibrate.py) and load the saved "
                       "artifact once at startup")
            elif last in PREFIX_INVENTORY_CALLS or (
                "." in name
                and last in PREFIX_FEDERATION_CALLS
                and any(h in seg for seg in name.split(".")[:-1]
                        for h in PREFIX_FEDERATION_HINTS)
            ):
                why = (f"pod prefix-federation call in hot path {fn.name}(): "
                       f"{name}() blocks on a cross-host blob fetch / "
                       "store-lock inventory walk — start the fetch from the "
                       "waiting-queue pass on its own thread and let "
                       "admission read the per-request flag")
            else:
                continue
            findings.append(Finding(
                "MST115", mod.display_path, node.lineno, node.col_offset,
                why, context=qualname_for_line(mod.tree, node.lineno),
            ))
    return findings


def _check_latent_reconstruct_in_tick(mod: ModuleInfo) -> list[Finding]:
    """MST116: a compressed-latent KV codec call inside a tick-hot
    function. ``reconstruct_block()``/``reconstruct_pages()`` materialize
    the dense per-head pages from rank-r latents — a ``(tokens, r) @
    (r, H*D)`` host-numpy up-projection over every page of every layer —
    and ``compress_pages()`` is its transpose. The discipline: compress
    in ``to_host`` on the flusher/handoff threads, reconstruct in
    ``prefetch``'s overlapped stage or the consumer's non-hot import
    path; the tick only ever touches already-dense pages."""
    findings = []
    for fn in _hot_functions(mod):
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                break  # nested defs are jit bodies; not host hot-path code
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None:
                continue
            if name.split(".")[-1] not in LATENT_RECONSTRUCT_CALLS:
                continue
            findings.append(Finding(
                "MST116", mod.display_path, node.lineno, node.col_offset,
                f"latent reconstruction in hot path {fn.name}(): {name}() "
                "materializes dense per-head pages from rank-r latents in "
                "host numpy — compress in to_host on the flusher/handoff "
                "threads, reconstruct in prefetch's overlapped stage or "
                "the consumer's import path, never on the tick thread",
                context=qualname_for_line(mod.tree, node.lineno),
            ))
    return findings


def _spawn_hot_functions(mod: ModuleInfo) -> list[ast.FunctionDef]:
    configured = SPAWN_HOT_FUNCS.get(mod.basename, set())
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        annotated = any(
            line in mod.spawn_hot_lines
            for line in (node.lineno, node.lineno - 1)
        )
        if node.name in configured or annotated:
            out.append(node)
    return out


def _check_spawn_weight_upload(mod: ModuleInfo) -> list[Finding]:
    """MST110: a full param-tree placement inside a spawn-hot function.
    Non-transitive by design — the sanctioned path hands a builder callable
    to ``WeightStore.acquire`` (the upload runs once, inside the store, not
    per spawn), and that callable's own body is where ``place_weights``
    belongs. Only the factory's DIRECT body is scanned, and only calls
    whose arguments name param-ish data fire, so a factory staging KV or
    slot state stays clean."""
    findings = []
    for fn in _spawn_hot_functions(mod):
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                break  # nested defs (incl. the store's builder) are exempt
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None or name.split(".")[-1] not in WEIGHT_UPLOAD_CALLS:
                continue
            idents: set[str] = set()
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        idents.add(sub.id.lower())
                    elif isinstance(sub, ast.Attribute):
                        idents.add(sub.attr.lower())
            if not any(h in ident for ident in idents
                       for h in PARAM_TREE_HINTS):
                continue
            findings.append(Finding(
                "MST110", mod.display_path, node.lineno, node.col_offset,
                f"param-tree upload in spawn-hot {fn.name}(): "
                f"{name.split('.')[-1]}(...) re-places the checkpoint on "
                "every spawn — alias the host's resident tree through "
                "WeightStore.acquire and leave the one real upload to the "
                "store's builder",
                context=qualname_for_line(mod.tree, node.lineno),
            ))
    return findings


def _check_dense_dequant(mod: ModuleInfo, table: dict) -> list[Finding]:
    """MST105: a dense dequantized-weight materialization reachable from a
    decode-hot function. Roots come from ``DECODE_HOT_FUNCS`` (by basename)
    and ``# mst: decode-hot`` annotations; reachability is the same
    same-file call closure the trace rules use. Only a dequant call bound
    by an assignment fires — a dequant expression consumed in place inside
    a kernel body never appears here, because kernel bodies are passed to
    ``pallas_call`` rather than called by name."""
    roots: list[ast.AST] = []
    configured = DECODE_HOT_FUNCS.get(mod.basename, set())
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        annotated = any(
            line in mod.decode_hot_lines
            for line in (node.lineno, node.lineno - 1)
        )
        if node.name in configured or annotated:
            roots.append(node)
    findings = []
    for fn in _traced_closure(roots, table):
        for node in ast.walk(fn):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            name = dotted_name(node.value.func)
            if name is None or name.split(".")[-1] not in DEQUANT_CALLS:
                continue
            fname = getattr(fn, "name", "<lambda>")
            findings.append(Finding(
                "MST105", mod.display_path, node.lineno, node.col_offset,
                f"dense dequantized weight materialized in decode-hot "
                f"{fname}(): {name}(...) rebuilds the full-precision tile "
                "in HBM every step — fuse the dequant into the kernel or "
                "justify the guarded fallback",
                context=qualname_for_line(mod.tree, node.lineno),
            ))
    return findings


def _jitted_names(tree: ast.Module) -> set[str]:
    """Names (locals and self.attrs) bound to a jax.jit(...) result."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if dotted_name(node.value.func) in ("jax.jit", "jit", "pjit",
                                                "jax.pjit"):
                for t in node.targets:
                    name = dotted_name(t)
                    if name:
                        names.add(name)
    return names


def _dynamic_shape(expr: ast.AST) -> bool:
    """Does ``expr`` derive from request data sizes (len/.size/.shape[..])
    without passing through a bucketing helper?"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name == "len":
                return True
            if name and name.split(".")[-1] in BUCKETING_FUNCS:
                return False  # routed through bucketing: fine
        if isinstance(node, ast.Attribute) and node.attr == "size":
            return True
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "shape"
        ):
            return True
    return False


def _check_sync_import(mod: ModuleInfo) -> list[Finding]:
    """MST109: a demand-paged KV block upload inside a tick-hot function.
    Matches an ``UPLOAD_CALLS`` call whose argument subtree touches a
    block's page payload (``.k_pages``/``.v_pages``) or a name assigned
    from a spill-tier lookup (``.take()``/``.peek()``) earlier in the same
    function — the resume discipline is prefetch-on-schedule (overlapped
    with decode), demand import only as a counted off-tick fallback."""
    findings = []
    for fn in _hot_functions(mod):
        block_names: set[str] = set()
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr in TIER_LOOKUP_ATTRS):
                for t in node.targets:
                    tname = dotted_name(t)
                    if tname:
                        block_names.add(tname.split(".")[-1])
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                break  # nested defs are jit bodies; not host hot-path code
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name not in UPLOAD_CALLS:
                continue
            touches_block = any(
                (isinstance(sub, ast.Attribute)
                 and sub.attr in BLOCK_PAGE_ATTRS)
                or (isinstance(sub, ast.Name) and sub.id in block_names)
                for arg in node.args
                for sub in ast.walk(arg)
            )
            if touches_block:
                findings.append(Finding(
                    "MST109", mod.display_path, node.lineno, node.col_offset,
                    f"demand-paged KV import in hot path {fn.name}(): "
                    f"{name}() marshals a spilled block's host pages inline, "
                    "stalling every live slot's decode for the full "
                    "host→device copy — stage the block with "
                    "KVPageBlock.prefetch() when the slot is scheduled to "
                    "rejoin (the copy overlaps the current block's compute) "
                    "and keep demand import off the tick as a counted "
                    "fallback",
                    context=qualname_for_line(mod.tree, node.lineno),
                ))
    return findings


def _check_store_import(mod: ModuleInfo) -> list[Finding]:
    """MST111: a prefix-store host block uploaded inside a tick-hot
    function. MST109-shaped, but tracking the store's lookup surface
    (``<...store...>.lookup()`` / ``.host_block()``) instead of the spill
    tier's ``take``/``peek`` — the admission discipline stages the block
    via the non-hot waiting-queue prefetch pass and keeps demand import
    off the tick as a counted fallback."""
    findings = []
    for fn in _hot_functions(mod):
        block_names: set[str] = set()
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr in STORE_LOOKUP_ATTRS):
                continue
            recv = dotted_name(node.value.func.value)
            if recv is None or "store" not in recv.lower():
                continue
            for t in node.targets:
                tname = dotted_name(t)
                if tname:
                    block_names.add(tname.split(".")[-1])
        if not block_names:
            continue
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                break  # nested defs are jit bodies; not host hot-path code
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name not in UPLOAD_CALLS:
                continue
            touches_block = any(
                isinstance(sub, ast.Name) and sub.id in block_names
                for arg in node.args
                for sub in ast.walk(arg)
            )
            if touches_block:
                findings.append(Finding(
                    "MST111", mod.display_path, node.lineno, node.col_offset,
                    f"prefix-store import in hot path {fn.name}(): "
                    f"{name}() marshals a store-held host block inline, "
                    "stalling every live slot's decode for the full "
                    "host→device copy — stage it with KVPageBlock.prefetch() "
                    "from the waiting-queue pass (the copy overlaps decode) "
                    "and keep demand import off the tick as a counted "
                    "fallback",
                    context=qualname_for_line(mod.tree, node.lineno),
                ))
    return findings


def _check_recompile_hazards(mod: ModuleInfo) -> list[Finding]:
    jitted = _jitted_names(mod.tree)
    if not jitted:
        return []
    findings = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func)
        if callee not in jitted:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if not isinstance(sub, ast.Call):
                    continue
                bname = dotted_name(sub.func)
                if bname is None:
                    continue
                parts = bname.split(".")
                if parts[-1] not in ARRAY_BUILDERS or len(parts) < 2:
                    continue
                if sub.args and _dynamic_shape(sub.args[0]):
                    findings.append(Finding(
                        "MST103", mod.display_path, sub.lineno,
                        sub.col_offset,
                        f"data-dependent shape at jitted call site "
                        f"{callee}(): {bname} sized from request data "
                        "recompiles per distinct length — route through a "
                        "bucketing helper",
                        context=qualname_for_line(mod.tree, sub.lineno),
                    ))
    return findings


# MST112: receivers that mark a call as tracing work, and the guard test —
# a hot function may touch the tracer only behind a no-op check that
# branches on one of these identifiers (the `if tr is not None:` pattern)
TRACE_RECEIVER_NAMES = {"tr", "_tr", "tracer", "_tracer", "tracing"}


def _trace_ident(ident: str) -> bool:
    low = ident.lower()
    return low in TRACE_RECEIVER_NAMES or "trace" in low


def _is_trace_guard(test: ast.AST) -> bool:
    """Does this If/IfExp test branch on a trace-ish identifier?"""
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and _trace_ident(n.id):
            return True
        if isinstance(n, ast.Attribute) and _trace_ident(n.attr):
            return True
    return False


def _is_trace_call(node: ast.Call) -> bool:
    """A call whose RECEIVER path is trace-ish: ``tr.add(...)``,
    ``req._trace.point(...)``, ``tracing.bind(...)`` — but not a bare
    function that merely mentions trace in its own name."""
    name = dotted_name(node.func)
    if name is None:
        return False
    parts = name.split(".")
    return len(parts) > 1 and any(_trace_ident(p) for p in parts[:-1])


def _check_hot_trace_overhead(mod: ModuleInfo) -> list[Finding]:
    """MST112: tracing work in a tick-hot function outside the no-op
    guard. Walks each hot function with a guarded flag that turns on
    inside any If/IfExp whose test branches on a trace-ish identifier
    (both branches count — ``if tr is None: ... else: record`` is as valid
    as the positive form). ``time.perf_counter()`` is never flagged; the
    wall clock (``time.time()``) is, as hot-path timestamping."""
    findings = []

    def flag(node: ast.Call, what: str, fname: str):
        findings.append(Finding(
            "MST112", mod.display_path, node.lineno, node.col_offset,
            f"unguarded trace work in hot path {fname}(): {what} runs its "
            "marshalling and lock traffic on every decode block even with "
            "tracing off — bind the handle once (tr = req._trace) and gate "
            "span construction behind its `if tr is not None:` no-op check "
            "(timestamp with time.perf_counter, not time.time)",
            context=qualname_for_line(mod.tree, node.lineno),
        ))

    def scan(node: ast.AST, fn: ast.AST, guarded: bool):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node is not fn):
            return  # nested defs are jit bodies; not host hot-path code
        if isinstance(node, ast.Call) and not guarded:
            name = dotted_name(node.func)
            if name == "time.time":
                flag(node, "time.time()", fn.name)
            elif _is_trace_call(node):
                flag(node, f"{name}(...)", fn.name)
        if isinstance(node, (ast.If, ast.IfExp)):
            g = guarded or _is_trace_guard(node.test)
            # the test expression itself still runs unconditionally — a
            # call there is not protected by its own branch
            scan(node.test, fn, guarded)
            body = node.body if isinstance(node, ast.If) else [node.body]
            orelse = (node.orelse if isinstance(node, ast.If)
                      else [node.orelse])
            for child in body + orelse:
                scan(child, fn, g)
            return
        for child in ast.iter_child_nodes(node):
            scan(child, fn, guarded)

    for fn in _hot_functions(mod):
        for child in ast.iter_child_nodes(fn):
            scan(child, fn, False)
    return findings


# MST107: the wall clock spellings that must never feed a deadline, and the
# identifier fragments that mark an expression as deadline/timeout math
WALL_CLOCK_CALLS = {"time.time", "_time.time"}
# the monotonic spellings that bypass an INJECTED clock: only flagged
# inside classes that carry one (see _clocked_class_ranges) — a raw
# monotonic read there makes virtual-time tests pass while the shipped
# binary runs on a different clock
MONOTONIC_CALLS = {"time.monotonic", "_time.monotonic"}
DEADLINE_HINTS = (
    "deadline", "timeout", "expires", "expiry", "expire", "until",
    "budget", "retry_after", "ttft", "lease",
)


def _clocked_class_ranges(tree: ast.AST) -> list[tuple[int, int]]:
    """Line ranges of ClassDefs that reference an injectable clock
    attribute (``self.clock`` / ``self._clock``): inside these, deadline
    arithmetic must read the injected source, never ``time.monotonic()``
    directly."""
    ranges = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for n in ast.walk(node):
            if (isinstance(n, ast.Attribute)
                    and n.attr in ("clock", "_clock")
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"):
                ranges.append((node.lineno, node.end_lineno or node.lineno))
                break
    return ranges


def _check_wall_clock_deadlines(mod: ModuleInfo) -> list[Finding]:
    # context = the smallest statement (or branch condition) around the
    # call; if any identifier in it smells like a deadline, the wall clock
    # is feeding timeout arithmetic
    contexts: list[ast.AST] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                             ast.Return, ast.Expr, ast.Assert, ast.Raise)):
            contexts.append(node)
        elif isinstance(node, (ast.While, ast.If)):
            contexts.append(node.test)
    clocked = _clocked_class_ranges(mod.tree)

    def in_clocked_class(line: int) -> bool:
        return any(lo <= line <= hi for lo, hi in clocked)

    findings = []
    seen: set[tuple[int, int]] = set()
    for ctx in contexts:
        wall_calls, mono_calls = [], []
        for n in ast.walk(ctx):
            if not isinstance(n, ast.Call):
                continue
            name = dotted_name(n.func)
            if name in WALL_CLOCK_CALLS:
                wall_calls.append(n)
            elif name in MONOTONIC_CALLS and in_clocked_class(n.lineno):
                mono_calls.append(n)
        if not wall_calls and not mono_calls:
            continue
        idents: set[str] = set()
        for n in ast.walk(ctx):
            if isinstance(n, ast.Name):
                idents.add(n.id.lower())
            elif isinstance(n, ast.Attribute):
                idents.add(n.attr.lower())
        idents -= {"time", "_time"}  # the call itself is not evidence
        if not any(h in ident for ident in idents for h in DEADLINE_HINTS):
            continue
        for call, msg in (
            [(c, "time.time() feeding deadline/timeout arithmetic — the "
                 "wall clock steps/slews under NTP, so the deadline can "
                 "fire early or never; use time.monotonic()")
             for c in wall_calls]
            + [(c, "raw time.monotonic() feeding deadline arithmetic in a "
                   "class that carries an injectable clock — it bypasses "
                   "the injected time source, so virtual-clock tests and "
                   "the fleet simulator diverge from the shipped binary; "
                   "read self.clock()/self._clock() instead")
               for c in mono_calls]
        ):
            key = (call.lineno, call.col_offset)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                "MST107", mod.display_path, call.lineno, call.col_offset,
                msg, context=qualname_for_line(mod.tree, call.lineno)))
    return findings


def check_module(mod: ModuleInfo) -> list[Finding]:
    table = _collect_functions(mod.tree)
    traced = _traced_closure(_traced_roots(mod.tree, table), table)
    findings = _check_host_effects(mod, traced)
    findings += _check_hot_syncs(mod)
    findings += _check_spec_policy_syncs(mod)
    findings += _check_double_harvest(mod)
    findings += _check_sync_spill(mod)
    findings += _check_block_migration(mod)
    findings += _check_control_plane_in_tick(mod)
    findings += _check_prefix_federation_in_tick(mod)
    findings += _check_latent_reconstruct_in_tick(mod)
    findings += _check_sync_import(mod)
    findings += _check_store_import(mod)
    findings += _check_hot_trace_overhead(mod)
    findings += _check_spawn_weight_upload(mod)
    findings += _check_recompile_hazards(mod)
    findings += _check_dense_dequant(mod, table)
    findings += _check_wall_clock_deadlines(mod)
    return findings
