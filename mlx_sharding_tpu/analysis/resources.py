"""Central resource registry: every refcounted handle the stack hands out.

The serving layer runs on acquire/release pairs — ``WeightStore`` leases,
``PrefixStore`` COW leases, breaker probe tickets, slot/page allocations,
``KVSpillTier`` blocks, fault-site arms, tracing binds. The same bug class
(release missing on ONE exit path) kept escaping to review: the PR-3 probe
ticket not returned on ``ValueError``/``QueueFullError`` exits, leases that
must release "exactly once through drain/close/fault paths", demote-on-
last-release ordering. This registry is the single source of truth both
checkers read:

- the **static** MST40x verifier (:mod:`.resource_lifecycle`) uses the
  ``static`` specs to recognize acquire/release calls in the AST and run
  its path-sensitive must-release analysis;
- the **runtime** leak ledger (:mod:`.runtime` ``instrument_resources()``)
  tracks the ``RUNTIME_KINDS`` below as live-handle sets under a real
  composed workload and asserts zero live handles at teardown.

Adding a new handle type means adding a spec here — both checkers pick it
up without touching their engines.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class ResourceSpec:
    """One handle type's acquire/release vocabulary.

    ``acquire`` / ``release`` name the *bare* (last-dotted-component) call
    names. ``receiver_hints`` narrows acquire matching: the dotted receiver
    of the call must contain one of the substrings (``store.acquire`` is a
    lease; ``self._lock.acquire`` is not). ``receiver_blocklist`` rejects
    receivers outright (lock objects). ``handle_pos`` selects which element
    of a tuple-unpacked acquire result is the handle (``i, probe =
    self._pick(...)`` → position 1, the probe ticket). ``release_as_arg``
    marks release calls that take the handle as an argument
    (``self._done(i, probe)``) rather than as the receiver
    (``lease.release()``). ``cm`` marks acquires that are safe as ``with``
    context expressions (auto-released by ``__exit__``).
    """

    kind: str                       # "weights.lease"
    module: str                     # owning module (docs + registry table)
    acquire: tuple = ()
    release: tuple = ()
    receiver_hints: tuple = ()      # substrings; () = any receiver
    receiver_blocklist: tuple = ("lock", "mutex", "cond", "sem")
    handle_pos: Optional[int] = None
    release_as_arg: bool = False
    cm: bool = False                # acquire usable as a `with` context
    static: bool = True             # tracked by the MST40x verifier
    escape_attrs: tuple = ()        # doc-only: where handles legally live
    notes: str = ""


# --------------------------------------------------------------- registry
REGISTRY: tuple = (
    ResourceSpec(
        kind="weights.lease",
        module="weights.py",
        acquire=("acquire",),
        release=("release",),
        receiver_hints=("store", "weight"),
        escape_attrs=("engine._weight_lease",),
        notes="refcounted device-resident packed param tree; released "
        "exactly once via engine close()/drain/fault paths (PR 11)",
    ),
    ResourceSpec(
        kind="prefix.lease",
        module="prefix_store.py",
        acquire=("register",),
        release=("release",),
        receiver_hints=("store", "prefix"),
        escape_attrs=("req._please",),
        notes="COW claim on shared prefix KV pages; LAST release demotes "
        "the entry to the host tier (PR 12 ordering)",
    ),
    ResourceSpec(
        kind="replica.probe",
        module="replicas.py",
        acquire=("_pick",),
        release=("_done",),
        handle_pos=1,
        release_as_arg=True,
        notes="half-open breaker probe ticket; must come back on EVERY "
        "exit path or the replica can never be probed again (PR 3)",
    ),
    ResourceSpec(
        kind="faults.arm",
        module="testing/faults.py",
        acquire=("arm",),
        release=("disarm",),
        static=False,  # disarm is site-keyed, not handle-keyed
        notes="armed fault site; a test that forgets disarm() poisons "
        "every later test in the process",
    ),
    ResourceSpec(
        kind="tracing.bind",
        module="tracing.py",
        acquire=("bind",),
        release=(),
        receiver_hints=("tracing",),
        cm=True,
        notes="TLS trace binding; context-manager only — a dangling bind "
        "attributes spans to the wrong request",
    ),
    ResourceSpec(
        kind="tier.block",
        module="kv_transfer.py",
        acquire=("put",),
        release=("take", "drop", "clear"),
        static=False,  # put/take are tier-side ownership moves, not
        # caller-held handles; the runtime ledger tracks residency
        notes="host-DRAM spill-tier residency; close()/clear() must empty "
        "the tier or exported KV outlives every consumer",
    ),
    ResourceSpec(
        kind="scheduler.slot",
        module="scheduler.py",
        acquire=(),
        release=(),
        static=False,  # slots move through self._slots[] — attribute
        # state the runtime ledger tracks at its 3 fill / 6 clear sites
        notes="continuous-batcher slot occupancy; freed through _finish/"
        "_preempt/_suspend/_fail_all/close",
    ),
    ResourceSpec(
        kind="scheduler.page",
        module="scheduler.py",
        acquire=(),
        release=(),
        static=False,  # pool pops are covered by MST302; the ledger
        # balances _free_pages pops against _unref_pages/_evict returns
        notes="KV pool page; _page_ref counts slot claims + index/store "
        "entry claims; every pop must return via the free list",
    ),
)

# kinds the runtime ledger tracks (everything; static-only specs none)
RUNTIME_KINDS: tuple = tuple(s.kind for s in REGISTRY)

# specs the static verifier drives its dataflow from
STATIC_SPECS: tuple = tuple(s for s in REGISTRY if s.static and s.acquire)


# --------------------------------------------- static-analysis vocabulary
# Calls treated as non-raising when deciding whether a live handle can
# leak on an exception edge (MST401). Counters, logging and cheap builtins
# dominate acquire→escape windows in the real tree; treating them as
# raising would drown the signal in "if this counter bump raised" paths.
NONRAISING_PREFIXES = (
    "count_", "note_", "_note_", "log", "debug", "info", "warning", "error",
    "exception", "append", "extend", "add", "discard", "touch",
    "move_to_end",
)
NONRAISING_NAMES = frozenset({
    "len", "int", "float", "str", "bool", "list", "tuple", "set", "dict",
    "min", "max", "sum", "sorted", "range", "enumerate", "zip", "id",
    "isinstance", "getattr", "hasattr", "repr", "format", "print",
    "perf_counter", "monotonic", "time", "get", "items", "keys", "values",
    "current", "point", "inject",
})


def is_nonraising(bare_name: str) -> bool:
    """Heuristic: ``bare_name`` (last dotted component) never raises in
    practice, so a live handle crossing it is not an MST401 leak path."""
    return (bare_name in NONRAISING_NAMES
            or bare_name.startswith(NONRAISING_PREFIXES))


def match_acquire(bare_name: str, receiver: Optional[str]) -> Optional[ResourceSpec]:
    """The spec whose acquire vocabulary matches a call, or None.

    ``receiver`` is the dotted receiver ("store", "self._lock") or None
    for bare-name calls.
    """
    recv = (receiver or "").lower()
    for spec in STATIC_SPECS:
        if bare_name not in spec.acquire:
            continue
        if any(b in recv for b in spec.receiver_blocklist):
            continue
        if spec.receiver_hints and not any(h in recv for h in spec.receiver_hints):
            continue
        return spec
    return None


def match_release(bare_name: str) -> Optional[ResourceSpec]:
    """The spec whose release vocabulary matches ``bare_name``, or None."""
    for spec in STATIC_SPECS:
        if bare_name in spec.release:
            return spec
    return None


# ------------------------------------------------------- registry table
def registry_table() -> list:
    """Rows for the README resource-registry table and ``--format json``
    consumers: (kind, module, acquire, release, static, notes)."""
    return [
        {
            "kind": s.kind,
            "module": s.module,
            "acquire": list(s.acquire),
            "release": list(s.release),
            "static": s.static,
            "notes": s.notes,
        }
        for s in REGISTRY
    ]
