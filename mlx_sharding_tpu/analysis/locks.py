"""Lock-discipline rules (MST20x) for the threaded serving layer.

- **MST201 unlocked-guarded-access** — per class, an attribute counts as
  *guarded* when it is accessed somewhere under ``with self.<lock>`` AND
  written outside ``__init__``. Accesses to a guarded attribute from a
  *public* method with no lock held are reported; private methods are
  exempt (convention: the caller ensures locking).
- **MST202 check-then-act** — within one function, a ``with lock:`` block
  reads a guarded attribute and a *later, separate* ``with lock:`` block
  mutates it: the state can change between the two acquisitions (the
  non-atomic check-then-enqueue bug from PR 2).
- **MST203 lock-order-cycle** — the static lock-acquisition-order graph
  (nested ``with`` blocks, plus one level of intra- and cross-class call
  resolution) contains a cycle, i.e. a potential ABBA deadlock.

Graph nodes are named ``ClassName.attr`` — or the string literal handed to
``analysis.runtime.make_lock("...")``, which the serving modules use so the
static graph and the dynamically recorded one share a vocabulary.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from mlx_sharding_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    dotted_name,
    qualname_for_line,
)

# container calls that mutate their receiver
MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop", "popleft",
    "clear", "add", "discard", "update", "setdefault", "popitem",
    "put", "put_nowait", "get", "get_nowait", "move_to_end", "sort",
}


@dataclass(frozen=True)
class LockEdge:
    """src was held when dst was acquired (one observed static ordering)."""

    src: str
    dst: str
    path: str
    line: int

    def as_dict(self) -> dict:
        return {"src": self.src, "dst": self.dst, "path": self.path,
                "line": self.line}


@dataclass
class _Access:
    attr: str
    write: bool
    method: str
    public: bool
    line: int
    held: tuple


@dataclass
class _WithBlock:
    lock: str
    method: str
    line: int
    end: int
    reads: set = field(default_factory=set)
    writes: set = field(default_factory=set)


@dataclass
class _HeldCall:
    held: tuple
    method_name: str  # callee name
    recv_is_self: bool
    line: int


@dataclass
class _ClassInfo:
    name: str
    mod: ModuleInfo
    locks: dict  # attr -> graph node name
    accesses: list = field(default_factory=list)
    with_blocks: dict = field(default_factory=dict)  # method -> [_WithBlock]
    held_calls: list = field(default_factory=list)
    edges: list = field(default_factory=list)
    method_locks: dict = field(default_factory=dict)  # method -> set(node)


def _lock_factory(call: ast.Call) -> Optional[tuple]:
    """('named', literal) / ('plain', None) if ``call`` builds a lock."""
    name = dotted_name(call.func)
    if name is None:
        return None
    if name.split(".")[-1] == "make_lock":
        if (call.args and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)):
            return ("named", call.args[0].value)
        return ("plain", None)
    if name in ("Lock", "RLock", "threading.Lock", "threading.RLock"):
        return ("plain", None)
    return None


def _lock_value_label(value: ast.AST, cls: str, attr: str) -> Optional[str]:
    """Graph node name if ``self.attr = value`` constructs a lock."""
    if isinstance(value, ast.Call):
        fac = _lock_factory(value)
        if fac:
            return fac[1] or f"{cls}.{attr}"
        fn = dotted_name(value.func)
        if fn and fn.split(".")[-1] == "field":  # dataclasses.field
            for kw in value.keywords:
                if kw.arg != "default_factory":
                    continue
                v = kw.value
                if isinstance(v, ast.Lambda):
                    for sub in ast.walk(v.body):
                        if isinstance(sub, ast.Call):
                            f2 = _lock_factory(sub)
                            if f2:
                                return f2[1] or f"{cls}.{attr}"
                else:
                    d = dotted_name(v)
                    if d and d.split(".")[-1] in ("Lock", "RLock"):
                        return f"{cls}.{attr}"
    if isinstance(value, (ast.List, ast.ListComp)):
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                fac = _lock_factory(sub)
                if fac:
                    return fac[1] if fac[0] == "named" else f"{cls}.{attr}[*]"
    return None


def _find_locks(cls_node: ast.ClassDef, cls_name: str) -> dict:
    locks: dict[str, str] = {}
    for node in ast.walk(cls_node):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            d = dotted_name(t)
            if not (d and d.startswith("self.") and d.count(".") == 1):
                continue
            attr = d.split(".", 1)[1]
            label = _lock_value_label(node.value, cls_name, attr)
            if label:
                locks[attr] = label
    for stmt in cls_node.body:  # class attrs, incl. dataclass fields
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            attr = stmt.target.id
            if stmt.value is not None:
                label = _lock_value_label(stmt.value, cls_name, attr)
                if label:
                    locks[attr] = label
                    continue
            ann = dotted_name(stmt.annotation)
            if ann and ann.split(".")[-1] in ("Lock", "RLock"):
                locks.setdefault(attr, f"{cls_name}.{attr}")
        elif isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    label = _lock_value_label(stmt.value, cls_name, t.id)
                    if label:
                        locks[t.id] = label
    return locks


def _self_attr(node: ast.AST) -> Optional[str]:
    d = dotted_name(node)
    if d and d.startswith("self.") and d.count(".") >= 1:
        return d.split(".")[1]
    return None


def _analyze_class(mod: ModuleInfo, cls_node: ast.ClassDef) -> _ClassInfo:
    ci = _ClassInfo(name=cls_node.name, mod=mod,
                    locks=_find_locks(cls_node, cls_node.name))

    for method in cls_node.body:
        if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        mname = method.name
        public = not mname.startswith("_")
        aliases: dict[str, str] = {}  # local var -> lock node name
        blocks: list[_WithBlock] = []
        ci.with_blocks[mname] = blocks
        with_stack: list[_WithBlock] = []
        acquired: set[str] = set()

        def resolve_lock(expr: ast.AST) -> Optional[str]:
            attr = _self_attr(expr)
            if attr is not None and attr in ci.locks:
                return ci.locks[attr]
            if isinstance(expr, ast.Name) and expr.id in aliases:
                return aliases[expr.id]
            if isinstance(expr, ast.Subscript):
                base = _self_attr(expr.value)
                if base is not None and base in ci.locks:
                    return ci.locks[base]
            if isinstance(expr, ast.BoolOp):
                for v in expr.values:
                    r = resolve_lock(v)
                    if r:
                        return r
            if isinstance(expr, ast.IfExp):
                for v in (expr.body, expr.orelse):
                    r = resolve_lock(v)
                    if r:
                        return r
            return None

        def record_access(attr: str, write: bool, line: int, held: tuple):
            if attr in ci.locks:
                return
            ci.accesses.append(_Access(attr, write, mname, public, line, held))
            for wb in with_stack:
                (wb.writes if write else wb.reads).add(attr)

        def scan(node: ast.AST, held: tuple):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                taken: list[str] = []
                for item in node.items:
                    scan(item.context_expr, held)
                    lk = resolve_lock(item.context_expr)
                    if lk:
                        taken.append(lk)
                for lk in taken:
                    acquired.add(lk)
                    for h in held:
                        if h != lk:
                            ci.edges.append(LockEdge(
                                h, lk, mod.display_path, node.lineno))
                entries = [
                    _WithBlock(lk, mname, node.lineno,
                               getattr(node, "end_lineno", node.lineno))
                    for lk in taken
                ]
                blocks.extend(entries)
                with_stack.extend(entries)
                for stmt in node.body:
                    scan(stmt, held + tuple(lk for lk in taken
                                            if lk not in held))
                del with_stack[len(with_stack) - len(entries):]
                return
            if isinstance(node, ast.Assign):
                if (len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Subscript)):
                    base = _self_attr(node.value.value)
                    if base is not None and base in ci.locks:
                        aliases[node.targets[0].id] = ci.locks[base]
            if isinstance(node, ast.Call):
                func = node.func
                callee = None
                recv_self = False
                if isinstance(func, ast.Attribute):
                    callee = func.attr
                    recv_self = dotted_name(func.value) == "self"
                    if callee in MUTATORS:
                        base = _self_attr(func.value)
                        if base is not None:
                            record_access(base, True, node.lineno, held)
                        callee = None
                elif (isinstance(func, ast.Call)
                        and dotted_name(func.func) == "getattr"
                        and len(func.args) >= 2
                        and isinstance(func.args[1], ast.Constant)
                        and isinstance(func.args[1].value, str)):
                    callee = func.args[1].value
                    recv_self = dotted_name(func.args[0]) == "self"
                if callee and held:
                    ci.held_calls.append(
                        _HeldCall(held, callee, recv_self, node.lineno))
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                record_access(node.attr,
                              isinstance(node.ctx, (ast.Store, ast.Del)),
                              node.lineno, held)
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, (ast.Store, ast.Del))):
                base = _self_attr(node.value)
                if base is not None:
                    record_access(base, True, node.lineno, held)
            for child in ast.iter_child_nodes(node):
                scan(child, held)

        for stmt in method.body:
            scan(stmt, ())
        ci.method_locks[mname] = acquired
    return ci


def _guarded_attrs(ci: _ClassInfo) -> dict:
    """attr -> lock node name believed to guard it."""
    locked_under: dict[str, dict] = {}
    written_late: set[str] = set()
    for a in ci.accesses:
        if a.held:
            counts = locked_under.setdefault(a.attr, {})
            counts[a.held[-1]] = counts.get(a.held[-1], 0) + 1
        if a.write and a.method != "__init__":
            written_late.add(a.attr)
    out = {}
    for attr, counts in locked_under.items():
        if attr in written_late:
            out[attr] = sorted(counts, key=lambda k: (-counts[k], k))[0]
    return out


def _mst201(ci: _ClassInfo, guarded: dict) -> list[Finding]:
    findings = []
    seen = set()
    for a in ci.accesses:
        if a.held or not a.public or a.attr not in guarded:
            continue
        msg = (f"'{a.attr}' is guarded by {guarded[a.attr]} elsewhere but "
               f"accessed with no lock held in public method "
               f"{ci.name}.{a.method}()")
        key = (a.attr, a.method, a.line)
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            "MST201", ci.mod.display_path, a.line, 0, msg,
            context=qualname_for_line(ci.mod.tree, a.line)))
    return findings


def _mst202(ci: _ClassInfo, guarded: dict) -> list[Finding]:
    findings = []
    for method, blocks in ci.with_blocks.items():
        ordered = sorted(blocks, key=lambda b: b.line)
        for i, first in enumerate(ordered):
            for second in ordered[i + 1:]:
                if second.lock != first.lock or second.line <= first.end:
                    continue  # different lock, or nested/overlapping
                for attr in sorted(first.reads & second.writes):
                    if attr not in guarded:
                        continue
                    findings.append(Finding(
                        "MST202", ci.mod.display_path, second.line, 0,
                        f"check-then-act: '{attr}' read under {first.lock} "
                        f"then mutated under a separate acquisition in "
                        f"{ci.name}.{method}() — the state can change "
                        "between the two lock scopes",
                        context=qualname_for_line(ci.mod.tree, second.line)))
    return findings


def _find_cycles(edges: list[LockEdge]) -> list[Finding]:
    graph: dict[str, set] = {}
    rep: dict[tuple, LockEdge] = {}
    for e in edges:
        graph.setdefault(e.src, set()).add(e.dst)
        graph.setdefault(e.dst, set())
        rep.setdefault((e.src, e.dst), e)
    findings = []
    color: dict[str, int] = {}
    stack: list[str] = []

    def dfs(u: str):
        color[u] = 1
        stack.append(u)
        for v in sorted(graph[u]):
            if color.get(v, 0) == 0:
                dfs(v)
            elif color[v] == 1:  # back edge: cycle
                e = rep[(u, v)]
                cyc = stack[stack.index(v):] + [v]
                findings.append(Finding(
                    "MST203", e.path, e.line, 0,
                    "lock-order cycle (potential ABBA deadlock): "
                    + " -> ".join(cyc),
                    context=f"{e.src}->{e.dst}"))
        color[u] = 2
        stack.pop()

    for u in sorted(graph):
        if color.get(u, 0) == 0:
            dfs(u)
    return findings


def module_facts(mod: ModuleInfo) -> dict:
    """Per-file half of the lock analysis, as a JSON-safe dict.

    Everything derivable from this file alone lives here — MST201/202
    findings, this file's nested-``with`` edges, and the held-call /
    method-locks tables the cross-module pass resolves later. This split
    is what makes the incremental cache sound: a cached file contributes
    its facts without being reparsed, and only :func:`global_check`
    (method-name resolution + cycle detection) reruns every time.
    """
    classes = [
        _analyze_class(mod, node)
        for node in ast.walk(mod.tree)
        if isinstance(node, ast.ClassDef)
    ]
    findings: list[Finding] = []
    for ci in classes:
        guarded = _guarded_attrs(ci)
        findings += _mst201(ci, guarded)
        findings += _mst202(ci, guarded)
    return {
        "findings": [f.__dict__.copy() for f in findings],
        "classes": [
            {
                "edges": [e.as_dict() for e in ci.edges],
                "method_locks": {m: sorted(lks)
                                 for m, lks in ci.method_locks.items() if lks},
                "held_calls": [
                    {"held": list(hc.held), "callee": hc.method_name,
                     "recv_is_self": hc.recv_is_self, "line": hc.line}
                    for hc in ci.held_calls
                ],
            }
            for ci in classes
        ],
    }


def global_check(facts_by_path: dict) -> tuple[list[Finding], list[LockEdge]]:
    """Cross-module half: resolve held calls through the fleet-wide
    method-name → locks map, then hunt lock-order cycles. Cheap (pure
    dict work), so it reruns on every scan even when all files hit the
    cache."""
    # method name -> locks that method acquires, in any class of any file
    global_map: dict[str, set] = {}
    for facts in facts_by_path.values():
        for cls in facts["classes"]:
            for m, lks in cls["method_locks"].items():
                global_map.setdefault(m, set()).update(lks)

    edges: list[LockEdge] = []
    for path, facts in facts_by_path.items():
        for cls in facts["classes"]:
            edges.extend(LockEdge(**e) for e in cls["edges"])
            for hc in cls["held_calls"]:
                callee_locks = (
                    set(cls["method_locks"].get(hc["callee"], ()))
                    if hc["recv_is_self"]
                    else global_map.get(hc["callee"], set())
                )
                for src in hc["held"]:
                    for dst in sorted(callee_locks):
                        if src != dst:
                            edges.append(LockEdge(src, dst, path, hc["line"]))

    findings = _find_cycles(edges)
    uniq: dict[tuple, LockEdge] = {}
    for e in edges:
        uniq.setdefault((e.src, e.dst), e)
    return findings, sorted(uniq.values(), key=lambda e: (e.src, e.dst))


def check_modules(modules: list[ModuleInfo]) -> tuple[list[Finding], list[LockEdge]]:
    facts = {mod.display_path: module_facts(mod) for mod in modules}
    findings = [
        Finding(**f) for fx in facts.values() for f in fx["findings"]
    ]
    cycle_findings, edges = global_check(facts)
    return findings + cycle_findings, edges
