"""mstcheck driver: file walking, suppressions, baseline, reporting.

The checker is pure-stdlib (``ast`` + ``re``) so the self-scan test adds no
heavyweight imports — ``python -m mlx_sharding_tpu.analysis mlx_sharding_tpu/``
runs in well under a second on this repo.

Workflow pieces living here:

- **Suppressions** — ``# mst: allow(MST102): <reason>`` on the finding line
  (or the line above) silences that rule there. The reason is mandatory: a
  bare ``allow(...)`` is itself reported as MST001, so every silenced finding
  carries its justification in the diff.
- **Baseline** — ``analysis/baseline.json`` holds grandfathered findings
  keyed by (rule, path, enclosing symbol, message); matching findings are
  reported as baselined and do not fail the run. ``--write-baseline``
  regenerates the file from the current findings.
"""

from __future__ import annotations

import ast
import json
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

SUPPRESS_RE = re.compile(
    r"#\s*mst:\s*allow\(\s*([A-Za-z0-9_\-, ]+?)\s*\)(?:\s*:\s*(\S.*))?"
)
HOT_PATH_RE = re.compile(r"#\s*mst:\s*hot-path\b")
DECODE_HOT_RE = re.compile(r"#\s*mst:\s*decode-hot\b")
SPAWN_HOT_RE = re.compile(r"#\s*mst:\s*spawn-hot\b")


@dataclass(frozen=True)
class Finding:
    rule: str  # "MST101"
    path: str  # posix path as scanned
    line: int
    col: int
    message: str
    context: str = ""  # enclosing ClassName.method / function, for baselining

    def key(self) -> tuple:
        # line numbers churn with unrelated edits; the baseline matches on
        # the stable parts only
        return (self.rule, self.path, self.context, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


@dataclass
class ModuleInfo:
    """One parsed file plus everything the rules need alongside the AST."""

    path: Path
    display_path: str
    tree: ast.Module
    source_lines: list[str]
    # line -> set of rule ids allowed there (valid suppressions only)
    suppressions: dict[int, set[str]] = field(default_factory=dict)
    bad_suppressions: list[int] = field(default_factory=list)
    hot_lines: set[int] = field(default_factory=set)  # '# mst: hot-path'
    decode_hot_lines: set[int] = field(default_factory=set)  # 'decode-hot'
    spawn_hot_lines: set[int] = field(default_factory=set)  # 'spawn-hot'

    @property
    def basename(self) -> str:
        return self.path.name

    def is_suppressed(self, finding: Finding) -> bool:
        for line in (finding.line, finding.line - 1):
            if finding.rule in self.suppressions.get(line, ()):
                return True
        return False


def qualname_for_line(tree: ast.Module, target_line: int) -> str:
    """Dotted enclosing-symbol name for a line (baseline context)."""
    best: list[str] = []

    def walk(n, stack):
        for child in ast.iter_child_nodes(n):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                end = getattr(child, "end_lineno", child.lineno)
                if child.lineno <= target_line <= end:
                    nonlocal best
                    best = stack + [child.name]
                    walk(child, best)
            else:
                walk(child, stack)

    walk(tree, [])
    return ".".join(best) or "<module>"


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains; None for anything dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def parse_module(path: Path, display_path: str) -> tuple[Optional[ModuleInfo], list[Finding]]:
    try:
        source = path.read_text(encoding="utf-8")
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError, ValueError) as e:
        line = getattr(e, "lineno", 1) or 1
        return None, [
            Finding("MST000", display_path, line, 0, f"unparseable file: {e}")
        ]
    mod = ModuleInfo(path=path, display_path=display_path, tree=tree,
                     source_lines=source.splitlines())
    for i, text in enumerate(mod.source_lines, start=1):
        if "mst:" not in text:
            continue
        if HOT_PATH_RE.search(text):
            mod.hot_lines.add(i)
        if DECODE_HOT_RE.search(text):
            mod.decode_hot_lines.add(i)
        if SPAWN_HOT_RE.search(text):
            mod.spawn_hot_lines.add(i)
        m = SUPPRESS_RE.search(text)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = (m.group(2) or "").strip()
            if not reason:
                mod.bad_suppressions.append(i)
            else:
                mod.suppressions.setdefault(i, set()).update(rules)
    return mod, []


def collect_files(paths: list[str]) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(
                f for f in sorted(path.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        else:
            files.append(path)
    return files


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)  # actionable
    baselined: list[Finding] = field(default_factory=list)
    lock_edges: list = field(default_factory=list)  # locks.LockEdge
    files_scanned: int = 0


def analyze_paths(paths: list[str], baseline: Optional[set] = None) -> Report:
    """Run every rule family over ``paths``; returns the triaged report."""
    from mlx_sharding_tpu.analysis import lifecycle, locks, trace_safety

    report = Report()
    raw: list[Finding] = []
    modules: list[ModuleInfo] = []
    for f in collect_files(paths):
        mod, errors = parse_module(f, f.as_posix())
        raw.extend(errors)
        if mod is None:
            continue
        modules.append(mod)
        report.files_scanned += 1
        for line in mod.bad_suppressions:
            raw.append(Finding(
                "MST001", mod.display_path, line, 0,
                "suppression without a reason — write "
                "'# mst: allow(<rule>): <why this is safe>'",
                context=qualname_for_line(mod.tree, line),
            ))
        raw.extend(trace_safety.check_module(mod))
        raw.extend(lifecycle.check_module(mod))
    lock_findings, edges = locks.check_modules(modules)
    raw.extend(lock_findings)
    report.lock_edges = edges

    by_path = {m.display_path: m for m in modules}
    for finding in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        mod = by_path.get(finding.path)
        if mod is not None and finding.rule != "MST001" and mod.is_suppressed(finding):
            continue
        if baseline and finding.key() in baseline:
            report.baselined.append(finding)
        else:
            report.findings.append(finding)
    return report


# ----------------------------------------------------------------- baseline
def load_baseline(path: Path) -> set:
    data = json.loads(path.read_text())
    return {
        (e["rule"], e["path"], e.get("context", ""), e["message"])
        for e in data.get("findings", [])
    }


def write_baseline(path: Path, findings: list[Finding]):
    entries = [
        {"rule": f.rule, "path": f.path, "context": f.context,
         "message": f.message}
        for f in findings
    ]
    path.write_text(json.dumps(
        {"version": 1, "findings": entries}, indent=2, sort_keys=True
    ) + "\n")


DEFAULT_BASELINE = Path(__file__).parent / "baseline.json"


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m mlx_sharding_tpu.analysis",
        description="mstcheck: trace-safety, lock-discipline and "
        "stream/resource-lifecycle static analysis for this repo",
    )
    parser.add_argument("paths", nargs="+", help="files or directories to scan")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline JSON (default: {DEFAULT_BASELINE})")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report grandfathered findings too")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                        "and exit 0")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--lock-graph", action="store_true",
                        help="print the static lock-acquisition-order graph")
    args = parser.parse_args(argv)

    baseline_path = Path(args.baseline) if args.baseline else DEFAULT_BASELINE
    baseline: Optional[set] = None
    if not args.no_baseline and not args.write_baseline and baseline_path.exists():
        baseline = load_baseline(baseline_path)

    report = analyze_paths(args.paths, baseline=baseline)

    if args.write_baseline:
        write_baseline(baseline_path, report.findings)
        print(f"wrote {len(report.findings)} finding(s) to {baseline_path}")
        return 0

    if args.format == "json":
        print(json.dumps({
            "findings": [f.__dict__ for f in report.findings],
            "baselined": [f.__dict__ for f in report.baselined],
            "lock_edges": [e.as_dict() for e in report.lock_edges],
        }, indent=2))
    else:
        for f in report.findings:
            print(f.render())
        if args.lock_graph:
            print("lock-order graph:")
            for e in sorted(set((e.src, e.dst) for e in report.lock_edges)):
                print(f"  {e[0]} -> {e[1]}")
        print(
            f"mstcheck: {len(report.findings)} finding(s), "
            f"{len(report.baselined)} baselined, "
            f"{report.files_scanned} file(s) scanned"
        )
    return 1 if report.findings else 0
