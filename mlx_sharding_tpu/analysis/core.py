"""mstcheck driver: file walking, suppressions, baseline, reporting.

The checker is pure-stdlib (``ast`` + ``re``) so the self-scan test adds no
heavyweight imports — ``python -m mlx_sharding_tpu.analysis mlx_sharding_tpu/``
runs in well under a second on this repo.

Workflow pieces living here:

- **Suppressions** — ``# mst: allow(MST102): <reason>`` on the finding line
  (or the line above) silences that rule there. The reason is mandatory: a
  bare ``allow(...)`` is itself reported as MST001, so every silenced finding
  carries its justification in the diff.
- **Baseline** — ``analysis/baseline.json`` holds grandfathered findings
  keyed by (rule, path, enclosing symbol, message); matching findings are
  reported as baselined and do not fail the run. ``--write-baseline``
  regenerates the file from the current findings. A baseline entry that no
  longer matches anything is itself a hard error (MST003) so the file can
  only shrink toward empty, never silently rot.
- **Dead suppressions** — an ``allow(...)`` comment whose rule no longer
  fires on that line is reported as MST002: suppressions must be deleted
  when the finding they silenced is fixed.
- **Incremental cache** — per-file facts (findings, suppression table,
  lock facts) keyed by content hash + a digest of the checker's own
  sources; unchanged files skip parsing and every rule. Only the cheap
  cross-module lock pass (method resolution + cycle hunt) reruns each
  scan. ``--no-cache`` / ``--cache PATH`` control it.
"""

from __future__ import annotations

import ast
import hashlib
import json
import re
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

SUPPRESS_RE = re.compile(
    r"#\s*mst:\s*allow\(\s*([A-Za-z0-9_\-, ]+?)\s*\)(?:\s*:\s*(\S.*))?"
)
HOT_PATH_RE = re.compile(r"#\s*mst:\s*hot-path\b")
DECODE_HOT_RE = re.compile(r"#\s*mst:\s*decode-hot\b")
SPAWN_HOT_RE = re.compile(r"#\s*mst:\s*spawn-hot\b")
SPEC_HOT_RE = re.compile(r"#\s*mst:\s*spec-hot\b")


@dataclass(frozen=True)
class Finding:
    rule: str  # "MST101"
    path: str  # posix path as scanned
    line: int
    col: int
    message: str
    context: str = ""  # enclosing ClassName.method / function, for baselining

    def key(self) -> tuple:
        # line numbers churn with unrelated edits; the baseline matches on
        # the stable parts only
        return (self.rule, self.path, self.context, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


@dataclass
class ModuleInfo:
    """One parsed file plus everything the rules need alongside the AST."""

    path: Path
    display_path: str
    tree: ast.Module
    source_lines: list[str]
    # line -> set of rule ids allowed there (valid suppressions only)
    suppressions: dict[int, set[str]] = field(default_factory=dict)
    bad_suppressions: list[int] = field(default_factory=list)
    hot_lines: set[int] = field(default_factory=set)  # '# mst: hot-path'
    decode_hot_lines: set[int] = field(default_factory=set)  # 'decode-hot'
    spawn_hot_lines: set[int] = field(default_factory=set)  # 'spawn-hot'
    spec_hot_lines: set[int] = field(default_factory=set)  # 'spec-hot'

    @property
    def basename(self) -> str:
        return self.path.name

    def is_suppressed(self, finding: Finding) -> bool:
        for line in (finding.line, finding.line - 1):
            if finding.rule in self.suppressions.get(line, ()):
                return True
        return False


def qualname_for_line(tree: ast.Module, target_line: int) -> str:
    """Dotted enclosing-symbol name for a line (baseline context)."""
    best: list[str] = []

    def walk(n, stack):
        for child in ast.iter_child_nodes(n):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                end = getattr(child, "end_lineno", child.lineno)
                if child.lineno <= target_line <= end:
                    nonlocal best
                    best = stack + [child.name]
                    walk(child, best)
            else:
                walk(child, stack)

    walk(tree, [])
    return ".".join(best) or "<module>"


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains; None for anything dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _comments(source: str):
    """(line, text) for real ``#`` comments only — a docstring that *shows*
    the suppression syntax must not register as a suppression."""
    import io
    import tokenize

    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return  # ast already accepted the file; partial comments suffice


def parse_module(path: Path, display_path: str,
                 source: Optional[str] = None
                 ) -> tuple[Optional[ModuleInfo], list[Finding]]:
    try:
        if source is None:
            source = path.read_text(encoding="utf-8")
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError, ValueError) as e:
        line = getattr(e, "lineno", 1) or 1
        return None, [
            Finding("MST000", display_path, line, 0, f"unparseable file: {e}")
        ]
    mod = ModuleInfo(path=path, display_path=display_path, tree=tree,
                     source_lines=source.splitlines())
    for i, text in _comments(source):
        if "mst:" not in text:
            continue
        if HOT_PATH_RE.search(text):
            mod.hot_lines.add(i)
        if DECODE_HOT_RE.search(text):
            mod.decode_hot_lines.add(i)
        if SPAWN_HOT_RE.search(text):
            mod.spawn_hot_lines.add(i)
        if SPEC_HOT_RE.search(text):
            mod.spec_hot_lines.add(i)
        m = SUPPRESS_RE.search(text)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = (m.group(2) or "").strip()
            if not reason:
                mod.bad_suppressions.append(i)
            else:
                mod.suppressions.setdefault(i, set()).update(rules)
    return mod, []


def collect_files(paths: list[str]) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(
                f for f in sorted(path.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        else:
            files.append(path)
    return files


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)  # actionable
    baselined: list[Finding] = field(default_factory=list)
    lock_edges: list = field(default_factory=list)  # locks.LockEdge
    files_scanned: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    # per-rule-family wall time (ms), per-file passes + global passes
    rule_timings: dict = field(default_factory=dict)
    # races.global_check's per-attr table: "Cls.attr" -> roles/lockset/verdict
    race_verdicts: dict = field(default_factory=dict)


# ------------------------------------------------------- per-file facts
# the cache key includes a digest of the checker's own sources: any edit
# to analysis/*.py invalidates every entry, so there is no manual
# version constant to forget to bump
CACHE_VERSION = 2

_checker_digest_memo: Optional[str] = None


def _checker_digest() -> str:
    global _checker_digest_memo
    if _checker_digest_memo is None:
        h = hashlib.sha256()
        for f in sorted(Path(__file__).parent.glob("*.py")):
            h.update(f.name.encode())
            h.update(f.read_bytes())
        _checker_digest_memo = h.hexdigest()[:16]
    return _checker_digest_memo


def file_facts(mod: ModuleInfo, timings: Optional[dict] = None) -> dict:
    """Everything the triage pass needs from one file, JSON-safe: the
    module-local findings of every rule family, the suppression table,
    and the per-file lock/role/doc facts for the cross-module passes.
    ``timings`` (family name -> accumulated ms) feeds the per-rule timing
    block of ``--format json``."""
    from mlx_sharding_tpu.analysis import (
        docs,
        lifecycle,
        locks,
        resource_lifecycle,
        thread_roles,
        trace_safety,
    )

    def timed(name, fn, *fn_args):
        t0 = time.perf_counter()
        out = fn(*fn_args)
        if timings is not None:
            timings[name] = (timings.get(name, 0.0)
                             + (time.perf_counter() - t0) * 1e3)
        return out

    findings: list[Finding] = []
    for line in mod.bad_suppressions:
        findings.append(Finding(
            "MST001", mod.display_path, line, 0,
            "suppression without a reason — write "
            "'# mst: allow(<rule>): <why this is safe>'",
            context=qualname_for_line(mod.tree, line),
        ))
    findings.extend(timed("trace_safety", trace_safety.check_module, mod))
    findings.extend(timed("lifecycle", lifecycle.check_module, mod))
    findings.extend(timed("resource_lifecycle",
                          resource_lifecycle.check_module, mod))
    return {
        "findings": [f.__dict__.copy() for f in findings],
        "suppressions": {
            str(line): sorted(rules)
            for line, rules in mod.suppressions.items()
        },
        "lock": timed("locks", locks.module_facts, mod),
        "roles": timed("thread_roles", thread_roles.module_facts, mod),
        "doc": timed("docs", docs.module_facts, mod),
    }


def _error_facts(errors: list[Finding]) -> dict:
    return {
        "findings": [f.__dict__.copy() for f in errors],
        "suppressions": {},
        "lock": {"findings": [], "classes": []},
        "roles": {"entries": [], "classes": {}},
        "doc": {"metrics": [], "flags": []},
    }


def _load_cache(cache_path: Optional[Path]) -> dict:
    if cache_path is not None and cache_path.exists():
        try:
            data = json.loads(cache_path.read_text())
            if (data.get("version") == CACHE_VERSION
                    and data.get("checker") == _checker_digest()):
                return data
        except (OSError, ValueError):
            pass
    return {"version": CACHE_VERSION, "checker": _checker_digest(),
            "files": {}}


REGEN_HINT = ("regenerate with `python -m mlx_sharding_tpu.analysis "
              "mlx_sharding_tpu/ --write-baseline`")


def analyze_paths(paths: list[str], baseline: Optional[set] = None,
                  cache_path: Optional[Path] = None,
                  baseline_path: Optional[Path] = None,
                  changed: Optional[set] = None) -> Report:
    """Run every rule family over ``paths``; returns the triaged report.

    With ``cache_path``, per-file results are reused when the file's
    content hash and the checker's own digest both match — self-scan
    cost becomes proportional to what changed since the last run.

    With ``changed`` (a set of repo-relative posix paths, e.g. from
    ``git diff --name-only``), any collected file *not* in the set is
    served straight from the cache without even re-reading it — the
    ``--changed`` pre-commit path. Files in the set (and files the cache
    has never seen) go through the normal hash-and-check route.
    """
    from mlx_sharding_tpu.analysis import docs, locks, races

    report = Report()
    timings = report.rule_timings
    cache = _load_cache(cache_path)
    records: dict[str, dict] = {}  # display_path -> facts
    for f in collect_files(paths):
        display = f.as_posix()
        if changed is not None and display not in changed:
            entry = cache["files"].get(display)
            if entry is not None:
                records[display] = entry["facts"]
                report.cache_hits += 1
                report.files_scanned += 1
                continue
        try:
            data = f.read_bytes()
        except OSError as e:
            records[display] = _error_facts([Finding(
                "MST000", display, 1, 0, f"unparseable file: {e}")])
            report.files_scanned += 1
            continue
        digest = hashlib.sha256(data).hexdigest()
        entry = cache["files"].get(display)
        if entry is not None and entry.get("hash") == digest:
            facts = entry["facts"]
            report.cache_hits += 1
        else:
            mod, errors = parse_module(
                f, display, source=data.decode("utf-8", errors="replace"))
            facts = (_error_facts(errors) if mod is None
                     else file_facts(mod, timings))
            cache["files"][display] = {"hash": digest, "facts": facts}
            report.cache_misses += 1
        records[display] = facts
        report.files_scanned += 1

    if cache_path is not None and report.cache_misses:
        try:
            cache_path.write_text(json.dumps(cache))
        except OSError:
            pass  # the cache is an optimization, never a failure

    def timed_global(name, fn, *fn_args):
        t0 = time.perf_counter()
        out = fn(*fn_args)
        timings[name] = (timings.get(name, 0.0)
                         + (time.perf_counter() - t0) * 1e3)
        return out

    # cross-module lock pass (cheap dict work; always recomputed)
    lock_findings, edges = timed_global(
        "locks_global", locks.global_check,
        {p: r["lock"] for p, r in records.items()})
    report.lock_edges = edges

    # cross-module race pass (thread-role propagation + MST501-504)
    race_findings, verdicts = timed_global(
        "races_global", races.global_check,
        {p: r["roles"] for p, r in records.items()})
    report.race_verdicts = verdicts

    # doc-drift gate (MST005): README tables vs the live inventory
    doc_findings = timed_global(
        "docs_global", docs.global_check,
        {p: r["doc"] for p, r in records.items()}, docs.find_readme(paths))

    raw: list[Finding] = [
        Finding(**d)
        for r in records.values()
        for d in r["findings"] + r["lock"]["findings"]
    ]
    raw.extend(lock_findings)
    raw.extend(race_findings)
    raw.extend(doc_findings)

    # MST002: every suppression must still be earning its keep
    fired_by_path: dict[str, set] = {}
    for f in raw:
        fired_by_path.setdefault(f.path, set()).update(
            [(f.rule, f.line), (f.rule, f.line - 1)])
    for path, r in records.items():
        fired = fired_by_path.get(path, set())
        for line_s, rules in sorted(r["suppressions"].items(),
                                    key=lambda kv: int(kv[0])):
            if any((rule, int(line_s)) in fired for rule in rules):
                continue
            listed = ",".join(sorted(rules))
            raw.append(Finding(
                "MST002", path, int(line_s), 0,
                f"dead suppression: allow({listed}) no longer matches any "
                "finding here — the bug it silenced is gone, delete the "
                "comment",
                context=f"allow({listed})",
            ))

    suppression_exempt = {"MST001", "MST002"}
    for finding in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        r = records.get(finding.path)
        if r is not None and finding.rule not in suppression_exempt:
            sup = r["suppressions"]
            if any(finding.rule in sup.get(str(line), ())
                   for line in (finding.line, finding.line - 1)):
                continue
        if baseline and finding.key() in baseline:
            report.baselined.append(finding)
        else:
            report.findings.append(finding)

    # MST003: stale baseline entries are a hard error, not silent rot
    if baseline:
        matched = {f.key() for f in report.baselined}
        for key in sorted(baseline - matched):
            rule, path, context, message = key
            report.findings.append(Finding(
                "MST003", str(baseline_path or DEFAULT_BASELINE), 0, 0,
                f"stale baseline entry ({rule} {path} {context!r}): the "
                f"finding it grandfathers is gone — {REGEN_HINT}",
                context=context,
            ))
    return report


# ----------------------------------------------------------------- baseline
def load_baseline(path: Path) -> set:
    data = json.loads(path.read_text())
    return {
        (e["rule"], e["path"], e.get("context", ""), e["message"])
        for e in data.get("findings", [])
    }


def write_baseline(path: Path, findings: list[Finding]):
    entries = [
        {"rule": f.rule, "path": f.path, "context": f.context,
         "message": f.message}
        for f in findings
    ]
    path.write_text(json.dumps(
        {"version": 1, "findings": entries}, indent=2, sort_keys=True
    ) + "\n")


DEFAULT_BASELINE = Path(__file__).parent / "baseline.json"


DEFAULT_CACHE = Path(".mstcheck-cache.json")


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import time

    parser = argparse.ArgumentParser(
        prog="python -m mlx_sharding_tpu.analysis",
        description="mstcheck: trace-safety, lock-discipline and "
        "stream/resource-lifecycle static analysis for this repo",
    )
    parser.add_argument("paths", nargs="+", help="files or directories to scan")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline JSON (default: {DEFAULT_BASELINE})")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report grandfathered findings too")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                        "and exit 0")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--lock-graph", action="store_true",
                        help="print the static lock-acquisition-order graph")
    parser.add_argument("--cache", default=str(DEFAULT_CACHE),
                        help="per-file incremental result cache "
                        f"(default: {DEFAULT_CACHE})")
    parser.add_argument("--no-cache", action="store_true",
                        help="reparse and recheck every file")
    parser.add_argument("--changed", action="store_true",
                        help="git-diff-scoped scan: only files changed vs "
                        "HEAD (plus untracked) are re-checked; everything "
                        "else is served from the cache without re-hashing")
    args = parser.parse_args(argv)

    changed: Optional[set] = None
    if args.changed and not args.no_cache:
        import subprocess

        try:
            diff = subprocess.run(
                ["git", "diff", "--name-only", "HEAD"],
                capture_output=True, text=True, check=True).stdout
            untracked = subprocess.run(
                ["git", "ls-files", "--others", "--exclude-standard"],
                capture_output=True, text=True, check=True).stdout
            changed = {ln.strip() for ln in
                       (diff + untracked).splitlines() if ln.strip()}
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"mstcheck: --changed needs git ({e}); full scan",
                  file=sys.stderr)

    baseline_path = Path(args.baseline) if args.baseline else DEFAULT_BASELINE
    baseline: Optional[set] = None
    if not args.no_baseline and not args.write_baseline and baseline_path.exists():
        baseline = load_baseline(baseline_path)

    t0 = time.perf_counter()
    report = analyze_paths(
        args.paths, baseline=baseline,
        cache_path=None if args.no_cache else Path(args.cache),
        baseline_path=baseline_path,
        changed=changed,
    )
    elapsed_ms = (time.perf_counter() - t0) * 1e3

    if args.write_baseline:
        write_baseline(baseline_path, report.findings)
        print(f"wrote {len(report.findings)} finding(s) to {baseline_path}")
        return 0

    if args.format == "json":
        from mlx_sharding_tpu.analysis import resources, thread_roles

        print(json.dumps({
            "findings": [f.__dict__ for f in report.findings],
            "baselined": [f.__dict__ for f in report.baselined],
            "lock_edges": [e.as_dict() for e in report.lock_edges],
            "files_scanned": report.files_scanned,
            "cache_hits": report.cache_hits,
            "cache_misses": report.cache_misses,
            "elapsed_ms": round(elapsed_ms, 1),
            "rule_timings_ms": {k: round(v, 2) for k, v in
                                sorted(report.rule_timings.items())},
            "resource_registry": resources.registry_table(),
            "thread_roles": thread_roles.role_table(),
            "race_verdicts": report.race_verdicts,
        }, indent=2))
    else:
        for f in report.findings:
            print(f.render())
        if args.lock_graph:
            print("lock-order graph:")
            for e in sorted(set((e.src, e.dst) for e in report.lock_edges)):
                print(f"  {e[0]} -> {e[1]}")
        print(
            f"mstcheck: {len(report.findings)} finding(s), "
            f"{len(report.baselined)} baselined, "
            f"{report.files_scanned} file(s) scanned "
            f"({report.cache_hits} cached) in {elapsed_ms:.0f}ms"
        )
    return 1 if report.findings else 0
