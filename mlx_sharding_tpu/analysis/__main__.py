import sys

from mlx_sharding_tpu.analysis.core import main

sys.exit(main())
