"""mstcheck: repo-native static analysis for the serving stack.

Three rule families over plain ``ast`` (no third-party deps):

- MST1xx trace safety (host effects in jit-traced code, device syncs in
  hot paths, recompilation hazards) — :mod:`.trace_safety`
- MST2xx lock discipline (guarded-attribute access, check-then-act,
  lock-order cycles) — :mod:`.locks`
- MST3xx stream/resource lifecycles (generator leaks, alloc/free pairing,
  fault-injection-site coverage) — :mod:`.lifecycle`

Run with ``python -m mlx_sharding_tpu.analysis <paths>``. See the README's
"Static analysis" section for the rule catalog, suppression syntax, and the
baseline workflow.
"""

from mlx_sharding_tpu.analysis.core import (  # noqa: F401
    Finding,
    Report,
    analyze_paths,
    main,
)
