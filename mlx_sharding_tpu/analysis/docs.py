"""MST005 doc-drift gate: README reference tables vs the live code.

The README's metrics reference and CLI flag tables rot silently — a new
``mst_*`` family or ``--flag`` ships, the table doesn't. This pass makes
drift a *finding*:

- the per-file half extracts the live inventory: every metric family the
  exposition code can emit (``# TYPE mst_x <type>`` string literals plus
  ``Histogram.render_into(lines, "mst_x", ...)`` family arguments) and
  every ``add_argument("--flag", ...)`` an argparse parser registers;
- the global half parses the README regions fenced by HTML markers

  .. code-block:: markdown

     <!-- mstcheck:metrics -->            ... | `mst_x` | ... |
     <!-- /mstcheck:metrics -->
     <!-- mstcheck:flags path/to/module.py -->   ... | `--flag` | ... |
     <!-- /mstcheck:flags -->

  and reports any name present in exactly one side. A flags region whose
  module was not part of this scan is skipped (a ``--changed`` or
  single-file run must not fabricate drift).

The gate arms only when the scan saw at least one metrics-bearing file
and a README sits next to the scanned tree — fixture and tmp-dir scans
never trip it.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Optional

from mlx_sharding_tpu.analysis.core import Finding, ModuleInfo, dotted_name

_TYPE_RE = re.compile(r"#\s*TYPE\s+(mst_\w+)\s+(?:counter|gauge|summary|"
                      r"histogram)")
_METRIC_TOKEN_RE = re.compile(r"`(mst_\w+)`")
_FLAG_TOKEN_RE = re.compile(r"`(--[\w][\w-]*)`")
_METRICS_OPEN = "<!-- mstcheck:metrics -->"
_METRICS_CLOSE = "<!-- /mstcheck:metrics -->"
_FLAGS_OPEN_RE = re.compile(r"<!--\s*mstcheck:flags\s+(\S+)\s*-->")
_FLAGS_CLOSE = "<!-- /mstcheck:flags -->"


def module_facts(mod: ModuleInfo) -> dict:
    """Live inventory of one file: emittable metric families + CLI flags."""
    metrics: set = set()
    flags: set = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for m in _TYPE_RE.finditer(node.value):
                metrics.add(m.group(1))
        elif isinstance(node, ast.Call):
            fn = dotted_name(node.func)
            leaf = fn.split(".")[-1] if fn else ""
            if leaf == "render_into":
                for a in node.args:
                    if (isinstance(a, ast.Constant)
                            and isinstance(a.value, str)
                            and a.value.startswith("mst_")):
                        metrics.add(a.value)
            elif leaf == "add_argument":
                for a in node.args:
                    if (isinstance(a, ast.Constant)
                            and isinstance(a.value, str)
                            and a.value.startswith("--")):
                        flags.add(a.value)
    return {"metrics": sorted(metrics), "flags": sorted(flags)}


def find_readme(paths: list) -> Optional[Path]:
    """README.md in (or one level above) the first scanned directory."""
    for p in paths:
        base = Path(p)
        if not base.is_dir():
            base = base.parent
        for cand in (base / "README.md", base.parent / "README.md"):
            if cand.is_file():
                return cand
    return None


def _drift(rule_path: str, line: int, table: str, missing: list,
           extra: list) -> list:
    findings = []
    if missing:
        names = ", ".join(f"`{n}`" for n in missing[:8])
        more = f" (+{len(missing) - 8} more)" if len(missing) > 8 else ""
        findings.append(Finding(
            "MST005", rule_path, line, 0,
            f"doc drift: {table} table is missing {names}{more} — the "
            "code emits them, the README does not document them",
            context=table))
    if extra:
        names = ", ".join(f"`{n}`" for n in extra[:8])
        more = f" (+{len(extra) - 8} more)" if len(extra) > 8 else ""
        findings.append(Finding(
            "MST005", rule_path, line, 0,
            f"doc drift: {table} table documents {names}{more} but the "
            "code no longer emits them — delete the rows or restore the "
            "code",
            context=table))
    return findings


def global_check(doc_facts_by_path: dict,
                 readme: Optional[Path]) -> list:
    """Compare README marker regions against the scan's live inventory."""
    live_metrics: set = set()
    flags_by_path: dict[str, set] = {}
    for path, facts in doc_facts_by_path.items():
        live_metrics.update(facts["metrics"])
        if facts["flags"]:
            flags_by_path[path] = set(facts["flags"])

    if not live_metrics or readme is None or not readme.is_file():
        return []  # not a repo-shaped scan: fixture/tmp trees stay silent
    try:
        lines = readme.read_text(encoding="utf-8").splitlines()
    except OSError:
        return []
    rule_path = readme.as_posix()

    findings: list = []
    # ---- metrics region
    open_line = close_line = None
    documented: set = set()
    for i, text in enumerate(lines, 1):
        if _METRICS_OPEN in text:
            open_line = i
        elif _METRICS_CLOSE in text and open_line is not None:
            close_line = i
            break
        elif open_line is not None:
            documented.update(_METRIC_TOKEN_RE.findall(text))
    if open_line is None or close_line is None:
        findings.append(Finding(
            "MST005", rule_path, 1, 0,
            f"README has no metrics table marked with {_METRICS_OPEN} … "
            f"{_METRICS_CLOSE} — the doc-drift gate cannot check the "
            "metric reference",
            context="metrics"))
    else:
        findings += _drift(rule_path, open_line, "metrics",
                           sorted(live_metrics - documented),
                           sorted(documented - live_metrics))

    # ---- flags regions (one per parser-bearing module)
    region_mod = region_line = None
    region_flags: set = set()
    for i, text in enumerate(lines, 1):
        m = _FLAGS_OPEN_RE.search(text)
        if m:
            region_mod, region_line, region_flags = m.group(1), i, set()
            continue
        if _FLAGS_CLOSE in text and region_mod is not None:
            live = [flags_by_path[p] for p in flags_by_path
                    if p.endswith(region_mod)]
            if live:  # module not in this scan -> no verdict
                live_flags = set().union(*live)
                findings += _drift(
                    rule_path, region_line, f"flags[{region_mod}]",
                    sorted(live_flags - region_flags),
                    sorted(region_flags - live_flags))
            region_mod = None
            continue
        if region_mod is not None:
            region_flags.update(_FLAG_TOKEN_RE.findall(text))
    return findings
