"""Runtime companion to mstcheck: named locks + dynamic lock-order recording.

The serving modules construct their locks through :func:`make_lock`, naming
each one with the same ``ClassName.attr`` vocabulary the static analyzer
uses for its lock-order graph. In normal operation ``make_lock`` returns a
plain ``threading.Lock`` — zero overhead. When a test calls
:func:`enable_tracing` first, subsequently constructed locks are
instrumented: every acquire records "<held> -> <acquired>" edges into a
:class:`LockOrderRecorder`, giving the *dynamic* lock-order graph actually
exercised by a workload. ``tests/test_lock_order_dynamic.py`` drives the
resilience-style workload under tracing and asserts the dynamic graph is
acyclic and never reverses a static edge.

The same pattern covers shared-state races: :func:`enable_locksets` arms
an Eraser-style :class:`LocksetRecorder`, and :func:`watch_attrs` swaps an
instance's class for a shim whose ``__setattr__`` reports every attribute
write together with the locks the writing thread holds (the
``_held_stack`` the instrumented locks already maintain) and the writer's
thread *role* (``analysis.thread_roles`` maps thread names — the registry
the static MST50x pass propagates). ``tests/test_lockset_dynamic.py``
drives real workloads under it and asserts the dynamic observations never
contradict the static per-attribute race verdicts.

This module imports only ``threading`` and ``collections`` at module
level so production modules can depend on it without cycles or
heavyweight imports; the role registry is imported lazily when a test
arms the lockset recorder.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

_TRACE: Optional["LockOrderRecorder"] = None
_TLS = threading.local()  # per-thread stack of held instrumented-lock names


class LockOrderRecorder:
    """Accumulates (held, acquired) lock-order edges across all threads."""

    def __init__(self):
        self._mu = threading.Lock()
        self._edges: dict[tuple, int] = {}

    def record(self, held: tuple, acquired: str):
        with self._mu:
            for h in held:
                if h != acquired:
                    key = (h, acquired)
                    self._edges[key] = self._edges.get(key, 0) + 1

    def edges(self) -> set:
        with self._mu:
            return set(self._edges)

    def find_cycle(self, extra_edges: set = frozenset()) -> Optional[list]:
        """A node list forming a cycle in edges ∪ extra_edges, or None."""
        graph: dict[str, set] = {}
        for src, dst in self.edges() | set(extra_edges):
            graph.setdefault(src, set()).add(dst)
            graph.setdefault(dst, set())
        color: dict[str, int] = {}
        stack: list[str] = []

        def dfs(u):
            color[u] = 1
            stack.append(u)
            for v in sorted(graph[u]):
                if color.get(v, 0) == 0:
                    found = dfs(v)
                    if found:
                        return found
                elif color[v] == 1:
                    return stack[stack.index(v):] + [v]
            color[u] = 2
            stack.pop()
            return None

        for u in sorted(graph):
            if color.get(u, 0) == 0:
                found = dfs(u)
                if found:
                    return found
        return None


def _held_stack() -> list:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


class InstrumentedLock:
    """threading.Lock wrapper that reports acquisition order to a recorder."""

    def __init__(self, name: str, recorder: LockOrderRecorder):
        self.name = name
        self._recorder = recorder
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._recorder.record(tuple(_held_stack()), self.name)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _held_stack().append(self.name)
        return ok

    def release(self):
        self._inner.release()
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"InstrumentedLock({self.name!r})"


def make_lock(name: str):
    """A lock for the serving layer, named for the lock-order graphs.

    Returns a plain ``threading.Lock`` unless tracing is enabled, in which
    case locks constructed from here on are instrumented. ``name`` should
    be the static graph's node name (``ClassName.attr``).
    """
    recorder = _TRACE
    if recorder is None:
        return threading.Lock()
    return InstrumentedLock(name, recorder)


def enable_tracing() -> LockOrderRecorder:
    """Instrument locks constructed after this call; returns the recorder."""
    global _TRACE
    _TRACE = LockOrderRecorder()
    return _TRACE


def disable_tracing():
    global _TRACE
    _TRACE = None


# --------------------------------------------------- dynamic locksets
_LOCKSETS: Optional["LocksetRecorder"] = None


class LocksetRecorder:
    """Eraser-style per-attribute candidate-lockset recorder.

    For every watched write it notes the writer's thread role (via the
    MST50x role registry), its thread ident, and the instrumented locks
    held. Per ``Cls.attr`` it keeps the Eraser phases: accesses by the
    first thread alone are the *exclusive* (initialization) phase and
    refine nothing; once a second thread touches the attr it is *shared*
    and every further write intersects the candidate lockset C(v). An
    attr is reported racy when it was written from two roles (or twice
    from one multi-instance role on distinct threads) and C(v) emptied —
    the same verdict shape the static pass emits, so the two can be
    compared key-by-key.
    """

    def __init__(self):
        from mlx_sharding_tpu.analysis.thread_roles import (
            CONCURRENT_ROLES,
            role_for_thread_name,
        )
        self._mu = threading.Lock()
        self._role_for = role_for_thread_name
        self._concurrent = CONCURRENT_ROLES
        self._attrs: dict[str, dict] = {}

    def record(self, cls_name: str, attr: str, *, write: bool = True):
        if attr.startswith("__"):
            return
        ident = threading.get_ident()
        role = self._role_for(threading.current_thread().name) or "api"
        held = frozenset(_held_stack())
        key = f"{cls_name}.{attr}"
        with self._mu:
            st = self._attrs.get(key)
            if st is None:
                st = self._attrs[key] = {
                    "first": ident, "shared": False,
                    "roles": set(), "writers": set(), "lockset": None,
                }
            st["roles"].add(role)
            if ident != st["first"]:
                st["shared"] = True
            if write:
                st["writers"].add((role, ident))
                if st["shared"]:
                    st["lockset"] = (held if st["lockset"] is None
                                     else st["lockset"] & held)

    def observations(self) -> dict:
        """``Cls.attr`` -> {roles, lockset, racy} for every shared attr."""
        out = {}
        with self._mu:
            for key, st in self._attrs.items():
                if not st["shared"]:
                    continue
                wroles = {r for r, _ in st["writers"]}
                multi = len(wroles) >= 2 or any(
                    r in self._concurrent and sum(
                        1 for wr, _ in st["writers"] if wr == r) >= 2
                    for r in wroles)
                lockset = st["lockset"] or frozenset()
                out[key] = {
                    "roles": sorted(st["roles"]),
                    "lockset": sorted(lockset),
                    "racy": bool(multi and not lockset and st["writers"]),
                }
        return out

    def racy(self) -> dict:
        return {k: v for k, v in self.observations().items() if v["racy"]}


# dynamic-subclass cache: base class -> watching shim class
_WATCHED: dict = {}


def watch_attrs(obj):
    """Swap ``obj``'s class for a shim reporting attribute writes to the
    lockset recorder. A no-op (returns ``obj`` unchanged) when no
    recorder is armed, so call sites can wrap unconditionally."""
    if _LOCKSETS is None:
        return obj
    base = type(obj)
    sub = _WATCHED.get(base)
    if sub is None:

        def _setattr(self, name, value, _cls=base.__name__):
            rec = _LOCKSETS
            if rec is not None:
                rec.record(_cls, name, write=True)
            object.__setattr__(self, name, value)

        sub = type(f"_Watched_{base.__name__}", (base,), {
            "__slots__": (), "__setattr__": _setattr})
        _WATCHED[base] = sub
    obj.__class__ = sub
    return obj


def enable_locksets() -> LocksetRecorder:
    """Arm the dynamic lockset recorder; returns it. Pair with
    :func:`enable_tracing` so lock acquisitions feed the held stack."""
    global _LOCKSETS
    _LOCKSETS = LocksetRecorder()
    return _LOCKSETS


def disable_locksets():
    global _LOCKSETS
    _LOCKSETS = None


# --------------------------------------------------------- leak ledger
# Runtime cross-check for the static MST40x verifier, in the same shape
# as make_lock/_TRACE: a module global that is None in production (the
# note_* hooks are a single global read, then return) and a live
# ResourceLedger under test. Serving modules report acquire/release of
# every registry handle kind (analysis/resources.py); a test drives the
# real composed stack, then asserts zero live handles at teardown —
# mirroring how test_lock_order_dynamic.py validates the static lock
# graph with a dynamically recorded one.

_RESOURCES: Optional["ResourceLedger"] = None


class ResourceLedger:
    """Live-handle shadow ledger: every acquire must meet its release.

    Keys are (kind, key) where ``kind`` comes from the resource registry
    and ``key`` identifies one handle (``id(lease)``, ``(id(batcher),
    slot)``, ...). Anomalies — release of a handle that isn't live, or a
    second acquire of a live key — are recorded, never raised, so the
    workload runs to completion and the test reports everything at once.
    The anomaly log is a bounded ring (``ANOMALY_RING``): a pathological
    double-release loop keeps the newest entries instead of growing the
    list without bound; ``anomalies_total`` keeps the true count (and is
    exported as ``mst_ledger_anomalies_total``).
    """

    ANOMALY_RING = 256

    def __init__(self):
        self._mu = threading.Lock()
        self._live: dict[tuple, dict] = {}
        self._acquired: dict[str, int] = {}
        self._released: dict[str, int] = {}
        self._anomalies: deque = deque(maxlen=self.ANOMALY_RING)
        self.anomalies_total = 0

    def _anomaly(self, msg: str):
        # caller holds self._mu
        self._anomalies.append(msg)
        self.anomalies_total += 1

    def note_acquire(self, kind: str, key, **meta):
        with self._mu:
            k = (kind, key)
            if k in self._live:
                self._anomaly(
                    f"double acquire of live handle {kind}:{key!r} {meta!r}")
            self._live[k] = meta
            self._acquired[kind] = self._acquired.get(kind, 0) + 1

    def note_release(self, kind: str, key):
        with self._mu:
            if self._live.pop((kind, key), None) is None:
                self._anomaly(
                    f"release of non-live handle {kind}:{key!r} "
                    "(double release, or release without acquire)")
            self._released[kind] = self._released.get(kind, 0) + 1

    def note_reset(self, kind: str, match=None):
        """Bulk release: a container discarded its handles wholesale
        (tier ``clear()``/``close()``, store ``drop_owner``). ``match``
        filters on the handle key (callable key -> bool)."""
        with self._mu:
            for k in [k for k in self._live
                      if k[0] == kind and (match is None or match(k[1]))]:
                del self._live[k]
                self._released[kind] = self._released.get(kind, 0) + 1

    def live(self) -> dict:
        with self._mu:
            return dict(self._live)

    def counts(self) -> dict:
        with self._mu:
            kinds = set(self._acquired) | set(self._released)
            return {k: (self._acquired.get(k, 0), self._released.get(k, 0))
                    for k in sorted(kinds)}

    def anomalies(self) -> list:
        with self._mu:
            return list(self._anomalies)

    def assert_clean(self, ignore: tuple = ()):
        """Raise AssertionError naming every live handle and anomaly."""
        live = [f"  live {kind}:{key!r} {meta!r}"
                for (kind, key), meta in sorted(
                    self.live().items(), key=lambda kv: str(kv[0]))
                if kind not in ignore]
        problems = live + [f"  anomaly: {a}" for a in self.anomalies()]
        if problems:
            counts = ", ".join(f"{k}={a}/{r}"
                               for k, (a, r) in self.counts().items())
            raise AssertionError(
                f"leak ledger not clean at teardown ({counts}):\n"
                + "\n".join(problems))


def instrument_resources() -> ResourceLedger:
    """Track handle acquire/release from here on; returns the ledger."""
    global _RESOURCES
    _RESOURCES = ResourceLedger()
    return _RESOURCES


def deinstrument_resources():
    global _RESOURCES
    _RESOURCES = None


def note_acquire(kind: str, key, **meta):
    led = _RESOURCES
    if led is not None:
        led.note_acquire(kind, key, **meta)


def note_release(kind: str, key):
    led = _RESOURCES
    if led is not None:
        led.note_release(kind, key)


def note_reset(kind: str, match=None):
    led = _RESOURCES
    if led is not None:
        led.note_reset(kind, match)
