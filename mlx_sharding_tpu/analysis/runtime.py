"""Runtime companion to mstcheck: named locks + dynamic lock-order recording.

The serving modules construct their locks through :func:`make_lock`, naming
each one with the same ``ClassName.attr`` vocabulary the static analyzer
uses for its lock-order graph. In normal operation ``make_lock`` returns a
plain ``threading.Lock`` — zero overhead. When a test calls
:func:`enable_tracing` first, subsequently constructed locks are
instrumented: every acquire records "<held> -> <acquired>" edges into a
:class:`LockOrderRecorder`, giving the *dynamic* lock-order graph actually
exercised by a workload. ``tests/test_lock_order_dynamic.py`` drives the
resilience-style workload under tracing and asserts the dynamic graph is
acyclic and never reverses a static edge.

This module imports only ``threading`` so production modules can depend on
it without cycles or heavyweight imports.
"""

from __future__ import annotations

import threading
from typing import Optional

_TRACE: Optional["LockOrderRecorder"] = None
_TLS = threading.local()  # per-thread stack of held instrumented-lock names


class LockOrderRecorder:
    """Accumulates (held, acquired) lock-order edges across all threads."""

    def __init__(self):
        self._mu = threading.Lock()
        self._edges: dict[tuple, int] = {}

    def record(self, held: tuple, acquired: str):
        with self._mu:
            for h in held:
                if h != acquired:
                    key = (h, acquired)
                    self._edges[key] = self._edges.get(key, 0) + 1

    def edges(self) -> set:
        with self._mu:
            return set(self._edges)

    def find_cycle(self, extra_edges: set = frozenset()) -> Optional[list]:
        """A node list forming a cycle in edges ∪ extra_edges, or None."""
        graph: dict[str, set] = {}
        for src, dst in self.edges() | set(extra_edges):
            graph.setdefault(src, set()).add(dst)
            graph.setdefault(dst, set())
        color: dict[str, int] = {}
        stack: list[str] = []

        def dfs(u):
            color[u] = 1
            stack.append(u)
            for v in sorted(graph[u]):
                if color.get(v, 0) == 0:
                    found = dfs(v)
                    if found:
                        return found
                elif color[v] == 1:
                    return stack[stack.index(v):] + [v]
            color[u] = 2
            stack.pop()
            return None

        for u in sorted(graph):
            if color.get(u, 0) == 0:
                found = dfs(u)
                if found:
                    return found
        return None


def _held_stack() -> list:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


class InstrumentedLock:
    """threading.Lock wrapper that reports acquisition order to a recorder."""

    def __init__(self, name: str, recorder: LockOrderRecorder):
        self.name = name
        self._recorder = recorder
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._recorder.record(tuple(_held_stack()), self.name)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _held_stack().append(self.name)
        return ok

    def release(self):
        self._inner.release()
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"InstrumentedLock({self.name!r})"


def make_lock(name: str):
    """A lock for the serving layer, named for the lock-order graphs.

    Returns a plain ``threading.Lock`` unless tracing is enabled, in which
    case locks constructed from here on are instrumented. ``name`` should
    be the static graph's node name (``ClassName.attr``).
    """
    recorder = _TRACE
    if recorder is None:
        return threading.Lock()
    return InstrumentedLock(name, recorder)


def enable_tracing() -> LockOrderRecorder:
    """Instrument locks constructed after this call; returns the recorder."""
    global _TRACE
    _TRACE = LockOrderRecorder()
    return _TRACE


def disable_tracing():
    global _TRACE
    _TRACE = None
