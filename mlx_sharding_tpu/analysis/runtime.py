"""Runtime companion to mstcheck: named locks + dynamic lock-order recording.

The serving modules construct their locks through :func:`make_lock`, naming
each one with the same ``ClassName.attr`` vocabulary the static analyzer
uses for its lock-order graph. In normal operation ``make_lock`` returns a
plain ``threading.Lock`` — zero overhead. When a test calls
:func:`enable_tracing` first, subsequently constructed locks are
instrumented: every acquire records "<held> -> <acquired>" edges into a
:class:`LockOrderRecorder`, giving the *dynamic* lock-order graph actually
exercised by a workload. ``tests/test_lock_order_dynamic.py`` drives the
resilience-style workload under tracing and asserts the dynamic graph is
acyclic and never reverses a static edge.

This module imports only ``threading`` so production modules can depend on
it without cycles or heavyweight imports.
"""

from __future__ import annotations

import threading
from typing import Optional

_TRACE: Optional["LockOrderRecorder"] = None
_TLS = threading.local()  # per-thread stack of held instrumented-lock names


class LockOrderRecorder:
    """Accumulates (held, acquired) lock-order edges across all threads."""

    def __init__(self):
        self._mu = threading.Lock()
        self._edges: dict[tuple, int] = {}

    def record(self, held: tuple, acquired: str):
        with self._mu:
            for h in held:
                if h != acquired:
                    key = (h, acquired)
                    self._edges[key] = self._edges.get(key, 0) + 1

    def edges(self) -> set:
        with self._mu:
            return set(self._edges)

    def find_cycle(self, extra_edges: set = frozenset()) -> Optional[list]:
        """A node list forming a cycle in edges ∪ extra_edges, or None."""
        graph: dict[str, set] = {}
        for src, dst in self.edges() | set(extra_edges):
            graph.setdefault(src, set()).add(dst)
            graph.setdefault(dst, set())
        color: dict[str, int] = {}
        stack: list[str] = []

        def dfs(u):
            color[u] = 1
            stack.append(u)
            for v in sorted(graph[u]):
                if color.get(v, 0) == 0:
                    found = dfs(v)
                    if found:
                        return found
                elif color[v] == 1:
                    return stack[stack.index(v):] + [v]
            color[u] = 2
            stack.pop()
            return None

        for u in sorted(graph):
            if color.get(u, 0) == 0:
                found = dfs(u)
                if found:
                    return found
        return None


def _held_stack() -> list:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


class InstrumentedLock:
    """threading.Lock wrapper that reports acquisition order to a recorder."""

    def __init__(self, name: str, recorder: LockOrderRecorder):
        self.name = name
        self._recorder = recorder
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._recorder.record(tuple(_held_stack()), self.name)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _held_stack().append(self.name)
        return ok

    def release(self):
        self._inner.release()
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"InstrumentedLock({self.name!r})"


def make_lock(name: str):
    """A lock for the serving layer, named for the lock-order graphs.

    Returns a plain ``threading.Lock`` unless tracing is enabled, in which
    case locks constructed from here on are instrumented. ``name`` should
    be the static graph's node name (``ClassName.attr``).
    """
    recorder = _TRACE
    if recorder is None:
        return threading.Lock()
    return InstrumentedLock(name, recorder)


def enable_tracing() -> LockOrderRecorder:
    """Instrument locks constructed after this call; returns the recorder."""
    global _TRACE
    _TRACE = LockOrderRecorder()
    return _TRACE


def disable_tracing():
    global _TRACE
    _TRACE = None


# --------------------------------------------------------- leak ledger
# Runtime cross-check for the static MST40x verifier, in the same shape
# as make_lock/_TRACE: a module global that is None in production (the
# note_* hooks are a single global read, then return) and a live
# ResourceLedger under test. Serving modules report acquire/release of
# every registry handle kind (analysis/resources.py); a test drives the
# real composed stack, then asserts zero live handles at teardown —
# mirroring how test_lock_order_dynamic.py validates the static lock
# graph with a dynamically recorded one.

_RESOURCES: Optional["ResourceLedger"] = None


class ResourceLedger:
    """Live-handle shadow ledger: every acquire must meet its release.

    Keys are (kind, key) where ``kind`` comes from the resource registry
    and ``key`` identifies one handle (``id(lease)``, ``(id(batcher),
    slot)``, ...). Anomalies — release of a handle that isn't live, or a
    second acquire of a live key — are recorded, never raised, so the
    workload runs to completion and the test reports everything at once.
    """

    def __init__(self):
        self._mu = threading.Lock()
        self._live: dict[tuple, dict] = {}
        self._acquired: dict[str, int] = {}
        self._released: dict[str, int] = {}
        self._anomalies: list[str] = []

    def note_acquire(self, kind: str, key, **meta):
        with self._mu:
            k = (kind, key)
            if k in self._live:
                self._anomalies.append(
                    f"double acquire of live handle {kind}:{key!r} {meta!r}")
            self._live[k] = meta
            self._acquired[kind] = self._acquired.get(kind, 0) + 1

    def note_release(self, kind: str, key):
        with self._mu:
            if self._live.pop((kind, key), None) is None:
                self._anomalies.append(
                    f"release of non-live handle {kind}:{key!r} "
                    "(double release, or release without acquire)")
            self._released[kind] = self._released.get(kind, 0) + 1

    def note_reset(self, kind: str, match=None):
        """Bulk release: a container discarded its handles wholesale
        (tier ``clear()``/``close()``, store ``drop_owner``). ``match``
        filters on the handle key (callable key -> bool)."""
        with self._mu:
            for k in [k for k in self._live
                      if k[0] == kind and (match is None or match(k[1]))]:
                del self._live[k]
                self._released[kind] = self._released.get(kind, 0) + 1

    def live(self) -> dict:
        with self._mu:
            return dict(self._live)

    def counts(self) -> dict:
        with self._mu:
            kinds = set(self._acquired) | set(self._released)
            return {k: (self._acquired.get(k, 0), self._released.get(k, 0))
                    for k in sorted(kinds)}

    def anomalies(self) -> list:
        with self._mu:
            return list(self._anomalies)

    def assert_clean(self, ignore: tuple = ()):
        """Raise AssertionError naming every live handle and anomaly."""
        live = [f"  live {kind}:{key!r} {meta!r}"
                for (kind, key), meta in sorted(
                    self.live().items(), key=lambda kv: str(kv[0]))
                if kind not in ignore]
        problems = live + [f"  anomaly: {a}" for a in self.anomalies()]
        if problems:
            counts = ", ".join(f"{k}={a}/{r}"
                               for k, (a, r) in self.counts().items())
            raise AssertionError(
                f"leak ledger not clean at teardown ({counts}):\n"
                + "\n".join(problems))


def instrument_resources() -> ResourceLedger:
    """Track handle acquire/release from here on; returns the ledger."""
    global _RESOURCES
    _RESOURCES = ResourceLedger()
    return _RESOURCES


def deinstrument_resources():
    global _RESOURCES
    _RESOURCES = None


def note_acquire(kind: str, key, **meta):
    led = _RESOURCES
    if led is not None:
        led.note_acquire(kind, key, **meta)


def note_release(kind: str, key):
    led = _RESOURCES
    if led is not None:
        led.note_release(kind, key)


def note_reset(kind: str, match=None):
    led = _RESOURCES
    if led is not None:
        led.note_reset(kind, match)
