"""Thread-role inference for the MST5xx cross-thread race rules.

Every lock-discipline contract in the serving stack is really a statement
about *which threads* touch a piece of state. This module names those
threads: a **role** is a family of threads with one entry point — the
continuous-batcher tick loop, the HTTP handler pool, the spill flusher,
the autoscaler loop, the pod heartbeat, sim actors. The registry below is
the single vocabulary shared by

- the static half (:mod:`analysis.races`), which seeds roles at
  ``Thread(target=..., name="...")`` / ``sim.spawn(...)`` / ``do_*``
  handler sites and propagates them over the call graph, and
- the dynamic half (:class:`analysis.runtime.LocksetRecorder`), which maps
  ``threading.current_thread().name`` through the *same* table when it
  attributes an observed access — so a dynamic observation and a static
  verdict always speak about the same role.

Per-file extraction walks each class once (statement reachability comes
from :mod:`analysis.cfg` — code after a ``raise``/``return`` contributes
no accesses) and summarizes, per function, the ``self._attr`` read/write
sets with the locks held at each access, the outgoing calls the global
pass resolves, blocking calls made under a lock, and bare
``return self._attr`` publications. Nested ``def``s handed to
``Thread(target=run)`` are separate functions (``"start.run"``): their
bodies run on the spawned thread's role, not the spawner's.
"""

from __future__ import annotations

import ast
from typing import Optional

from mlx_sharding_tpu.analysis import cfg as cfglib
from mlx_sharding_tpu.analysis.core import ModuleInfo, dotted_name
from mlx_sharding_tpu.analysis.locks import MUTATORS, _find_locks

# ------------------------------------------------------------------ registry
# thread-name literal (exact) -> role. Names are the ones the serving
# modules pass to threading.Thread(name=...); keep in sync with the
# README's thread-role table (the MST005 doc gate does not check this one,
# the agreement test in tests/test_lockset_dynamic.py does better: it
# attributes real observed accesses through it).
ROLE_BY_THREAD_NAME = {
    "continuous-batcher": "tick",
    "kv-spill-flusher": "spill_flusher",
    "mst-autoscaler": "autoscaler",
    "mst-pod-fleet": "pod_heartbeat",
    "mst-pod-transport": "pod_transport",
    "mst-pod-serve": "pod_serve",
    "mst-ctrl": "ctrl",
    "mst-pod-ctrl": "ctrl",
}

# thread-name prefix -> role (f-string names: f"sim-{name}", "mst-drain-3")
ROLE_PREFIXES = (
    ("sim-", "sim_actor"),
    ("mst-drain", "drain_worker"),
)

# roles that run MANY concurrent instances: two threads of the same role
# still race with each other, so one access from such a role conflicts
# with itself. ``api`` (the public surface of a thread-owning class) is
# deliberately NOT here — external callers may or may not be concurrent,
# and claiming they are would flag every one-shot start()/configure().
# ``http_handler`` self-concurrency is applied per class: a
# BaseHTTPRequestHandler subclass gets a fresh instance per request, so
# its *own* attrs never alias; the shared objects its handlers call into
# do.
CONCURRENT_ROLES = frozenset({"sim_actor", "http_handler", "pod_serve",
                              "drain_worker"})


def role_for_thread_name(name: Optional[str]) -> Optional[str]:
    """Role for a live/literal thread name, or None if unregistered."""
    if not name:
        return None
    role = ROLE_BY_THREAD_NAME.get(name)
    if role:
        return role
    for prefix, prole in ROLE_PREFIXES:
        if name.startswith(prefix):
            return prole
    return None


# ------------------------------------------------------------ per-file scan
_CONTAINER_CALLS = {"dict", "list", "set", "defaultdict", "OrderedDict",
                    "deque", "Counter"}
# constructors whose instances carry their own synchronization: calling
# .put()/.get()/.wait() on one is not a data race on the *attribute*, and
# rebinding happens-before the consumer thread starts (Thread.start is a
# barrier). MST501/502/503 skip attrs bound to these.
_THREADSAFE_CALLS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                     "Event", "Condition", "Semaphore", "BoundedSemaphore",
                     "Barrier"}
_QUEUE_HINTS = ("queue", "inbox", "mailbox")
_SLEEP_NAMES = {"sleep", "virtual_sleep"}


def _self_attr(node: ast.AST) -> Optional[str]:
    d = dotted_name(node)
    if d and d.startswith("self.") and d.count(".") >= 1:
        return d.split(".")[1]
    return None


def _thread_name_literal(call: ast.Call) -> Optional[str]:
    """The name= literal of a Thread(...) call; f-string -> leading text."""
    for kw in call.keywords:
        if kw.arg != "name":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return v.value
        if isinstance(v, ast.JoinedStr) and v.values:
            head = v.values[0]
            if isinstance(head, ast.Constant) and isinstance(head.value, str):
                return head.value  # prefix is enough for ROLE_PREFIXES
    return None


def _is_queue_recv(recv: Optional[str]) -> bool:
    if not recv:
        return False
    leaf = recv.split(".")[-1].lower()
    return leaf.endswith("_q") or any(h in leaf for h in _QUEUE_HINTS)


_SIMPLE_STMTS = (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr,
                 ast.Return, ast.Raise, ast.Delete, ast.Assert)


def _scoped_walk(fn: ast.AST):
    """ast.walk that does not descend into nested function definitions."""
    todo = list(ast.iter_child_nodes(fn))
    while todo:
        node = todo.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            todo.extend(ast.iter_child_nodes(node))


def _unreachable_stmts(fn: ast.AST) -> set:
    """ids of simple statements the cfg proves unreachable (dead code
    after return/raise contributes no role facts)."""
    try:
        graph = cfglib.build_cfg(fn, may_raise=lambda node: True)
    except (RecursionError, ValueError):
        return set()
    seen, todo = {graph.entry}, [graph.entry]
    while todo:
        for dst, _kind in graph.nodes[todo.pop()].succ:
            if dst not in seen:
                seen.add(dst)
                todo.append(dst)
    reached = {id(graph.nodes[i].stmt) for i in seen
               if graph.nodes[i].stmt is not None}
    return {id(n) for n in _scoped_walk(fn)
            if isinstance(n, _SIMPLE_STMTS) and id(n) not in reached}


class _FuncScan:
    """One function-like body's facts, in cache-ready (JSON list) shape."""

    def __init__(self, public: bool, line: int):
        self.public = public
        self.line = line
        self.accesses: list = []      # [attr, write, line, [held...]]
        self.calls: list = []         # [recv, callee, line]
        self.locks_taken: set = set()
        self.blocking: list = []      # [kind, line, [held...]]
        self.returns_bare: list = []  # [attr, line]

    def as_dict(self) -> dict:
        return {
            "public": self.public,
            "line": self.line,
            "accesses": self.accesses,
            "calls": self.calls,
            "locks_taken": sorted(self.locks_taken),
            "blocking": self.blocking,
            "returns_bare": self.returns_bare,
        }


def _scan_class(mod: ModuleInfo, cls_node: ast.ClassDef) -> tuple[dict, list]:
    """(class facts, entries) for one class."""
    cls = cls_node.name
    locks = _find_locks(cls_node, cls)
    bases = [dotted_name(b) or "" for b in cls_node.bases]
    init_types: dict[str, str] = {}
    containers: set[str] = set()
    safe_attrs: set[str] = set()
    funcs: dict[str, _FuncScan] = {}
    entries: list[dict] = []

    handler = any("RequestHandler" in b for b in bases)

    def classify_assigns(method: ast.AST, is_init: bool):
        for node in ast.walk(method):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            for t in targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                if isinstance(value, ast.Call):
                    fn = dotted_name(value.func)
                    leaf = fn.split(".")[-1] if fn else ""
                    # a lazily-built self._work = Queue() in any method
                    # still marks the attr internally-synchronized
                    if leaf in _THREADSAFE_CALLS:
                        safe_attrs.add(attr)
                if not is_init:
                    continue
                if isinstance(value, (ast.Dict, ast.List, ast.Set,
                                      ast.ListComp, ast.DictComp,
                                      ast.SetComp)):
                    containers.add(attr)
                elif isinstance(value, ast.Call):
                    fn = dotted_name(value.func)
                    leaf = fn.split(".")[-1] if fn else ""
                    if leaf in _CONTAINER_CALLS:
                        containers.add(attr)
                    elif leaf and leaf[0].isupper():
                        init_types[attr] = leaf

    def resolve_target(arg: ast.AST, enclosing: str) -> list[str]:
        """Function keys a Thread/spawn target resolves to within this
        class: ``self._m`` -> ['_m']; nested-def name -> ['outer.name'];
        a lambda -> the self-methods its body calls."""
        if isinstance(arg, ast.Lambda):
            out = []
            for sub in ast.walk(arg.body):
                if isinstance(sub, ast.Call):
                    a = _self_attr(sub.func)
                    if a is not None:
                        out.append(a)
            return out
        a = _self_attr(arg)
        if a is not None:
            return [a]
        if isinstance(arg, ast.Name):
            return [f"{enclosing}.{arg.id}"]
        return []

    def scan_function(fn_node: ast.AST, path: str, public: bool):
        fs = funcs[path] = _FuncScan(public, fn_node.lineno)
        nested_here: set = set()
        dead = _unreachable_stmts(fn_node)

        def scan(node: ast.AST, held: tuple):
            if id(node) in dead:
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # separate function: runs on whatever thread calls/spawns it
                nested_here.add(node.name)
                scan_function(node, f"{path}.{node.name}", False)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                taken = []
                for item in node.items:
                    scan(item.context_expr, held)
                    attr = _self_attr(item.context_expr)
                    if attr is not None and attr in locks:
                        taken.append(locks[attr])
                    elif isinstance(item.context_expr, ast.Subscript):
                        base = _self_attr(item.context_expr.value)
                        if base is not None and base in locks:
                            taken.append(locks[base])
                fs.locks_taken.update(taken)
                inner = held + tuple(lk for lk in taken if lk not in held)
                for stmt in node.body:
                    scan(stmt, inner)
                return
            if isinstance(node, ast.Return) and node.value is not None:
                attr = _self_attr(node.value)
                if attr is not None and attr not in locks:
                    fs.returns_bare.append([attr, node.lineno])
            if isinstance(node, ast.Call):
                func = node.func
                callee, recv = None, ""
                if isinstance(func, ast.Attribute):
                    callee = func.attr
                    recv = dotted_name(func.value) or ""
                elif isinstance(func, ast.Name):
                    callee = func.id
                if callee:
                    fname = dotted_name(func) or callee
                    if fname.split(".")[-1] == "Thread" or fname == "Thread":
                        tname = _thread_name_literal(node)
                        role = role_for_thread_name(tname)
                        for kw in node.keywords:
                            if kw.arg == "target":
                                for key in resolve_target(kw.value, path):
                                    entries.append({
                                        "cls": cls, "func": key,
                                        "role": role or
                                        f"thread:{cls}.{key}",
                                        "line": node.lineno,
                                    })
                    elif callee == "spawn" and node.args:
                        for key in resolve_target(node.args[0], path):
                            entries.append({"cls": cls, "func": key,
                                            "role": "sim_actor",
                                            "line": node.lineno})
                    if callee in MUTATORS:
                        base = _self_attr(func.value) \
                            if isinstance(func, ast.Attribute) else None
                        if base is not None and base not in locks:
                            fs.accesses.append(
                                [base, 1, node.lineno, list(held)])
                    if isinstance(func, ast.Attribute) or recv == "":
                        fs.calls.append([recv, callee, node.lineno])
                    if held:
                        kind = None
                        if callee == "acquire":
                            kind = "lock acquire"
                        elif callee in ("wait", "join"):
                            kind = f"blocking {callee}()"
                        elif callee == "get" and _is_queue_recv(recv):
                            kind = "queue get"
                        elif callee in _SLEEP_NAMES:
                            kind = "clock sleep"
                        if kind:
                            fs.blocking.append([kind, node.lineno, list(held)])
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr not in locks):
                fs.accesses.append([
                    node.attr,
                    1 if isinstance(node.ctx, (ast.Store, ast.Del)) else 0,
                    node.lineno, list(held)])
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, (ast.Store, ast.Del))):
                base = _self_attr(node.value)
                if base is not None and base not in locks:
                    fs.accesses.append([base, 1, node.lineno, list(held)])
            for child in ast.iter_child_nodes(node):
                scan(child, held)

        for stmt in fn_node.body:
            scan(stmt, ())

        # bare local calls to sibling nested defs resolve right here
        for c in fs.calls:
            if c[0] == "" and c[1] in nested_here:
                c[1] = f"{path}.{c[1]}"

    for method in cls_node.body:
        if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        classify_assigns(method, method.name == "__init__")
        public = not method.name.startswith("_")
        scan_function(method, method.name, public)
        if handler and method.name.startswith("do_"):
            entries.append({"cls": cls, "func": method.name,
                            "role": "http_handler", "line": method.lineno})

    facts = {
        "bases": bases,
        "locks": locks,
        "init_types": init_types,
        "containers": sorted(containers),
        "safe_attrs": sorted(safe_attrs),
        "funcs": {k: v.as_dict() for k, v in funcs.items()},
    }
    return facts, entries


def module_facts(mod: ModuleInfo) -> dict:
    """Per-file half: JSON-safe role facts for the incremental cache."""
    classes: dict[str, dict] = {}
    entries: list[dict] = []
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef):
            facts, cls_entries = _scan_class(mod, node)
            classes[node.name] = facts
            entries.extend(cls_entries)
    return {"entries": entries, "classes": classes}


# ------------------------------------------------------- global propagation
def propagate(facts_by_path: dict) -> dict:
    """Roles per function: ``(path, cls, func) -> set of role names``.

    Seeds at the thread entry points each file reported, adds the ``api``
    role to the public surface of every thread-owning class (any caller
    thread may enter there), then closes over the call graph: ``self.m()``
    stays in-class, ``self.attr.m()`` follows the one-level ``__init__``
    type inference when the attribute's class name is globally unique,
    nested defs resolve to their dotted key.
    """
    # class name -> [(path, cls)] for cross-class receiver resolution
    cls_index: dict[str, list] = {}
    for path, facts in facts_by_path.items():
        for cls in facts["classes"]:
            cls_index.setdefault(cls, []).append(path)

    roles: dict[tuple, set] = {}
    work: list[tuple] = []

    def add(path: str, cls: str, func: str, new_roles: set):
        fcls = facts_by_path[path]["classes"].get(cls)
        if fcls is None:
            return
        if func not in fcls["funcs"]:
            # a target like "_serve" may be nested; try dotted suffixes
            cands = [k for k in fcls["funcs"]
                     if k == func or k.endswith("." + func)]
            if len(cands) != 1:
                return
            func = cands[0]
        key = (path, cls, func)
        cur = roles.setdefault(key, set())
        missing = new_roles - cur
        if missing:
            cur |= missing
            work.append(key)

    for path, facts in facts_by_path.items():
        for e in facts["entries"]:
            add(path, e["cls"], e["func"], {e["role"]})
        # the public surface of a thread-owning class is reachable from
        # arbitrary caller threads
        owning = {e["cls"] for e in facts["entries"]}
        for cls in owning:
            fcls = facts["classes"].get(cls)
            if fcls is None:
                continue
            for func, ff in fcls["funcs"].items():
                if ff["public"] and not func.startswith("do_"):
                    add(path, cls, func, {"api"})

    for _ in range(100_000):  # bounded fixpoint; each pop shrinks work
        if not work:
            break
        path, cls, func = work.pop()
        key_roles = roles[(path, cls, func)]
        fcls = facts_by_path[path]["classes"][cls]
        ff = fcls["funcs"].get(func)
        if ff is None:
            continue
        for recv, callee, _line in ff["calls"]:
            if recv == "self":
                add(path, cls, callee, key_roles)
            elif recv == "" and "." in callee:
                add(path, cls, callee, key_roles)  # nested def
            elif recv.startswith("self.") and recv.count(".") == 1:
                attr = recv.split(".")[1]
                tcls = fcls["init_types"].get(attr)
                if tcls and len(cls_index.get(tcls, ())) == 1:
                    add(cls_index[tcls][0], tcls, callee, key_roles)
    return roles


def role_table() -> list[dict]:
    """The registry as rows (for ``--format json`` and the README table)."""
    rows = [{"thread_name": k, "role": v, "match": "exact"}
            for k, v in sorted(ROLE_BY_THREAD_NAME.items())]
    rows += [{"thread_name": p + "*", "role": r, "match": "prefix"}
             for p, r in ROLE_PREFIXES]
    return rows
