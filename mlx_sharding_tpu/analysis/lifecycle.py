"""Stream/resource-lifecycle rules (MST30x).

- **MST301 generator-leak** — a generator function that acquires a resource
  (``.acquire()``, ``._pick()``, ``alloc*``/``reserve*``/``open_*`` calls)
  but yields outside any ``try`` with a ``finally`` or a ``GeneratorExit``
  handler. A consumer dropping the stream mid-flight (client disconnect →
  ``it.close()``) then skips the release — the PR-2 probe-ticket bug.
- **MST302 alloc-leak-on-raise** — a resource is allocated (``.pop()`` from
  a free/pool/pages list, or an ``alloc*``/``acquire*``/``reserve*`` call)
  and a later ``raise`` in the same function can exit before any release
  (``free*``/``release*`` or ``.append()`` back onto the pool) with no
  ``try/finally`` in between: the page/slot leaks on the error path.
- **MST303 unknown-fault-site** — ``inject("<site>")`` with a site string
  not in the registered set; a typo here silently never fires.
- **MST304 missing-fault-site** — a serving module that must carry its
  fault-injection hook (``testing/faults.py`` contract) no longer calls
  ``inject()`` with its site string; the resilience suite would silently
  stop exercising that failure domain.
"""

from __future__ import annotations

import ast
from typing import Optional

from mlx_sharding_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    dotted_name,
    qualname_for_line,
)

ACQUIRE_NAMES = {"acquire", "_pick"}
ACQUIRE_PREFIXES = ("alloc", "acquire", "reserve", "open_")
RELEASE_PREFIXES = ("release", "free", "_done")
POOL_HINTS = ("free", "pool", "pages", "slots")

KNOWN_FAULT_SITES = {
    "scheduler.tick", "scheduler.harvest", "replica.dispatch",
    "multihost.exchange", "server.sse_write",
    # KV migration (kv_transfer.py): block export at preemption/drain,
    # block import at resume, the overlapped prefetch stage, and the
    # replica drain entry point
    "cache.export", "cache.import", "cache.prefetch", "replica.drain",
    # elastic fleet (fleet.py): autoscaler control tick and the
    # ReplicaFactory spawn call — both must degrade to the static fleet
    "autoscaler.tick", "replica.spawn",
    # disaggregated serving (disagg.py): the prefill→decode handoff
    # control point — must degrade to serve-in-place, never drop a stream
    "disagg.handoff",
    # content-addressed prefix store (prefix_store.py): the admission-time
    # LPM probe — must degrade to plain prefill, never a wrong stream
    "cache.prefix_lookup",
    # pod fleet (pod.py): the cross-host prefill→decode handoff control
    # point — must degrade to the single-host plan (serve-in-place or
    # blockless re-prefill), never a dropped stream
    "pod.handoff",
    # pod prefix federation (pod.py): the cross-host prefix blob fetch on
    # a local store miss — must degrade to plain prefill, counted, never
    # a wrong or dropped stream
    "pod.prefix_fetch",
    # speculative decoding (scheduler.py / speculative.py): before each
    # round's draft proposals — a faulted draft source must degrade that
    # tick to plain decode, counted, never a wrong or dropped stream
    "spec.draft",
    # compressed-latent KV transport (kv_compress.py): every codec
    # encode/decode — a faulted encode ships the block raw (counted), a
    # faulted decode lands on the consumer's counted re-prefill path;
    # neither may drop or corrupt a stream
    "cache.compress",
}
# basename -> the inject() sites that file must keep calling (a file can
# own more than one failure domain — the scheduler carries both the tick
# wedge and the speculative draft-degradation hook)
REQUIRED_FAULT_SITES = {
    "scheduler.py": ("scheduler.tick", "spec.draft"),
    "replicas.py": ("replica.dispatch",),
    "multihost.py": ("multihost.exchange",),
    "openai_api.py": ("server.sse_write",),
    "fleet.py": ("autoscaler.tick",),
    "kv_transfer.py": ("cache.export",),
    "disagg.py": ("disagg.handoff",),
    "prefix_store.py": ("cache.prefix_lookup",),
    "pod.py": ("pod.handoff", "pod.prefix_fetch"),
    "kv_compress.py": ("cache.compress",),
}


def _own_nodes(fn: ast.AST):
    """Walk ``fn`` without descending into nested function definitions."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_generator(fn: ast.AST) -> bool:
    return any(isinstance(n, (ast.Yield, ast.YieldFrom))
               for n in _own_nodes(fn))


def _call_name(node: ast.Call) -> Optional[str]:
    name = dotted_name(node.func)
    return name.split(".")[-1] if name else None


def _is_acquire(node: ast.Call) -> bool:
    bare = _call_name(node)
    if bare is None:
        return False
    return bare in ACQUIRE_NAMES or bare.startswith(ACQUIRE_PREFIXES)


def _is_release(node: ast.Call) -> bool:
    bare = _call_name(node)
    if bare is None:
        return False
    if bare.startswith(RELEASE_PREFIXES):
        return True
    if bare == "append" and isinstance(node.func, ast.Attribute):
        base = dotted_name(node.func.value) or ""
        return any(h in base for h in POOL_HINTS)
    return False


def _is_pool_pop(node: ast.Call) -> bool:
    if not (isinstance(node.func, ast.Attribute) and node.func.attr == "pop"):
        return False
    base = dotted_name(node.func.value) or ""
    return any(h in base for h in POOL_HINTS)


def _try_protects(t: ast.Try) -> bool:
    if t.finalbody:
        return True
    for h in t.handlers:
        if h.type is None:
            return True  # bare except catches BaseException incl. GeneratorExit
        name = dotted_name(h.type)
        if name in ("GeneratorExit", "BaseException"):
            return True
        if isinstance(h.type, ast.Tuple):
            for elt in h.type.elts:
                if dotted_name(elt) in ("GeneratorExit", "BaseException"):
                    return True
    return False


def _check_generators(mod: ModuleInfo) -> list[Finding]:
    findings = []
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _is_generator(fn):
            continue
        acquires = [n for n in _own_nodes(fn)
                    if isinstance(n, ast.Call) and _is_acquire(n)]
        if not acquires:
            continue

        unprotected: list[ast.AST] = []

        def scan(node, protected):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, (ast.Yield, ast.YieldFrom)) and not protected:
                unprotected.append(node)
            if isinstance(node, ast.Try):
                inner = protected or _try_protects(node)
                for stmt in node.body + node.orelse:
                    scan(stmt, inner)
                # handler/finally bodies run during unwinding: treat as safe
                for h in node.handlers:
                    for stmt in h.body:
                        scan(stmt, True)
                for stmt in node.finalbody:
                    scan(stmt, True)
                return
            for child in ast.iter_child_nodes(node):
                scan(child, protected)

        for stmt in fn.body:
            scan(stmt, False)
        if unprotected:
            node = min(unprotected, key=lambda n: (n.lineno, n.col_offset))
            findings.append(Finding(
                "MST301", mod.display_path, node.lineno, node.col_offset,
                f"generator {fn.name}() acquires a resource but yields "
                "outside try/finally or a GeneratorExit handler — a dropped "
                "stream (it.close()) leaks the resource",
                context=qualname_for_line(mod.tree, node.lineno)))
    return findings


def _check_alloc_paths(mod: ModuleInfo) -> list[Finding]:
    findings = []
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        allocs: list[int] = []
        releases: list[int] = []
        raises: list[ast.Raise] = []

        def scan(node, protected):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.Call):
                if _is_pool_pop(node) or _is_acquire(node):
                    allocs.append(node.lineno)
                elif _is_release(node):
                    releases.append(node.lineno)
            if isinstance(node, ast.Raise) and not protected:
                raises.append(node)
            if isinstance(node, ast.Try):
                inner = protected or bool(node.finalbody)
                for stmt in node.body + node.orelse:
                    scan(stmt, inner)
                for h in node.handlers:
                    for stmt in h.body:
                        scan(stmt, inner)
                for stmt in node.finalbody:
                    scan(stmt, protected)
                return
            for child in ast.iter_child_nodes(node):
                scan(child, protected)

        for stmt in fn.body:
            scan(stmt, False)
        if not allocs or not raises:
            continue
        first_alloc = min(allocs)
        flagged = False
        for r in sorted(raises, key=lambda n: n.lineno):
            if r.lineno <= first_alloc:
                continue
            released_before = any(first_alloc < rel < r.lineno
                                  for rel in releases)
            if not released_before and not flagged:
                findings.append(Finding(
                    "MST302", mod.display_path, r.lineno, r.col_offset,
                    f"{fn.name}() allocates from a pool then raises before "
                    "any release on this path — the resource leaks on the "
                    "error exit (wrap in try/finally or release first)",
                    context=qualname_for_line(mod.tree, r.lineno)))
                flagged = True  # one finding per function is enough signal
    return findings


def _check_fault_sites(mod: ModuleInfo) -> list[Finding]:
    findings = []
    called_sites: set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if not name or name.split(".")[-1] != "inject":
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        site = node.args[0].value
        called_sites.add(site)
        if site not in KNOWN_FAULT_SITES:
            findings.append(Finding(
                "MST303", mod.display_path, node.lineno, node.col_offset,
                f"unknown fault-injection site {site!r} — not in the "
                "registered set, so it can never be armed",
                context=qualname_for_line(mod.tree, node.lineno)))
    required = REQUIRED_FAULT_SITES.get(mod.basename, ())
    missing = [s for s in required if s not in called_sites]
    if missing:
        # one finding per file, naming every dropped site — a module that
        # loses two hooks is one regression, not two
        sites = ", ".join(repr(s) for s in missing)
        findings.append(Finding(
            "MST304", mod.display_path, 1, 0,
            f"{mod.basename} must call inject() with site(s) {sites} so "
            "the resilience suite keeps exercising this failure domain",
            context="<module>"))
    return findings


def check_module(mod: ModuleInfo) -> list[Finding]:
    return (_check_generators(mod) + _check_alloc_paths(mod)
            + _check_fault_sites(mod))
