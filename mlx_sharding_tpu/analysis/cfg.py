"""Per-function control-flow graphs for the MST40x lifecycle verifier.

Builds a statement-level CFG from the Python AST with the edges that
matter for must-release analysis:

- branches (``if``/``while``/``for`` tests carry ``true``/``false`` edge
  kinds so the interpreter can refine ``x is None`` checks per arm);
- loops (back edges; bodies are traversed 0 or 1 times by the path
  enumerator — a bounded unrolling that catches acquire/release pairing
  without fixpoint iteration);
- ``try``/``except``/``finally`` with real unwind semantics: exception
  edges from raising statements dispatch to handler entries; ``finally``
  bodies are *inlined* per abrupt exit (return / raise / break /
  continue / fall-through each get their own instantiation, exactly like
  the bytecode compiler duplicates FINALLY blocks), so a release inside a
  ``finally`` is visible on every path that crosses it;
- ``with`` blocks as try/finally sugar: a synthetic ``with_exit`` node
  releases the ``as`` target on every exit path, including unwinds;
- ``return``/``raise`` edges to the function's normal/exceptional exits;
- generator semantics: every ``yield`` gets a ``genexit`` edge — the
  consumer may ``close()`` the generator there, raising ``GeneratorExit``
  at the yield point, which only bare / ``BaseException`` /
  ``GeneratorExit`` handlers (or a ``finally``) intercept.

Nodes hold references to the original AST statements; the same AST node
may back several CFG nodes (finally inlining). The graph is pure
structure — which calls can raise is the caller's policy, injected via
the ``may_raise`` predicate.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Callable, Optional

# edge kinds
NEXT = "next"          # sequential flow
TRUE = "true"          # branch taken
FALSE = "false"        # branch not taken
EXC = "exc"            # exception unwind
GENEXIT = "genexit"    # GeneratorExit raised at a yield
BACK = "back"          # loop back edge


@dataclass
class Node:
    idx: int
    kind: str            # "entry","exit","raise","stmt","branch","loop",
    #                      "with_exit","dispatch","yield"
    stmt: Optional[ast.AST] = None   # backing AST node (stmt or expr)
    line: int = 0
    succ: list = field(default_factory=list)   # [(dst_idx, edge_kind)]

    def __repr__(self):  # debugging aid only
        return f"<{self.idx}:{self.kind}@{self.line}>"


@dataclass
class CFG:
    nodes: list
    entry: int
    exit: int          # normal exit (fall-off / return)
    raise_exit: int    # exception leaves the function
    is_generator: bool = False


@dataclass
class _Frame:
    kind: str                       # "try" | "finally" | "with" | "loop"
    # try:
    dispatch: Optional[int] = None  # exception dispatch node
    catches_all: bool = False       # bare / BaseException / Exception
    catches_genexit: bool = False   # bare / BaseException / GeneratorExit
    # finally:
    stmts: Optional[list] = None
    # with: the withitem whose __exit__ runs on unwind
    item: Optional[ast.withitem] = None
    # loop:
    head: Optional[int] = None
    breaks: Optional[list] = None   # frontier entries collected by break
    # try: whether the dispatch node already has its outward unwind route
    escalated: bool = False


_BROAD = {"BaseException", "Exception"}
_GENEXIT_OK = {"BaseException", "GeneratorExit"}


def _handler_names(h: ast.ExceptHandler) -> list:
    if h.type is None:
        return ["*"]
    names = []
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    for t in types:
        parts = []
        n = t
        while isinstance(n, ast.Attribute):
            parts.append(n.attr)
            n = n.value
        if isinstance(n, ast.Name):
            parts.append(n.id)
        names.append(".".join(reversed(parts)) if parts else "?")
    return names


class _Builder:
    def __init__(self, may_raise: Callable[[ast.AST], bool]):
        self.nodes: list[Node] = []
        self.frames: list[_Frame] = []
        self.may_raise = may_raise
        self.is_generator = False
        self._budget = 4000  # node cap: give up on pathological functions

    # ------------------------------------------------------------ helpers
    def new(self, kind: str, stmt: Optional[ast.AST] = None) -> int:
        if len(self.nodes) >= self._budget:
            raise _Overflow()
        line = getattr(stmt, "lineno", 0) if stmt is not None else 0
        if not line and isinstance(stmt, ast.withitem):
            line = stmt.context_expr.lineno
        n = Node(len(self.nodes), kind, stmt, line)
        self.nodes.append(n)
        return n.idx

    def edge(self, src: int, dst: int, kind: str = NEXT):
        self.nodes[src].succ.append((dst, kind))

    def connect(self, frontier: list, dst: int):
        for src, kind in frontier:
            self.edge(src, dst, kind)

    # --------------------------------------------------- abrupt transfers
    def _unwind(self, frontier: list, *, stop: Callable[[_Frame], bool],
                on_stop: Callable[[list, _Frame, int], Optional[list]],
                at_bottom: Callable[[list], None]):
        """Route ``frontier`` outward through the frame stack: inline every
        ``finally``/``with`` crossed; at the first frame where ``stop`` is
        true hand the frontier to ``on_stop`` (which may consume it or
        return a remainder to keep propagating); falling off the stack
        calls ``at_bottom``."""
        i = len(self.frames) - 1
        while i >= 0 and frontier:
            fr = self.frames[i]
            if fr.kind in ("finally", "with"):
                frontier = self._inline_cleanup(frontier, i)
            elif stop(fr):
                frontier = on_stop(frontier, fr, i) or []
            i -= 1
        if frontier:
            at_bottom(frontier)

    def _inline_cleanup(self, frontier: list, frame_idx: int) -> list:
        """Instantiate the finally body (or with __exit__) at ``frame_idx``
        for this abrupt edge; returns the cleanup's own exit frontier."""
        fr = self.frames[frame_idx]
        saved = self.frames
        self.frames = self.frames[:frame_idx]  # cleanup runs OUTSIDE itself
        try:
            if fr.kind == "with":
                node = self.new("with_exit", fr.item)
                self.connect(frontier, node)
                out = [(node, NEXT)]
            else:
                out = self.block(fr.stmts or [], frontier)
        finally:
            self.frames = saved
        return out

    def do_raise(self, frontier: list, *, genexit: bool = False):
        """Exception (or GeneratorExit) leaves ``frontier`` statements."""

        def stop(fr: _Frame) -> bool:
            return fr.kind == "try"

        def on_stop(front: list, fr: _Frame, i: int):
            if genexit:
                # GeneratorExit is BaseException: narrow handlers never see
                # it, so either this try catches it or it keeps unwinding
                if fr.catches_genexit:
                    self.connect(front, fr.dispatch)
                    return None
                return front
            self.connect(front, fr.dispatch)
            if fr.catches_all:
                return None
            # maybe-uncaught: dispatch also unwinds outward — route it once
            if fr.escalated:
                return None
            fr.escalated = True
            return [(fr.dispatch, EXC)]

        def at_bottom(front: list):
            self.connect(front, self.raise_exit)

        self._unwind(frontier, stop=stop, on_stop=on_stop,
                     at_bottom=at_bottom)

    def do_return(self, frontier: list):
        self._unwind(
            frontier, stop=lambda fr: False,
            on_stop=lambda f, fr, i: f,
            at_bottom=lambda front: self.connect(front, self.exit),
        )

    def do_loop_jump(self, frontier: list, *, is_break: bool):
        def stop(fr: _Frame) -> bool:
            return fr.kind == "loop"

        def on_stop(front: list, fr: _Frame, i: int):
            if is_break:
                fr.breaks.extend(front)
            else:
                for src, kind in front:
                    self.edge(src, fr.head, BACK)
            return None

        self._unwind(frontier, stop=stop, on_stop=on_stop,
                     at_bottom=lambda front: self.connect(front, self.exit))

    # ------------------------------------------------------------- blocks
    def block(self, stmts: list, frontier: list) -> list:
        for stmt in stmts:
            if not frontier:
                return []  # unreachable tail
            frontier = self.statement(stmt, frontier)
        return frontier

    def statement(self, stmt: ast.AST, frontier: list) -> list:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return frontier  # nested defs are opaque
        if isinstance(stmt, ast.If):
            return self._if(stmt, frontier)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, frontier)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, frontier)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, frontier)
        if isinstance(stmt, ast.Return):
            node = self.new("stmt", stmt)
            self.connect(frontier, node)
            self._maybe_exc(node, stmt)
            self.do_return([(node, NEXT)])
            return []
        if isinstance(stmt, ast.Raise):
            node = self.new("stmt", stmt)
            self.connect(frontier, node)
            self.do_raise([(node, NEXT)])
            return []
        if isinstance(stmt, ast.Break):
            node = self.new("stmt", stmt)
            self.connect(frontier, node)
            self.do_loop_jump([(node, NEXT)], is_break=True)
            return []
        if isinstance(stmt, ast.Continue):
            node = self.new("stmt", stmt)
            self.connect(frontier, node)
            self.do_loop_jump([(node, NEXT)], is_break=False)
            return []
        # simple statement (assign/expr/assert/del/...)
        has_yield = any(isinstance(n, (ast.Yield, ast.YieldFrom))
                        for n in ast.walk(stmt)
                        if not isinstance(n, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.Lambda)))
        node = self.new("yield" if has_yield else "stmt", stmt)
        self.connect(frontier, node)
        if has_yield:
            self.is_generator = True
            # the consumer may close() us here: GeneratorExit at the yield
            self.do_raise([(node, GENEXIT)], genexit=True)
        self._maybe_exc(node, stmt)
        if isinstance(stmt, ast.Assert):
            # a failing assert raises; the pass-through edge continues
            self.do_raise([(node, EXC)])
        return [(node, NEXT)]

    def _maybe_exc(self, node_idx: int, stmt: ast.AST):
        if self.may_raise(stmt):
            self.do_raise([(node_idx, EXC)])

    def _if(self, stmt: ast.If, frontier: list) -> list:
        test = self.new("branch", stmt)
        self.connect(frontier, test)
        self._maybe_exc(test, stmt.test)
        body_out = self.block(stmt.body, [(test, TRUE)])
        else_out = self.block(stmt.orelse, [(test, FALSE)])
        return body_out + else_out

    def _loop(self, stmt, frontier: list) -> list:
        head = self.new("loop", stmt)
        self.connect(frontier, head)
        self._maybe_exc(head, stmt)  # iterator / test can raise
        fr = _Frame(kind="loop", head=head, breaks=[])
        self.frames.append(fr)
        try:
            body_out = self.block(stmt.body, [(head, TRUE)])
        finally:
            self.frames.pop()
        for src, kind in body_out:
            self.edge(src, head, BACK)
        # loop exhausts (or while-test false) → orelse → after
        after = self.block(stmt.orelse, [(head, FALSE)])
        return after + fr.breaks

    def _try(self, stmt: ast.Try, frontier: list) -> list:
        dispatch = self.new("dispatch", stmt)
        catches_all = False
        catches_genexit = False
        for h in stmt.handlers:
            names = _handler_names(h)
            if "*" in names or any(n.split(".")[-1] in _BROAD for n in names):
                catches_all = True
            if "*" in names or any(n.split(".")[-1] in _GENEXIT_OK
                                   for n in names):
                catches_genexit = True

        fin = _Frame(kind="finally", stmts=stmt.finalbody) \
            if stmt.finalbody else None
        if fin is not None:
            self.frames.append(fin)
        tryf = _Frame(kind="try", dispatch=dispatch,
                      catches_all=catches_all,
                      catches_genexit=catches_genexit)
        self.frames.append(tryf)
        try:
            body_out = self.block(stmt.body, frontier)
            body_out = self.block(stmt.orelse, body_out)
        finally:
            self.frames.pop()  # try frame: handlers run OUTSIDE it

        # handler bodies: their own exceptions propagate outward (and
        # through this try's finally, which is still on the stack)
        handler_out: list = []
        for h in stmt.handlers:
            entry = self.new("stmt", h)
            self.edge(dispatch, entry, EXC)
            handler_out += self.block(h.body, [(entry, NEXT)])
        if not stmt.handlers:
            # try/finally with no handlers: dispatched exceptions keep
            # unwinding (through the finally frame still on the stack)
            self.do_raise([(dispatch, EXC)])

        out = body_out + handler_out
        if fin is not None:
            self.frames.pop()  # finally frame
            # normal completion runs the finally once, outside itself
            saved = self.frames
            out2 = self.block(stmt.finalbody, out) if out else []
            self.frames = saved
            return out2
        return out

    def _with(self, stmt, frontier: list) -> list:
        # context expressions evaluate before any __exit__ is registered
        inner_frames = 0
        for item in stmt.items:
            node = self.new("stmt", item)
            self.connect(frontier, node)
            self._maybe_exc(node, item.context_expr)
            frontier = [(node, NEXT)]
            self.frames.append(_Frame(kind="with", item=item))
            inner_frames += 1
        try:
            out = self.block(stmt.body, frontier)
        finally:
            for _ in range(inner_frames):
                fr = self.frames.pop()
                # normal exit also runs __exit__
                if out:
                    node = self.new("with_exit", fr.item)
                    self.connect(out, node)
                    out = [(node, NEXT)]
        return out


class _Overflow(Exception):
    pass


def build_cfg(fn: ast.AST,
              may_raise: Optional[Callable[[ast.AST], bool]] = None
              ) -> Optional[CFG]:
    """CFG for a FunctionDef/AsyncFunctionDef; None when the function is
    too large/pathological to model (the caller skips it — best-effort).

    ``may_raise(stmt)`` decides which statements get exception edges;
    the default gives one to every statement containing a call.
    """
    if may_raise is None:
        def may_raise(stmt):
            return any(isinstance(n, ast.Call) for n in ast.walk(stmt))

    b = _Builder(may_raise)
    try:
        entry = b.new("entry")
        b.exit = b.new("exit")
        b.raise_exit = b.new("raise")
        out = b.block(fn.body, [(entry, NEXT)])
        b.connect(out, b.exit)  # fall off the end
    except (_Overflow, RecursionError):
        return None
    return CFG(nodes=b.nodes, entry=entry, exit=b.exit,
               raise_exit=b.raise_exit, is_generator=b.is_generator)
