"""Compressed-latent KV transport (TPLA-style, arXiv:2508.15881).

The capacity stack's first three multipliers (int8 pages, cold-slot
spill, pod-federated prefix store) all shrink or relocate *pool* bytes;
every byte the fleet *moves* — spill-tier flushes, prefix-store host
demotions, ``PodPrefixFederation.fetch`` blobs, disagg handoffs, the
``pod.handoff`` relay — still travels as raw per-head page payloads.
This module is the layout half of the fix: a codec that rewrites a
``KVPageBlock``'s page payload into a compact wire form at the host
boundary (``KVPageBlock.to_host``) and reconstructs it at import, in
one of two modes:

- **``latent`` (MLA-native, exact)** — DeepSeek-V2's
  ``mla_cache_mode="compressed"`` pool already stores ONE shared latent
  "head" per row (``models/deepseek_v2.py``: ``cache_num_heads() == 1``,
  head dim ``kv_lora_rank + qk_rope_head_dim``) and a dummy all-zero V
  buffer. The codec ships the latent K payload directly and replaces
  every dummy-V leaf with a :class:`ZeroLeaf` geometry stub — exact and
  bit-identical on reconstruction, at ~``num_heads×`` fewer bytes than
  the decompressed per-head layout the same checkpoint would otherwise
  move (and strictly fewer than its own raw serialization).
- **``lowrank`` (calibrated, bounded error)** — for GQA models with no
  native latent: an offline-calibrated :class:`KVCompressMap` (per-layer
  SVD down/up projections over the flattened ``H*D`` row axis, emitted
  by ``cli/kv_compress_calibrate.py``) projects every KV row to ``rank``
  float16 coefficients at export and reconstructs at import. Opt-in via
  ``--kv-compress-map`` (+ optional ``--kv-compress-rank`` truncation:
  SVD bases are nested, so a lower rank is a slice, not a recalibration)
  and lossy within the reconstruction tolerance stamped into the
  artifact at calibration time. Greedy streams stay bit-identical
  whenever the flag is off or the model is MLA-native.

Layout identity: :attr:`KVCompressCodec.compress_hash` joins the block
fingerprint exactly like ``kv_share.KVShareMap.share_hash`` does — a
block compressed under one geometry can never reconstruct into a pool
running another; the import fails closed with a remediation hint and
the consumer's existing counted re-prefill fallback runs. The same hash
rides the pod heartbeat's prefix-inventory compatibility check so
mismatched hosts skip each other before any bytes move.

Failure degradation (fault site ``cache.compress``): a compress fault
leaves the block raw (counted — the transfer still happens, just fat);
a reconstruct fault surfaces as the importer's integrity/fault path —
re-prefill, never a dropped stream.

Asynchrony discipline: compression runs where ``to_host`` runs (the
spill tier's flusher thread, drain, disagg's consumer thread) and
reconstruction runs at import/prefetch — never inside a tick-hot
function. Materializing a dense up-projection on the tick path is an
mstcheck violation (MST116).
"""

from __future__ import annotations

import hashlib
import json
import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from mlx_sharding_tpu.cache import is_quantized_kv
from mlx_sharding_tpu.testing.faults import inject

FORMAT = "mst-kv-compress-map-v1"

# wire dtype for low-rank coefficients: the SVD truncation dominates the
# error budget, so half-precision coefficients cost ~nothing on top and
# halve the moved bytes again vs f32
_WIRE_DTYPE = np.float16


class CompressError(ValueError):
    """A compress map/codec failed validation, doesn't fit the pool
    geometry, or a compress/reconstruct step failed."""


class ZeroLeaf:
    """Geometry stub standing in for an all-zero payload leaf on the
    wire (the MLA-native dummy V buffer). Not a numpy array on purpose:
    ``jax.tree`` treats it as an opaque leaf, so it rides the payload
    pytree through pickling/fingerprinting at ~0 bytes and
    :meth:`KVCompressCodec.reconstruct_block` re-materializes the zeros
    exactly (same shape, same dtype) at import."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)

    def __repr__(self):  # joins the block fingerprint
        return f"ZeroLeaf(shape={self.shape}, dtype={self.dtype.name})"

    def __eq__(self, other):
        return (
            isinstance(other, ZeroLeaf)
            and self.shape == other.shape
            and self.dtype == other.dtype
        )

    def __reduce__(self):
        return (ZeroLeaf, (self.shape, self.dtype.name))


def _as_f32_rows(buf) -> np.ndarray:
    """Host payload leaf/tree → dense float32 rows ``(..., H, D)``,
    dequantizing int8 ``{"d", "s"}`` pairs."""
    if is_quantized_kv(buf):
        return np.asarray(buf["d"], np.float32) * np.asarray(
            buf["s"], np.float32
        )
    return np.asarray(buf, np.float32)


def _latent_geometry_hash(num_heads: int, k_dim: int, v_dim: int) -> str:
    payload = f"mst-kv-latent-v1:{num_heads}:{k_dim}:{v_dim}"
    return hashlib.blake2b(payload.encode(), digest_size=16).hexdigest()


# ---------------------------------------------------------------- artifact
@dataclass(frozen=True)
class KVCompressMap:
    """Per-layer low-rank KV projection pair, calibrated offline.

    ``k_down``/``v_down`` are ``(L, H*D, r)`` down-projections applied to
    flattened KV rows at export; ``k_up``/``v_up`` are their ``(L, r,
    H*D)`` transposes applied at import. ``num_layers`` counts the POOL's
    layer axis (share groups under a KVSharer map, hence the stamped
    ``share_hash`` — the two layout artifacts compose or neither loads).
    """

    num_layers: int
    rank: int
    num_heads: int
    head_dim_k: int
    head_dim_v: int
    k_down: np.ndarray
    k_up: np.ndarray
    v_down: np.ndarray
    v_up: np.ndarray
    share_hash: Optional[str] = None
    meta: dict = field(default_factory=dict, compare=False, hash=False)

    def __post_init__(self):
        if self.num_layers < 1 or self.rank < 1:
            raise CompressError(
                f"compress map needs num_layers >= 1 and rank >= 1 "
                f"(got {self.num_layers}, {self.rank})"
            )
        fk = self.num_heads * self.head_dim_k
        fv = self.num_heads * self.head_dim_v
        want = {
            "k_down": (self.num_layers, fk, self.rank),
            "k_up": (self.num_layers, self.rank, fk),
            "v_down": (self.num_layers, fv, self.rank),
            "v_up": (self.num_layers, self.rank, fv),
        }
        for name, shape in want.items():
            arr = np.ascontiguousarray(
                np.asarray(getattr(self, name), np.float32)
            )
            if arr.shape != shape:
                raise CompressError(
                    f"compress map {name} has shape {arr.shape}, "
                    f"expected {shape}"
                )
            object.__setattr__(self, name, arr)
        if self.rank >= fk or self.rank >= fv:
            raise CompressError(
                f"rank {self.rank} does not compress "
                f"{self.num_heads}x({self.head_dim_k},{self.head_dim_v}) "
                f"KV rows — pick rank < H*D"
            )

    # ------------------------------------------------------------ derived
    @property
    def compress_hash(self) -> str:
        """Layout identity for export/import integrity checks — covers
        geometry AND matrix bytes, so two maps with the same rank but
        different calibrations (or a truncated map) never alias."""
        h = hashlib.blake2b(digest_size=16)
        h.update(
            f"{FORMAT}:{self.num_layers}:{self.rank}:{self.num_heads}:"
            f"{self.head_dim_k}:{self.head_dim_v}:"
            f"share={self.share_hash}".encode()
        )
        for arr in (self.k_down, self.k_up, self.v_down, self.v_up):
            h.update(np.ascontiguousarray(arr, np.float32).tobytes())
        return h.hexdigest()

    def truncate(self, rank: int) -> "KVCompressMap":
        """Slice to a lower rank — SVD bases are nested, so truncation is
        exact calibration at the smaller rank, no recalibration needed."""
        if rank == self.rank:
            return self
        if not (1 <= rank < self.rank):
            raise CompressError(
                f"--kv-compress-rank {rank} must be in [1, {self.rank}] "
                f"for this artifact (calibrated at rank {self.rank})"
            )
        return KVCompressMap(
            num_layers=self.num_layers,
            rank=rank,
            num_heads=self.num_heads,
            head_dim_k=self.head_dim_k,
            head_dim_v=self.head_dim_v,
            k_down=self.k_down[:, :, :rank],
            k_up=self.k_up[:, :rank, :],
            v_down=self.v_down[:, :, :rank],
            v_up=self.v_up[:, :rank, :],
            share_hash=self.share_hash,
            meta=dict(self.meta, truncated_from=self.rank),
        )

    # --------------------------------------------------------- validation
    def validate_for(
        self,
        num_layers: int,
        num_heads: int,
        head_dim_k: int,
        head_dim_v: int,
        share_hash: Optional[str] = None,
    ) -> None:
        """Pool-geometry fit check with a remediation hint."""
        got = (num_layers, num_heads, head_dim_k, head_dim_v)
        have = (
            self.num_layers, self.num_heads,
            self.head_dim_k, self.head_dim_v,
        )
        if got != have:
            raise CompressError(
                f"compress map was calibrated for pool geometry "
                f"(layers, heads, k_dim, v_dim)={have} but this engine's "
                f"pool is {got} — recalibrate with "
                f"cli/kv_compress_calibrate.py against this checkpoint, "
                f"or drop --kv-compress-map"
            )
        if share_hash != self.share_hash:
            raise CompressError(
                f"compress map was calibrated on a pool with "
                f"share_hash={self.share_hash!r} but this engine runs "
                f"{share_hash!r} — the two layout artifacts must be "
                f"calibrated together (rerun cli/kv_compress_calibrate.py "
                f"with the same --kv-share-map)"
            )

    # --------------------------------------------------------------- disk
    def save(self, path: str) -> None:
        header = json.dumps({
            "format": FORMAT,
            "num_layers": self.num_layers,
            "rank": self.rank,
            "num_heads": self.num_heads,
            "head_dim_k": self.head_dim_k,
            "head_dim_v": self.head_dim_v,
            "share_hash": self.share_hash,
            "compress_hash": self.compress_hash,
            "meta": self.meta,
        }, sort_keys=True)
        with open(path, "wb") as f:
            np.savez(
                f,
                header=np.frombuffer(header.encode(), np.uint8),
                k_down=self.k_down, k_up=self.k_up,
                v_down=self.v_down, v_up=self.v_up,
            )

    @classmethod
    def load(cls, path: str) -> "KVCompressMap":
        try:
            with np.load(path) as z:
                doc = json.loads(bytes(z["header"]).decode())
                mats = {
                    n: np.asarray(z[n], np.float32)
                    for n in ("k_down", "k_up", "v_down", "v_up")
                }
        except Exception as e:  # noqa: BLE001 — any read failure is a bad artifact
            raise CompressError(
                f"--kv-compress-map {path!r} is not a readable npz "
                f"artifact: {e}"
            ) from e
        if not isinstance(doc, dict) or doc.get("format") != FORMAT:
            raise CompressError(
                f"--kv-compress-map {path!r} is not a {FORMAT} artifact "
                f"(found format="
                f"{doc.get('format') if isinstance(doc, dict) else type(doc).__name__!r}) "
                f"— emit one with cli/kv_compress_calibrate.py"
            )
        try:
            m = cls(
                num_layers=int(doc["num_layers"]),
                rank=int(doc["rank"]),
                num_heads=int(doc["num_heads"]),
                head_dim_k=int(doc["head_dim_k"]),
                head_dim_v=int(doc["head_dim_v"]),
                share_hash=doc.get("share_hash"),
                meta=dict(doc.get("meta") or {}),
                **mats,
            )
        except CompressError:
            raise
        except (KeyError, TypeError, ValueError) as e:
            raise CompressError(
                f"--kv-compress-map {path!r} is malformed: {e}"
            ) from e
        stamped = doc.get("compress_hash")
        if stamped is not None and stamped != m.compress_hash:
            raise CompressError(
                f"--kv-compress-map {path!r} stamped compress_hash "
                f"{stamped!r} disagrees with its own projections (hash "
                f"{m.compress_hash!r}) — the artifact was edited; "
                f"recalibrate instead of patching it"
            )
        return m


def load_compress_map(
    path: Optional[str], rank: Optional[int] = None
) -> Optional[KVCompressMap]:
    """Engine-facing loader: ``None`` path → no compression; an explicit
    ``rank`` truncates the artifact's nested SVD basis to a cheaper
    operating point."""
    if not path:
        if rank is not None:
            raise CompressError(
                "--kv-compress-rank needs --kv-compress-map (the rank "
                "slices a calibrated artifact; there is nothing to "
                "truncate without one)"
            )
        return None
    m = KVCompressMap.load(path)
    if rank is not None:
        m = m.truncate(int(rank))
    return m


# ------------------------------------------------------------- calibration
def calibrate_compress_map(
    k,
    v,
    *,
    rank: int,
    valid_tokens: Optional[int] = None,
    share_hash: Optional[str] = None,
    meta: Optional[dict] = None,
) -> KVCompressMap:
    """Per-layer truncated SVD over flattened KV rows.

    ``k``/``v`` are dense calibration buffers ``(L, B, S, H, D)``
    (cache.py layout) after a calibration prefill; ``valid_tokens`` trims
    right-padding before fitting. Each layer's rows ``(B*S, H*D)`` get an
    orthonormal rank-``r`` basis from the top right-singular vectors;
    ``down = V_r`` and ``up = V_r^T``, so reconstruction is the orthogonal
    projection onto the calibration row space. The per-layer relative
    reconstruction error over the calibration set is stamped into
    ``meta["calibration"]`` — the documented parity tolerance."""
    k = np.asarray(k, np.float32)
    v = np.asarray(v, np.float32)
    if k.ndim != 5 or v.ndim != 5:
        raise CompressError(
            f"calibration buffers must be (L, B, S, H, D); got "
            f"k{k.shape} v{v.shape}"
        )
    if valid_tokens is not None:
        k = k[:, :, :valid_tokens]
        v = v[:, :, :valid_tokens]
    L, _, _, H, Dk = k.shape
    Dv = v.shape[-1]

    def fit(buf, feat):
        downs, ups, errs = [], [], []
        for layer in range(L):
            rows = buf[layer].reshape(-1, feat)
            # economy SVD of the row matrix; V_r spans the best rank-r
            # row subspace in Frobenius norm (Eckart–Young)
            _, _, vt = np.linalg.svd(rows, full_matrices=False)
            basis = vt[:rank].T  # (feat, r)
            downs.append(basis)
            ups.append(basis.T)
            recon = (rows @ basis) @ basis.T
            denom = max(float(np.linalg.norm(rows)), 1e-12)
            errs.append(float(np.linalg.norm(rows - recon) / denom))
        return np.stack(downs), np.stack(ups), errs

    if not (1 <= rank < min(H * Dk, H * Dv)):
        raise CompressError(
            f"rank must be in [1, {min(H * Dk, H * Dv) - 1}] for "
            f"{H}x({Dk},{Dv}) KV rows (got {rank})"
        )
    k_down, k_up, k_err = fit(k, H * Dk)
    v_down, v_up, v_err = fit(v, H * Dv)
    info = dict(meta or {})
    info["calibration"] = {
        "rank": rank,
        "k_rel_err": k_err,
        "v_rel_err": v_err,
        "max_rel_err": max(k_err + v_err),
        "rows_per_layer": int(np.prod(k.shape[1:3])),
    }
    return KVCompressMap(
        num_layers=L, rank=rank, num_heads=H,
        head_dim_k=Dk, head_dim_v=Dv,
        k_down=k_down, k_up=k_up, v_down=v_down, v_up=v_up,
        share_hash=share_hash, meta=info,
    )


# ------------------------------------------------------------------- codec
class KVCompressCodec:
    """Pool-side compress/reconstruct engine for ``KVPageBlock`` payloads.

    Built once per engine (``parallel/pipeline.py``) from the pool's
    geometry; threaded by the scheduler into every export/import boundary.
    ``mode`` is ``"latent"`` (MLA-native, exact, auto-detected) or
    ``"lowrank"`` (calibrated map, opt-in, bounded error). Counters are
    the ``mst_kv_compress_*`` observability surface; they are updated off
    the tick path only (flusher/import threads), under ``_lock``."""

    def __init__(
        self,
        mode: str,
        *,
        compress_map: Optional[KVCompressMap] = None,
        num_heads: int = 1,
        head_dim_k: int = 0,
        head_dim_v: int = 0,
    ):
        if mode not in ("latent", "lowrank"):
            raise CompressError(f"unknown codec mode {mode!r}")
        if mode == "lowrank" and compress_map is None:
            raise CompressError("lowrank codec needs a compress map")
        self.mode = mode
        self.map = compress_map
        self.num_heads = int(num_heads)
        self.head_dim_k = int(head_dim_k)
        self.head_dim_v = int(head_dim_v)
        self.compress_hash = (
            compress_map.compress_hash
            if mode == "lowrank"
            else _latent_geometry_hash(num_heads, head_dim_k, head_dim_v)
        )
        self._lock = threading.Lock()
        self.blocks_compressed = 0
        self.blocks_reconstructed = 0
        self.compress_faults = 0
        self.reconstruct_faults = 0
        self.bytes_raw_total = 0
        self.bytes_wire_total = 0

    # ---------------------------------------------------------- accounting
    def _note(self, raw: int, wire: int) -> None:
        with self._lock:
            self.blocks_compressed += 1
            self.bytes_raw_total += int(raw)
            self.bytes_wire_total += int(wire)

    def note_fault(self, op: str) -> None:
        with self._lock:
            if op == "encode":
                self.compress_faults += 1
            else:
                self.reconstruct_faults += 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "mode": self.mode,
                "compress_hash": self.compress_hash,
                "rank": self.map.rank if self.map is not None else None,
                "blocks_compressed": self.blocks_compressed,
                "blocks_reconstructed": self.blocks_reconstructed,
                "compress_faults": self.compress_faults,
                "reconstruct_faults": self.reconstruct_faults,
                "bytes_raw_total": self.bytes_raw_total,
                "bytes_wire_total": self.bytes_wire_total,
                "bytes_saved_total": (
                    self.bytes_raw_total - self.bytes_wire_total
                ),
            }

    # ------------------------------------------------------------ compress
    @staticmethod
    def _tree_bytes(tree) -> int:
        import jax

        return sum(
            0 if isinstance(leaf, ZeroLeaf)
            else int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(tree)
        )

    def compress_pages(self, k_pages, v_pages) -> tuple:
        """Host payload trees → ``(kind, k_wire, v_wire)``. Runs at the
        ``to_host`` boundary (flusher/drain/handoff threads — never
        tick-hot). Fault site ``cache.compress`` (op="encode") models a
        failed compression; the caller keeps the raw payload and counts
        the degradation — the block still moves, just uncompressed."""
        import jax

        inject("cache.compress", op="encode", mode=self.mode)
        raw = self._tree_bytes((k_pages, v_pages))
        if self.mode == "latent":
            # the pool ALREADY stores the shared latent in k; v is the
            # dummy zeros buffer MLA never reads — ship geometry, not bytes
            k_wire = k_pages
            v_wire = jax.tree.map(
                lambda leaf: ZeroLeaf(leaf.shape, np.asarray(leaf).dtype),
                v_pages,
            )
            self._note(raw, self._tree_bytes((k_wire, v_wire)))
            return "latent", k_wire, v_wire
        m = self.map

        def down(buf, mats, feat):
            rows = _as_f32_rows(buf)  # (S, L, P, B, page, H, D)
            if rows.ndim != 7:
                raise CompressError(
                    f"lowrank compress wants 7-D pool page leaves; got "
                    f"{rows.shape}"
                )
            flat = rows.reshape(rows.shape[:5] + (feat,))
            return np.einsum(
                "slpbtf,lfr->slpbtr", flat, mats, optimize=True
            ).astype(_WIRE_DTYPE)

        k_wire = down(k_pages, m.k_down, m.num_heads * m.head_dim_k)
        v_wire = down(v_pages, m.v_down, m.num_heads * m.head_dim_v)
        self._note(raw, self._tree_bytes((k_wire, v_wire)))
        return "lowrank", k_wire, v_wire

    # --------------------------------------------------------- reconstruct
    def reconstruct_pages(self, kind: str, k_wire, v_wire) -> tuple:
        """Wire trees → pool-shaped ``(k_pages, v_pages)``. Runs at
        import/prefetch — materializing the dense up-projection inside a
        tick-hot function is MST116. Fault site ``cache.compress``
        (op="decode") models a failed reconstruction; importers land on
        their counted re-prefill fallback, never a drop."""
        import jax

        inject("cache.compress", op="decode", mode=self.mode)
        if kind == "latent":
            if self.mode != "latent":
                raise CompressError(
                    "latent block reached a lowrank codec — layout "
                    "identity check should have rejected it upstream"
                )
            v_pages = jax.tree.map(
                lambda z: np.zeros(z.shape, z.dtype), v_wire,
                is_leaf=lambda x: isinstance(x, ZeroLeaf),
            )
            with self._lock:
                self.blocks_reconstructed += 1
            return k_wire, v_pages
        if kind != "lowrank" or self.mode != "lowrank":
            raise CompressError(
                f"cannot reconstruct kind={kind!r} with a "
                f"{self.mode} codec"
            )
        m = self.map

        def up(wire, mats, heads, dim):
            coef = np.asarray(wire, np.float32)
            if coef.ndim != 6 or coef.shape[-1] != m.rank:
                raise CompressError(
                    f"lowrank wire leaf has shape {coef.shape}; expected "
                    f"rank-{m.rank} coefficients"
                )
            flat = np.einsum(
                "slpbtr,lrf->slpbtf", coef, mats, optimize=True
            )
            return flat.reshape(flat.shape[:5] + (heads, dim))

        k_pages = up(k_wire, m.k_up, m.num_heads, m.head_dim_k)
        v_pages = up(v_wire, m.v_up, m.num_heads, m.head_dim_v)
        with self._lock:
            self.blocks_reconstructed += 1
        return k_pages, v_pages

    def reconstruct_block(self, block) -> tuple:
        """Reconstruct a compressed :class:`KVPageBlock`'s pool payload.
        The caller may hold the block lock; this reads the payload fields
        it is handed via the block attributes without re-locking."""
        return self.reconstruct_pages(
            block.compress_kind, block.k_pages, block.v_pages
        )


def build_codec(
    model,
    *,
    paged: bool,
    kv_quant: bool,
    num_stages: int,
    pool_layers: int,
    share_hash: Optional[str] = None,
    compress_map: Optional[KVCompressMap] = None,
) -> Optional[KVCompressCodec]:
    """Engine-side codec selection (``parallel/pipeline.py``).

    MLA-native pools (``mla_cache_mode="compressed"``: one shared latent
    head) get the exact ``latent`` codec automatically — there is no
    reason to ever move the dummy V bytes. A calibrated map opts a GQA
    pool into ``lowrank``; geometry/layout mismatches fail closed at
    construction with remediation hints, mirroring kv_share's checks."""
    if not paged:
        if compress_map is not None:
            raise CompressError(
                "--kv-compress-map requires a paged engine (pool_pages): "
                "compression rides the KVPageBlock export path"
            )
        return None
    hd = model.cache_head_dim()
    k_dim, v_dim = (hd, hd) if not isinstance(hd, (tuple, list)) else hd
    heads = model.cache_num_heads()
    mla_native = (
        heads == 1
        and getattr(model.config, "mla_cache_mode", None) == "compressed"
    )
    if mla_native:
        if compress_map is not None:
            raise CompressError(
                "--kv-compress-map is redundant on an MLA-native pool "
                "(mla_cache_mode='compressed' already stores the latent; "
                "export ships it exactly) — drop the flag"
            )
        return KVCompressCodec(
            "latent", num_heads=heads, head_dim_k=k_dim, head_dim_v=v_dim
        )
    if compress_map is None:
        return None
    if num_stages != 1:
        raise CompressError(
            "--kv-compress-map requires a pp=1 engine: the per-layer "
            "projections span the full layer stack, which a stage split "
            "cuts"
        )
    if kv_quant:
        raise CompressError(
            "--kv-compress-map composes with bf16 pools only: int8 pages "
            "already halve row bytes and a dequant→project→requant trip "
            "compounds both error terms — pick one of --kv-dtype int8 or "
            "--kv-compress-map"
        )
    compress_map.validate_for(
        pool_layers, heads, k_dim, v_dim, share_hash=share_hash
    )
    return KVCompressCodec(
        "lowrank",
        compress_map=compress_map,
        num_heads=heads,
        head_dim_k=k_dim,
        head_dim_v=v_dim,
    )
