"""Autoregressive generation driver.

Replaces the reference's decode loops (``generate_step`` ref: generate.py:52-88
and ``create_generate_step_with_grpc`` ref: shard/utils.py:111-188) with a
TPU-shaped design:

- **Two compiled shapes, ever.** Prefill runs in fixed-size chunks (right-
  padded final chunk) and decode at T=1, so nothing recompiles on prompt
  length. Pad-position K/V entries are always overwritten before any valid
  query can attend them (writes are contiguous and each step writes before it
  reads), so padding needs no masking beyond the causal rule.
- **Sampling is fused into the decode program** (temperature / top-p /
  repetition-penalty as dynamic scalars) so the only host transfer per token
  is the sampled id — the reference instead pays Python serde per stage per
  token (SURVEY §3.5).
- **One-token lookahead**: step N+1 is dispatched before step N's token is
  read on host, the same overlap the reference gets from ``mx.async_eval``
  (ref: shard/utils.py:180-186) — with JAX's async dispatch it falls out
  naturally.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mlx_sharding_tpu.cache import KVCache, reset
from mlx_sharding_tpu.sample import (
    SamplerParams,
    init_recent_tokens,
    make_sampler_params,
    sample_token,
    update_recent_tokens,
)

DEFAULT_PREFILL_CHUNK = 256
REPETITION_WINDOW = 20  # reference default repetition_context_size (openai_api.py)
DEFAULT_DECODE_BLOCK = 16
LOGPROB_TOPK = 10  # the server's documented logprobs cap (ref openai_api.py:262)


@dataclass
class TokenLogprobs:
    """Per-token logprob summary, computed ON DEVICE inside the decode block
    (``jax.lax.top_k``) and pulled to host once per block — replacing the
    per-token full-vocab host argsort the reference's server does
    (ref: shard/openai_api.py:388-392). ``top_indices``/``top_values`` are
    descending, length LOGPROB_TOPK; slice to the requested k."""

    chosen: float
    top_indices: np.ndarray
    top_values: np.ndarray


def block_lp_outputs(tok_flat, logprobs):
    """Per-step scan outputs for a decode block when logprobs are wanted:
    ``(tokens, chosen, top_values, top_indices)``. Single source of the
    positional convention every engine's block program emits —
    :func:`block_token_logprobs` is its reader."""
    chosen = jnp.take_along_axis(
        logprobs, tok_flat.reshape(-1, 1).astype(jnp.int32), axis=-1
    )[:, 0]
    top_v, top_i = jax.lax.top_k(logprobs, LOGPROB_TOPK)
    return chosen, top_v, top_i


def block_token_logprobs(outs, j, row=0) -> TokenLogprobs:
    """Read one (step j, batch row) TokenLogprobs from a pulled block-output
    tuple ``(tokens, chosen, top_values, top_indices)``."""
    return TokenLogprobs(
        float(outs[1][j, row]), outs[3][j, row], outs[2][j, row]
    )


def blocked_token_stream(dispatch, carry, remaining, block_size, want_logprobs,
                         tok_index=(0,), sink=None):
    """The blocked-decode host loop shared by every engine: one-BLOCK
    lookahead — block i+1 is dispatched (chained on block i's device-side
    carry, no host sync) before block i's tokens are pulled, so the host
    pull's round trip overlaps the next block's compute. Per token that
    leaves max(step_time, RTT/block_size) instead of RTT.

    ``dispatch(carry) -> (block_outputs, carry)`` launches one block;
    ``tok_index`` selects the yielded row from the (K, …) token stack.
    ``sink`` (optional) receives each pulled block's full (K, …) token
    array — including tokens past ``remaining`` that are never yielded —
    so a prompt cache can account for every KV row the blocks wrote."""
    n_blocks = -(-remaining // block_size)
    pending, carry = dispatch(carry)
    pending = [pending]
    emitted = 0
    for bi in range(n_blocks):
        if bi + 1 < n_blocks:
            nxt, carry = dispatch(carry)
            pending.append(nxt)
        outs = jax.device_get(pending.pop(0))
        toks = outs[0]
        if sink is not None:
            sink(toks)
        for j in range(toks.shape[0]):
            if emitted >= remaining:
                break
            lp = block_token_logprobs(outs, j) if want_logprobs else None
            yield int(toks[(j, *tok_index)]), lp
            emitted += 1


@dataclass
class StreamChunk:
    text: str = ""
    token: Optional[int] = None
    logprobs: Optional[np.ndarray] = None
    finish_reason: Optional[str] = None
    # set on the final chunk, matching the reference's instrumentation
    # (generate.py:97-122): prompt/gen tok/s and TTFT
    prompt_tokens: int = 0
    generation_tokens: int = 0
    prompt_tps: float = 0.0
    generation_tps: float = 0.0
    ttft: float = 0.0


class Generator:
    """Owns the jitted step programs for one (model, params) pair.

    The same object serves many requests (the API server holds one, like the
    reference's ModelProvider, ref: shard/openai_api.py:70-127); per-request
    state (cache, recent-token window, PRNG key) is created per call.
    """

    def __init__(
        self,
        model,
        params,
        *,
        max_seq: int = 4096,
        batch: int = 1,
        cache_dtype=jnp.bfloat16,
        prefill_chunk: int = DEFAULT_PREFILL_CHUNK,
        sp_mesh=None,
        sp_decode: bool = False,
        decode_block: int = DEFAULT_DECODE_BLOCK,
        prompt_cache: bool = False,
    ):
        self.model = model
        # Build-time projection fusion (keep-quantized loads, single-chip):
        # concatenate each declared group's packed triples along OUT so
        # decode runs QKV / gate+up as one fused-GEMV launch each. The
        # caller's params are not mutated (shallow-copied layer stack);
        # sp paths keep the separate projections (long-prefill bound, and
        # their params are placed before fusion would apply).
        self.fused_projections: list[str] = []
        if sp_mesh is None and os.environ.get("MST_FUSE_PROJ", "1") != "0":
            from mlx_sharding_tpu.models.base import apply_projection_fusion

            layers = params.get("layers")
            if isinstance(layers, dict):
                layers = {
                    k: dict(v) if isinstance(v, dict) else v
                    for k, v in layers.items()
                }
                fused = apply_projection_fusion(model, layers)
                if fused:
                    params = {**params, "layers": layers}
                    self.fused_projections = fused
        self.params = params
        # Prompt-prefix caching: keep the previous request's KV cache and
        # token sequence; a new request prefills only past the longest
        # common token prefix. The chat pattern — system prompt + growing
        # history — re-sends the whole previous context every turn, so TTFT
        # drops from O(context) to O(new tokens). Rows past the matched
        # prefix are stale but NEVER attended (validity derives from the
        # offset), the same invariant the speculative rollback leans on.
        # The reference resets every remote cache per request instead
        # (shard/utils.py:122-124).
        self._prompt_cache = bool(prompt_cache)
        self._pc = None  # {"tokens": np (T,), "cache": KVCache}
        self.last_prefix_hit = 0  # observability + tests
        # optional sequence-parallel prefill: prompts longer than one chunk
        # are sharded over the mesh's sp axis (ring attention) instead of
        # looping chunks on one device — see parallel/sp_prefill.py.
        # sp_decode additionally keeps the KV cache sequence-sharded for the
        # whole generation (parallel/sp_decode.py): capacity scales with the
        # mesh instead of one chip's HBM, removing the round-2 all-gather.
        self.sp_mesh = sp_mesh
        self._sp_prefill = None
        self._sp_decode = None
        if sp_decode and sp_mesh is None:
            raise ValueError("sp_decode requires sp_mesh")
        if sp_mesh is not None:
            from mlx_sharding_tpu.parallel.sp_prefill import (
                SpPrefill,
                supports_sp_prefill,
            )

            if not supports_sp_prefill(model):
                raise ValueError(
                    f"{type(model).__name__} does not support sequence-"
                    "parallel prefill (needs supports_sp = True with the "
                    "sp_layer/sp_groups hooks, on a full first+last stage)"
                )
            self._sp_prefill = SpPrefill(
                model, params, sp_mesh, prefill_chunk, keep_sharded=sp_decode
            )
            if sp_decode:
                from mlx_sharding_tpu.parallel.sp_decode import SpDecode

                self._sp_decode = SpDecode(
                    model, self._sp_prefill.params, sp_mesh,
                    decode_block=decode_block,
                )
        # Round capacity up to a chunk multiple: every (possibly padded)
        # prefill chunk then writes entirely inside the buffer, so padded
        # writes can never clamp-and-corrupt valid entries. Sharded-decode
        # capacity must additionally split evenly across the sp devices.
        quantum = prefill_chunk
        if sp_decode:
            from mlx_sharding_tpu.parallel.mesh import AXIS_SP

            quantum = sp_mesh.shape[AXIS_SP] * prefill_chunk
        self.max_seq = -(-max_seq // quantum) * quantum
        self.batch = batch
        self.cache_dtype = cache_dtype
        self.prefill_chunk = prefill_chunk

        def prefill_fn(params, tokens, cache, n_valid):
            out, cache = model(params, tokens, cache, n_valid=n_valid)
            last = jax.lax.dynamic_index_in_dim(out, n_valid - 1, axis=1)
            return last[:, 0], cache  # (B, V) logits (or hidden mid-pipeline)

        def decode_fn(params, token, cache, recent, key, sp):
            logits, cache = model(params, token, cache)
            key, sub = jax.random.split(key)
            tok, logprobs = sample_token(sub, logits[:, -1], sp, recent)
            recent = update_recent_tokens(recent, tok)
            return tok, logprobs, cache, recent, key

        def sample_fn(logits, recent, key, sp):
            key, sub = jax.random.split(key)
            tok, logprobs = sample_token(sub, logits, sp, recent)
            recent = update_recent_tokens(recent, tok)
            return tok, logprobs, recent, key

        def decode_block_fn(params, token, cache, recent, key, sp, want_lp):
            """``decode_block`` decode steps fused into ONE program via
            lax.scan: the host pulls tokens once per block instead of once per
            token. Over a network-attached chip (the axon tunnel's host pull
            is ~100ms against a ~8ms device step) this is the difference
            between RTT-bound and HBM-bound decode. Logprob summaries
            (chosen + top-k) are computed on device inside the same scan."""

            def step(carry, _):
                tok, cache, recent, key = carry
                logits, cache = model(params, tok[:, None], cache)
                key, sub = jax.random.split(key)
                tok, logprobs = sample_token(sub, logits[:, -1], sp, recent)
                recent = update_recent_tokens(recent, tok)
                if want_lp:
                    out = (tok, *block_lp_outputs(tok, logprobs))
                else:
                    out = (tok,)
                return (tok, cache, recent, key), out

            (token, cache, recent, key), outs = jax.lax.scan(
                step, (token, cache, recent, key), None, length=decode_block
            )
            return outs, token, cache, recent, key

        self._prefill = jax.jit(prefill_fn, donate_argnums=(2,))
        self._decode = jax.jit(decode_fn, donate_argnums=(2, 3))
        self._sample = jax.jit(sample_fn, donate_argnums=(1,))
        self._decode_block = jax.jit(
            decode_block_fn, donate_argnums=(2, 3), static_argnums=(6,)
        )
        self.decode_block = decode_block

    # ------------------------------------------------------------------
    def run_prefill(self, prompt: np.ndarray, cache):
        """Chunked prefill of ``prompt`` (B, T) into ``cache`` — fixed-size
        chunks, right-padded tail (see the module docstring). Returns
        (last_valid_logits, cache). Shared by generate_step and the
        speculative decoder (both models prefill the same way)."""
        c = self.prefill_chunk
        logits = None
        for start in range(0, prompt.shape[1], c):
            chunk = prompt[:, start : start + c]
            n_valid = chunk.shape[1]
            if n_valid < c:
                chunk = np.pad(chunk, ((0, 0), (0, c - n_valid)))
            logits, cache = self._prefill(
                self.params, jnp.asarray(chunk), cache,
                jnp.asarray(n_valid, jnp.int32),
            )
        return logits, cache

    def generate_step(
        self,
        prompt_tokens: list[int] | np.ndarray,
        *,
        temperature: float = 0.0,
        top_p: float = 1.0,
        repetition_penalty: Optional[float] = None,
        repetition_context_size: int = REPETITION_WINDOW,
        logit_bias: Optional[dict[int, float]] = None,
        seed: Optional[int] = None,
        max_tokens: int = 256,
        want_logprobs: bool = False,
    ) -> Iterator[tuple[int, Optional[TokenLogprobs]]]:
        """Yields ``(token, logprobs)`` per generated token — the contract of
        the reference's generate_step closures (shard/utils.py:152-186).
        ``logprobs`` is a :class:`TokenLogprobs` when ``want_logprobs`` else
        None; the summary is computed on device inside the decode block."""
        sp = make_sampler_params(temperature, top_p, repetition_penalty, logit_bias)
        key = jax.random.PRNGKey(int(time.time_ns()) & 0x7FFFFFFF if seed is None else seed)
        prompt = np.asarray(prompt_tokens, np.int32).reshape(self.batch, -1)
        n_prompt = prompt.shape[1]
        if n_prompt == 0:
            raise ValueError("empty prompt")
        if n_prompt + max_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({n_prompt}) + max_tokens ({max_tokens}) exceeds KV "
                f"capacity {self.max_seq}"
            )

        recent = init_recent_tokens(self.batch, repetition_context_size, prompt)
        if self._sp_decode is not None:
            yield from self._generate_sp(
                prompt, recent, key, sp, max_tokens, want_logprobs
            )
            return

        # prompt-prefix reuse: consume the previous request's cache (its
        # buffer is about to be donated either way) and compute the longest
        # common token prefix. Cap at n_prompt - 1 — at least one token must
        # prefill to produce logits.
        use_pc = self._prompt_cache and self.batch == 1
        pc_hit = 0
        cache = None
        if use_pc:
            pc, self._pc = self._pc, None
            if pc is not None:
                known = pc["tokens"]
                limit = min(known.size, n_prompt - 1)
                eq = known[:limit] == prompt[0, :limit]
                pc_hit = limit if eq.all() else int(eq.argmin())
                # the padded FINAL suffix chunk must not cross max_seq —
                # dynamic_update_slice would clamp its start and overwrite
                # valid rows. If a non-aligned hit would overflow, align it
                # down to a chunk boundary (aligned prefill always fits:
                # max_seq is a chunk multiple and n_prompt <= max_seq).
                c = self.prefill_chunk
                if pc_hit and pc_hit + -(-(n_prompt - pc_hit) // c) * c > self.max_seq:
                    pc_hit = (pc_hit // c) * c
                cache = (
                    pc["cache"]._replace(
                        offset=jnp.asarray(pc_hit, jnp.int32)
                    )
                    if pc_hit > 0
                    else reset(pc["cache"])  # reuse the buffer, offset 0
                )
        self.last_prefix_hit = pc_hit
        if cache is None:
            cache = self.model.make_cache(self.batch, self.max_seq, self.cache_dtype)

        # chunked prefill (ref does whole-prompt single shot, shard/utils.py:158;
        # chunking bounds activation memory and fixes compile shapes). Capacity
        # was verified above with host arithmetic — no per-chunk device sync.
        use_sp = (
            self._sp_prefill is not None
            and n_prompt > self.prefill_chunk
            and pc_hit == 0  # sp prefill shards the WHOLE prompt from 0
            # quantum padding may need more cache rows than the prompt itself;
            # fall back to the chunked path rather than fail a fitting request
            and self._sp_prefill.padded_len(n_prompt) <= cache.max_seq
        )
        if use_sp:
            last_logits, cache = self._sp_prefill(prompt, cache)
        else:
            last_logits, cache = self.run_prefill(prompt[:, pc_hit:], cache)

        tok, logprobs, recent, key = self._sample(last_logits, recent, key, sp)

        first_lp = None
        if want_logprobs:
            chosen, top_v, top_i = block_lp_outputs(tok, logprobs)
            first_lp = TokenLogprobs(
                float(chosen[0]), np.asarray(top_i[0]), np.asarray(top_v[0])
            )

        last = {"cache": cache}  # latest un-donated cache in the chain
        collected: list[np.ndarray] = []

        def dispatch(carry):
            outs, t, c, r, kk = self._decode_block(
                self.params, carry[0], carry[1], carry[2], carry[3],
                sp, want_logprobs,
            )
            last["cache"] = c
            return outs, (t, c, r, kk)

        try:
            yield int(tok[0]), first_lp
            remaining = max_tokens - 1
            if remaining <= 0:
                return
            yield from blocked_token_stream(
                dispatch, (tok, cache, recent, key), remaining,
                self.decode_block, want_logprobs,
                sink=(lambda toks: collected.append(np.asarray(toks)[:, 0]))
                if use_pc else None,
            )
        finally:
            if use_pc:
                # tokens whose KV rows we can ACCOUNT FOR: the prompt plus
                # every fed decode token from pulled blocks (the last
                # sampled token was never fed; rows written by dispatched-
                # but-unpulled lookahead blocks hold tokens we can't name —
                # the prefix match is simply capped at what we know)
                fed = [int(np.asarray(tok)[0])]
                for blk in collected:
                    fed.extend(int(t) for t in blk)
                self._pc = {
                    "tokens": np.concatenate(
                        [prompt[0], np.asarray(fed[:-1], np.int32)]
                    ),
                    "cache": last["cache"],
                }


    # ------------------------------------------------------------------
    def _generate_sp(self, prompt, recent, key, sp, max_tokens, want_logprobs):
        """Generation over an sp-sharded KV cache: sequence-parallel prefill
        (no gather), distributed decode attention (parallel/sp_decode.py).
        Same blocked/lookahead host loop as the dense path."""
        spd = self._sp_decode
        n_prompt = prompt.shape[1]
        # capacity holds by construction: max_seq is a quantum multiple and
        # generate_step already checked n_prompt + max_tokens <= max_seq
        assert self._sp_prefill.padded_len(n_prompt) <= self.max_seq
        cache = spd.make_cache(self.batch, self.max_seq, self.cache_dtype)
        logits, ks, vs = self._sp_prefill.prefill_sharded(prompt)
        cache = spd.write_prefill(cache, ks, vs, n_prompt)
        tok, logprobs, recent, key = self._sample(logits, recent, key, sp)

        first_lp = None
        if want_logprobs:
            chosen, top_v, top_i = block_lp_outputs(tok, logprobs)
            first_lp = TokenLogprobs(
                float(chosen[0]), np.asarray(top_i[0]), np.asarray(top_v[0])
            )
        yield int(tok[0]), first_lp
        remaining = max_tokens - 1
        if remaining <= 0:
            return

        prog = spd.block_prog(want_logprobs)

        def dispatch(carry):
            outs, tok, k, v, off, recent, key = prog(spd.params, *carry, sp)
            return outs, (tok, k, v, off, recent, key)

        yield from blocked_token_stream(
            dispatch, (tok, cache.k, cache.v, cache.offset, recent, key),
            remaining, spd.decode_block, want_logprobs,
        )


def stream_generate(
    generator: Generator,
    tokenizer,
    prompt_tokens: list[int],
    *,
    max_tokens: int = 256,
    stop_id_sequences: Optional[list[list[int]]] = None,
    eos_token_ids: Optional[list[int]] = None,
    **sampler_kwargs,
) -> Iterator[StreamChunk]:
    """Detokenized streaming with stop handling + tok/s instrumentation
    (semantics of ref generate.py:90-122 stream_generate)."""
    from mlx_sharding_tpu.tokenizer_utils import (
        StreamingDetokenizer,
        sequence_overlap,
        stopping_criteria,
    )

    stop_id_sequences = stop_id_sequences or []
    if eos_token_ids is None:
        eos = getattr(tokenizer, "eos_token_id", None)
        eos_token_ids = [eos] if eos is not None else []
    detok = StreamingDetokenizer(tokenizer)
    tokens: list[int] = []
    in_flight: list[int] = []  # withheld: could still grow into a stop sequence

    start = time.perf_counter()
    first_token_time = None
    finish_reason = "length"
    for token, logprobs in generator.generate_step(
        prompt_tokens, max_tokens=max_tokens, **sampler_kwargs
    ):
        if first_token_time is None:
            first_token_time = time.perf_counter()
        tokens.append(token)
        if token in eos_token_ids:
            finish_reason = "stop"
            in_flight.clear()
            break
        stop = stopping_criteria(tokens, stop_id_sequences, None)
        if stop.stop_met:
            # the matched stop sequence itself is trimmed, never emitted
            # (ref shard/openai_api.py:465-474 trim semantics)
            finish_reason = "stop"
            tokens = tokens[: len(tokens) - stop.trim_length]
            in_flight.clear()
            break
        if stop_id_sequences and any(
            sequence_overlap(tokens, s) for s in stop_id_sequences
        ):
            in_flight.append(token)
            continue
        for t in in_flight:
            detok.add_token(t)
        in_flight.clear()
        detok.add_token(token)
        if detok.last_segment:
            yield StreamChunk(text=detok.last_segment, token=token)
    # a run that ended on length while buffering emits the buffered tokens —
    # they were never part of a completed stop sequence
    for t in in_flight:
        detok.add_token(t)
    detok.finalize()
    end = time.perf_counter()

    n_prompt = len(prompt_tokens)
    ttft = (first_token_time or end) - start
    gen_time = max(end - (first_token_time or end), 1e-9)
    yield StreamChunk(
        text=detok.last_segment if detok.last_segment else "",
        finish_reason=finish_reason,
        prompt_tokens=n_prompt,
        generation_tokens=len(tokens),
        prompt_tps=n_prompt / max(ttft, 1e-9),
        generation_tps=max(len(tokens) - 1, 0) / gen_time,
        ttft=ttft,
    )
