"""KV page-block migration: the transferable unit of decode state.

The serving stack's two pressure valves used to be destructive: overcommit
preemption discarded the victim's KV and re-prefilled from the folded-back
prompt, and a replica could only leave the fleet via ``close()``, killing
its in-flight work. This module turns both into *moves* instead of
*deletes* by extracting the piece of state they both need to relocate —
a request's KV pages plus the sampler state that makes its continuation
bit-exact — into a serializable :class:`KVPageBlock`:

- **Spill-don't-discard preemption** — ``ContinuousBatcher._preempt``
  exports the victim's page chain into a :class:`KVSpillTier` (host-DRAM
  LRU, budgeted by ``--spill-bytes``). Resume re-imports the pages into
  freshly allocated pool pages instead of re-prefilling: preemption cost
  becomes one page-gather + one page-scatter rather than a full prefill.
- **Graceful replica drain** — ``ReplicaSet.drain(i)`` asks replica *i*'s
  batcher to export every admitted request as a host-resident block and
  end its stream with ``RequestMigratedError``; the dispatcher re-places
  each one on a healthy replica, which imports the block (same pool
  geometry) or re-prefills (different geometry / import failure).
- **Crash-safe re-placement** — when a replica dies mid-stream, the
  dispatcher rebuilds a blockless ``ResumeState`` from its own record of
  delivered tokens; the failover replica folds the history into the
  prompt and continues from the last emitted token.

Asynchrony discipline (the PRESERVE-style overlap ``quant_gemv_pipelined``
practices, arXiv:2501.08192): the tick-hot path only ever *dispatches* the
device-side page gather — the device→host copy happens on the tier's
background flusher thread via :meth:`KVPageBlock.to_host`. A synchronous
full-block ``device_get`` in a tick-hot function is an mstcheck violation
(MST106). Drain is the one exception: it runs quiesced, off the decode
loop, where a blocking copy is shutdown-grade work.

The return trip is symmetric: when the scheduler knows a spilled block is
about to rejoin decode (a cold slot's consumer caught up, a preempted
request reached the head of the waiting line), it calls
:meth:`KVPageBlock.prefetch` — a dispatch-only ``jax.device_put`` of the
host payload, so the host→device DMA overlaps the decode block already in
flight and the admission-time page scatter consumes device-resident
arrays. Without the prefetch, the scatter marshals host numpy at import
time — the demand-paged resume stall mstcheck's MST109 polices in
tick-hot code.

Failure degradation: every consumer treats a failed export/import (fault
sites ``cache.export`` / ``cache.import``, corrupt block checksum, budget
or pool exhaustion) as "fall back to yesterday's behavior" — fold the
emitted history into the prompt and re-prefill. Token streams stay exact
either way because the sampler PRNG row and repetition window
(``resume_keys`` / ``resume_recent``) ride along in both paths.
"""

from __future__ import annotations

import hashlib
import logging
import queue
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional

import jax
import numpy as np

from mlx_sharding_tpu import tracing
from mlx_sharding_tpu.analysis.runtime import (
    make_lock,
    note_acquire,
    note_release,
    note_reset,
)
from mlx_sharding_tpu.cache import export_pool_pages, import_pool_pages
from mlx_sharding_tpu.kv_compress import ZeroLeaf
from mlx_sharding_tpu.testing.faults import inject

logger = logging.getLogger(__name__)


class BlockIntegrityError(RuntimeError):
    """A host-materialized block failed its checksum or structural
    validation — treat as corrupt and fall back to re-prefill."""


def _leaves(tree) -> list:
    return jax.tree.leaves(tree)


@dataclass(eq=False)
class KVPageBlock:
    """One request's relocatable decode state: its KV page payloads (codes
    *and* scales for int8 pools) plus everything the sampler needs to
    continue the exact token stream on any engine with the same pool
    geometry.

    ``k_pages`` / ``v_pages`` mirror the paged pool's leaf structure with
    the pool axis (2) narrowed to this request's page chain, in chain
    order. They start as device arrays (the export gather is dispatched,
    not waited on) and become numpy after :meth:`to_host`, which also
    stamps ``checksum`` so a later :meth:`verify` catches corruption
    before the pages are scattered into a pool.

    KV-row accounting (matches the batcher's decode-write semantics): a
    request that has emitted ``len(history)`` tokens has
    ``prompt.size + len(history) - 1`` valid KV rows — the last emitted
    token's KV is unwritten; its id is ``last_tok`` and it is fed as the
    next decode step's input."""

    k_pages: object
    v_pages: object
    n_tokens: int            # valid KV rows covered by the pages
    page_size: int
    prompt: np.ndarray       # original prompt ids (pre-fold)
    history: list            # tokens emitted since admission/fold
    produced: int            # total tokens delivered to the client
    last_tok: int            # next decode input (== history[-1])
    resume_keys: object      # sampler PRNG key row at export
    resume_recent: object    # repetition-penalty recent window at export
    # KV share-map layout identity (kv_share.KVShareMap.share_hash) of the
    # pool the pages were lifted from; None == unshared/identity layout.
    # Joins the fingerprint and is re-checked at import so a block can
    # never scatter into a pool with a different layer→group layout.
    share_hash: Optional[str] = None
    # Compressed-latent wire form (kv_compress.KVCompressCodec): when
    # set, k_pages/v_pages hold the WIRE payload — the MLA latent with
    # ZeroLeaf stubs for the dummy V ("latent", exact) or rank-r float16
    # coefficients ("lowrank", calibrated) — and compress_hash names the
    # codec geometry that can reconstruct it. Both join the fingerprint;
    # import re-checks them so a block can never reconstruct under a
    # different layout.
    compress_kind: Optional[str] = None
    compress_hash: Optional[str] = None
    checksum: Optional[str] = None
    _host: bool = False
    # device-resident (k_pages, v_pages) staged by prefetch(); consumed by
    # payload() at import so the scatter never marshals host numpy. For a
    # compressed block the staged tuple is the RECONSTRUCTED pool form —
    # prefetch pays the up-projection off-tick so import never does.
    _staged: Optional[tuple] = None
    # the exporting engine's codec (kv_compress.KVCompressCodec); rides
    # the in-process block so the flusher's to_host can compress, never
    # serialized — from_bytes receivers pass their own codec at import
    _codec: object = None
    _lock: object = field(default_factory=lambda: make_lock("KVPageBlock._lock"), repr=False)

    @property
    def n_pages(self) -> int:
        return _leaves(self.k_pages)[0].shape[2]  # mst: allow(MST201): shape is invariant across the to_host swap

    @property
    def nbytes(self) -> int:
        """Payload size used against the spill budget (KV pages dominate;
        the sampler rows are a few hundred bytes and are not counted)."""
        return int(sum(
            0 if isinstance(leaf, ZeroLeaf)
            else int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
            for leaf in _leaves((self.k_pages, self.v_pages))  # mst: allow(MST201): shapes/dtypes invariant across the to_host swap
        ))

    @property
    def is_host(self) -> bool:
        return self._host  # mst: allow(MST201): monotonic flag; to_host is idempotent on a racy False

    @property
    def is_prefetched(self) -> bool:
        return self._staged is not None  # mst: allow(MST201): racy read is gauge-grade; importers re-read under payload()'s lock

    def prefetch(self, put=None, codec=None) -> "KVPageBlock":
        """Stage the host-resident page payload back onto the device ahead
        of a scheduled import (the PRESERVE-style overlap, arXiv:2501.08192):
        ``jax.device_put`` only DISPATCHES the host→device DMA, so the copy
        rides alongside the decode block in flight and the admission-time
        page scatter consumes already-device-resident arrays. Idempotent; a
        block the flusher hasn't copied to host yet needs no staging (its
        payload never left the device). A compressed block reconstructs its
        pool-form payload here — off the tick path — so the import scatter
        never materializes an up-projection (MST116). Fault site
        ``cache.prefetch`` models a failed/refused stage — callers catch,
        count, and degrade to the demand import (then to re-prefill),
        never a dropped stream."""
        inject("cache.prefetch", n_bytes=self.nbytes)
        putfn = put if put is not None else jax.device_put
        with self._lock:
            if not self._host or self._staged is not None:
                return self
            if self.compress_kind is not None:
                dec = codec if codec is not None else self._codec
                if dec is None:
                    # nothing local can reconstruct it; the demand import
                    # (which carries the pool's codec) will
                    return self
                # a reconstruct fault propagates: the caller counts a
                # prefetch fault and the demand path retries at import
                k_pages, v_pages = dec.reconstruct_block(self)
            else:
                k_pages, v_pages = self.k_pages, self.v_pages
            self._staged = (
                jax.tree.map(putfn, k_pages),
                jax.tree.map(putfn, v_pages),
            )
        return self

    def payload(self) -> tuple:
        """``(k_pages, v_pages)`` for the import scatter: the prefetch-staged
        device copies when present, else the raw payload (host numpy after a
        flush — the demand path — or still-device arrays before one)."""
        with self._lock:
            if self._staged is not None:
                return self._staged
            return self.k_pages, self.v_pages

    def drop_prefetch(self) -> None:
        """Release staged device copies — a block leaving this engine
        (cross-replica migration) must not pin another mesh's buffers."""
        with self._lock:
            self._staged = None

    def to_host(self) -> "KVPageBlock":
        """Materialize the page payloads in host DRAM and stamp the
        checksum. Idempotent and thread-safe: the tier's flusher thread
        and a drain both may race to flush the same block. This is the
        only place the export's device→host copy blocks — never call it
        from a tick-hot function (MST106)."""
        # the one blocking device→host copy: span it when the caller bound
        # a trace (disagg handoff, drain); the tier's flusher thread has no
        # binding, so steady-state spills record nothing here
        tr = tracing.current()
        t0 = time.perf_counter() if tr is not None else 0.0
        with self._lock:
            if self._host:
                return self
            k, v = jax.device_get((self.k_pages, self.v_pages))
            self.k_pages = jax.tree.map(np.asarray, k)
            self.v_pages = jax.tree.map(np.asarray, v)
            if self._codec is not None:
                # compress at the host boundary — every downstream mover
                # (spill tier, prefix demotion, federation blob, handoff
                # wire) sees the wire form. A fault/codec failure leaves
                # the block raw: counted degradation, the bytes still move
                try:
                    kind, kw, vw = self._codec.compress_pages(
                        self.k_pages, self.v_pages
                    )
                    self.k_pages, self.v_pages = kw, vw
                    self.compress_kind = kind
                    self.compress_hash = self._codec.compress_hash
                except Exception:  # noqa: BLE001 — degrade to raw, never lose the block
                    self._codec.note_fault("encode")
                    logger.warning(
                        "KV compress failed; block ships raw", exc_info=True
                    )
            if self.resume_keys is not None:
                self.resume_keys = np.asarray(self.resume_keys)
            if self.resume_recent is not None:
                self.resume_recent = np.asarray(self.resume_recent)
            self.checksum = self._fingerprint()
            self._host = True
        if tr is not None:
            tr.add("kv_to_host", t0, time.perf_counter(), bytes=self.nbytes)
        return self

    def _fingerprint(self) -> str:
        h = hashlib.blake2b(digest_size=16)
        head = f"{self.n_tokens}:{self.page_size}:{self.last_tok}"
        if self.share_hash:
            # unshared blocks keep the legacy header so their checksums
            # (and the pod-federated digests derived from them) are stable
            head += f":share={self.share_hash}"
        if self.compress_kind:
            # compressed blocks fingerprint their WIRE payload, so the
            # checksum verifies on arrival without a codec; the kind and
            # codec geometry are bound in so a relabeled payload fails
            head += f":compress={self.compress_kind}:{self.compress_hash}"
        h.update(head.encode())
        for leaf in _leaves((self.k_pages, self.v_pages)):
            if isinstance(leaf, ZeroLeaf):
                h.update(repr(leaf).encode())
            else:
                h.update(np.ascontiguousarray(leaf).tobytes())
        return h.hexdigest()

    def verify(self) -> None:
        """Structural checks always; checksum when host-materialized.
        Raises :class:`BlockIntegrityError` on any mismatch — importers
        catch it and fall back to re-prefill."""
        if self.page_size < 1 or self.n_tokens < 1:
            raise BlockIntegrityError(
                f"degenerate block: page_size={self.page_size} "
                f"n_tokens={self.n_tokens}"
            )
        if self.n_tokens > self.n_pages * self.page_size:
            raise BlockIntegrityError(
                f"block claims {self.n_tokens} KV rows but carries only "
                f"{self.n_pages} pages of {self.page_size}"
            )
        if not self.history and self.produced != 0:
            # resume blocks always carry history; only a pure-prefix block
            # (prefix_store demotion: prompt KV, nothing emitted) may be
            # history-less, and it must claim zero produced tokens
            raise BlockIntegrityError("block without emitted history")
        # hold the block lock so the fingerprint reads a consistent
        # (payload, checksum) pair against a racing flusher to_host()
        with self._lock:
            if self._host and self.checksum is not None:
                if self._fingerprint() != self.checksum:
                    raise BlockIntegrityError(
                        "KV page payload checksum mismatch (corrupt block)"
                    )

    def to_bytes(self) -> bytes:
        """Wire format for cross-host shipment (the pod handoff): the
        host-materialized payload trees plus every resume field, one
        pickled dict. Host-materialization is the caller's job (``ship``
        runs off-tick, so the blocking :meth:`to_host` is legal there);
        the stamped checksum rides along and :meth:`from_bytes` re-verifies
        it on arrival, so transport corruption surfaces as
        :class:`BlockIntegrityError` — the importer's re-prefill fallback —
        never as wrong KV rows."""
        with self._lock:
            if not self._host:
                raise BlockIntegrityError(
                    "to_bytes() needs a host-materialized block — "
                    "call to_host() first (off the tick path)"
                )
            payload = {
                "k_pages": self.k_pages,
                "v_pages": self.v_pages,
                "n_tokens": self.n_tokens,
                "page_size": self.page_size,
                "prompt": self.prompt,
                "history": list(self.history),
                "produced": self.produced,
                "last_tok": self.last_tok,
                "resume_keys": self.resume_keys,
                "resume_recent": self.resume_recent,
                "share_hash": self.share_hash,
                "compress_kind": self.compress_kind,
                "compress_hash": self.compress_hash,
                "checksum": self.checksum,
            }
        import pickle

        return pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)

    @staticmethod
    def from_bytes(data: bytes) -> "KVPageBlock":
        """Rebuild a shipped block on the receiving host and verify it.
        Raises :class:`BlockIntegrityError` on any truncation, unpickle
        failure, or checksum mismatch — the caller counts the fallback and
        re-prefills from the resume history instead."""
        import pickle

        try:
            payload = pickle.loads(data)
            blk = KVPageBlock(
                k_pages=payload["k_pages"],
                v_pages=payload["v_pages"],
                n_tokens=int(payload["n_tokens"]),
                page_size=int(payload["page_size"]),
                prompt=np.asarray(payload["prompt"], np.int32),
                history=[int(t) for t in payload["history"]],
                produced=int(payload["produced"]),
                last_tok=int(payload["last_tok"]),
                resume_keys=payload["resume_keys"],
                resume_recent=payload["resume_recent"],
                share_hash=payload.get("share_hash"),
                compress_kind=payload.get("compress_kind"),
                compress_hash=payload.get("compress_hash"),
                checksum=payload["checksum"],
                _host=True,
            )
        except BlockIntegrityError:
            raise
        except Exception as e:  # noqa: BLE001 — any decode failure is corruption
            raise BlockIntegrityError(
                f"undecodable shipped block: {e!r}"
            ) from e
        blk.verify()
        return blk

    def compatible_with(self, cache) -> Optional[str]:
        """``None`` if this block's pages can be scattered into ``cache``'s
        pool; else a reason string. Catches cross-mode imports (int8 block
        into a bf16 pool and vice versa — the leaf trees differ) and any
        per-leaf geometry mismatch outside the pool axis. Compressed
        blocks are judged on their RECONSTRUCTED payload — import decodes
        first and calls :func:`pages_compatible` directly."""
        with self._lock:  # consistent payload view vs a racing to_host()
            return pages_compatible(self.k_pages, self.v_pages, cache)


def pages_compatible(k_pages, v_pages, cache, check_dtype=True) -> Optional[str]:
    """``None`` if the payload trees can be scattered into ``cache``'s
    pool; else a reason string. ``check_dtype=False`` is the lossy-lowrank
    import path: reconstruction yields float32 rows that the scatter casts
    into the pool dtype (the payload was never bit-exact to begin with)."""
    ours = jax.tree.structure((k_pages, v_pages))
    theirs = jax.tree.structure((cache.k, cache.v))
    if ours != theirs:
        return (
            f"KV storage mode mismatch: block {ours} vs pool {theirs}"
        )
    for blk, pool in zip(
        _leaves((k_pages, v_pages)),
        _leaves((cache.k, cache.v)),
    ):
        bs, ps = tuple(blk.shape), tuple(pool.shape)
        if len(bs) != len(ps) or bs[:2] != ps[:2] or bs[3:] != ps[3:]:
            return (
                f"page geometry mismatch: block leaf {bs} vs pool {ps}"
            )
        if check_dtype and np.dtype(blk.dtype) != np.dtype(pool.dtype):
            return (
                f"dtype mismatch: block {blk.dtype} vs pool {pool.dtype}"
            )
    return None


def export_block(
    cache,
    page_ids,
    *,
    page_size: int,
    n_tokens: int,
    prompt,
    history,
    produced: int,
    resume_keys,
    resume_recent,
    share_hash: Optional[str] = None,
    codec=None,
    gather=None,
    put=None,
) -> KVPageBlock:
    """Lift a request's page chain out of a paged cache as a
    :class:`KVPageBlock`. Dispatch-only on the device side: the returned
    block holds device arrays until someone calls :meth:`to_host`.

    ``gather`` lets the batcher pass its jitted ``export_pool_pages``;
    ``put`` its device-placement hook; ``codec`` the pool's
    ``kv_compress.KVCompressCodec`` — the block carries it so whoever
    flushes it to host (the spill tier's flusher, drain, a handoff)
    compresses the payload at that boundary. Fault site ``cache.export``
    fires before any device work so an injected failure leaves the cache
    untouched."""
    inject("cache.export", n_pages=len(page_ids), n_tokens=n_tokens)
    ids = np.asarray(list(page_ids), np.int32)
    if put is not None:
        ids = put(ids)
    fn = gather if gather is not None else export_pool_pages
    # self-instrumentation on the caller-bound trace (tracing.bind in the
    # scheduler/coordinator): the gather DISPATCH cost, not the DMA — the
    # copy itself lands in to_host on whoever pulls the block
    tr = tracing.current()
    if tr is not None:
        with tr.timed("kv_export", pages=len(page_ids), tokens=n_tokens):
            k_pages, v_pages = fn(cache, ids)
    else:
        k_pages, v_pages = fn(cache, ids)
    history = [int(t) for t in history]
    return KVPageBlock(
        k_pages=k_pages,
        v_pages=v_pages,
        n_tokens=int(n_tokens),
        page_size=int(page_size),
        prompt=np.array(prompt, np.int32, copy=True),
        history=history,
        produced=int(produced),
        # a pure-prefix export (prefix_store demotion) has emitted nothing:
        # there is no next decode input, so last_tok is a sentinel
        last_tok=int(history[-1]) if history else -1,
        resume_keys=resume_keys,
        resume_recent=resume_recent,
        share_hash=share_hash,
        _codec=codec,
    )


def import_block(cache, block: KVPageBlock, page_ids, *, share_hash=None,
                 codec=None, scatter=None, put=None):
    """Scatter ``block``'s page payloads into pool pages ``page_ids`` of
    ``cache`` and return the updated cache. Validates the block first
    (checksum + geometry + share-map and compress layout identities
    against the pool's ``share_hash``/``codec``); raises on any problem
    so the caller can release the pages and fall back to re-prefill.
    A compressed block reconstructs here (or consumes the prefetch-staged
    reconstruction); fault sites ``cache.import`` / ``cache.compress``
    model mid-import and mid-reconstruct failure."""
    inject("cache.import", n_pages=len(page_ids), n_tokens=block.n_tokens)
    block.verify()
    if block.share_hash != share_hash:
        # the geometry check below can't see this (a 2-layer-pair share
        # map halves the pool's layer axis, but two DIFFERENT maps with
        # the same group count are byte-compatible and silently wrong)
        raise BlockIntegrityError(
            f"KV share-map layout mismatch: block was exported under "
            f"share_hash={block.share_hash!r} but this pool runs "
            f"{share_hash!r} — re-prefill, or serve both hosts with the "
            f"same --kv-share-map artifact"
        )
    if block.compress_kind is not None:
        want = codec.compress_hash if codec is not None else None
        if block.compress_hash != want:
            raise BlockIntegrityError(
                f"KV compress layout mismatch: block carries a "
                f"{block.compress_kind!r} payload under compress_hash="
                f"{block.compress_hash!r} but this pool's codec is "
                f"{want!r} — re-prefill, or serve both hosts with the "
                f"same model/--kv-compress-map geometry"
            )
    if len(page_ids) != block.n_pages:
        raise BlockIntegrityError(
            f"import wants {len(page_ids)} pages for a {block.n_pages}-page block"
        )
    # prefetch-staged device copies when present (the overlapped path —
    # already reconstructed for compressed blocks); otherwise the raw
    # payload, reconstructed here — host numpy here IS the demand import
    if block.compress_kind is not None and not block.is_prefetched:
        try:
            k_pages, v_pages = codec.reconstruct_block(block)
        except Exception as e:  # noqa: BLE001 — fault or codec failure, same fallback
            codec.note_fault("decode")
            raise BlockIntegrityError(
                f"compressed block reconstruction failed: {e}"
            ) from e
        reason = pages_compatible(
            k_pages, v_pages, cache,
            check_dtype=block.compress_kind == "latent",
        )
    elif block.compress_kind is not None:
        k_pages, v_pages = block.payload()
        reason = pages_compatible(
            k_pages, v_pages, cache, check_dtype=False,
        )
    else:
        reason = block.compatible_with(cache)
        k_pages, v_pages = block.payload()
    if reason is not None:
        raise BlockIntegrityError(reason)
    ids = np.asarray(list(page_ids), np.int32)
    if put is not None:
        ids = put(ids)
    fn = scatter if scatter is not None else import_pool_pages
    tr = tracing.current()
    if tr is not None:
        with tr.timed("kv_import", pages=len(page_ids),
                      tokens=block.n_tokens):
            return fn(cache, k_pages, v_pages, ids)
    return fn(cache, k_pages, v_pages, ids)


class KVSpillTier:
    """Host-DRAM LRU spill tier for preempted requests' KV blocks.

    ``put`` is cheap on the caller (scheduler) thread: it only links the
    block into the LRU map and enqueues it for the background flusher
    thread, which performs the blocking device→host copy off the tick
    path. Eviction is strict LRU by insertion/refresh order; a block
    larger than the whole budget is rejected outright (the caller falls
    back to discard-and-re-prefill, exactly the pre-spill behavior).

    Keys are the owning request objects (identity), so a tier entry dies
    with its request and two requests can never collide."""

    def __init__(self, budget_bytes: int, flush_async: bool = True):
        if not isinstance(budget_bytes, int) or isinstance(budget_bytes, bool) \
                or budget_bytes <= 0:
            raise ValueError("spill budget must be a positive byte count")
        self.budget_bytes = budget_bytes
        self._blocks: "OrderedDict[object, KVPageBlock]" = OrderedDict()
        # bytes each resident block is currently charged against the
        # budget. A block's nbytes SHRINKS when the flusher's to_host
        # compresses it (kv_compress), so accounting must remember what
        # was charged at insert and re-charge after the flush — reading
        # blk.nbytes at pop time would leak the difference forever.
        self._sizes: dict = {}
        self._bytes = 0
        self.bytes_compress_saved = 0
        self._lock = make_lock("KVSpillTier._lock")
        self.evictions = 0
        # rejects split by reason (the aggregate stays for back-compat):
        # oversize = the block alone exceeds the whole budget; closed = a
        # put raced the tier's shutdown
        self.rejects = 0
        self.rejects_oversize = 0
        self.rejects_closed = 0
        # take() outcomes: a hit hands the resume its block (one scatter
        # instead of a re-prefill), a miss means LRU pressure evicted it
        # since the spill — the caller re-prefills. hit_rate in stats() is
        # hits / (hits + misses).
        self.hits = 0
        self.misses = 0
        self.bytes_spilled_total = 0
        self._flush_async = flush_async
        self._flush_q: "queue.Queue" = queue.Queue()
        self._flusher: Optional[threading.Thread] = None
        self._stopped = False

    # ------------------------------------------------------------- flusher
    def _ensure_flusher(self):
        # caller holds self._lock
        if self._flusher is None or not self._flusher.is_alive():
            self._flusher = threading.Thread(
                target=self._flush_loop, name="kv-spill-flusher", daemon=True
            )
            self._flusher.start()

    def _flush_loop(self):
        while True:
            item = self._flush_q.get()
            if item is None:
                return
            key, blk = item
            try:
                blk.to_host()
            except Exception:
                # a failed flush leaves the block device-resident; take()
                # still works while the arrays are alive, and verify() has
                # no checksum to mismatch — degraded, not broken
                logger.exception("KV spill flush failed; block stays on device")
            else:
                self._reaccount(key, blk)

    def _reaccount(self, key, blk: KVPageBlock) -> None:
        """Re-charge a flushed block at its post-compression size — the
        compressed-latent wire form counts fewer bytes against the budget,
        so the tier holds proportionally more blocks (the transfer
        multiplier doubles as a capacity multiplier)."""
        nb = blk.nbytes
        with self._lock:
            if self._blocks.get(key) is not blk:
                return  # dropped/replaced while flushing
            old = self._sizes.get(key, nb)
            if nb != old:
                self._sizes[key] = nb
                self._bytes += nb - old
                if nb < old:
                    self.bytes_compress_saved += old - nb

    # ------------------------------------------------------------- LRU map
    def put(self, key, block: KVPageBlock) -> bool:
        """Admit ``block`` under the budget, evicting LRU entries as
        needed. Returns False (and counts a reject) when the block alone
        exceeds the budget or the tier is closed."""
        nb = block.nbytes
        with self._lock:
            if self._stopped:
                self.rejects += 1
                self.rejects_closed += 1
                return False
            if nb > self.budget_bytes:
                self.rejects += 1
                self.rejects_oversize += 1
                return False
            old = self._blocks.pop(key, None)
            if old is not None:
                self._bytes -= self._sizes.pop(key, old.nbytes)
                note_release("tier.block", (id(self), key))
            while self._bytes + nb > self.budget_bytes and self._blocks:
                ek, evicted = self._blocks.popitem(last=False)
                self._bytes -= self._sizes.pop(ek, evicted.nbytes)
                self.evictions += 1
                note_release("tier.block", (id(self), ek))
            self._blocks[key] = block
            self._sizes[key] = nb
            self._bytes += nb
            note_acquire("tier.block", (id(self), key), nbytes=nb)
            self.bytes_spilled_total += nb
            if self._flush_async:
                self._ensure_flusher()
        if self._flush_async:
            self._flush_q.put((key, block))
        else:
            block.to_host()
            self._reaccount(key, block)
        return True

    def _pop(self, key) -> Optional[KVPageBlock]:
        # caller-agnostic removal: no hit/miss accounting (drop() uses it
        # for cancelled streams, which are neither)
        with self._lock:
            blk = self._blocks.pop(key, None)
            if blk is not None:
                self._bytes -= self._sizes.pop(key, blk.nbytes)
                note_release("tier.block", (id(self), key))
            return blk

    def take(self, key) -> Optional[KVPageBlock]:
        """Remove and return ``key``'s block for a resume, or None if LRU
        pressure evicted it since the spill; counts the hit/miss."""
        blk = self._pop(key)
        with self._lock:
            if blk is not None:
                self.hits += 1
            else:
                self.misses += 1
        return blk

    def peek(self, key) -> Optional[KVPageBlock]:
        with self._lock:
            return self._blocks.get(key)

    def touch(self, key) -> None:
        """LRU refresh without removal — the scheduler calls this when a
        spilled request is back in the resume path (head of the waiting
        line, a cold slot's consumer caught up), so budget pressure evicts
        some genuinely-cold block instead of the one about to re-import."""
        with self._lock:
            if key in self._blocks:
                self._blocks.move_to_end(key)

    def contains(self, key) -> bool:
        with self._lock:
            return key in self._blocks

    def keys(self) -> list:
        """Snapshot of resident keys, MRU-first — the prefix store's pod
        inventory reads this to gossip what this host can serve."""
        with self._lock:
            return list(reversed(self._blocks.keys()))

    def share_hashes(self) -> set:
        """Distinct ``share_hash`` values across resident blocks — the
        prefix store's share-map bind check reads this to reject a layout
        change over blocks exported under another one."""
        with self._lock:
            return {b.share_hash for b in self._blocks.values()}

    def compress_hashes(self) -> set:
        """Distinct ``compress_hash`` values across resident blocks — the
        prefix store's compress bind check reads this the same way. A
        still-raw block (flusher hasn't compressed it yet, or no codec)
        contributes None, which is always bind-compatible: raw payloads
        import anywhere their geometry fits."""
        with self._lock:
            return {b.compress_hash for b in self._blocks.values()}

    def drop(self, key) -> None:
        self._pop(key)

    def clear(self) -> None:
        with self._lock:
            self._blocks.clear()
            self._sizes.clear()
            self._bytes = 0
            tid = id(self)
            note_reset("tier.block", lambda k: k[0] == tid)

    def stats(self) -> dict:
        with self._lock:
            lookups = self.hits + self.misses
            return {
                "budget_bytes": self.budget_bytes,
                "bytes_in_use": self._bytes,
                "blocks": len(self._blocks),
                # blocks the flusher has host-materialized so far — the
                # prefetchable population (a still-device block needs no
                # staging); also what lets tests wait out the async flush
                "blocks_host": sum(
                    1 for b in self._blocks.values() if b.is_host
                ),
                "evictions": self.evictions,
                "rejects": self.rejects,
                "rejects_oversize": self.rejects_oversize,
                "rejects_closed": self.rejects_closed,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (self.hits / lookups) if lookups else 0.0,
                "bytes_spilled_total": self.bytes_spilled_total,
                # budget headroom reclaimed by compressed-latent flushes
                "bytes_compress_saved": self.bytes_compress_saved,
            }

    def close(self) -> None:
        with self._lock:
            self._stopped = True
            flusher = self._flusher
        self._flush_q.put(None)
        if flusher is not None and flusher.is_alive():
            flusher.join(timeout=5)
        self.clear()
