"""Llama-family decoder (also serves Mistral/Qwen2 via MODEL_REMAPPING,
as in the reference: shard/utils.py:14-17).

Capability parity target: shard/server/model/llama.py — pipeline-aware
stage model with embed on first stage, norm + head (or tied embedding) on
last (llama.py:26-36,74-89), causal masking with cache offset (llama.py:48-53),
out-of-range weight dropping (sanitize, llama.py:92-107 — done in our loader).

TPU-native design: the stage's layers run as one ``lax.scan`` over stacked
parameters; the KV cache rides through the scan as xs/ys so XLA keeps all
per-layer state in HBM with in-place dynamic-update-slice writes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mlx_sharding_tpu.cache import KVCache, advance, write_layer_kv
from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.models.base import BaseModel, dense_init
from mlx_sharding_tpu.ops import apply_rope, causal_attention, rms_norm, rope_frequencies


class LlamaModel(BaseModel):
    # decoder-layer projections may stay 4-bit packed in HBM
    # (loading.load_model(keep_quantized=True) → ops.quant.linear dispatch)
    supports_packed = True
    # sequence-parallel paths use the default sp_layer over the
    # layer_attn_inputs/layer_finish hook pair below
    supports_sp = True

    def __init__(self, config: LlamaConfig):
        super().__init__(config)
        self.inv_freq = jnp.asarray(
            rope_frequencies(config.head_dim, config.rope_theta, config.rope_scaling)
        )
        self.scale = config.head_dim ** -0.5

    # ------------------------------------------------------------------
    def layer_attn_inputs(self, p, h, offset):
        """Pre-attention half of a decoder layer: norm + QKV + RoPE at
        absolute positions ``offset..offset+T``. Split out so the sequence-
        parallel prefill path (parallel/sp_prefill.py) can swap the attention
        op (ring over ``sp``) while reusing the exact projection math.

        Head counts are derived from the projection OUTPUT shapes, not the
        config — under tensor parallelism each device's param shard carries
        heads/tp heads and this same code runs unchanged on the slice."""
        cfg = self.config
        b, t, _ = h.shape
        d = cfg.head_dim

        r = rms_norm(h, p["input_norm"], cfg.rms_norm_eps)
        if "qkv_proj" in p:
            # build-time fused packed projection (engine applied
            # fused_projection_groups): one kernel launch, one pass over the
            # activation planes. Split sizes come from the CONFIG (not the
            # shard) because fusion is only applied at tp == 1.
            nq, nkv = cfg.num_attention_heads * d, cfg.num_key_value_heads * d
            qkv = self._linear(r, p["qkv_proj"])
            q, k, v = jnp.split(qkv, [nq, nq + nkv], axis=-1)
        else:
            q = self._linear(r, p["q_proj"])
            k = self._linear(r, p["k_proj"])
            v = self._linear(r, p["v_proj"])
        if cfg.attention_bias:  # Qwen2-style QKV biases
            q = q + p["q_bias"]
            k = k + p["k_bias"]
            v = v + p["v_bias"]
        q = q.reshape(b, t, q.shape[-1] // d, d)
        k = k.reshape(b, t, k.shape[-1] // d, d)
        v = v.reshape(b, t, v.shape[-1] // d, d)
        q = apply_rope(q, self.inv_freq, offset)
        k = apply_rope(k, self.inv_freq, offset)
        return q, k, v

    def layer_finish(self, p, h, attn, tp_axis=None):
        """Post-attention half: output projection + SwiGLU MLP. Under TP the
        O and down projections contract over sharded dims, so their partial
        products psum over ``tp_axis`` — exactly two collectives per layer
        (Megatron-style column/row split), riding ICI."""
        cfg = self.config
        b, t, _ = h.shape
        attn_out = self._linear(attn.reshape(b, t, -1), p["o_proj"])
        if tp_axis is not None:
            attn_out = jax.lax.psum(attn_out, tp_axis)
        h = h + attn_out
        r = rms_norm(h, p["post_norm"], cfg.rms_norm_eps)
        if "gate_up_proj" in p:  # build-time fused packed gate+up (tp == 1)
            gu = self._linear(r, p["gate_up_proj"])
            gate, up = jnp.split(gu, [cfg.intermediate_size], axis=-1)
            ff = self._linear(jax.nn.silu(gate) * up, p["down_proj"])
        else:
            ff = self._linear(
                jax.nn.silu(self._linear(r, p["gate_proj"]))
                * self._linear(r, p["up_proj"]),
                p["down_proj"],
            )
        if tp_axis is not None:
            ff = jax.lax.psum(ff, tp_axis)
        return h + ff

    def _layer(self, h, p, k_buf, v_buf, offset, tp_axis=None):
        q, k, v = self.layer_attn_inputs(p, h, offset)
        k_buf, v_buf = write_layer_kv(k_buf, v_buf, k, v, offset)
        attn = causal_attention(q, k_buf, v_buf, offset, self.scale)
        return self.layer_finish(p, h, attn, tp_axis), k_buf, v_buf

    def run_layers(self, layer_params, h, k, v, offset, mask=None, tp_axis=None):
        """The stage body: scan the (local) stacked layers, threading the
        full-capacity K/V buffers (L, B, S, H, D) through as scan xs/ys.
        This is the piece the SPMD pipeline executes per tick; ``__call__``
        wraps it with embed/head for the single-program path. ``mask`` is an
        optional (L,) bool marking active layers — padding slots in the fused
        engine's uniform per-stage stacks scan as no-ops. ``tp_axis`` names
        the mesh axis attention heads / MLP columns are sharded over."""
        from mlx_sharding_tpu.models.base import scan_layers

        def body(h, p, k_buf, v_buf):
            return self._layer(h, p, k_buf, v_buf, offset, tp_axis)

        return scan_layers(body, h, layer_params, k, v, mask)

    def tp_layer_axes(self) -> dict:
        """Per-layer-param dim (counted after the stacked-L axis) sharded
        over tp: column-parallel QKV/gate/up (output dim), row-parallel
        O/down (contracting dim); norms replicated."""
        axes = {
            "input_norm": None, "post_norm": None,
            "q_proj": 1, "k_proj": 1, "v_proj": 1, "o_proj": 0,
            "gate_proj": 1, "up_proj": 1, "down_proj": 0,
        }
        if self.config.attention_bias:
            axes.update({"q_bias": 0, "k_bias": 0, "v_bias": 0})
        return axes

    def fused_projection_groups(self) -> dict:
        """QKV and gate+up share their input activations — the engines may
        concatenate each group's packed triples along OUT at build time so
        decode issues one kernel launch per group instead of three/two."""
        return {
            "qkv_proj": ("q_proj", "k_proj", "v_proj"),
            "gate_up_proj": ("gate_proj", "up_proj"),
        }

    def head_input(self, params, h):
        """Final norm before the (tied-embedding aware) LM head — ref
        llama.py:74-77, 84-89."""
        return rms_norm(h, params["final_norm"]["weight"], self.config.rms_norm_eps)

    def __call__(self, params, x, cache: KVCache, n_valid=None):
        """``n_valid`` (traced scalar) advances the cache by fewer positions
        than T when the input is a right-padded prefill chunk; pad-position
        K/V writes are overwritten by later contiguous writes before any
        valid query can attend them (see generate.py docstring)."""
        cfg = self.config
        h = self.embed(params, x) if cfg.is_first_stage else x
        offset = cache.offset
        h, k, v = self.run_layers(params["layers"], h, cache.k, cache.v, offset)
        cache = KVCache(k=k, v=v, offset=offset)
        cache = advance(cache, x.shape[1] if n_valid is None else n_valid)

        if cfg.is_last_stage:
            return self.apply_head(params, h), cache
        return h, cache

    # ------------------------------------------------------------------
    HF_LAYER_MAP = {
        "input_layernorm.weight": ("input_norm", False),
        "post_attention_layernorm.weight": ("post_norm", False),
        "self_attn.q_proj.weight": ("q_proj", True),
        "self_attn.k_proj.weight": ("k_proj", True),
        "self_attn.v_proj.weight": ("v_proj", True),
        "self_attn.o_proj.weight": ("o_proj", True),
        "mlp.gate_proj.weight": ("gate_proj", True),
        "mlp.up_proj.weight": ("up_proj", True),
        "mlp.down_proj.weight": ("down_proj", True),
    }

    def map_weights(self, weights: dict, dtype=jnp.bfloat16) -> dict:
        """HF-named (already stage-filtered, dequantized) tensors → the
        scan-ready stacked pytree. Plays the role of the reference models'
        sanitize + load_weights (shard/server/model/llama.py:92-107,
        shard/utils.py:66-67)."""
        from mlx_sharding_tpu.loading import (
            collect_layer_stack,
            first_key,
            vocab_param,
        )

        cfg = self.config
        layer_map = dict(self.HF_LAYER_MAP)
        if cfg.attention_bias:  # Qwen2 checkpoints carry QKV biases
            layer_map.update(
                {
                    "self_attn.q_proj.bias": ("q_bias", False),
                    "self_attn.k_proj.bias": ("k_bias", False),
                    "self_attn.v_proj.bias": ("v_bias", False),
                }
            )
        params = {"layers": collect_layer_stack(weights, cfg, layer_map, dtype)}
        if cfg.needs_embed:
            embed = first_key(weights, "model.embed_tokens.weight", "embed_tokens.weight")
            params["embed"] = {"weight": vocab_param(embed, dtype)}
        if cfg.needs_head:
            norm = first_key(weights, "model.norm.weight", "norm.weight")
            params["final_norm"] = {"weight": jnp.asarray(norm, dtype)}
            if not cfg.tie_word_embeddings:
                head = first_key(weights, "lm_head.weight")
                params["lm_head"] = {"weight": vocab_param(head, dtype, transpose=True)}
        return params

    def init_params(self, key, dtype=jnp.bfloat16):
        """Random params for this stage — tests and benchmarks only."""
        cfg = self.config
        hd, hq, hkv, d = cfg.hidden_size, cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        inter, nl = cfg.intermediate_size, cfg.num_local_layers
        keys = iter(jax.random.split(key, 8 * nl + 4))

        def layer():
            out = {
                "input_norm": jnp.ones((hd,), dtype),
                "post_norm": jnp.ones((hd,), dtype),
                "q_proj": dense_init(next(keys), hd, hq * d, dtype),
                "k_proj": dense_init(next(keys), hd, hkv * d, dtype),
                "v_proj": dense_init(next(keys), hd, hkv * d, dtype),
                "o_proj": dense_init(next(keys), hq * d, hd, dtype),
                "gate_proj": dense_init(next(keys), hd, inter, dtype),
                "up_proj": dense_init(next(keys), hd, inter, dtype),
                "down_proj": dense_init(next(keys), inter, hd, dtype),
            }
            if cfg.attention_bias:
                out["q_bias"] = jnp.zeros((hq * d,), dtype)
                out["k_bias"] = jnp.zeros((hkv * d,), dtype)
                out["v_bias"] = jnp.zeros((hkv * d,), dtype)
            return out

        from mlx_sharding_tpu.models.base import stack_layers

        params = {"layers": stack_layers([layer() for _ in range(nl)])}
        if cfg.needs_embed:
            params["embed"] = {
                "weight": dense_init(next(keys), cfg.vocab_size, hd, dtype, scale=0.02)
            }
        if cfg.needs_head:
            params["final_norm"] = {"weight": jnp.ones((hd,), dtype)}
            if not cfg.tie_word_embeddings:
                params["lm_head"] = {"weight": dense_init(next(keys), hd, cfg.vocab_size, dtype)}
        return params
