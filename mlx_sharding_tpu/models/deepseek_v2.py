"""DeepSeek-V2 decoder — MLA attention + fine-grained MoE with shared experts.

Capability parity: shard/server/model/deepseek_v2.py — the reference reuses
mlx_lm's DeepseekV2DecoderLayer (ref :8,30), stacks per-expert weights into
fused switch tensors in sanitize (ref :101-112), and exposes the MLA tuple
head-dim cache shape (ref :120-125). Here the architecture is first-party:

- **MLA**: queries (optionally LoRA-factored), K/V decompressed from a
  shared low-rank latent (``kv_a_proj_with_mqa`` → rank + single-head rope
  part; ``kv_b_proj`` → per-head nope-K and V), interleaved complex-pair
  RoPE with YaRN frequencies/attention-scaling, K dim ≠ V dim in the cache
  (our KVCache carries per-tensor head dims).
- **MoE**: first ``first_k_dense_replace`` layers are dense SwiGLU; the rest
  route over ``n_routed_experts`` small experts (greedy or
  group-limited-greedy top-k on fp32 softmax scores, routed_scaling_factor)
  plus always-on shared experts. Experts stay stage-local (SURVEY §2.3 EP)
  as stacked (L, E, …) tensors driven by the scan/gather dispatch.

The stage's layers run as TWO scans (dense prefix, then MoE) since their
param trees differ; the KV cache is one stacked buffer sliced between them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mlx_sharding_tpu.cache import KVCache, advance, write_layer_kv
from mlx_sharding_tpu.config import DeepseekV2Config
from mlx_sharding_tpu.models.base import BaseModel, dense_init, stack_layers
from mlx_sharding_tpu.ops import causal_attention, rms_norm
from mlx_sharding_tpu.ops.moe import apply_experts, deepseek_routing
from mlx_sharding_tpu.ops.rope import (
    apply_rope_interleaved,
    rope_frequencies,
    yarn_frequencies,
    yarn_get_mscale,
)


class DeepseekV2Model(BaseModel):
    # MLA projections and the (E, …) expert stacks may stay 4-bit packed in
    # HBM; the router (fp32 routing einsum) and — in compressed cache mode —
    # kv_b (absorbed into einsums as a tensor) load dense via
    # packed_keep_dense_re.
    supports_packed = True
    supports_sp = True  # sp_layer below (MLA-aware, grouped dense/moe scan)

    def __init__(self, config: DeepseekV2Config):
        super().__init__(config)
        scaling = config.rope_scaling
        rope_type = (scaling or {}).get("type", (scaling or {}).get("rope_type"))
        if rope_type == "yarn":
            inv_freq, self.rope_scale = yarn_frequencies(
                config.qk_rope_head_dim,
                config.rope_theta,
                scaling,
                config.max_position_embeddings,
            )
        else:
            inv_freq = rope_frequencies(config.qk_rope_head_dim, config.rope_theta, None)
            self.rope_scale = 1.0
        self.inv_freq = jnp.asarray(inv_freq)
        self.scale = config.head_dim**-0.5  # head_dim == qk_nope + qk_rope
        # DeepSeek's YaRN variant also rescales the softmax scale itself when
        # mscale_all_dim is set (mlx_lm DeepseekV2Attention; DeepSeek remote
        # code). The cos/sin attention_factor above is 1.0 for real V2
        # checkpoints (mscale == mscale_all_dim == 0.707), so without this the
        # logits come out ~1.59x too small at factor=40.
        if rope_type == "yarn" and scaling.get("mscale_all_dim"):
            self.scale *= yarn_get_mscale(
                float(scaling["factor"]), float(scaling["mscale_all_dim"])
            ) ** 2

    def cache_head_dim(self):
        cfg = self.config
        if cfg.mla_cache_mode == "compressed":
            # one shared "head": latent + rope dims; the v buffer is a dummy
            # (values are a slice of the latent key)
            return (cfg.kv_lora_rank + cfg.qk_rope_head_dim, 1)
        # (K dim, V dim) tuple — ref deepseek_v2.py:120-125
        return (cfg.qk_nope_head_dim + cfg.qk_rope_head_dim, cfg.v_head_dim)

    def cache_num_heads(self) -> int:
        cfg = self.config
        return 1 if cfg.mla_cache_mode == "compressed" else cfg.num_attention_heads

    def cache_tp_replicated(self) -> bool:
        # the compressed-latent cache stores ONE shared latent "head" whose
        # writes are computed from tp-replicated projections — identical on
        # every tp device, so the buffer replicates while q heads shard
        return self.config.mla_cache_mode == "compressed"

    def layer_group_ranges(self) -> dict:
        cfg = self.config
        fk = min(max(cfg.first_k_dense_replace, 0), cfg.num_hidden_layers)
        out = {}
        if fk > 0:
            out["dense"] = (0, fk)
        if fk < cfg.num_hidden_layers:
            out["moe"] = (fk, cfg.num_hidden_layers)
        return out

    def ep_layer_axes(self) -> dict:
        """Nested (per-group) map: only the moe group's routed expert
        stacks shard over ep; shared experts/router/attention replicate."""
        return {"moe": {"w_gate": 0, "w_up": 0, "w_down": 0}}

    def packed_keep_dense_re(self) -> str | None:
        # router feeds the fp32 routing einsum; kv_b is consumed as a raw
        # (rank, heads, nope+v) tensor by the absorbed compressed-cache
        # einsums — per-token dequant there would cost more HBM traffic
        # than dense residency saves
        if self.config.mla_cache_mode == "compressed":
            return r"mlp\.gate\.weight$|self_attn\.kv_b_proj\.weight$"
        return r"mlp\.gate\.weight$"

    def tp_layer_axes(self) -> dict:
        """MLA tensor parallelism (per-group nested map; dims counted after
        the stacked-L axis). Per-head projections shard: q/q_b and kv_b
        column-parallel (whole heads per device — the output dim is
        (heads, head_dim) flattened, so a contiguous heads/tp split is
        head-aligned), o_proj row-parallel. The low-rank latent path
        (q_a/kv_a + norms) and the router replicate. Expert stacks shard
        their intermediate dim over tp (overridden to the E dim by
        ep_layer_axes when a tp x ep mesh is in play — the engine merges
        ep after tp); shared experts split column/row like a dense MLP."""
        attn = {
            "input_norm": None, "post_norm": None,
            "kv_a_proj": None, "kv_a_norm": None,
            "kv_b_proj": 1, "o_proj": 0,
        }
        if self.config.q_lora_rank is None:
            attn["q_proj"] = 1
        else:
            attn.update({"q_a_proj": None, "q_a_norm": None, "q_b_proj": 1})
        out = {}
        if "dense" in self.layer_group_ranges():
            out["dense"] = {
                **attn, "gate_proj": 1, "up_proj": 1, "down_proj": 0,
            }
        if "moe" in self.layer_group_ranges():
            out["moe"] = {
                **attn, "router": None,
                "shared_gate": 1, "shared_up": 1, "shared_down": 0,
                "w_gate": 2, "w_up": 2, "w_down": 1,
            }
        return out

    # ------------------------------------------------------------------
    def _attn_qkv(self, p, h, offset):
        """Shared MLA projection math of the causal and sequence-parallel
        attention paths. Compressed mode returns ``(q_cat (B,T,H,rank+rope),
        k_new (B,T,1,rank+rope), None, w_bv (rank,H,v_d))`` — kv_b absorbed
        into the query side, values are the latent slice of the keys.
        Decompressed: ``(q_full, k, v, None)`` with per-head K/V."""
        cfg = self.config
        b, t, _ = h.shape
        nope, rope_d, v_d = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
        rank = cfg.kv_lora_rank

        r = rms_norm(h, p["input_norm"], cfg.rms_norm_eps)
        if cfg.q_lora_rank is None:
            q = self._linear(r, p["q_proj"])
        else:
            q = self._linear(
                rms_norm(self._linear(r, p["q_a_proj"]), p["q_a_norm"], cfg.rms_norm_eps),
                p["q_b_proj"],
            )
        q = q.reshape(b, t, -1, nope + rope_d)
        q_nope, q_pe = q[..., :nope], q[..., nope:]
        q_pe = apply_rope_interleaved(q_pe, self.inv_freq, offset, self.rope_scale)

        ckv = self._linear(r, p["kv_a_proj"])  # (B, T, rank + rope_d)
        compressed, k_pe_raw = ckv[..., :rank], ckv[..., rank:]
        latent = rms_norm(compressed, p["kv_a_norm"], cfg.rms_norm_eps)
        k_pe = apply_rope_interleaved(
            k_pe_raw[:, :, None, :], self.inv_freq, offset, self.rope_scale
        )  # single shared rope head

        if cfg.mla_cache_mode == "compressed":
            w_b = p["kv_b_proj"].reshape(rank, -1, nope + v_d)
            w_bk, w_bv = w_b[..., :nope], w_b[..., nope:]
            q_lat = jnp.einsum(
                "bthn,rhn->bthr", q_nope, w_bk, preferred_element_type=jnp.float32
            ).astype(h.dtype)
            q_cat = jnp.concatenate([q_lat, q_pe], axis=-1)  # (B,T,H,rank+rope)
            k_new = jnp.concatenate([latent[:, :, None, :], k_pe], axis=-1)
            return q_cat, k_new, None, w_bv
        kv = self._linear(latent, p["kv_b_proj"]).reshape(b, t, -1, nope + v_d)
        k_nope, v = kv[..., :nope], kv[..., nope:]
        k = jnp.concatenate(
            [k_nope, jnp.broadcast_to(k_pe, (*k_nope.shape[:-1], rope_d))],
            axis=-1,
        )
        q_full = jnp.concatenate([q_nope, q_pe], axis=-1)
        return q_full, k, v, None

    def _attention(self, h, p, k_buf, v_buf, offset, tp_axis=None):
        """MLA under tensor parallelism: the low-rank latent path
        (kv_a_proj / kv_a_norm and the single rope head) is REPLICATED —
        it is head-count independent — while the per-head projections
        (q/q_b, kv_b, o) shard over tp. Head counts derive from the
        projection shard shapes, so this code runs the full model and any
        tp slice unchanged; one psum after o_proj completes the row-parallel
        output projection."""
        cfg = self.config
        b, t, _ = h.shape
        rank = cfg.kv_lora_rank
        q, k_new, v_new, w_bv = self._attn_qkv(p, h, offset)
        if cfg.mla_cache_mode == "compressed":
            # Cache the latent, not per-head K/V: per token only
            # rank + rope_d numbers, independent of head count. kv_b is
            # absorbed into the query (scores) and output (values) sides, so
            # the math is identical to the decompressed path.
            dummy_v = jnp.zeros((b, t, 1, 1), v_buf.dtype)
            k_buf, v_buf = write_layer_kv(k_buf, v_buf, k_new, dummy_v, offset)
            # MQA over the single latent head; "values" are the latent slice
            # of the key buffer, so no second buffer is stored.
            out_lat = causal_attention(
                q, k_buf, k_buf[..., :rank], offset, self.scale
            )  # (B,T,H,rank)
            attn = jnp.einsum(
                "bthr,rhv->bthv", out_lat, w_bv, preferred_element_type=jnp.float32
            ).astype(h.dtype)
        else:
            k_buf, v_buf = write_layer_kv(k_buf, v_buf, k_new, v_new, offset)
            attn = causal_attention(q, k_buf, v_buf, offset, self.scale)
        attn_out = self._linear(attn.reshape(b, t, -1), p["o_proj"])
        if tp_axis is not None:
            attn_out = jax.lax.psum(attn_out, tp_axis)
        return h + attn_out, k_buf, v_buf

    def sp_groups(self):
        return list(self.layer_group_ranges().keys())

    def sp_layer(self, p, h, offset, attn_fn, group=None):
        """Sequence-parallel MLA layer. Compressed mode rides the injected
        attention as MQA over the single latent head with ``values_from_k``
        (the latent slice of the key rows serves as values — the same kv_b
        absorption as _attention), so ring prefill and sharded-KV decode
        both work on the compressed cache layout; the returned rows match
        it (latent+rope keys, dummy values)."""
        cfg = self.config
        b, t, _ = h.shape
        rank = cfg.kv_lora_rank
        q, k_new, v_new, w_bv = self._attn_qkv(p, h, offset)
        if cfg.mla_cache_mode == "compressed":
            v_new = jnp.zeros((b, t, 1, 1), h.dtype)
            out_lat = attn_fn(q, k_new, v_new, values_from_k=rank)
            attn = jnp.einsum(
                "bthr,rhv->bthv", out_lat, w_bv, preferred_element_type=jnp.float32
            ).astype(h.dtype)
        else:
            attn = attn_fn(q, k_new, v_new)
        h = h + self._linear(attn.reshape(b, t, -1), p["o_proj"])
        r = rms_norm(h, p["post_norm"], cfg.rms_norm_eps)
        if group == "moe":
            ff = self._moe_mlp(r.reshape(b * t, -1), p).reshape(b, t, -1)
        else:
            ff = self._swiglu(r, p["gate_proj"], p["up_proj"], p["down_proj"])
        return h + ff, k_new, v_new

    def _swiglu(self, r, gate, up, down):
        return self._linear(
            jax.nn.silu(self._linear(r, gate)) * self._linear(r, up), down
        )

    def _dense_layer(self, h, p, k_buf, v_buf, offset, tp_axis=None):
        cfg = self.config
        h, k_buf, v_buf = self._attention(h, p, k_buf, v_buf, offset, tp_axis)
        r = rms_norm(h, p["post_norm"], cfg.rms_norm_eps)
        ff = self._swiglu(r, p["gate_proj"], p["up_proj"], p["down_proj"])
        if tp_axis is not None:
            ff = jax.lax.psum(ff, tp_axis)
        return h + ff, k_buf, v_buf

    def _moe_mlp(self, flat, p, tp_axis=None, ep_axis=None):
        """Routed + shared experts over (N, hidden) rows. Routing is
        replicated over ep (router weights replicated, global expert ids);
        only the expert stacks shard."""
        cfg = self.config
        weights, idx = deepseek_routing(
            flat, p["router"], cfg.num_experts_per_tok,
            norm_topk_prob=cfg.norm_topk_prob,
            routed_scaling_factor=cfg.routed_scaling_factor,
            topk_method=cfg.topk_method,
            n_group=cfg.n_group,
            topk_group=cfg.topk_group,
        )
        routed = apply_experts(
            flat, weights, idx, p["w_gate"], p["w_up"], p["w_down"],
            ep_axis=ep_axis, group_size=self._gs, bits=self._bits,
        )
        # shared experts are always-on and replicated across ep — their
        # contribution must NOT enter the ep psum
        shared = self._swiglu(
            flat, p["shared_gate"], p["shared_up"], p["shared_down"]
        )
        if tp_axis is not None:
            if ep_axis is None:
                # experts shard their intermediate dim over tp: routed AND
                # shared are both partial products — one combined psum
                return jax.lax.psum(routed + shared, tp_axis)
            # tp x ep: expert stacks shard over ep (full after the ep
            # psum inside apply_experts, replicated across tp); only the
            # tp-sharded shared experts need the tp psum
            return routed + jax.lax.psum(shared, tp_axis)
        return routed + shared

    def _moe_layer(self, h, p, k_buf, v_buf, offset, tp_axis=None, ep_axis=None):
        cfg = self.config
        b, t, hidden = h.shape
        h, k_buf, v_buf = self._attention(h, p, k_buf, v_buf, offset, tp_axis)
        r = rms_norm(h, p["post_norm"], cfg.rms_norm_eps)
        combined = self._moe_mlp(r.reshape(b * t, hidden), p, tp_axis, ep_axis)
        return h + combined.reshape(b, t, hidden), k_buf, v_buf

    # ------------------------------------------------------------------
    def _layer_split(self) -> tuple[int, int]:
        """(#dense, #moe) layers in this stage's local range."""
        cfg = self.config
        n_dense = max(
            0, min(cfg.end_layer, cfg.first_k_dense_replace) - cfg.start_layer
        )
        return n_dense, cfg.num_local_layers - n_dense

    def run_layers(
        self, layer_params, h, k, v, offset, mask=None, tp_axis=None,
        ep_axis=None,
    ):
        """Two scans (dense prefix, MoE suffix) over structurally distinct
        param stacks. The group sizes come from the param stacks themselves
        (not the config bounds), so the fused engine's padded uniform stacks
        and the single-program/chained stage params both work; ``mask`` is a
        matching {group: (L,) bool} dict for padded slots."""
        from mlx_sharding_tpu.models.base import scan_layers

        n_dense = (
            # tree.leaves: group values may be packed {q, scales, biases}
            jax.tree.leaves(layer_params["dense"])[0].shape[0]
            if "dense" in layer_params
            else 0
        )
        ks, vs = [], []
        if "dense" in layer_params:
            h, kd, vd = scan_layers(
                lambda h, p, kb, vb: self._dense_layer(
                    h, p, kb, vb, offset, tp_axis=tp_axis
                ),
                h, layer_params["dense"], k[:n_dense], v[:n_dense],
                None if mask is None else mask["dense"],
            )
            ks.append(kd)
            vs.append(vd)
        if "moe" in layer_params:
            h, km, vm = scan_layers(
                lambda h, p, kb, vb: self._moe_layer(
                    h, p, kb, vb, offset, tp_axis=tp_axis, ep_axis=ep_axis
                ),
                h, layer_params["moe"], k[n_dense:], v[n_dense:],
                None if mask is None else mask["moe"],
            )
            ks.append(km)
            vs.append(vm)
        return h, jnp.concatenate(ks, axis=0), jnp.concatenate(vs, axis=0)

    def head_input(self, params, h):
        return rms_norm(h, params["final_norm"]["weight"], self.config.rms_norm_eps)

    def __call__(self, params, x, cache: KVCache, n_valid=None):
        cfg = self.config
        h = self.embed(params, x) if cfg.is_first_stage else x
        offset = cache.offset
        h, k, v = self.run_layers(params["layers"], h, cache.k, cache.v, offset)
        cache = KVCache(k=k, v=v, offset=offset)
        cache = advance(cache, x.shape[1] if n_valid is None else n_valid)
        if cfg.is_last_stage:
            return self.apply_head(params, h), cache
        return h, cache

    # ------------------------------------------------------------------
    def _attn_map(self) -> dict:
        cfg = self.config
        m = {
            "input_layernorm.weight": ("input_norm", False),
            "post_attention_layernorm.weight": ("post_norm", False),
            "self_attn.kv_a_proj_with_mqa.weight": ("kv_a_proj", True),
            "self_attn.kv_a_layernorm.weight": ("kv_a_norm", False),
            "self_attn.kv_b_proj.weight": ("kv_b_proj", True),
            "self_attn.o_proj.weight": ("o_proj", True),
        }
        if cfg.q_lora_rank is None:
            m["self_attn.q_proj.weight"] = ("q_proj", True)
        else:
            m["self_attn.q_a_proj.weight"] = ("q_a_proj", True)
            m["self_attn.q_a_layernorm.weight"] = ("q_a_norm", False)
            m["self_attn.q_b_proj.weight"] = ("q_b_proj", True)
        return m

    def map_weights(self, weights: dict, dtype=jnp.bfloat16) -> dict:
        """Stage-filtered HF tensors → {dense: (Ld,…), moe: (Lm,…)} stacks.
        Per-expert tensors fuse into switch stacks — the load-time version of
        the reference's sanitize stacking (deepseek_v2.py:101-112)."""
        from mlx_sharding_tpu.loading import fetch_weight, first_key, stack_tree, vocab_param

        cfg = self.config
        attn_map = self._attn_map()
        dense_map = {
            **attn_map,
            "mlp.gate_proj.weight": ("gate_proj", True),
            "mlp.up_proj.weight": ("up_proj", True),
            "mlp.down_proj.weight": ("down_proj", True),
        }
        moe_map = {
            **attn_map,
            "mlp.gate.weight": ("router", True),
            "mlp.shared_experts.gate_proj.weight": ("shared_gate", True),
            "mlp.shared_experts.up_proj.weight": ("shared_up", True),
            "mlp.shared_experts.down_proj.weight": ("shared_down", True),
        }

        def collect(indices, name_map):
            stacked = {our: [] for our, _ in name_map.values()}
            for i in indices:
                for suffix, (our, transpose) in name_map.items():
                    stacked[our].append(
                        fetch_weight(
                            weights, f"model.layers.{i}.{suffix}", dtype, transpose
                        )
                    )
            return {k2: stack_tree(v2) for k2, v2 in stacked.items()}

        dense_idx = [
            i for i in range(cfg.start_layer, cfg.end_layer)
            if i < cfg.first_k_dense_replace
        ]
        moe_idx = [
            i for i in range(cfg.start_layer, cfg.end_layer)
            if i >= cfg.first_k_dense_replace
        ]
        layers: dict = {}
        if dense_idx:
            layers["dense"] = collect(dense_idx, dense_map)
        if moe_idx:
            moe = collect(moe_idx, moe_map)
            for our, which in (
                ("w_gate", "gate_proj"),
                ("w_up", "up_proj"),
                ("w_down", "down_proj"),
            ):
                moe[our] = stack_tree(
                    [
                        stack_tree(
                            [
                                fetch_weight(
                                    weights,
                                    f"model.layers.{i}.mlp.experts.{e}.{which}.weight",
                                    dtype,
                                )
                                for e in range(cfg.n_routed_experts)
                            ]
                        )
                        for i in moe_idx
                    ]
                )
            layers["moe"] = moe

        params = {"layers": layers}
        if cfg.needs_embed:
            embed = first_key(weights, "model.embed_tokens.weight", "embed_tokens.weight")
            params["embed"] = {"weight": vocab_param(embed, dtype)}
        if cfg.needs_head:
            norm = first_key(weights, "model.norm.weight", "norm.weight")
            params["final_norm"] = {"weight": jnp.asarray(norm, dtype)}
            params["lm_head"] = {"weight": vocab_param(weights["lm_head.weight"], dtype, transpose=True)}
        return params

    def init_params(self, key, dtype=jnp.bfloat16):
        cfg = self.config
        hd = cfg.hidden_size
        heads = cfg.num_attention_heads
        nope, rope_d, v_d = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
        rank = cfg.kv_lora_rank
        keys = iter(jax.random.split(key, 64 * max(cfg.num_local_layers, 1) + 8))

        def attn_params():
            p = {
                "input_norm": jnp.ones((hd,), dtype),
                "post_norm": jnp.ones((hd,), dtype),
                "kv_a_proj": dense_init(next(keys), hd, rank + rope_d, dtype),
                "kv_a_norm": jnp.ones((rank,), dtype),
                "kv_b_proj": dense_init(next(keys), rank, heads * (nope + v_d), dtype),
                "o_proj": dense_init(next(keys), heads * v_d, hd, dtype),
            }
            if cfg.q_lora_rank is None:
                p["q_proj"] = dense_init(next(keys), hd, heads * (nope + rope_d), dtype)
            else:
                p["q_a_proj"] = dense_init(next(keys), hd, cfg.q_lora_rank, dtype)
                p["q_a_norm"] = jnp.ones((cfg.q_lora_rank,), dtype)
                p["q_b_proj"] = dense_init(
                    next(keys), cfg.q_lora_rank, heads * (nope + rope_d), dtype
                )
            return p

        n_dense, n_moe = self._layer_split()
        layers: dict = {}
        if n_dense:
            layers["dense"] = stack_layers(
                [
                    {
                        **attn_params(),
                        "gate_proj": dense_init(next(keys), hd, cfg.intermediate_size, dtype),
                        "up_proj": dense_init(next(keys), hd, cfg.intermediate_size, dtype),
                        "down_proj": dense_init(next(keys), cfg.intermediate_size, hd, dtype),
                    }
                    for _ in range(n_dense)
                ]
            )
        if n_moe:
            e, mi = cfg.n_routed_experts, cfg.moe_intermediate_size
            si = mi * (cfg.n_shared_experts or 1)
            layers["moe"] = stack_layers(
                [
                    {
                        **attn_params(),
                        "router": dense_init(next(keys), hd, e, dtype),
                        "w_gate": jnp.stack(
                            [dense_init(next(keys), hd, mi, dtype) for _ in range(e)]
                        ),
                        "w_up": jnp.stack(
                            [dense_init(next(keys), hd, mi, dtype) for _ in range(e)]
                        ),
                        "w_down": jnp.stack(
                            [dense_init(next(keys), mi, hd, dtype) for _ in range(e)]
                        ),
                        "shared_gate": dense_init(next(keys), hd, si, dtype),
                        "shared_up": dense_init(next(keys), hd, si, dtype),
                        "shared_down": dense_init(next(keys), si, hd, dtype),
                    }
                    for _ in range(n_moe)
                ]
            )
        params = {"layers": layers}
        if cfg.needs_embed:
            params["embed"] = {
                "weight": dense_init(next(keys), cfg.vocab_size, hd, dtype, scale=0.02)
            }
        if cfg.needs_head:
            params["final_norm"] = {"weight": jnp.ones((hd,), dtype)}
            params["lm_head"] = {"weight": dense_init(next(keys), hd, cfg.vocab_size, dtype)}
        return params
