"""Qwen3 decoder — Llama structure with per-head Q/K RMSNorm.

Beyond the reference's model set (it ships Llama/Gemma-2/DeepSeek-V2 and
aliases Mistral, /root/reference/shard/utils.py:14-17); Qwen3 is the current
generation of the Qwen2 family the reference serves through its Llama alias.
Differences from Llama, per HF ``Qwen3Attention``:

- RMSNorm over each head's query/key vector (weight shape (head_dim,)),
  applied after the projection reshape and BEFORE RoPE;
- no QKV biases (Qwen2 had them);
- ``head_dim`` set explicitly in the config, decoupled from
  hidden_size / num_heads.

Everything else (SwiGLU MLP, GQA, tied-embedding option, stage placement,
TP axes, packed-quant linear dispatch) is inherited from LlamaModel.
"""

from __future__ import annotations

import jax.numpy as jnp

from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.ops import apply_rope, rms_norm


class Qwen3Model(LlamaModel):
    HF_LAYER_MAP = {
        **LlamaModel.HF_LAYER_MAP,
        "self_attn.q_norm.weight": ("q_norm", False),
        "self_attn.k_norm.weight": ("k_norm", False),
    }

    def layer_attn_inputs(self, p, h, offset):
        cfg = self.config
        b, t, _ = h.shape
        d = cfg.head_dim

        r = rms_norm(h, p["input_norm"], cfg.rms_norm_eps)
        q = self._linear(r, p["q_proj"])
        k = self._linear(r, p["k_proj"])
        v = self._linear(r, p["v_proj"])
        if cfg.attention_bias:  # supported by HF Qwen3Config
            q = q + p["q_bias"]
            k = k + p["k_bias"]
            v = v + p["v_bias"]
        q = q.reshape(b, t, -1, d)
        k = k.reshape(b, t, -1, d)
        v = v.reshape(b, t, -1, d)
        # per-head q/k norm before RoPE (HF Qwen3Attention)
        q = rms_norm(q, p["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, p["k_norm"], cfg.rms_norm_eps)
        q = apply_rope(q, self.inv_freq, offset)
        k = apply_rope(k, self.inv_freq, offset)
        return q, k, v

    def tp_layer_axes(self) -> dict:
        # q/k norms are (head_dim,) — shared across heads, replicated over tp
        return {**super().tp_layer_axes(), "q_norm": None, "k_norm": None}

    def init_params(self, key, dtype=jnp.bfloat16):
        params = super().init_params(key, dtype)
        nl, d = self.config.num_local_layers, self.config.head_dim
        params["layers"]["q_norm"] = jnp.ones((nl, d), dtype)
        params["layers"]["k_norm"] = jnp.ones((nl, d), dtype)
        return params
