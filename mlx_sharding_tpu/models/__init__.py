"""Model registry.

Replaces the reference's importlib-based arch resolution
(shard/utils.py:20-30) with an explicit registry keyed by the remapped
``model_type`` (remapping itself lives in config.MODEL_REMAPPING, mirroring
shard/utils.py:14-17).
"""

from __future__ import annotations

import importlib

from mlx_sharding_tpu.config import config_from_dict, resolve_model_type

# model_type -> (module, class). Keys must match config.CONFIG_REGISTRY.
MODEL_REGISTRY: dict[str, tuple[str, str]] = {
    "llama": ("mlx_sharding_tpu.models.llama", "LlamaModel"),
    "qwen3": ("mlx_sharding_tpu.models.qwen3", "Qwen3Model"),
    "gemma2": ("mlx_sharding_tpu.models.gemma2", "Gemma2Model"),
    "deepseek_v2": ("mlx_sharding_tpu.models.deepseek_v2", "DeepseekV2Model"),
    "mixtral": ("mlx_sharding_tpu.models.mixtral", "MixtralModel"),
}


def get_model_class(model_type: str):
    model_type = resolve_model_type(model_type)
    if model_type not in MODEL_REGISTRY:
        raise ValueError(
            f"Model type {model_type!r} not supported. Supported: {sorted(MODEL_REGISTRY)}"
        )
    module_name, class_name = MODEL_REGISTRY[model_type]
    try:
        module = importlib.import_module(module_name)
    except ModuleNotFoundError as exc:
        raise ValueError(
            f"Model type {model_type!r} is registered but its implementation "
            f"({module_name}) is not available."
        ) from exc
    return getattr(module, class_name)


def build_model(config_dict: dict):
    """config.json dict → (model, config)."""
    cfg = config_from_dict(config_dict)
    return get_model_class(cfg.model_type)(cfg), cfg
