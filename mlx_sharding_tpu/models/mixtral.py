"""Mixtral (sparse MoE Llama) decoder.

BASELINE.json config #4 names Mixtral-8x7B with "expert routing inside
stage" — experts stay stage-local exactly as the reference treats MoE
(SURVEY §2.3 "EP": fused and replicated within the owning stage; the
reference itself only ships DeepSeek-V2's MoE, deepseek_v2.py:101-112).
Attention/norm structure is Llama's; the MLP is a top-2 router over 8 SwiGLU
experts (HF semantics: softmax over all logits → top-k → renormalize).
Expert weights are stacked (L, E, H, I) so the layer scan + expert
scan/gather dispatch (ops/moe.py) run with static shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mlx_sharding_tpu.cache import KVCache, advance, write_layer_kv
from mlx_sharding_tpu.config import MixtralConfig
from mlx_sharding_tpu.models.base import BaseModel, dense_init, stack_layers
from mlx_sharding_tpu.ops import apply_rope, causal_attention, rms_norm, rope_frequencies
from mlx_sharding_tpu.ops.moe import apply_experts, mixtral_routing


class MixtralModel(BaseModel):
    # attention projections and the (E, …) expert stacks may stay 4-bit
    # packed; the router loads dense (fp32 routing matmul on a tiny weight)
    supports_packed = True
    supports_sp = True  # sp_layer below (window-aware, replicated MoE MLP)

    def packed_keep_dense_re(self) -> str | None:
        return r"block_sparse_moe\.gate\.weight$"

    def __init__(self, config: MixtralConfig):
        super().__init__(config)
        self.inv_freq = jnp.asarray(
            rope_frequencies(config.head_dim, config.rope_theta, config.rope_scaling)
        )
        self.scale = config.head_dim**-0.5

    # ------------------------------------------------------------------
    def layer_attn_inputs(self, p, h, offset):
        """Pre-attention half: norm + QKV + RoPE. Head counts derive from
        the projection shards, so the same code runs the full model and any
        tp slice (heads split over tp)."""
        cfg = self.config
        b, t, _ = h.shape
        d = cfg.head_dim
        r = rms_norm(h, p["input_norm"], cfg.rms_norm_eps)
        q = self._linear(r, p["q_proj"]).reshape(b, t, -1, d)
        k = self._linear(r, p["k_proj"]).reshape(b, t, -1, d)
        v = self._linear(r, p["v_proj"]).reshape(b, t, -1, d)
        q = apply_rope(q, self.inv_freq, offset)
        k = apply_rope(k, self.inv_freq, offset)
        return q, k, v

    def layer_finish(self, p, h, attn, tp_axis=None, ep_axis=None):
        """Post-attention half: O projection + routed top-k expert MLP."""
        cfg = self.config
        b, t, hidden = h.shape
        attn_out = self._linear(attn.reshape(b, t, -1), p["o_proj"])
        if tp_axis is not None:
            attn_out = jax.lax.psum(attn_out, tp_axis)
        h = h + attn_out

        r = rms_norm(h, p["post_norm"], cfg.rms_norm_eps)
        flat = r.reshape(b * t, hidden)
        weights, idx = mixtral_routing(flat, p["router"], cfg.num_experts_per_tok)
        moe = apply_experts(
            flat, weights, idx, p["w_gate"], p["w_up"], p["w_down"],
            ep_axis=ep_axis, group_size=self._gs, bits=self._bits,
        )
        if tp_axis is not None and ep_axis is None:
            # experts shard their intermediate dim over tp — the down-proj
            # outputs are partial products. Under tp x ep the expert stacks
            # shard over ep instead (ep overrides tp in the engine's merge)
            # and apply_experts' internal ep psum already made them full.
            moe = jax.lax.psum(moe, tp_axis)
        return h + moe.reshape(b, t, hidden)

    def sp_layer(self, p, h, offset, attn_fn, group=None):
        """Sequence-parallel layer: the injected attention gets Mixtral's
        (optional) sliding window; the MoE MLP runs replicated per sp
        device on its local T/S rows."""
        q, k, v = self.layer_attn_inputs(p, h, offset)
        attn = attn_fn(q, k, v, sliding_window=self.config.sliding_window)
        return self.layer_finish(p, h, attn), k, v

    def _layer(self, h, p, k_buf, v_buf, offset, tp_axis=None, ep_axis=None):
        q, k, v = self.layer_attn_inputs(p, h, offset)
        k_buf, v_buf = write_layer_kv(k_buf, v_buf, k, v, offset)
        attn = causal_attention(
            q, k_buf, v_buf, offset, self.scale,
            sliding_window=self.config.sliding_window,
        )
        return self.layer_finish(p, h, attn, tp_axis, ep_axis), k_buf, v_buf

    def run_layers(
        self, layer_params, h, k, v, offset, mask=None, tp_axis=None,
        ep_axis=None,
    ):
        from mlx_sharding_tpu.models.base import scan_layers

        def body(h, p, k_buf, v_buf):
            return self._layer(
                h, p, k_buf, v_buf, offset, tp_axis=tp_axis, ep_axis=ep_axis
            )

        return scan_layers(body, h, layer_params, k, v, mask)

    def ep_layer_axes(self) -> dict:
        """Expert stacks shard their leading (E) dim over ep; everything
        else replicates across ep devices."""
        return {"w_gate": 0, "w_up": 0, "w_down": 0}

    def tp_layer_axes(self) -> dict:
        """Megatron column/row split for attention (whole heads per tp
        device); expert stacks shard their intermediate dim over tp, the
        router replicates (routing computed identically on every device).
        Dims counted after the stacked-L axis."""
        return {
            "input_norm": None, "post_norm": None,
            "q_proj": 1, "k_proj": 1, "v_proj": 1, "o_proj": 0,
            "router": None,
            "w_gate": 2, "w_up": 2, "w_down": 1,
        }

    def head_input(self, params, h):
        return rms_norm(h, params["final_norm"]["weight"], self.config.rms_norm_eps)

    def __call__(self, params, x, cache: KVCache, n_valid=None):
        cfg = self.config
        h = self.embed(params, x) if cfg.is_first_stage else x
        offset = cache.offset
        h, k, v = self.run_layers(params["layers"], h, cache.k, cache.v, offset)
        cache = KVCache(k=k, v=v, offset=offset)
        cache = advance(cache, x.shape[1] if n_valid is None else n_valid)
        if cfg.is_last_stage:
            return self.apply_head(params, h), cache
        return h, cache

    # ------------------------------------------------------------------
    HF_LAYER_MAP = {
        "input_layernorm.weight": ("input_norm", False),
        "post_attention_layernorm.weight": ("post_norm", False),
        "self_attn.q_proj.weight": ("q_proj", True),
        "self_attn.k_proj.weight": ("k_proj", True),
        "self_attn.v_proj.weight": ("v_proj", True),
        "self_attn.o_proj.weight": ("o_proj", True),
        "block_sparse_moe.gate.weight": ("router", True),
    }

    def map_weights(self, weights: dict, dtype=jnp.bfloat16) -> dict:
        """Per-expert w1/w2/w3 tensors are stacked into fused (L, E, …)
        switch tensors — the same fusion the reference performs in sanitize
        (deepseek_v2.py:101-112), applied at load time."""
        from mlx_sharding_tpu.loading import (
            collect_layer_stack,
            fetch_weight,
            first_key,
            stack_tree,
            vocab_param,
        )

        cfg = self.config
        layers = collect_layer_stack(weights, cfg, self.HF_LAYER_MAP, dtype)

        def expert_stack(which: str):
            # (L, E, in, out) dense / {q,scales,biases} (L, E, out, …) packed
            return stack_tree(
                [
                    stack_tree(
                        [
                            fetch_weight(
                                weights,
                                f"model.layers.{i}.block_sparse_moe."
                                f"experts.{e}.{which}.weight",
                                dtype,
                            )
                            for e in range(cfg.num_local_experts)
                        ]
                    )
                    for i in range(cfg.start_layer, cfg.end_layer)
                ]
            )

        layers["w_gate"] = expert_stack("w1")
        layers["w_up"] = expert_stack("w3")
        layers["w_down"] = expert_stack("w2")
        params = {"layers": layers}
        if cfg.needs_embed:
            embed = first_key(weights, "model.embed_tokens.weight", "embed_tokens.weight")
            params["embed"] = {"weight": vocab_param(embed, dtype)}
        if cfg.needs_head:
            norm = first_key(weights, "model.norm.weight", "norm.weight")
            params["final_norm"] = {"weight": jnp.asarray(norm, dtype)}
            if not cfg.tie_word_embeddings:
                params["lm_head"] = {"weight": vocab_param(weights["lm_head.weight"], dtype, transpose=True)}
        return params

    def init_params(self, key, dtype=jnp.bfloat16):
        cfg = self.config
        hd, hq, hkv, d = cfg.hidden_size, cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        inter, nl, ne = cfg.intermediate_size, cfg.num_local_layers, cfg.num_local_experts
        keys = iter(jax.random.split(key, (8 + 3 * ne) * nl + 4))

        def layer():
            return {
                "input_norm": jnp.ones((hd,), dtype),
                "post_norm": jnp.ones((hd,), dtype),
                "q_proj": dense_init(next(keys), hd, hq * d, dtype),
                "k_proj": dense_init(next(keys), hd, hkv * d, dtype),
                "v_proj": dense_init(next(keys), hd, hkv * d, dtype),
                "o_proj": dense_init(next(keys), hq * d, hd, dtype),
                "router": dense_init(next(keys), hd, ne, dtype),
                "w_gate": jnp.stack([dense_init(next(keys), hd, inter, dtype) for _ in range(ne)]),
                "w_up": jnp.stack([dense_init(next(keys), hd, inter, dtype) for _ in range(ne)]),
                "w_down": jnp.stack([dense_init(next(keys), inter, hd, dtype) for _ in range(ne)]),
            }

        params = {"layers": stack_layers([layer() for _ in range(nl)])}
        if cfg.needs_embed:
            params["embed"] = {
                "weight": dense_init(next(keys), cfg.vocab_size, hd, dtype, scale=0.02)
            }
        if cfg.needs_head:
            params["final_norm"] = {"weight": jnp.ones((hd,), dtype)}
            if not cfg.tie_word_embeddings:
                params["lm_head"] = {"weight": dense_init(next(keys), hd, cfg.vocab_size, dtype)}
        return params
