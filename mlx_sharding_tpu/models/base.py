"""Shared model infrastructure.

The reference builds per-arch ``nn.Module`` trees with ``IdentityBlock``
placeholders for non-local layers so weight indices line up
(ref: shard/server/model/base.py:6-8, llama.py:28-33). On TPU that trick is
unnecessary and harmful: materializing per-layer Python modules defeats
``lax.scan``. Instead a stage's parameters are a pytree of arrays **stacked
over its local layers** (leading axis = layer), the forward pass is one scan,
and layer-index bookkeeping lives only in the checkpoint loader (which maps
global HF layer indices ``start_layer..end_layer`` onto stack positions
``0..L``) — the same sanitize-by-range semantics as
shard/server/model/llama.py:92-107, applied at load time.

Models here are *functional*: a model object holds only the (static) config;
parameters and KV cache are explicit pytree arguments. That is what makes
them jit/pjit/shard_map-transparent.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mlx_sharding_tpu.cache import KVCache, init_cache
from mlx_sharding_tpu.ops.quant import (
    dequantize,
    is_quantized,
    linear as quant_linear,
)


def dense_init(key, in_dim: int, out_dim: int, dtype, scale: float | None = None):
    """Random (in, out) weight for x @ W. Used by tests/bench only —
    real weights come from checkpoints."""
    if scale is None:
        scale = 1.0 / np.sqrt(in_dim)
    return (jax.random.normal(key, (in_dim, out_dim), jnp.float32) * scale).astype(dtype)


def scan_layers(layer_fn, h, layer_params, k, v, mask=None):
    """``lax.scan`` over a stacked layer group with optional per-layer
    active masking.

    ``layer_fn(h, p, k_buf, v_buf) -> (h, k_buf, v_buf)`` is the single-layer
    body; ``mask`` is an (L,) bool array (or None == all active). Masked-out
    slots leave both the hidden state and their cache rows untouched, which is
    what lets the fused SPMD engine pad uneven/heterogeneous stages to a
    uniform per-stage slot count: padding slots carry zero params and scan
    through as no-ops regardless of architecture semantics."""

    def body(h, xs):
        if mask is None:
            p, k_buf, v_buf = xs
            h, k_buf, v_buf = layer_fn(h, p, k_buf, v_buf)
            return h, (k_buf, v_buf)
        p, k_buf, v_buf, m = xs
        h2, k2, v2 = layer_fn(h, p, k_buf, v_buf)
        # tree-map: K/V buffers may be int8 {d, s} leaf pairs (paged pools)
        sel = lambda a, b: jnp.where(m, a, b)  # noqa: E731
        return jnp.where(m, h2, h), (
            jax.tree.map(sel, k2, k_buf),
            jax.tree.map(sel, v2, v_buf),
        )

    xs = (layer_params, k, v) if mask is None else (layer_params, k, v, mask)
    h, (k, v) = jax.lax.scan(body, h, xs)
    return h, k, v


def stack_layers(per_layer: list[dict]) -> dict:
    """[{name: (…)}, …] → {name: (L, …)} for lax.scan consumption."""
    out = {}
    for name in per_layer[0]:
        out[name] = jnp.stack([p[name] for p in per_layer])
    return out


def apply_projection_fusion(model, layer_stack: dict) -> list[str]:
    """Fuse each group the model declares via ``fused_projection_groups``
    IN PLACE in ``layer_stack`` (a flat ``{name: w}`` stack, or nested
    ``{group: {name: w}}`` keyed like ``layer_group_ranges``): the group's
    packed triples concatenate along OUT (ops.quant.fuse_packed) and the
    sources are removed, so decode serves the whole group with one fused-
    GEMV launch over one pass of the activation planes. Groups with any
    dense (non-packed) member are left untouched. Returns the fused names
    added. Callers gate on tp == 1 and the MST_FUSE_PROJ env switch."""
    from mlx_sharding_tpu.ops.quant import fuse_packed

    groups = model.fused_projection_groups()
    if not groups:
        return []
    ranges = model.layer_group_ranges()
    stacks = (
        [layer_stack] if list(ranges) == [None]
        else [layer_stack[k] for k in ranges if k in layer_stack]
    )
    fused = []
    for stack in stacks:
        for fname, parts in groups.items():
            if not all(p in stack and is_quantized(stack[p]) for p in parts):
                continue
            stack[fname] = fuse_packed([stack[p] for p in parts])
            for p in parts:
                del stack[p]
            if fname not in fused:
                fused.append(fname)
    return fused


class BaseModel:
    """Common surface every architecture implements.

    ``__call__(params, x, cache)`` where ``x`` is int32 tokens (B, T) on the
    first stage or hidden states (B, T, H) downstream, returning logits on
    the last stage or hidden states otherwise — mirroring the reference's
    stage models (shard/server/model/llama.py:39-62).
    """

    #: decoder-layer projections may stay 4-bit packed in HBM
    #: (loading.load_model(keep_quantized=True) → ops.quant.linear dispatch)
    supports_packed = False

    def __init__(self, config):
        self.config = config
        q = getattr(config, "quantization", None) or {}
        self._gs = int(q.get("group_size", 64))
        self._bits = int(q.get("bits", 4))

    def _linear(self, x, w):
        """``x @ w`` that transparently serves packed 4-bit params
        (ops.quant.linear dispatch); dense arrays go straight to the MXU."""
        from mlx_sharding_tpu.ops.quant import linear

        return linear(x, w, self._gs, self._bits)

    def fused_projection_groups(self) -> dict:
        """{fused_param_name: (source_param_names, …)} — groups of packed
        per-layer projections sharing the same input activations that the
        engines may concatenate along OUT at build time (ops.quant.fuse_packed)
        so one kernel invocation serves the whole group. The forward code must
        dispatch on the fused name's presence in the layer pytree. Empty dict
        → the architecture has no fusable groups wired."""
        return {}

    def packed_keep_dense_re(self) -> str | None:
        """Regex over HF weight names that must stay DENSE under
        ``keep_quantized`` (their triples are dequantized on load). Used for
        weights consumed as tensors rather than matmul operands — e.g. MoE
        routers feeding the fp32 routing einsum, or MLA's kv_b when the
        compressed-latent cache absorbs it into einsums."""
        return None

    # -- cache ------------------------------------------------------------
    def make_cache(self, batch: int, max_seq: int, dtype=jnp.bfloat16) -> KVCache:
        """Stage-local cache (the reference's make_cache / per-layer KVCache
        construction, shard/utils.py:142-150)."""
        cfg = self.config
        return init_cache(
            cfg.num_local_layers, batch, max_seq, self.cache_num_heads(),
            self.cache_head_dim(), dtype,
        )

    def cache_head_dim(self):
        """Int or (k_dim, v_dim) tuple (MLA, ref deepseek_v2.py:120-125)."""
        return self.config.head_dim

    def cache_num_heads(self) -> int:
        """Head count of the KV buffers. Models whose cache layout departs
        from plain GQA (e.g. MLA's single compressed-latent head) override
        this — engines must use it instead of config.num_key_value_heads."""
        return self.config.num_key_value_heads

    def cache_tp_replicated(self) -> bool:
        """True when the KV cache is head-count INDEPENDENT and must
        replicate over tp rather than head-shard (MLA's shared compressed
        latent). A genuine MQA model (num_key_value_heads == 1) is NOT
        that — its single head cannot be split, so tp > 1 must still be
        rejected by the divisibility check."""
        return False

    def tp_layer_axes(self) -> dict:
        """{layer_param_name: per-layer dim index (after the stacked-L axis)
        sharded over tp, or None for replicated}. Empty dict → the
        architecture has no tensor-parallel wiring yet and engines must
        reject tp > 1."""
        return {}

    def ep_layer_axes(self) -> dict:
        """Same shape as :meth:`tp_layer_axes` for the expert-parallel axis:
        which per-layer dims hold the expert stacks. Empty dict → the
        architecture has no EP wiring and engines must reject ep > 1."""
        return {}

    # -- layer structure ---------------------------------------------------
    def layer_group_ranges(self) -> dict:
        """Global-layer ranges of structurally distinct layer groups.

        ``{group_key: (g0, g1)}`` where ``group_key=None`` means the model's
        ``params["layers"]`` is itself the stacked pytree (homogeneous
        models); string keys name sub-dicts (DeepSeek's dense/moe split).
        The fused pipeline engine uses this to build per-stage uniform
        stacks with masked padding for uneven/heterogeneous splits."""
        return {None: (0, self.config.num_hidden_layers)}

    # -- sequence parallelism ---------------------------------------------
    #: architectures wired for the sequence-parallel paths (sp_prefill's
    #: ring attention, sp_decode's partial-softmax merge) set this True
    supports_sp = False

    def sp_groups(self) -> list:
        """Layer-group keys the sp paths scan over, in forward order.
        ``[None]`` = ``params["layers"]`` is one homogeneous stack;
        DeepSeek returns its present ["dense", "moe"] sub-stacks."""
        return [None]

    def sp_layer(self, p, h, offset, attn_fn, group=None):
        """One decoder layer with the attention op INJECTED — the shared
        body of both sp paths. ``attn_fn(q, k_new, v_new, **opts) -> attn``
        is ring attention (prefill: k/v are this shard's T_local rows) or
        the sharded-KV partial-softmax attention (decode: the backend
        owner-writes k/v into its shard first). Supported opts:
        ``logit_softcap``, ``sliding_window`` (per-layer traced scalars ok),
        and ``values_from_k`` (attend values = keys[..., :n] — MLA's
        latent-as-values trick; v_new is then a dummy). Returns
        ``(h, k_new, v_new)`` — the new rows double as the prefill scan's
        cache ys. Default: the Llama-family hook pair."""
        q, k, v = self.layer_attn_inputs(p, h, offset)
        return self.layer_finish(p, h, attn_fn(q, k, v)), k, v

    # -- forward ----------------------------------------------------------
    def __call__(self, params, x, cache: KVCache):
        raise NotImplementedError

    def init_params(self, key, dtype=jnp.bfloat16):
        raise NotImplementedError

    # compute dtype for paths that must materialize dense values from
    # packed 4-bit params (embed row dequant); load_model overrides it with
    # the checkpoint load dtype so packed and dense loads agree bit-for-bit
    compute_dtype = jnp.bfloat16

    def _quant_args(self) -> tuple[int, int]:
        q = getattr(self.config, "quantization", None) or {}
        return int(q.get("group_size", 64)), int(q.get("bits", 4))

    def embed_tokens(self, params, tokens):
        w = params["embed"]["weight"]
        if is_quantized(w):
            # gather the packed rows for these tokens and dequantize just
            # those — O(T·H) work; the (V, H) dense table never exists
            gs, bits = self._quant_args()
            rows = jax.tree.map(lambda a: jnp.take(a, tokens, axis=0), w)
            return dequantize(
                rows["q"], rows["scales"], rows["biases"], gs, bits,
                self.compute_dtype,
            )
        return jnp.take(w, tokens, axis=0)

    # -- embed/head decomposition -----------------------------------------
    # The fused engine vocab-shards the embedding table and LM head over the
    # pp axis (each device holds vocab/S rows); these hooks isolate the
    # arch-specific pieces around the sharded table lookup / vocab matmul so
    # the engine can own the collectives. apply_head/embed compose them for
    # the single-program and chained paths.

    def embed_transform(self, h):
        """Post-lookup transform (Gemma-2 scales by sqrt(hidden))."""
        return h

    def head_input(self, params, h):
        """Transform before the vocab projection (the final norm)."""
        raise NotImplementedError

    def head_transform(self, logits):
        """Elementwise transform after the vocab projection (Gemma-2
        softcap). Must be shard-local: applied per vocab shard."""
        return logits

    def head_is_tied(self) -> bool:
        """True when logits project through the embedding table transposed."""
        return bool(getattr(self.config, "tie_word_embeddings", False))

    def embed(self, params, tokens):
        return self.embed_transform(self.embed_tokens(params, tokens))

    def apply_head(self, params, h):
        h = self.head_input(params, h)
        w = (
            params["embed"]["weight"]
            if self.head_is_tied()
            else params["lm_head"]["weight"]
        )
        if is_quantized(w):
            # MLX packs (out, in) = (V, H) — exactly quant.linear's packed
            # orientation for the H→V projection, tied or not; the vocab
            # matmul runs off the packed bytes (4x less weight bandwidth
            # on the biggest dense read of a decode step)
            gs, bits = self._quant_args()
            return self.head_transform(quant_linear(h, w, gs, bits))
        w = w.T if self.head_is_tied() else w
        return self.head_transform(h @ w)
