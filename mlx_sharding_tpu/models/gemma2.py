"""Gemma-2 decoder.

Capability parity: shard/server/model/gemma2.py — tied embeddings so the
embedding table is needed on the first AND last stage (ref gemma2.py:23-24,
sanitize :98-99), embedding scaled by sqrt(hidden) (ref :42-43), final logit
softcapping (ref :80-84). Architecture specifics beyond the reference's
borrowed blocks (SURVEY §2.2): zero-centered (1+w) RMSNorm, four norms per
layer (pre/post attention, pre/post feedforward), attention-logit
softcapping, alternating sliding/global attention (window on even layers),
GeGLU MLP, query_pre_attn_scalar attention scale.

The alternating window runs inside the single layer scan: the layer index is
scanned alongside the stacked params and selects window-vs-global as a traced
scalar — no per-layer Python modules, no unrolling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mlx_sharding_tpu.cache import KVCache, advance, write_layer_kv
from mlx_sharding_tpu.config import Gemma2Config
from mlx_sharding_tpu.models.base import BaseModel, dense_init, stack_layers
from mlx_sharding_tpu.ops import apply_rope, causal_attention, rms_norm, rope_frequencies

_GLOBAL_WINDOW = 1 << 30  # "no window" encoded as a huge traced window


class Gemma2Model(BaseModel):
    supports_packed = True
    supports_sp = True  # sp_layer below carries the window/softcap opts

    def __init__(self, config: Gemma2Config):
        super().__init__(config)
        self.inv_freq = jnp.asarray(
            rope_frequencies(config.head_dim, config.rope_theta, config.rope_scaling)
        )
        self.scale = config.query_pre_attn_scalar**-0.5

    # ------------------------------------------------------------------
    def _window(self, layer_idx):
        # sliding window on even layers, global on odd (HF Gemma-2 layout)
        return jnp.where(
            layer_idx % 2 == 0, self.config.sliding_window, _GLOBAL_WINDOW
        )

    def layer_attn_inputs(self, p, h, offset):
        """Pre-attention half: zero-centered norm + QKV + RoPE. Head counts
        derive from the projection shards, so the same code runs the full
        model and any tp slice (heads split over tp)."""
        cfg = self.config
        b, t, _ = h.shape
        d = cfg.head_dim
        r = rms_norm(h, p["input_norm"], cfg.rms_norm_eps, offset=1.0)
        q = self._linear(r, p["q_proj"]).reshape(b, t, -1, d)
        k = self._linear(r, p["k_proj"]).reshape(b, t, -1, d)
        v = self._linear(r, p["v_proj"]).reshape(b, t, -1, d)
        q = apply_rope(q, self.inv_freq, offset)
        k = apply_rope(k, self.inv_freq, offset)
        return q, k, v

    def layer_finish(self, p, h, attn, tp_axis=None):
        """Post-attention half: O projection into the POST-attention norm
        (sandwich norms), then GeGLU into the post-ffw norm."""
        cfg = self.config
        b, t, _ = h.shape
        eps = cfg.rms_norm_eps
        attn_out = self._linear(attn.reshape(b, t, -1), p["o_proj"])
        if tp_axis is not None:
            # the post-attention norm is NONLINEAR: partial row-parallel
            # products must be summed BEFORE it, unlike Llama's plain residual
            attn_out = jax.lax.psum(attn_out, tp_axis)
        h = h + rms_norm(attn_out, p["post_attn_norm"], eps, offset=1.0)

        r = rms_norm(h, p["pre_ffw_norm"], eps, offset=1.0)
        ff = self._linear(
            jax.nn.gelu(self._linear(r, p["gate_proj"]), approximate=True)
            * self._linear(r, p["up_proj"]),
            p["down_proj"],
        )
        if tp_axis is not None:
            ff = jax.lax.psum(ff, tp_axis)
        return h + rms_norm(ff, p["post_ffw_norm"], eps, offset=1.0)

    def sp_layer(self, p, h, offset, attn_fn, group=None):
        """Sequence-parallel layer: the injected attention gets Gemma-2's
        logit softcap and the layer's sliding/global window — the ring
        backend skips K/V blocks entirely behind a window (VERDICT r4 #4:
        window-aware ring block skipping)."""
        cfg = self.config
        q, k, v = self.layer_attn_inputs(p, h, offset)
        attn = attn_fn(
            q, k, v,
            logit_softcap=cfg.attn_logit_softcapping,
            sliding_window=self._window(p["layer_idx"]),
        )
        return self.layer_finish(p, h, attn), k, v

    def _layer(self, h, p, k_buf, v_buf, offset, layer_idx, tp_axis=None):
        cfg = self.config
        q, k, v = self.layer_attn_inputs(p, h, offset)
        k_buf, v_buf = write_layer_kv(k_buf, v_buf, k, v, offset)
        attn = causal_attention(
            q, k_buf, v_buf, offset, self.scale,
            logit_softcap=cfg.attn_logit_softcapping,
            sliding_window=self._window(layer_idx),
        )
        return self.layer_finish(p, h, attn, tp_axis), k_buf, v_buf

    def run_layers(self, layer_params, h, k, v, offset, mask=None, tp_axis=None):
        # The GLOBAL layer index travels inside the param stack
        # ("layer_idx", added by map_weights/init_params): window alternation
        # follows it, so arbitrary stage slices — including the fused SPMD
        # engine's per-device shards, which can't see start_layer — stay
        # consistent with the full model.
        from mlx_sharding_tpu.models.base import scan_layers

        def body(h, p, k_buf, v_buf):
            return self._layer(h, p, k_buf, v_buf, offset, p["layer_idx"], tp_axis)

        return scan_layers(body, h, layer_params, k, v, mask)

    def tp_layer_axes(self) -> dict:
        return {
            "input_norm": None, "post_attn_norm": None, "pre_ffw_norm": None,
            "post_ffw_norm": None, "layer_idx": None,
            "q_proj": 1, "k_proj": 1, "v_proj": 1, "o_proj": 0,
            "gate_proj": 1, "up_proj": 1, "down_proj": 0,
        }

    def embed_transform(self, h):
        # embedding scaled by sqrt(hidden) (ref gemma2.py:42-43)
        return h * jnp.asarray(self.config.hidden_size**0.5, h.dtype)

    def head_input(self, params, h):
        return rms_norm(
            h, params["final_norm"]["weight"], self.config.rms_norm_eps, offset=1.0
        )

    def head_transform(self, logits):
        cap = self.config.final_logit_softcapping
        if cap:  # ref gemma2.py:80-84
            logits = cap * jnp.tanh(logits / cap)
        return logits

    def head_is_tied(self) -> bool:
        return True  # always projects through the embedding (ref :23-24)

    def __call__(self, params, x, cache: KVCache, n_valid=None):
        cfg = self.config
        h = self.embed(params, x) if cfg.is_first_stage else x
        offset = cache.offset
        h, k, v = self.run_layers(params["layers"], h, cache.k, cache.v, offset)
        cache = KVCache(k=k, v=v, offset=offset)
        cache = advance(cache, x.shape[1] if n_valid is None else n_valid)
        if cfg.is_last_stage:
            return self.apply_head(params, h), cache
        return h, cache

    # ------------------------------------------------------------------
    HF_LAYER_MAP = {
        "input_layernorm.weight": ("input_norm", False),
        "post_attention_layernorm.weight": ("post_attn_norm", False),
        "pre_feedforward_layernorm.weight": ("pre_ffw_norm", False),
        "post_feedforward_layernorm.weight": ("post_ffw_norm", False),
        "self_attn.q_proj.weight": ("q_proj", True),
        "self_attn.k_proj.weight": ("k_proj", True),
        "self_attn.v_proj.weight": ("v_proj", True),
        "self_attn.o_proj.weight": ("o_proj", True),
        "mlp.gate_proj.weight": ("gate_proj", True),
        "mlp.up_proj.weight": ("up_proj", True),
        "mlp.down_proj.weight": ("down_proj", True),
    }

    def map_weights(self, weights: dict, dtype=jnp.bfloat16) -> dict:
        from mlx_sharding_tpu.loading import collect_layer_stack, first_key, vocab_param

        cfg = self.config
        layers = collect_layer_stack(weights, cfg, self.HF_LAYER_MAP, dtype)
        layers["layer_idx"] = jnp.arange(cfg.start_layer, cfg.end_layer, dtype=jnp.int32)
        params = {"layers": layers}
        if cfg.needs_embed:
            embed = first_key(weights, "model.embed_tokens.weight", "embed_tokens.weight")
            params["embed"] = {"weight": vocab_param(embed, dtype)}
        if cfg.needs_head:
            norm = first_key(weights, "model.norm.weight", "norm.weight")
            params["final_norm"] = {"weight": jnp.asarray(norm, dtype)}
        return params

    def init_params(self, key, dtype=jnp.bfloat16):
        cfg = self.config
        hd, hq, hkv, d = cfg.hidden_size, cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        inter, nl = cfg.intermediate_size, cfg.num_local_layers
        keys = iter(jax.random.split(key, 8 * nl + 4))

        def layer():
            return {
                "input_norm": jnp.zeros((hd,), dtype),
                "post_attn_norm": jnp.zeros((hd,), dtype),
                "pre_ffw_norm": jnp.zeros((hd,), dtype),
                "post_ffw_norm": jnp.zeros((hd,), dtype),
                "q_proj": dense_init(next(keys), hd, hq * d, dtype),
                "k_proj": dense_init(next(keys), hd, hkv * d, dtype),
                "v_proj": dense_init(next(keys), hd, hkv * d, dtype),
                "o_proj": dense_init(next(keys), hq * d, hd, dtype),
                "gate_proj": dense_init(next(keys), hd, inter, dtype),
                "up_proj": dense_init(next(keys), hd, inter, dtype),
                "down_proj": dense_init(next(keys), inter, hd, dtype),
            }

        layers = stack_layers([layer() for _ in range(nl)])
        layers["layer_idx"] = jnp.arange(cfg.start_layer, cfg.end_layer, dtype=jnp.int32)
        params = {"layers": layers}
        if cfg.needs_embed:
            params["embed"] = {
                "weight": dense_init(next(keys), cfg.vocab_size, hd, dtype, scale=0.02)
            }
        if cfg.needs_head:
            params["final_norm"] = {"weight": jnp.zeros((hd,), dtype)}
        return params
