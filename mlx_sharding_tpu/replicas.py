"""Data-parallel serving: independent engine replicas behind one dispatcher.

The mesh axis story (parallel/mesh.py) gives dp to the training step; this
module gives it to SERVING — `--replicas R` builds R fully independent
engines (each a PipelineEngine [+ ContinuousBatcher] on its own slice of
``jax.devices()``) and routes each request to the least-loaded replica.
Replication multiplies aggregate throughput by R at identical per-request
latency, the standard inference-serving dp recipe; the reference's topology
has no equivalent (one gRPC chain serves one request at a time,
ref: shard/openai_api.py:543-563).

Each replica holds its own copy of the weights (device_put onto its own
mesh by PipelineEngine) and its own KV state; requests never migrate, so
per-request streams are exactly what the replica alone would produce.
"""

from __future__ import annotations

import threading
from typing import Optional


class ReplicaSet:
    """``generate_step`` dispatcher over independent replica generators.

    Routing: least in-flight requests, ties to the lowest index — a
    deterministic, state-light policy (no cross-replica queues; a replica's
    own ContinuousBatcher provides intra-replica queueing when built with
    ``--concurrent``)."""

    concurrent = True  # the server must not serialize requests around us

    def __init__(self, replicas: list):
        if not replicas:
            raise ValueError("ReplicaSet needs at least one replica")
        self.replicas = list(replicas)
        self._inflight = [0] * len(self.replicas)
        self.served = [0] * len(self.replicas)  # lifetime request counts
        self._lock = threading.Lock()
        # non-concurrent replicas (plain engines) serve one request at a
        # time each; per-replica locks replace the server's global one
        self._serial_locks: list[Optional[threading.Lock]] = [
            None if getattr(r, "concurrent", False) else threading.Lock()
            for r in self.replicas
        ]

    def _pick(self) -> int:
        with self._lock:
            i = min(range(len(self.replicas)), key=lambda j: self._inflight[j])
            self._inflight[i] += 1
            self.served[i] += 1
            return i

    def _done(self, i: int):
        with self._lock:
            self._inflight[i] -= 1

    def generate_step(self, prompt_tokens, **kw):
        i = self._pick()
        try:
            serial = self._serial_locks[i]
            if serial is not None:
                with serial:
                    yield from self.replicas[i].generate_step(
                        prompt_tokens, **kw
                    )
            else:
                yield from self.replicas[i].generate_step(prompt_tokens, **kw)
        finally:
            self._done(i)

    # ------------------------------------------------------- observability
    def stats(self):
        """Aggregate (slots, active, queued) across replicas for /metrics.
        Non-batcher replicas count as one slot each, active while a request
        is in flight."""
        slots = active = queued = 0
        for i, r in enumerate(self.replicas):
            if hasattr(r, "stats"):
                s, a, q = r.stats()
                slots, active, queued = slots + s, active + a, queued + q
            else:
                slots += 1
                active += min(self._inflight[i], 1)
                queued += max(self._inflight[i] - 1, 0)
        return slots, active, queued

    def page_stats(self):
        totals = [r.page_stats() for r in self.replicas if hasattr(r, "page_stats")]
        totals = [t for t in totals if t is not None]
        if not totals:
            return None
        return tuple(sum(col) for col in zip(*totals))

    def close(self):
        for r in self.replicas:
            if hasattr(r, "close"):
                r.close()
