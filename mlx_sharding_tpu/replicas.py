"""Data-parallel serving: independent engine replicas behind one dispatcher.

The mesh axis story (parallel/mesh.py) gives dp to the training step; this
module gives it to SERVING — `--replicas R` builds R fully independent
engines (each a PipelineEngine [+ ContinuousBatcher] on its own slice of
``jax.devices()``) and routes each request to the least-loaded replica.
Replication multiplies aggregate throughput by R at identical per-request
latency, the standard inference-serving dp recipe; the reference's topology
has no equivalent (one gRPC chain serves one request at a time,
ref: shard/openai_api.py:543-563).

Each replica holds its own copy of the weights (device_put onto its own
mesh by PipelineEngine) and its own KV state. Requests route once and
normally stay put; when a stream must leave its replica anyway — graceful
drain or a mid-stream crash — it migrates as a ``ResumeState`` (see
``kv_transfer``): the replica (or the dispatcher's own delivered-token
record) captures prompt + emitted history + sampler rows + optionally the
host-materialized KV page block, and the dispatcher re-places the request
on a healthy replica, resuming from the last token the client saw.

Resilience: the dispatcher is also the failure domain boundary. A replica
that keeps failing dispatches is circuit-broken out of routing (consecutive
failures ≥ ``breaker_threshold`` opens the breaker for ``probe_interval``
seconds; after that ONE live request is let through as a half-open probe —
success closes the breaker, failure re-opens it). Requests that fail before
their first token retry on another replica. Started streams migrate only
when a token-exact continuation is possible: the target must advertise
``supports_resume`` and every delivered token must have been trackable —
otherwise the failure surfaces to the client as before. ``drain(i)``
retires a replica without dropping work: it stops routing to *i*, asks its
batcher to ``migrate_out()`` every admitted request, waits for in-flight
dispatches to unwind, then closes it. While at least one replica lives the
set keeps serving and ``health()`` reports degraded, not dead.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from mlx_sharding_tpu.analysis.runtime import make_lock
from mlx_sharding_tpu.resilience import (
    QueueFullError,
    ReplicasUnavailableError,
    RequestMigratedError,
    RequestTimeoutError,
    ResumeState,
)
from mlx_sharding_tpu.testing.faults import inject


class _ResumeUnsupported(Exception):
    """Internal: the picked replica can't continue a migrated stream
    (no ``supports_resume``). Not a failure — just the wrong target."""


class ReplicaSet:
    """``generate_step`` dispatcher over independent replica generators.

    Routing: least in-flight requests, ties to the lowest index — a
    deterministic, state-light policy (no cross-replica queues; a replica's
    own ContinuousBatcher provides intra-replica queueing when built with
    ``--concurrent``). Circuit-broken replicas are skipped; a half-open
    replica receives at most one probe request at a time."""

    concurrent = True  # the server must not serialize requests around us

    def __init__(self, replicas: list, *, breaker_threshold: int = 3,
                 probe_interval: float = 5.0, resume_streams: bool = True):
        if not replicas:
            raise ValueError("ReplicaSet needs at least one replica")
        if breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        if probe_interval <= 0:
            raise ValueError("probe_interval must be positive")
        self.replicas = list(replicas)
        self.breaker_threshold = breaker_threshold
        self.probe_interval = probe_interval
        # crash-safe re-placement: when a replica dies mid-stream, rebuild
        # the request from the dispatcher's delivered-token record and
        # resume it on a healthy replica (False restores the old raise)
        self.resume_streams = bool(resume_streams)
        n = len(self.replicas)
        self._inflight = [0] * n
        self.served = [0] * n  # lifetime dispatch counts (retries included)
        self.failures = [0] * n  # lifetime dispatch failures
        self.breaker_opens = [0] * n  # closed→open transitions
        self._fails_consec = [0] * n
        # drain lifecycle (all under _lock): draining = migrate_out in
        # progress, no new dispatches, in-flight streams unwinding;
        # retired = permanently out of routing (drain completed)
        self._draining = [False] * n     # routing quarantine (sticky on failure)
        self._drain_active = [False] * n  # a drain() call is currently running
        self._retired = [False] * n
        self.drains = 0            # completed drain() calls
        self.migrated_streams = 0  # resumed attempts that delivered a token
        # monotonic stamp until which the breaker holds the replica out of
        # routing; 0 = closed. Past the stamp the replica is HALF-OPEN: one
        # request may probe it (_probing guards against a probe stampede).
        self._open_until = [0.0] * n
        self._probing = [False] * n
        self._lock = make_lock("ReplicaSet._lock")
        # non-concurrent replicas (plain engines) serve one request at a
        # time each; per-replica locks replace the server's global one
        self._serial_locks: list = [
            None if getattr(r, "concurrent", False)
            else make_lock("ReplicaSet._serial_locks[*]")
            for r in self.replicas
        ]

    @property
    def supports_deadlines(self) -> bool:
        """Deadline kwargs can be forwarded only when every replica
        understands them (mixed sets would crash on the plain engines)."""
        return all(
            getattr(r, "supports_deadlines", False) for r in self.replicas
        )

    # ------------------------------------------------------------- routing
    def _breaker_state(self, j: int, now: float) -> str:
        if self._open_until[j] == 0:
            return "closed"
        return "half_open" if now >= self._open_until[j] else "open"

    def _pick(self, exclude=()) -> tuple[int, bool]:
        with self._lock:
            now = time.monotonic()
            closed, half_open = [], []
            for j in range(len(self.replicas)):
                if j in exclude or self._draining[j] or self._retired[j]:
                    continue
                state = self._breaker_state(j, now)
                if state == "closed":
                    closed.append(j)
                elif state == "half_open" and not self._probing[j]:
                    half_open.append(j)
            probe = False
            if half_open:
                # recovery beats load balance: route this request as the
                # probe, or an idle fleet would never close the breaker
                i = half_open[0]
                self._probing[i] = True
                probe = True
            elif closed:
                i = min(closed, key=lambda j: self._inflight[j])
            else:
                raise ReplicasUnavailableError(
                    "no replica available: every replica is circuit-broken "
                    "or already failed this request"
                )
            self._inflight[i] += 1
            self.served[i] += 1
            return i, probe

    def _done(self, i: int, probe: bool = False):
        with self._lock:
            self._inflight[i] -= 1
            if probe:
                # the probe ticket must come back on EVERY exit path (bad
                # request, queue-full, consumer close, crash) — a leaked
                # ticket would bar the replica from ever being probed again
                self._probing[i] = False

    def _record_success(self, i: int):
        with self._lock:
            self._fails_consec[i] = 0
            self._open_until[i] = 0.0
            self._probing[i] = False

    def _record_failure(self, i: int):
        with self._lock:
            self.failures[i] += 1
            self._fails_consec[i] += 1
            self._probing[i] = False
            now = time.monotonic()
            if self._open_until[i] > 0:
                # failed half-open probe: straight back to open
                self._open_until[i] = now + self.probe_interval
            elif self._fails_consec[i] >= self.breaker_threshold:
                self._open_until[i] = now + self.probe_interval
                self.breaker_opens[i] += 1

    @staticmethod
    def _note_token(emitted: list, item) -> bool:
        """Record a delivered token for crash-resume accounting. Items are
        ``(token, logprobs)`` pairs from the engines (or bare tokens from
        simple generators); False means the token wasn't an integer and the
        stream can no longer be resumed exactly."""
        tok = item[0] if isinstance(item, (tuple, list)) else item
        try:
            emitted.append(int(tok))
            return True
        except (TypeError, ValueError):
            return False

    def generate_step(self, prompt_tokens, **kw):
        excluded: set[int] = set()
        last_exc: Optional[BaseException] = None
        resume: Optional[ResumeState] = None  # carried across attempts
        emitted: list = []  # every token delivered to the client so far
        trackable = True    # ints only; else crash-resume is refused
        while True:
            try:
                i, probe = self._pick(excluded)
            except ReplicasUnavailableError:
                if last_exc is not None:
                    # mst: allow(MST302): _pick raised — no ticket was taken
                    raise last_exc  # concrete failure beats the generic 503
                raise
            started = False
            try:
                rep = self.replicas[i]
                fwd = kw
                if resume is not None:
                    if not getattr(rep, "supports_resume", False):
                        # a resumed stream needs the _resume protocol; a
                        # plain engine would re-run from scratch and
                        # double-emit — try the other replicas instead
                        raise _ResumeUnsupported()
                    fwd = dict(kw, _resume=resume)
                inject("replica.dispatch", replica=i)
                serial = self._serial_locks[i]
                if serial is not None:
                    with serial:
                        for item in rep.generate_step(prompt_tokens, **fwd):
                            if not started:
                                started = True
                                if resume is not None:
                                    with self._lock:
                                        self.migrated_streams += 1
                            if trackable:
                                trackable = self._note_token(emitted, item)
                            yield item
                else:
                    for item in rep.generate_step(prompt_tokens, **fwd):
                        if not started:
                            started = True
                            if resume is not None:
                                with self._lock:
                                    self.migrated_streams += 1
                        if trackable:
                            trackable = self._note_token(emitted, item)
                        yield item
                self._record_success(i)
                return
            except GeneratorExit:
                # The consumer closed the stream early — under the server
                # this is the COMMON success path (eos / stop word hit, so
                # it.close()s the stream). Tokens flowed, the replica did
                # its job: record the success, or a recovered probe would
                # stay half-open forever and ordinary early exits would
                # never reset the failure streak.
                if started:
                    self._record_success(i)
                raise
            except _ResumeUnsupported:
                excluded.add(i)  # keep last_exc: it names the real failure
            except ValueError:
                raise  # bad request — the replica is healthy
            except RequestMigratedError as exc:
                # graceful drain: the replica ended the stream with the
                # complete ResumeState (KV block or prompt+history). Not a
                # failure — no breaker strike; re-place and continue the
                # client's stream where it left off
                resume = exc.state
                excluded.add(i)
                last_exc = exc
            except QueueFullError as exc:
                # saturation (or ReplicaDrainingError, its drain-time
                # subtype), not sickness: no breaker penalty, but try the
                # other replicas before giving the client a 429
                excluded.add(i)
                last_exc = exc
            except RequestTimeoutError as exc:
                # the request's own budget is spent — a retry would only
                # blow it further. Only expiries that mark a WEDGED engine
                # (mid-stream stall, blown total budget) strike the breaker;
                # ttft/queue expiries are saturation, and client-settable
                # budgets must not circuit-break healthy-but-busy replicas
                if exc.kind in ("stall", "total"):
                    self._record_failure(i)
                raise
            except Exception as exc:  # noqa: BLE001 — any replica-side crash
                self._record_failure(i)
                if started:
                    if not (self.resume_streams and trackable):
                        raise  # tokens delivered, no exact resume possible
                    # crash-safe re-placement: rebuild the request from the
                    # dispatcher's own delivered-token record. Greedy
                    # streams resume token-exact; sampled streams reseed
                    # (the PRNG rows died with the replica) — distribution-
                    # correct, not bit-exact (see README)
                    resume = ResumeState(
                        prompt=prompt_tokens,
                        history=list(emitted),
                        produced=len(emitted),
                    )
                excluded.add(i)
                last_exc = exc
            finally:
                self._done(i, probe)

    # -------------------------------------------------------------- drain
    def drain(self, i: int, deadline: float = 30.0) -> dict:
        """Gracefully retire replica ``i``: stop routing to it, migrate its
        admitted requests off (each stream ends with a
        ``RequestMigratedError`` whose ``ResumeState`` this dispatcher
        re-places on a healthy replica — the client never notices), wait
        for in-flight dispatches to unwind, then close and retire it.

        Failure semantics: if the migration step itself fails (injected
        ``replica.drain`` fault, wedged batcher), the replica stays
        QUARANTINED — ``draining`` keeps new work away while the still-
        flowing streams finish — and the error surfaces so the operator can
        retry. The replica is never closed while un-migrated streams could
        be truncated; if in-flight dispatches don't unwind by ``deadline``
        it is retired without closing (``closed: False`` in the result) and
        the leak is logged."""
        n = len(self.replicas)
        if not isinstance(i, int) or isinstance(i, bool) or not 0 <= i < n:
            raise ValueError(f"replica index must be in [0, {n}); got {i!r}")
        with self._lock:
            if self._retired[i]:
                return {"replica": i, "migrated": 0, "closed": True,
                        "already_retired": True}
            if self._drain_active[i]:
                raise ValueError(f"replica {i} is already draining")
            others = [
                j for j in range(n)
                if j != i and not self._retired[j] and not self._draining[j]
            ]
            if not others:
                raise ValueError(
                    "cannot drain the last live replica — the migrated "
                    "requests would have nowhere to resume"
                )
            self._drain_active[i] = True
            self._draining[i] = True
        r = self.replicas[i]
        try:
            inject("replica.drain", replica=i)
            migrated = (
                r.migrate_out(deadline=deadline)
                if hasattr(r, "migrate_out") else 0
            )
        except Exception:
            # leave the replica quarantined (draining=True: no new routes,
            # in-flight streams keep flowing) and surface the failure —
            # the operator calls drain() again to retry; nothing was dropped
            logging.getLogger(__name__).exception(
                "drain of replica %d failed mid-migration; replica "
                "quarantined, retry drain()", i,
            )
            # mst: allow(MST202): slot i is owned by this call while _drain_active[i] is set
            with self._lock:
                self._drain_active[i] = False
            raise
        deadline_at = time.monotonic() + deadline
        while time.monotonic() < deadline_at:
            with self._lock:
                if self._inflight[i] == 0:
                    break
            time.sleep(0.01)
        with self._lock:
            leaked = self._inflight[i]
        closed = False
        if leaked == 0:
            if hasattr(r, "close"):
                r.close()
            closed = True
        else:
            logging.getLogger(__name__).warning(
                "replica %d retired with %d dispatches still unwinding — "
                "left unclosed to avoid truncating their streams",
                i, leaked,
            )
        # mst: allow(MST202): slot i is owned by this call while _drain_active[i] is set
        with self._lock:
            self._retired[i] = True
            self._draining[i] = False
            self._drain_active[i] = False
            self.drains += 1
        return {"replica": i, "migrated": migrated, "closed": closed}

    # ------------------------------------------------------- observability
    def stats(self):
        """Aggregate (slots, active, queued) across replicas for /metrics.
        Non-batcher replicas count as one slot each, active while a request
        is in flight."""
        with self._lock:
            inflight = list(self._inflight)
        slots = active = queued = 0
        for i, r in enumerate(self.replicas):
            if hasattr(r, "stats"):  # replica stats outside our lock: the
                s, a, q = r.stats()  # batcher takes its own admission lock
                slots, active, queued = slots + s, active + a, queued + q
            else:
                slots += 1
                active += min(inflight[i], 1)
                queued += max(inflight[i] - 1, 0)
        return slots, active, queued

    def page_stats(self):
        totals = [r.page_stats() for r in self.replicas if hasattr(r, "page_stats")]
        totals = [t for t in totals if t is not None]
        if not totals:
            return None
        return tuple(sum(col) for col in zip(*totals))

    def resilience_stats(self) -> dict:
        """Deadline/shedding/migration counters summed across replica
        batchers, plus the dispatcher's own drain/re-placement counts."""
        agg = {"timeouts": 0, "shed_queue_full": 0, "shed_deadline": 0,
               "max_queue": None, "scheduler_thread_live": True}
        summed = ("preemptions", "spills", "spill_hits", "spill_fallbacks",
                  "migrations_out", "migrations_in")
        for k in summed:
            agg[k] = 0
        for r in self.replicas:
            if not hasattr(r, "resilience_stats"):
                continue
            s = r.resilience_stats()
            agg["timeouts"] += s["timeouts"]
            agg["shed_queue_full"] += s["shed_queue_full"]
            agg["shed_deadline"] += s["shed_deadline"]
            for k in summed:
                agg[k] += s.get(k, 0)
            if s["max_queue"] is not None:
                agg["max_queue"] = (agg["max_queue"] or 0) + s["max_queue"]
            agg["scheduler_thread_live"] = (
                agg["scheduler_thread_live"] and s["scheduler_thread_live"]
            )
        with self._lock:
            agg["drains"] = self.drains
            agg["migrated_streams"] = self.migrated_streams
        return agg

    def spill_stats(self) -> Optional[dict]:
        """KV spill/migration counters summed across replica batchers (the
        ``mst_kv_*`` gauge source when serving through a ReplicaSet), plus
        the dispatcher's crash/drain re-placement count. None when no
        replica has a paged pool."""
        per = [
            r.spill_stats() for r in self.replicas
            if hasattr(r, "spill_stats")
        ]
        per = [s for s in per if s is not None]
        if not per:
            return None
        agg: dict = {"enabled": any(s.get("enabled") for s in per)}
        for k in ("spills", "spill_hits", "spill_fallbacks",
                  "migrations_out", "migrations_in", "reprefill_tokens",
                  "preemptions", "budget_bytes", "bytes_in_use", "blocks",
                  "evictions", "rejects"):
            agg[k] = sum(s.get(k, 0) for s in per)
        with self._lock:
            agg["migrated_streams"] = self.migrated_streams
            agg["drains"] = self.drains
        return agg

    def health(self) -> dict:
        """Partial-capacity health: ``draining`` while a drain is in
        progress, degraded (still serving) while at least one replica
        lives, dead only when none do. Retired replicas left the fleet on
        purpose — they don't count against ``ok``."""
        with self._lock:
            now = time.monotonic()
            states = [
                self._breaker_state(j, now) for j in range(len(self.replicas))
            ]
            consec = list(self._fails_consec)
            fails = list(self.failures)
            draining = list(self._draining)
            retired = list(self._retired)
        per, live = [], 0
        for j, r in enumerate(self.replicas):
            entry = {"replica": j, "breaker": states[j],
                     "consecutive_failures": consec[j], "failures": fails[j]}
            if retired[j]:
                entry["state"] = "retired"
            elif draining[j]:
                entry["state"] = "draining"
            sub = r.health() if hasattr(r, "health") else None
            alive = states[j] != "open"
            if sub is not None:
                entry["engine"] = sub["status"]
                alive = alive and sub["serving"]
            if alive and not retired[j] and not draining[j]:
                live += 1
            per.append(entry)
        n = len(self.replicas)
        expected = n - sum(retired)
        status = (
            "draining" if any(draining)
            else ("ok" if live == expected else "degraded")
        )
        return {
            "status": status,
            "serving": live >= 1,
            "replicas_total": n,
            "replicas_live": live,
            "replicas_draining": sum(draining),
            "replicas_retired": sum(retired),
            "replicas": per,
        }

    def close(self):
        for r in self.replicas:
            if hasattr(r, "close"):
                r.close()
