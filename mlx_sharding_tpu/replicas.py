"""Data-parallel serving: independent engine replicas behind one dispatcher.

The mesh axis story (parallel/mesh.py) gives dp to the training step; this
module gives it to SERVING — `--replicas R` builds R fully independent
engines (each a PipelineEngine [+ ContinuousBatcher] on its own slice of
``jax.devices()``) and routes each request to the least-loaded replica.
Replication multiplies aggregate throughput by R at identical per-request
latency, the standard inference-serving dp recipe; the reference's topology
has no equivalent (one gRPC chain serves one request at a time,
ref: shard/openai_api.py:543-563).

Each replica holds its own copy of the weights (device_put onto its own
mesh by PipelineEngine) and its own KV state; requests never migrate, so
per-request streams are exactly what the replica alone would produce.

Resilience: the dispatcher is also the failure domain boundary. A replica
that keeps failing dispatches is circuit-broken out of routing (consecutive
failures ≥ ``breaker_threshold`` opens the breaker for ``probe_interval``
seconds; after that ONE live request is let through as a half-open probe —
success closes the breaker, failure re-opens it). Requests that fail before
their first token retry on another replica; started streams never migrate
(their KV lives on the failed replica). While at least one replica lives the
set keeps serving and ``health()`` reports degraded, not dead.
"""

from __future__ import annotations

import time
from typing import Optional

from mlx_sharding_tpu.analysis.runtime import make_lock
from mlx_sharding_tpu.resilience import (
    QueueFullError,
    ReplicasUnavailableError,
    RequestTimeoutError,
)
from mlx_sharding_tpu.testing.faults import inject


class ReplicaSet:
    """``generate_step`` dispatcher over independent replica generators.

    Routing: least in-flight requests, ties to the lowest index — a
    deterministic, state-light policy (no cross-replica queues; a replica's
    own ContinuousBatcher provides intra-replica queueing when built with
    ``--concurrent``). Circuit-broken replicas are skipped; a half-open
    replica receives at most one probe request at a time."""

    concurrent = True  # the server must not serialize requests around us

    def __init__(self, replicas: list, *, breaker_threshold: int = 3,
                 probe_interval: float = 5.0):
        if not replicas:
            raise ValueError("ReplicaSet needs at least one replica")
        if breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        if probe_interval <= 0:
            raise ValueError("probe_interval must be positive")
        self.replicas = list(replicas)
        self.breaker_threshold = breaker_threshold
        self.probe_interval = probe_interval
        n = len(self.replicas)
        self._inflight = [0] * n
        self.served = [0] * n  # lifetime dispatch counts (retries included)
        self.failures = [0] * n  # lifetime dispatch failures
        self.breaker_opens = [0] * n  # closed→open transitions
        self._fails_consec = [0] * n
        # monotonic stamp until which the breaker holds the replica out of
        # routing; 0 = closed. Past the stamp the replica is HALF-OPEN: one
        # request may probe it (_probing guards against a probe stampede).
        self._open_until = [0.0] * n
        self._probing = [False] * n
        self._lock = make_lock("ReplicaSet._lock")
        # non-concurrent replicas (plain engines) serve one request at a
        # time each; per-replica locks replace the server's global one
        self._serial_locks: list = [
            None if getattr(r, "concurrent", False)
            else make_lock("ReplicaSet._serial_locks[*]")
            for r in self.replicas
        ]

    @property
    def supports_deadlines(self) -> bool:
        """Deadline kwargs can be forwarded only when every replica
        understands them (mixed sets would crash on the plain engines)."""
        return all(
            getattr(r, "supports_deadlines", False) for r in self.replicas
        )

    # ------------------------------------------------------------- routing
    def _breaker_state(self, j: int, now: float) -> str:
        if self._open_until[j] == 0:
            return "closed"
        return "half_open" if now >= self._open_until[j] else "open"

    def _pick(self, exclude=()) -> tuple[int, bool]:
        with self._lock:
            now = time.monotonic()
            closed, half_open = [], []
            for j in range(len(self.replicas)):
                if j in exclude:
                    continue
                state = self._breaker_state(j, now)
                if state == "closed":
                    closed.append(j)
                elif state == "half_open" and not self._probing[j]:
                    half_open.append(j)
            probe = False
            if half_open:
                # recovery beats load balance: route this request as the
                # probe, or an idle fleet would never close the breaker
                i = half_open[0]
                self._probing[i] = True
                probe = True
            elif closed:
                i = min(closed, key=lambda j: self._inflight[j])
            else:
                raise ReplicasUnavailableError(
                    "no replica available: every replica is circuit-broken "
                    "or already failed this request"
                )
            self._inflight[i] += 1
            self.served[i] += 1
            return i, probe

    def _done(self, i: int, probe: bool = False):
        with self._lock:
            self._inflight[i] -= 1
            if probe:
                # the probe ticket must come back on EVERY exit path (bad
                # request, queue-full, consumer close, crash) — a leaked
                # ticket would bar the replica from ever being probed again
                self._probing[i] = False

    def _record_success(self, i: int):
        with self._lock:
            self._fails_consec[i] = 0
            self._open_until[i] = 0.0
            self._probing[i] = False

    def _record_failure(self, i: int):
        with self._lock:
            self.failures[i] += 1
            self._fails_consec[i] += 1
            self._probing[i] = False
            now = time.monotonic()
            if self._open_until[i] > 0:
                # failed half-open probe: straight back to open
                self._open_until[i] = now + self.probe_interval
            elif self._fails_consec[i] >= self.breaker_threshold:
                self._open_until[i] = now + self.probe_interval
                self.breaker_opens[i] += 1

    def generate_step(self, prompt_tokens, **kw):
        excluded: set[int] = set()
        last_exc: Optional[BaseException] = None
        while True:
            try:
                i, probe = self._pick(excluded)
            except ReplicasUnavailableError:
                if last_exc is not None:
                    # mst: allow(MST302): _pick raised — no ticket was taken
                    raise last_exc  # concrete failure beats the generic 503
                raise
            started = False
            try:
                inject("replica.dispatch", replica=i)
                serial = self._serial_locks[i]
                if serial is not None:
                    with serial:
                        for item in self.replicas[i].generate_step(
                            prompt_tokens, **kw
                        ):
                            started = True
                            yield item
                else:
                    for item in self.replicas[i].generate_step(
                        prompt_tokens, **kw
                    ):
                        started = True
                        yield item
                self._record_success(i)
                return
            except GeneratorExit:
                # The consumer closed the stream early — under the server
                # this is the COMMON success path (eos / stop word hit, so
                # it.close()s the stream). Tokens flowed, the replica did
                # its job: record the success, or a recovered probe would
                # stay half-open forever and ordinary early exits would
                # never reset the failure streak.
                if started:
                    self._record_success(i)
                raise
            except ValueError:
                raise  # bad request — the replica is healthy
            except QueueFullError as exc:
                # saturation, not sickness: no breaker penalty, but try the
                # other replicas before giving the client a 429
                excluded.add(i)
                last_exc = exc
            except RequestTimeoutError as exc:
                # the request's own budget is spent — a retry would only
                # blow it further. Only expiries that mark a WEDGED engine
                # (mid-stream stall, blown total budget) strike the breaker;
                # ttft/queue expiries are saturation, and client-settable
                # budgets must not circuit-break healthy-but-busy replicas
                if exc.kind in ("stall", "total"):
                    self._record_failure(i)
                raise
            except Exception as exc:  # noqa: BLE001 — any replica-side crash
                self._record_failure(i)
                if started:
                    raise  # tokens were delivered; streams never migrate
                excluded.add(i)
                last_exc = exc
            finally:
                self._done(i, probe)

    # ------------------------------------------------------- observability
    def stats(self):
        """Aggregate (slots, active, queued) across replicas for /metrics.
        Non-batcher replicas count as one slot each, active while a request
        is in flight."""
        with self._lock:
            inflight = list(self._inflight)
        slots = active = queued = 0
        for i, r in enumerate(self.replicas):
            if hasattr(r, "stats"):  # replica stats outside our lock: the
                s, a, q = r.stats()  # batcher takes its own admission lock
                slots, active, queued = slots + s, active + a, queued + q
            else:
                slots += 1
                active += min(inflight[i], 1)
                queued += max(inflight[i] - 1, 0)
        return slots, active, queued

    def page_stats(self):
        totals = [r.page_stats() for r in self.replicas if hasattr(r, "page_stats")]
        totals = [t for t in totals if t is not None]
        if not totals:
            return None
        return tuple(sum(col) for col in zip(*totals))

    def resilience_stats(self) -> dict:
        """Deadline/shedding counters summed across replica batchers."""
        agg = {"timeouts": 0, "shed_queue_full": 0, "shed_deadline": 0,
               "max_queue": None, "scheduler_thread_live": True}
        for r in self.replicas:
            if not hasattr(r, "resilience_stats"):
                continue
            s = r.resilience_stats()
            agg["timeouts"] += s["timeouts"]
            agg["shed_queue_full"] += s["shed_queue_full"]
            agg["shed_deadline"] += s["shed_deadline"]
            if s["max_queue"] is not None:
                agg["max_queue"] = (agg["max_queue"] or 0) + s["max_queue"]
            agg["scheduler_thread_live"] = (
                agg["scheduler_thread_live"] and s["scheduler_thread_live"]
            )
        return agg

    def health(self) -> dict:
        """Partial-capacity health: degraded (still serving) while at least
        one replica lives, dead only when none do."""
        with self._lock:
            now = time.monotonic()
            states = [
                self._breaker_state(j, now) for j in range(len(self.replicas))
            ]
            consec = list(self._fails_consec)
            fails = list(self.failures)
        per, live = [], 0
        for j, r in enumerate(self.replicas):
            entry = {"replica": j, "breaker": states[j],
                     "consecutive_failures": consec[j], "failures": fails[j]}
            sub = r.health() if hasattr(r, "health") else None
            alive = states[j] != "open"
            if sub is not None:
                entry["engine"] = sub["status"]
                alive = alive and sub["serving"]
            if alive:
                live += 1
            per.append(entry)
        n = len(self.replicas)
        return {
            "status": "ok" if live == n else "degraded",
            "serving": live >= 1,
            "replicas_total": n,
            "replicas_live": live,
            "replicas": per,
        }

    def close(self):
        for r in self.replicas:
            if hasattr(r, "close"):
                r.close()
