"""Data-parallel serving: independent engine replicas behind one dispatcher.

The mesh axis story (parallel/mesh.py) gives dp to the training step; this
module gives it to SERVING — `--replicas R` builds R fully independent
engines (each a PipelineEngine [+ ContinuousBatcher] on its own slice of
``jax.devices()``) and routes each request to the best-scored replica.
Replication multiplies aggregate throughput by R at identical per-request
latency, the standard inference-serving dp recipe; the reference's topology
has no equivalent (one gRPC chain serves one request at a time,
ref: shard/openai_api.py:543-563).

Routing score: a replica's load is ``inflight + queue_depth`` (the queue
depth comes from its batcher's own admission stats). Two placement signals
may override pure least-loaded, both behind a load-imbalance escape hatch
(``route_imbalance``): session stickiness (``_session`` request key → the
replica that served the session last, keeping its KV/prompt-cache warm) and
prefix-cache affinity (chained page digests of the prompt → the replica
whose prompt cache holds the longest prefix, so the 4.57× warm-TTFT win
survives multi-replica placement). Requests with a tight TTFT budget drop
the escape hatch to zero — no deadline-headroom, no affinity detour.

Elasticity: the fleet can grow at runtime — ``add_replica()`` appends a
freshly spawned replica (indices are stable; retired slots keep their
position) and ``drain()`` retires one with zero dropped streams. The
decision loop that calls them under queue pressure lives in ``fleet.py``
(FleetAutoscaler + BrownoutController); this module only provides the
mechanisms plus the ``autoscale_events`` / ``replica_stats()`` /
``fleet_stats()`` surfaces that /metrics and /health report.

Each replica holds its own copy of the weights (device_put onto its own
mesh by PipelineEngine) and its own KV state. Requests route once and
normally stay put; when a stream must leave its replica anyway — graceful
drain or a mid-stream crash — it migrates as a ``ResumeState`` (see
``kv_transfer``): the replica (or the dispatcher's own delivered-token
record) captures prompt + emitted history + sampler rows + optionally the
host-materialized KV page block, and the dispatcher re-places the request
on a healthy replica, resuming from the last token the client saw.

Resilience: the dispatcher is also the failure domain boundary. A replica
that keeps failing dispatches is circuit-broken out of routing (consecutive
failures ≥ ``breaker_threshold`` opens the breaker for ``probe_interval``
seconds; after that ONE live request is let through as a half-open probe —
success closes the breaker, failure re-opens it). Requests that fail before
their first token retry on another replica. Started streams migrate only
when a token-exact continuation is possible: the target must advertise
``supports_resume`` and every delivered token must have been trackable —
otherwise the failure surfaces to the client as before. ``drain(i)``
retires a replica without dropping work: it stops routing to *i*, asks its
batcher to ``migrate_out()`` every admitted request, waits for in-flight
dispatches to unwind, then closes it. While at least one replica lives the
set keeps serving and ``health()`` reports degraded, not dead.
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Optional

from mlx_sharding_tpu import tracing
from mlx_sharding_tpu.analysis.runtime import make_lock, note_acquire, note_release
from mlx_sharding_tpu.utils.clock import MONOTONIC, WALL_SLEEP, Clock, SleepFn
from mlx_sharding_tpu.utils.digests import chunk_digests
from mlx_sharding_tpu.utils.observability import Histogram
from mlx_sharding_tpu.resilience import (
    HandoffReadyError,
    QueueFullError,
    ReplicasUnavailableError,
    RequestMigratedError,
    RequestTimeoutError,
    ResumeState,
)
from mlx_sharding_tpu.testing.faults import inject


class _ResumeUnsupported(Exception):
    """Internal: the picked replica can't continue a migrated stream
    (no ``supports_resume``). Not a failure — just the wrong target."""


class ReplicaSet:
    """``generate_step`` dispatcher over independent replica generators.

    Routing: lowest ``inflight + queue_depth`` score, ties to the lowest
    index — deterministic and state-light (no cross-replica queues; a
    replica's own ContinuousBatcher provides intra-replica queueing when
    built with ``--concurrent``). Session stickiness and prefix-cache
    affinity may override the score within ``route_imbalance`` load units,
    except for tight-TTFT requests (see module docstring). Circuit-broken
    replicas are skipped; a half-open replica receives at most one probe
    request at a time."""

    concurrent = True  # the server must not serialize requests around us
    supports_sessions = True  # the server may forward a _session key

    def __init__(self, replicas: list, *, breaker_threshold: int = 3,
                 probe_interval: float = 5.0, resume_streams: bool = True,
                 route_imbalance: int = 4, affinity_page: int = 128,
                 tight_ttft_s: float = 10.0, role: Optional[str] = None,
                 prefix_store=None, clock: Clock = MONOTONIC,
                 sleep: SleepFn = WALL_SLEEP):
        if not replicas:
            raise ValueError("ReplicaSet needs at least one replica")
        # injectable time source + wait primitive: breaker open/half-open
        # stamps and the drain unwind loop run on these, so the fleet
        # simulator can drive the whole dispatcher in virtual time
        self._clock = clock
        self._sleep = sleep
        # disaggregated serving: pools are role-tagged ("prefill"/"decode")
        # so fleet gauges, health blocks and autoscale events say which
        # pool they describe; None keeps the monolithic (unlabeled) forms
        self.role = role
        if breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        if probe_interval <= 0:
            raise ValueError("probe_interval must be positive")
        self.replicas = list(replicas)
        self.breaker_threshold = breaker_threshold
        self.probe_interval = probe_interval
        # crash-safe re-placement: when a replica dies mid-stream, rebuild
        # the request from the dispatcher's delivered-token record and
        # resume it on a healthy replica (False restores the old raise)
        self.resume_streams = bool(resume_streams)
        n = len(self.replicas)
        self._inflight = [0] * n
        self.served = [0] * n  # lifetime dispatch counts (retries included)
        self.failures = [0] * n  # lifetime dispatch failures
        self.breaker_opens = [0] * n  # closed→open transitions
        self._fails_consec = [0] * n
        # drain lifecycle (all under _lock): draining = migrate_out in
        # progress, no new dispatches, in-flight streams unwinding;
        # retired = permanently out of routing (drain completed)
        self._draining = [False] * n     # routing quarantine (sticky on failure)
        self._drain_active = [False] * n  # a drain() call is currently running
        self._retired = [False] * n
        self.drains = 0            # completed drain() calls
        self.migrated_streams = 0  # resumed attempts that delivered a token
        # monotonic stamp until which the breaker holds the replica out of
        # routing; 0 = closed. Past the stamp the replica is HALF-OPEN: one
        # request may probe it (_probing guards against a probe stampede).
        self._open_until = [0.0] * n
        self._probing = [False] * n
        self._lock = make_lock("ReplicaSet._lock")
        # non-concurrent replicas (plain engines) serve one request at a
        # time each; per-replica locks replace the server's global one
        self._serial_locks: list = [
            None if getattr(r, "concurrent", False)
            else make_lock("ReplicaSet._serial_locks[*]")
            for r in self.replicas
        ]
        # retirement callback (server wiring): invoked with the replica
        # object after drain() closes and retires it, so the owner can
        # recycle what the replica held — today the device-slice free-list
        # the spawn factories draw from. Failures are logged, never raised:
        # a broken recycle hook must not fail an otherwise-clean drain.
        self.on_retire = None
        # ---------------------------------------- load-aware routing state
        if route_imbalance < 0:
            raise ValueError("route_imbalance must be >= 0")
        if affinity_page < 1:
            raise ValueError("affinity_page must be >= 1")
        self.route_imbalance = route_imbalance
        self.affinity_page = affinity_page
        self.tight_ttft_s = tight_ttft_s
        # chained prompt-chunk digest -> replica index that last served it
        # (mirrors the batcher's prefix-cache page chaining, so a hit here
        # means that replica's prompt cache plausibly holds the prefix)
        self._affinity: OrderedDict = OrderedDict()
        self._affinity_cap = 8192
        # session key -> replica index that served the session last
        self._sticky: OrderedDict = OrderedDict()
        self._sticky_cap = 4096
        self.route_affinity_hits = 0
        self.route_sticky_hits = 0
        # fleet-wide prefix store (optional): a replica that HOLDS the
        # prompt's prefix as a live device entry beats the digest-affinity
        # guess — the hint is ground truth (zero-copy lease on admission)
        # where the affinity map is only a plausible-warmth memory
        self.prefix_store = prefix_store
        self.route_store_hits = 0
        # ------------------------------------------------- elastic fleet
        # autoscale event counters, written by the fleet controller via
        # record_autoscale_event (kind -> count; /metrics renders them)
        self.autoscale_events: dict = {}
        # FleetAutoscaler / BrownoutController attach themselves here so
        # health() can surface them and close() can stop the loop
        self.brownout = None
        self._controller = None

    @property
    def supports_deadlines(self) -> bool:
        """Deadline kwargs can be forwarded only when every replica
        understands them (mixed sets would crash on the plain engines)."""
        with self._lock:
            reps = list(self.replicas)
        return all(getattr(r, "supports_deadlines", False) for r in reps)

    @property
    def supports_trace(self) -> bool:
        """A ``_trace`` handle is forwarded verbatim to the picked replica;
        advertise it only when every replica accepts the kwarg."""
        with self._lock:
            reps = list(self.replicas)
        return all(getattr(r, "supports_trace", False) for r in reps)

    # ------------------------------------------------------------- routing
    def _breaker_state(self, j: int, now: float) -> str:
        if self._open_until[j] == 0:
            return "closed"
        return "half_open" if now >= self._open_until[j] else "open"

    def _affinity_chunks(self, prompt) -> list:
        """Chained digests over fixed ``affinity_page``-token chunks of the
        prompt, mirroring the prefix-cache page chaining: matching the
        first k digests means sharing a k-page prefix. The chain itself
        lives in ``utils.digests`` — the ONE content-address the prefix
        store keys on too, so a router hit and a store hit can never
        disagree about what "same prefix" means. Non-int prompts (or
        prompts shorter than one page) contribute no affinity signal."""
        try:
            return chunk_digests(prompt, self.affinity_page, max_chunks=32)
        except (TypeError, ValueError):
            return []

    def _queue_depths(self) -> list:
        """Per-replica queue-depth snapshot for routing, gathered OUTSIDE
        ``_lock``: a replica's stats() takes its own admission lock, and we
        must not order ours ahead of it. Racy by a tick — gauge-grade is
        all a routing hint needs."""
        with self._lock:
            reps = list(self.replicas)
            retired = list(self._retired)
        out = []
        for j, r in enumerate(reps):
            q = 0
            if not retired[j] and hasattr(r, "stats"):
                try:
                    _, _, q = r.stats()
                except Exception:  # noqa: BLE001 — a sick replica scores 0
                    q = 0
            out.append(q)
        return out

    def _route(self, closed: list, depths: list, chunks: list,
               session, tight: bool, hint=None) -> int:
        """Pick from the closed-breaker candidates (``_lock`` held).
        Stickiness, then the prefix-store owner hint, then affinity may
        override least-loaded — but only within ``route_imbalance`` load
        units of the best candidate, and never for tight-TTFT requests
        (their deadline headroom can't absorb a deeper queue)."""
        def load(j):
            return self._inflight[j] + (depths[j] if j < len(depths) else 0)

        base = min(load(j) for j in closed)
        tol = 0 if tight else self.route_imbalance
        if session is not None:
            s = self._sticky.get(session)
            if s in closed and load(s) - base <= tol:
                self.route_sticky_hits += 1
                return s
        if hint is not None:
            # the store says this replica holds the prompt's prefix as a
            # live DEVICE entry right now — admission there is a zero-copy
            # lease, so it outranks the affinity map's plausible warmth
            for j in closed:
                if self.replicas[j] is hint and load(j) - base <= tol:
                    self.route_store_hits += 1
                    return j
        if chunks:
            best, best_n = None, 0
            for j in closed:
                if load(j) - base > tol:
                    continue
                n = 0
                for k in chunks:
                    if self._affinity.get(k) != j:
                        break
                    n += 1
                if n > best_n:
                    best, best_n = j, n
            if best is not None:
                self.route_affinity_hits += 1
                return best
        return min(closed, key=lambda j: (load(j), j))

    def _remember_route(self, i: int, chunks: list, session):
        """Record the placement (``_lock`` held) so the NEXT request with
        this session/prefix lands on the same warm replica."""
        if session is not None:
            self._sticky[session] = i
            self._sticky.move_to_end(session)
            while len(self._sticky) > self._sticky_cap:
                self._sticky.popitem(last=False)
        for k in chunks:
            self._affinity[k] = i
            self._affinity.move_to_end(k)
        while len(self._affinity) > self._affinity_cap:
            self._affinity.popitem(last=False)

    def _pick(self, exclude=(), *, prompt=None, session=None,
              tight: bool = False) -> tuple[int, bool]:
        chunks = self._affinity_chunks(prompt) if prompt is not None else []
        hint = None
        if self.prefix_store is not None and prompt is not None:
            # OUTSIDE _lock: the store takes its own lock (never nested
            # under ours), and a sick store must not break routing
            try:
                hint = self.prefix_store.owner_hint(prompt)
            except Exception:  # noqa: BLE001 — hint is advisory only
                hint = None
        depths = self._queue_depths()
        with self._lock:
            now = self._clock()
            closed, half_open = [], []
            retry_eta = None  # earliest half-open retry among open breakers
            for j in range(len(self.replicas)):
                if j in exclude or self._draining[j] or self._retired[j]:
                    continue
                state = self._breaker_state(j, now)
                if state == "closed":
                    closed.append(j)
                elif state == "half_open" and not self._probing[j]:
                    half_open.append(j)
                elif state == "half_open":
                    # a probe is in flight — its verdict lands imminently
                    retry_eta = 0.0 if retry_eta is None else retry_eta
                else:
                    eta = self._open_until[j] - now
                    retry_eta = eta if retry_eta is None else min(retry_eta, eta)
            probe = False
            if half_open:
                # recovery beats load balance: route this request as the
                # probe, or an idle fleet would never close the breaker
                i = half_open[0]
                self._probing[i] = True
                probe = True
                note_acquire("replica.probe", (id(self), i))
            elif closed:
                i = self._route(closed, depths, chunks, session, tight, hint)
                self._remember_route(i, chunks, session)
            else:
                raise ReplicasUnavailableError(
                    "no replica available: every replica is circuit-broken "
                    "or already failed this request",
                    retry_after_s=retry_eta,
                )
            self._inflight[i] += 1
            self.served[i] += 1
            return i, probe

    def _done(self, i: int, probe: bool = False):
        with self._lock:
            self._inflight[i] -= 1
            if probe:
                # the probe ticket must come back on EVERY exit path (bad
                # request, queue-full, consumer close, crash) — a leaked
                # ticket would bar the replica from ever being probed again
                self._probing[i] = False
                note_release("replica.probe", (id(self), i))

    def _record_success(self, i: int):
        with self._lock:
            self._fails_consec[i] = 0
            self._open_until[i] = 0.0
            self._probing[i] = False

    def _record_failure(self, i: int):
        opened = False
        with self._lock:
            self.failures[i] += 1
            self._fails_consec[i] += 1
            self._probing[i] = False
            now = self._clock()
            if self._open_until[i] > 0:
                # failed half-open probe: straight back to open
                self._open_until[i] = now + self.probe_interval
            elif self._fails_consec[i] >= self.breaker_threshold:
                self._open_until[i] = now + self.probe_interval
                self.breaker_opens[i] += 1
                opened = True
        if opened:
            # flight recorder: freeze the recent request timelines at the
            # moment a replica is circuit-broken out of routing (outside
            # _lock — the tracer takes its own lock)
            tracing.auto_snapshot(f"breaker_open:replica{i}")

    @staticmethod
    def _note_token(emitted: list, item) -> bool:
        """Record a delivered token for crash-resume accounting. Items are
        ``(token, logprobs)`` pairs from the engines (or bare tokens from
        simple generators); False means the token wasn't an integer and the
        stream can no longer be resumed exactly."""
        tok = item[0] if isinstance(item, (tuple, list)) else item
        try:
            emitted.append(int(tok))
            return True
        except (TypeError, ValueError):
            return False

    def generate_step(self, prompt_tokens, **kw):
        # routing hints: session key (popped — replicas don't see it) and
        # deadline headroom (a tight TTFT budget disables warm-placement
        # detours — the request can't afford a deeper queue)
        session = kw.pop("_session", None)
        ttft = kw.get("ttft_timeout")
        tight = (
            isinstance(ttft, (int, float)) and not isinstance(ttft, bool)
            and ttft < self.tight_ttft_s
        )
        excluded: set[int] = set()
        last_exc: Optional[BaseException] = None
        # caller-seeded resume (disagg handoff: the coordinator re-places a
        # stream whose first tokens were delivered by the OTHER pool) —
        # distinct from `replaced`, which marks in-pool drain/crash hops
        resume: Optional[ResumeState] = kw.pop("_resume", None)
        replaced = False
        emitted: list = []  # every token delivered to the client so far
        trackable = True    # ints only; else crash-resume is refused
        if resume is not None:
            # seed the delivered-token record with the tokens the client
            # already saw, so a crash HERE rebuilds the full stream (an
            # empty seed would resume with the handed-off prefix missing)
            for t in list(resume.history or []):
                if not self._note_token(emitted, t):
                    trackable = False
                    break
        while True:
            try:
                i, probe = self._pick(
                    excluded, prompt=prompt_tokens, session=session,
                    tight=tight,
                )
            except ReplicasUnavailableError:
                if last_exc is not None:
                    # mst: allow(MST302): _pick raised — no ticket was taken
                    raise last_exc  # concrete failure beats the generic 503
                raise
            started = False
            try:
                with self._lock:
                    rep = self.replicas[i]
                    serial = self._serial_locks[i]
                fwd = kw
                if resume is not None:
                    if not getattr(rep, "supports_resume", False):
                        # a resumed stream needs the _resume protocol; a
                        # plain engine would re-run from scratch and
                        # double-emit — try the other replicas instead
                        raise _ResumeUnsupported()
                    fwd = dict(kw, _resume=resume)
                inject("replica.dispatch", replica=i)
                tr = kw.get("_trace")
                if tr is not None:
                    tr.point("dispatch", replica=i, probe=probe,
                             resumed=resume is not None)
                if serial is not None:
                    with serial:
                        for item in rep.generate_step(prompt_tokens, **fwd):
                            if not started:
                                started = True
                                if replaced:
                                    with self._lock:
                                        self.migrated_streams += 1
                            if trackable:
                                trackable = self._note_token(emitted, item)
                            yield item
                else:
                    for item in rep.generate_step(prompt_tokens, **fwd):
                        if not started:
                            started = True
                            if replaced:
                                with self._lock:
                                    self.migrated_streams += 1
                        if trackable:
                            trackable = self._note_token(emitted, item)
                        yield item
                self._record_success(i)
                return
            except GeneratorExit:
                # The consumer closed the stream early — under the server
                # this is the COMMON success path (eos / stop word hit, so
                # it.close()s the stream). Tokens flowed, the replica did
                # its job: record the success, or a recovered probe would
                # stay half-open forever and ordinary early exits would
                # never reset the failure streak.
                if started:
                    self._record_success(i)
                raise
            except _ResumeUnsupported:
                excluded.add(i)  # keep last_exc: it names the real failure
            except ValueError:
                raise  # bad request — the replica is healthy
            except HandoffReadyError:
                # disaggregated prefill: the replica completed its phase and
                # the stream ends with the ResumeState for the decode pool.
                # A successful exit — no breaker strike, no in-pool
                # re-placement; the DisaggCoordinator above catches it
                self._record_success(i)
                raise
            except RequestMigratedError as exc:
                # graceful drain: the replica ended the stream with the
                # complete ResumeState (KV block or prompt+history). Not a
                # failure — no breaker strike; re-place and continue the
                # client's stream where it left off
                resume = exc.state
                replaced = True
                excluded.add(i)
                last_exc = exc
                tr = kw.get("_trace")
                if tr is not None:
                    tr.point("drain_migrate", replica=i)
            except QueueFullError as exc:
                # saturation (or ReplicaDrainingError, its drain-time
                # subtype), not sickness: no breaker penalty, but try the
                # other replicas before giving the client a 429
                excluded.add(i)
                last_exc = exc
            except RequestTimeoutError as exc:
                # the request's own budget is spent — a retry would only
                # blow it further. Only expiries that mark a WEDGED engine
                # (mid-stream stall, blown total budget) strike the breaker;
                # ttft/queue expiries are saturation, and client-settable
                # budgets must not circuit-break healthy-but-busy replicas
                if exc.kind in ("stall", "total"):
                    self._record_failure(i)
                raise
            except Exception as exc:  # noqa: BLE001 — any replica-side crash
                self._record_failure(i)
                if started:
                    if not (self.resume_streams and trackable):
                        raise  # tokens delivered, no exact resume possible
                    # crash-safe re-placement: rebuild the request from the
                    # dispatcher's own delivered-token record. Greedy
                    # streams resume token-exact; sampled streams reseed
                    # (the PRNG rows died with the replica) — distribution-
                    # correct, not bit-exact (see README)
                    resume = ResumeState(
                        prompt=prompt_tokens,
                        history=list(emitted),
                        produced=len(emitted),
                    )
                    replaced = True
                excluded.add(i)
                last_exc = exc
                tr = kw.get("_trace")
                if tr is not None:
                    tr.point("failover", replica=i,
                             resumed=started and replaced)
            finally:
                self._done(i, probe)

    # -------------------------------------------------------------- drain
    def drain(self, i: int, deadline: float = 30.0) -> dict:
        """Gracefully retire replica ``i``: stop routing to it, migrate its
        admitted requests off (each stream ends with a
        ``RequestMigratedError`` whose ``ResumeState`` this dispatcher
        re-places on a healthy replica — the client never notices), wait
        for in-flight dispatches to unwind, then close and retire it.

        Failure semantics: if the migration step itself fails (injected
        ``replica.drain`` fault, wedged batcher), the replica stays
        QUARANTINED — ``draining`` keeps new work away while the still-
        flowing streams finish — and the error surfaces so the operator can
        retry. The replica is never closed while un-migrated streams could
        be truncated; if in-flight dispatches don't unwind by ``deadline``
        it is retired without closing (``closed: False`` in the result) and
        the leak is logged."""
        if not isinstance(i, int) or isinstance(i, bool):
            raise ValueError(f"replica index must be an int; got {i!r}")
        with self._lock:
            n = len(self.replicas)
            if not 0 <= i < n:
                raise ValueError(
                    f"replica index must be in [0, {n}); got {i!r}"
                )
            if self._retired[i]:
                return {"replica": i, "migrated": 0, "closed": True,
                        "already_retired": True}
            if self._drain_active[i]:
                raise ValueError(f"replica {i} is already draining")
            others = [
                j for j in range(n)
                if j != i and not self._retired[j] and not self._draining[j]
            ]
            if not others:
                raise ValueError(
                    "cannot drain the last live replica — the migrated "
                    "requests would have nowhere to resume"
                )
            self._drain_active[i] = True
            self._draining[i] = True
            r = self.replicas[i]
        try:
            inject("replica.drain", replica=i)
            migrated = (
                r.migrate_out(deadline=deadline)
                if hasattr(r, "migrate_out") else 0
            )
        except Exception:
            # leave the replica quarantined (draining=True: no new routes,
            # in-flight streams keep flowing) and surface the failure —
            # the operator calls drain() again to retry; nothing was dropped
            logging.getLogger(__name__).exception(
                "drain of replica %d failed mid-migration; replica "
                "quarantined, retry drain()", i,
            )
            # mst: allow(MST202): slot i is owned by this call while _drain_active[i] is set
            with self._lock:
                self._drain_active[i] = False
            raise
        deadline_at = self._clock() + deadline
        while self._clock() < deadline_at:
            with self._lock:
                if self._inflight[i] == 0:
                    break
            self._sleep(0.01)
        with self._lock:
            leaked = self._inflight[i]
        closed = False
        if leaked == 0:
            if hasattr(r, "close"):
                r.close()
            closed = True
            # replica fully out: hand its resources back (device-slice
            # free-list). Retired-without-closing replicas keep theirs —
            # their streams are still unwinding on those devices.
            hook = self.on_retire
            if hook is not None:
                try:
                    hook(r)
                except Exception:  # noqa: BLE001 — recycling is best-effort
                    logging.getLogger(__name__).exception(
                        "on_retire hook failed for replica %d", i
                    )
        else:
            logging.getLogger(__name__).warning(
                "replica %d retired with %d dispatches still unwinding — "
                "left unclosed to avoid truncating their streams",
                i, leaked,
            )
        # mst: allow(MST202): slot i is owned by this call while _drain_active[i] is set
        with self._lock:
            self._retired[i] = True
            self._draining[i] = False
            self._drain_active[i] = False
            self.drains += 1
        return {"replica": i, "migrated": migrated, "closed": closed}

    # ------------------------------------------------------ elastic fleet
    def add_replica(self, replica) -> int:
        """Append a freshly spawned replica to the fleet (the autoscaler's
        scale-up mechanism). Indices are stable — retired slots keep their
        position — so the new replica takes the next index, which is
        returned. The replica is routable immediately."""
        with self._lock:
            self.replicas.append(replica)
            self._serial_locks.append(
                None if getattr(replica, "concurrent", False)
                else make_lock("ReplicaSet._serial_locks[*]")
            )
            self._inflight.append(0)
            self.served.append(0)
            self.failures.append(0)
            self.breaker_opens.append(0)
            self._fails_consec.append(0)
            self._draining.append(False)
            self._drain_active.append(False)
            self._retired.append(False)
            self._open_until.append(0.0)
            self._probing.append(False)
            return len(self.replicas) - 1

    def record_autoscale_event(self, kind: str):
        """Count a fleet-controller event (spawn/drain/*_failed/...) for
        the ``mst_autoscale_events_total`` metric."""
        with self._lock:
            self.autoscale_events[kind] = self.autoscale_events.get(kind, 0) + 1

    def attach_controller(self, controller):
        """Bind the FleetAutoscaler so close() stops its loop and health()
        reports its state. Called by the controller's own __init__."""
        self._controller = controller
        self.brownout = getattr(controller, "brownout", None)

    def set_pressure(self, level: int):
        """Forward the brownout ladder level to every live replica that
        understands it (ContinuousBatcher.set_pressure)."""
        with self._lock:
            reps = [
                r for j, r in enumerate(self.replicas) if not self._retired[j]
            ]
        for r in reps:
            if hasattr(r, "set_pressure"):
                r.set_pressure(level)

    # ------------------------------------------------------- observability
    def stats(self):
        """Aggregate (slots, active, queued) across replicas for /metrics.
        Non-batcher replicas count as one slot each, active while a request
        is in flight."""
        with self._lock:
            inflight = list(self._inflight)
            reps = list(self.replicas)
        slots = active = queued = 0
        for i, r in enumerate(reps):
            if hasattr(r, "stats"):  # replica stats outside our lock: the
                s, a, q = r.stats()  # batcher takes its own admission lock
                slots, active, queued = slots + s, active + a, queued + q
            else:
                slots += 1
                active += min(inflight[i], 1)
                queued += max(inflight[i] - 1, 0)
        return slots, active, queued

    def pool_load(self) -> dict:
        """One heartbeat-sized load summary for the pod control plane:
        slot occupancy plus live-replica count, so a remote prefill host
        can price THIS pool as a decode target (``free`` slots) and the
        pod autoscaler can weigh its pressure by real capacity. Everything
        here is gauge-grade — stale by one pod tick by design."""
        slots, active, queued = self.stats()
        return {
            "slots": slots,
            "active": active,
            "queued": queued,
            "free": max(0, slots - active),
            "live": self.fleet_stats()["size"],
        }

    def replica_stats(self) -> list:
        """Per-replica routing/breaker snapshot for /metrics: inflight,
        queue depth, breaker state (numeric: 0 closed / 1 half-open /
        2 open), drain lifecycle. Queue depths come from each replica's own
        stats() OUTSIDE our lock (see _queue_depths)."""
        with self._lock:
            now = self._clock()
            reps = list(self.replicas)
            snap = []
            for j in range(len(reps)):
                state = self._breaker_state(j, now)
                snap.append({
                    "replica": j,
                    "role": self.role,
                    "inflight": self._inflight[j],
                    "breaker": state,
                    "breaker_state":
                        {"closed": 0, "half_open": 1, "open": 2}[state],
                    "draining": self._draining[j],
                    "retired": self._retired[j],
                })
        for j, r in enumerate(reps):
            q = 0
            if not snap[j]["retired"] and hasattr(r, "stats"):
                try:
                    _, _, q = r.stats()
                except Exception:  # noqa: BLE001 — gauge, not a contract
                    q = 0
            snap[j]["queue_depth"] = q
            # cross-replica shared weights (weights.WeightStore): which
            # replicas alias a resident tree vs own a private upload
            snap[j]["weights_shared"] = bool(
                getattr(r, "weights_shared", False)
            )
        return snap

    def fleet_stats(self) -> dict:
        """Fleet-level gauges: live size, retirements, autoscale event
        counts, and routing-cache occupancy/hits."""
        with self._lock:
            total = len(self.replicas)
            live = total - sum(self._retired)
            return {
                "role": self.role,
                "size": live,
                "total": total,
                "retired": sum(self._retired),
                "draining": sum(self._draining),
                "autoscale_events": dict(self.autoscale_events),
                "sticky_sessions": len(self._sticky),
                "affinity_entries": len(self._affinity),
                "affinity_hits": self.route_affinity_hits,
                "sticky_hits": self.route_sticky_hits,
                "store_hits": self.route_store_hits,
                "weights_shared": sum(
                    1 for j, r in enumerate(self.replicas)
                    if not self._retired[j]
                    and getattr(r, "weights_shared", False)
                ),
            }

    def page_stats(self):
        with self._lock:
            reps = list(self.replicas)
        totals = [r.page_stats() for r in reps if hasattr(r, "page_stats")]
        totals = [t for t in totals if t is not None]
        if not totals:
            return None
        return tuple(sum(col) for col in zip(*totals))

    def resilience_stats(self) -> dict:
        """Deadline/shedding/migration counters summed across replica
        batchers, plus the dispatcher's own drain/re-placement counts."""
        agg = {"timeouts": 0, "shed_queue_full": 0, "shed_deadline": 0,
               "max_queue": None, "scheduler_thread_live": True}
        summed = ("preemptions", "spills", "spill_hits", "spill_fallbacks",
                  "migrations_out", "migrations_in", "handoffs_out")
        for k in summed:
            agg[k] = 0
        with self._lock:
            reps = list(self.replicas)
        for r in reps:
            if not hasattr(r, "resilience_stats"):
                continue
            s = r.resilience_stats()
            agg["timeouts"] += s["timeouts"]
            agg["shed_queue_full"] += s["shed_queue_full"]
            agg["shed_deadline"] += s["shed_deadline"]
            for k in summed:
                agg[k] += s.get(k, 0)
            if s["max_queue"] is not None:
                agg["max_queue"] = (agg["max_queue"] or 0) + s["max_queue"]
            agg["scheduler_thread_live"] = (
                agg["scheduler_thread_live"] and s["scheduler_thread_live"]
            )
        with self._lock:
            agg["drains"] = self.drains
            agg["migrated_streams"] = self.migrated_streams
        return agg

    def latency_stats(self) -> Optional[dict]:
        """Cumulative latency histograms (ITL, queue-wait) merged across
        replica batchers — the /metrics renderer sees ONE fleet-wide
        histogram per family, not per-replica fragments. None when no
        replica keeps them (plain engines)."""
        with self._lock:
            reps = list(self.replicas)
        per = []
        for r in reps:
            fn = getattr(r, "latency_stats", None)
            if fn is None:
                continue
            s = fn()
            if s:
                per.append(s)
        if not per:
            return None
        return {k: Histogram.merge_dicts([s[k] for s in per if k in s])
                for k in set().union(*per)}

    def spill_stats(self) -> Optional[dict]:
        """KV spill/migration counters summed across replica batchers (the
        ``mst_kv_*`` gauge source when serving through a ReplicaSet), plus
        the dispatcher's crash/drain re-placement count. None when no
        replica has a paged pool."""
        with self._lock:
            reps = list(self.replicas)
        per = [
            r.spill_stats() for r in reps
            if hasattr(r, "spill_stats")
        ]
        per = [s for s in per if s is not None]
        if not per:
            return None
        agg: dict = {"enabled": any(s.get("enabled") for s in per)}
        for k in ("spills", "spill_hits", "spill_fallbacks",
                  "migrations_out", "migrations_in", "reprefill_tokens",
                  "preemptions", "budget_bytes", "bytes_in_use", "blocks",
                  "evictions", "rejects"):
            agg[k] = sum(s.get(k, 0) for s in per)
        with self._lock:
            agg["migrated_streams"] = self.migrated_streams
            agg["drains"] = self.drains
        return agg

    def spec_stats(self) -> Optional[dict]:
        """Speculation telemetry summed across replica batchers (the
        ``mst_spec_*`` gauge source when serving through a ReplicaSet).
        None when no replica speculates, so a non-speculating fleet's
        /metrics exposition stays label-free."""
        with self._lock:
            reps = list(self.replicas)
        per = [
            s for r in reps
            if hasattr(r, "spec_stats")
            for s in [r.spec_stats()]
            if s is not None
        ]
        if not per:
            return None
        agg: dict = {
            "mode": per[0].get("mode"),
            "window_max": max(s.get("window_max", 0) for s in per),
        }
        for k in ("rounds", "draft_tokens", "accepted_tokens",
                  "fallback_ticks", "replayed_tokens", "draft_faults",
                  "disabled_slots", "shed_events"):
            agg[k] = sum(s.get(k, 0) for s in per)
        agg["accept_rate"] = (
            agg["accepted_tokens"] / max(1, agg["draft_tokens"])
        )
        return agg

    def health(self) -> dict:
        """Partial-capacity health: ``draining`` while a drain is in
        progress, degraded (still serving) while at least one replica
        lives, dead only when none do. Retired replicas left the fleet on
        purpose — they don't count against ``ok``."""
        with self._lock:
            now = self._clock()
            reps = list(self.replicas)
            states = [
                self._breaker_state(j, now) for j in range(len(reps))
            ]
            consec = list(self._fails_consec)
            fails = list(self.failures)
            draining = list(self._draining)
            retired = list(self._retired)
        per, live = [], 0
        for j, r in enumerate(reps):
            entry = {"replica": j, "breaker": states[j],
                     "consecutive_failures": consec[j], "failures": fails[j]}
            if retired[j]:
                entry["state"] = "retired"
            elif draining[j]:
                entry["state"] = "draining"
            sub = r.health() if hasattr(r, "health") else None
            alive = states[j] != "open"
            if sub is not None:
                entry["engine"] = sub["status"]
                alive = alive and sub["serving"]
            if alive and not retired[j] and not draining[j]:
                live += 1
            per.append(entry)
        n = len(reps)
        expected = n - sum(retired)
        status = (
            "draining" if any(draining)
            else ("ok" if live == expected else "degraded")
        )
        out = {
            "status": status,
            "serving": live >= 1,
            "replicas_total": n,
            **({"role": self.role} if self.role is not None else {}),
            "replicas_live": live,
            "replicas_draining": sum(draining),
            "replicas_retired": sum(retired),
            "replicas": per,
        }
        # elastic-fleet surfaces (attached by fleet.FleetAutoscaler)
        ctrl, bro = self._controller, self.brownout
        if ctrl is not None:
            out["autoscaler"] = ctrl.state()
        if bro is not None:
            out["brownout"] = bro.state()
        return out

    def close(self):
        ctrl = self._controller
        if ctrl is not None:
            ctrl.stop()
        with self._lock:
            reps = list(self.replicas)
        for r in reps:
            if hasattr(r, "close"):
                r.close()
