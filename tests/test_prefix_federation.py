"""Pod-federated prefix store (ISSUE 19): pod-wide prefix reuse.

The load-bearing properties: (1) pod-wide, a hot prefix is prefilled
ONCE — a later same-prefix admission on ANY host pulls the owner's
exported ``KVPageBlock`` into its local host tier over the fabric, and
the fetch is counted (one blob, its bytes, its latency); (2) EVERY
federation failure — the ``pod.prefix_fetch`` fault site, a pod-wide
miss, a stale inventory, a dead owner, a silent owner, a corrupt or
geometry-mismatched blob, a host-tier budget reject — degrades to plain
prefill, counted by kind, never a wrong or dropped stream; (3) greedy
streams whose prefix rode the fabric are bit-identical to a monolithic
batcher's.

Unit tests drive :class:`PodPrefixFederation` directly over a fake
transport (the pod view is just ``peers()`` + ``send``); the end-to-end
test runs two real batchers over the :class:`LoopbackHub` exactly the
way ``bench.py``'s ``pod_prefix_federation`` phase does.
"""

import pickle
import threading
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.cache import KVCache
from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.kv_transfer import export_block
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.pod import (
    PREFIX_FETCH_TIMEOUT_S,
    LoopbackHub,
    PodFleet,
    PodPrefixFederation,
)
from mlx_sharding_tpu.prefix_store import PrefixStore
from mlx_sharding_tpu.scheduler import ContinuousBatcher
from mlx_sharding_tpu.testing import faults
from tests.helpers import hard_timeout

TINY = dict(vocab_size=256, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2)

PAGE = 8
# one shared 2-page prefix, divergent tails: the hot-prefix traffic shape
BASE = [7, 7, 2, 1, 9, 4, 4, 6, 3, 17, 42, 5, 11, 2, 2, 8]


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


def _pure_prefix_block(tokens, pages=(0, 1), share_hash=None):
    shape = (1, 2, 4, 1, PAGE, 2, 4)
    vals = jnp.arange(np.prod(shape), dtype=jnp.float32).reshape(shape)
    cache = KVCache(k=vals, v=vals + 1000.0, offset=jnp.zeros((), jnp.int32))
    return export_block(
        cache, list(pages), page_size=PAGE, n_tokens=len(pages) * PAGE,
        prompt=list(tokens), history=[], produced=0,
        resume_keys=None, resume_recent=None, share_hash=share_hash,
    ).to_host()


class _FakeTransport:
    """The slice of the pod fabric the federation touches: a static
    ``peers()`` view plus ``send`` capture with an optional synchronous
    responder (replies land on the requester's queue before ``q.get``)."""

    def __init__(self, host_id=0, peers=None):
        self.host_id = host_id
        self._peers = dict(peers or {})
        self.sent = []
        self.respond = None  # (host, kind, payload) -> None

    def peers(self):
        return self._peers

    def send(self, host, kind, payload):
        self.sent.append((host, kind, payload))
        if self.respond is not None:
            self.respond(host, kind, payload)


def _peer_entry(keys, *, age_s=0.0, page_size=PAGE, share=None):
    return {"info": {"prefix": {"keys": list(keys),
                                "page_size": page_size,
                                "share": share}},
            "age_s": age_s}


def _mk(store=None, peers=None, **kw):
    store = store or PrefixStore(host_bytes=1 << 20)
    if store.page_size is None:
        store.bind_page_size(PAGE)
    t = _FakeTransport(peers=peers)
    kw.setdefault("fetch_timeout_s", 0.25)
    return PodPrefixFederation(0, t, store, **kw), t, store


# -------------------------------------------------------- heartbeat surface
def test_local_info_advertises_inventory_and_geometry():
    fed, _, store = _mk()
    digests = store.digests_for(BASE + [5])
    store.host_put(digests[-1], _pure_prefix_block(BASE))
    info = fed.local_info()
    assert info["keys"] == [digests[-1].hex()]
    assert info["page_size"] == PAGE
    assert info["share"] is None
    store.close()


def test_local_info_sick_store_advertises_nothing():
    fed, _, store = _mk()
    store.host_inventory = lambda *a, **k: 1 / 0
    assert fed.local_info() == {}
    store.close()


def test_stats_shape():
    fed, _, store = _mk()
    s = fed.stats()
    assert set(s) == {"inventory_keys", "hits", "fetches", "fetch_bytes",
                      "blobs_served", "bytes_served", "fallbacks",
                      "fetch_ms_p50", "fetch_ms_p99"}
    assert s["fallbacks"] == {} and s["fetch_ms_p50"] is None
    store.close()


# ------------------------------------------------------------------ routing
def test_owner_for_prefers_freshest_live_compatible_peer():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    hexd = store.digests_for(BASE + [5])[-1].hex()
    fed, t, _ = _mk(store=store, peers={
        1: _peer_entry([hexd], age_s=1.2),
        2: _peer_entry([hexd], age_s=0.1),
        3: _peer_entry([hexd], age_s=0.0, page_size=16),   # wrong geometry
        4: _peer_entry([hexd], age_s=0.0, share="deadbeef"),  # wrong layout
        5: _peer_entry([], age_s=0.0),                     # doesn't have it
    })
    assert fed._owner_for(hexd) == (2, None)
    store.close()


def test_owner_for_stale_only_and_pod_miss():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    hexd = store.digests_for(BASE + [5])[-1].hex()
    fed, t, _ = _mk(store=store, heartbeat_timeout_s=2.0,
                    peers={1: _peer_entry([hexd], age_s=60.0)})
    assert fed._owner_for(hexd) == (None, "stale_inventory")
    t._peers = {}
    assert fed._owner_for(hexd) == (None, "miss")
    store.close()


# --------------------------------------------- fetch degradations, by kind
def test_fetch_fault_site_degrades_before_the_wire():
    fed, t, store = _mk(peers={1: _peer_entry(["ab"])})
    faults.arm("pod.prefix_fetch", exc=faults.FaultError)
    assert fed.fetch(b"\xab") is False
    assert fed.stats()["fallbacks"] == {"fetch_fault": 1}
    assert t.sent == []  # degraded before touching the fabric
    store.close()


def test_pod_miss_is_negative_cached():
    fed, t, store = _mk(peers={})
    digest = store.digests_for(BASE + [5])[-1]
    assert fed.fetch(digest) is False
    assert fed.fetch(digest) is False  # second probe: neg cache, no route
    assert fed.stats()["fallbacks"] == {"miss": 1, "neg_cached": 1}
    store.close()


def test_neg_cache_expires_on_the_clock():
    now = [100.0]
    fed, t, store = _mk(peers={}, neg_cache_s=30.0, clock=lambda: now[0])
    digest = store.digests_for(BASE + [5])[-1]
    assert fed.fetch(digest) is False
    now[0] += 31.0
    assert fed.fetch(digest) is False
    assert fed.stats()["fallbacks"] == {"miss": 2}  # re-probed, no neg hit
    store.close()


def test_owner_dead_when_send_raises():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    digest = store.digests_for(BASE + [5])[-1]
    fed, t, _ = _mk(store=store, peers={1: _peer_entry([digest.hex()])})
    t.respond = lambda *a: 1 / 0
    assert fed.fetch(digest) is False
    assert fed.stats()["fallbacks"] == {"owner_dead": 1}
    assert fed.stats()["hits"] == 1  # the pod view DID name an owner
    store.close()


def test_timeout_when_owner_goes_silent():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    digest = store.digests_for(BASE + [5])[-1]
    fed, t, _ = _mk(store=store, peers={1: _peer_entry([digest.hex()])},
                    fetch_timeout_s=0.05)
    assert fed.fetch(digest) is False
    assert fed.stats()["fallbacks"] == {"timeout": 1}
    assert fed._waiters == {}  # the waiter never leaks
    store.close()


def _respond_with(fed, kind, data):
    """Synchronous owner stand-in: answer the fetch on the requester's
    own queue before it starts waiting."""
    def responder(host, msg_kind, payload):
        rid = pickle.loads(payload)["rid"]
        fed.handle(host, kind, pickle.dumps((rid, data)))
    return responder


def test_owner_eviction_between_gossip_and_fetch_is_stale_inventory():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    digest = store.digests_for(BASE + [5])[-1]
    fed, t, _ = _mk(store=store, peers={1: _peer_entry([digest.hex()])})
    t.respond = _respond_with(fed, "prefix.miss", b"")
    assert fed.fetch(digest) is False
    assert fed.stats()["fallbacks"] == {"stale_inventory": 1}
    assert fed.fetch(digest) is False  # and the digest is neg-cached now
    assert fed.stats()["fallbacks"]["neg_cached"] == 1
    store.close()


def test_corrupt_blob_fails_integrity():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    digest = store.digests_for(BASE + [5])[-1]
    blob = bytearray(_pure_prefix_block(BASE).to_bytes())
    blob[-3] ^= 0xFF  # flip payload bits under the checksum
    fed, t, _ = _mk(store=store, peers={1: _peer_entry([digest.hex()])})
    t.respond = _respond_with(fed, "prefix.blob", bytes(blob))
    assert fed.fetch(digest) is False
    assert fed.stats()["fallbacks"] == {"integrity": 1}
    store.close()


def test_geometry_mismatched_blob_fails_integrity():
    """A lying inventory (advertised page_size matches, blob doesn't)
    still can't land a wrong-geometry block in the local tier."""
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(16)
    digest = store.digests_for(list(range(40)))[-1]
    fed, t, _ = _mk(store=store,
                    peers={1: _peer_entry([digest.hex()], page_size=16)})
    t.respond = _respond_with(
        fed, "prefix.blob", _pure_prefix_block(BASE).to_bytes())  # PAGE=8
    assert fed.fetch(digest) is False
    assert fed.stats()["fallbacks"] == {"integrity": 1}
    store.close()


def test_share_hash_mismatched_blob_fails_integrity():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    digest = store.digests_for(BASE + [5])[-1]
    blob = _pure_prefix_block(BASE, share_hash="feedface").to_bytes()
    fed, t, _ = _mk(store=store, peers={1: _peer_entry([digest.hex()])})
    t.respond = _respond_with(fed, "prefix.blob", blob)
    assert fed.fetch(digest) is False
    assert fed.stats()["fallbacks"] == {"integrity": 1}
    store.close()


def test_host_tier_budget_reject_is_host_reject():
    store = PrefixStore(host_bytes=1)  # nothing fits
    store.bind_page_size(PAGE)
    digest = store.digests_for(BASE + [5])[-1]
    fed, t, _ = _mk(store=store, peers={1: _peer_entry([digest.hex()])})
    t.respond = _respond_with(
        fed, "prefix.blob", _pure_prefix_block(BASE).to_bytes())
    assert fed.fetch(digest) is False
    assert fed.stats()["fallbacks"] == {"host_reject": 1}
    store.close()


# -------------------------------------------------------------- happy path
def test_fetch_roundtrip_imports_into_local_tier():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    digest = store.digests_for(BASE + [5])[-1]
    blob = _pure_prefix_block(BASE).to_bytes()
    fed, t, _ = _mk(store=store, peers={1: _peer_entry([digest.hex()])})
    t.respond = _respond_with(fed, "prefix.blob", blob)
    assert not store.host_contains(digest)
    assert fed.fetch(digest) is True
    assert store.host_contains(digest)  # the ordinary import path takes over
    s = fed.stats()
    assert s["hits"] == 1 and s["fetches"] == 1
    assert s["fetch_bytes"] == len(blob)
    assert s["fetch_ms_p50"] is not None and s["fallbacks"] == {}
    assert s["inventory_keys"] == 1
    store.close()


def test_serve_side_exports_blob_and_counts():
    """Owner side: a ``prefix.fetch`` message is consumed, served OFF the
    receive thread, and answered with the exported blob."""
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    digest = store.digests_for(BASE + [5])[-1]
    store.host_put(digest, _pure_prefix_block(BASE))
    fed, t, _ = _mk(store=store)
    req = pickle.dumps({"rid": "r1", "digest": digest})
    assert fed.handle(9, "prefix.fetch", req) is True
    deadline = time.monotonic() + 5.0
    while not t.sent and time.monotonic() < deadline:
        time.sleep(0.01)
    (host, kind, payload), = t.sent
    assert (host, kind) == (9, "prefix.blob")
    rid, data = pickle.loads(payload)
    assert rid == "r1" and len(data) > 0
    s = fed.stats()
    assert s["blobs_served"] == 1 and s["bytes_served"] == len(data)
    # a digest the tier doesn't hold answers prefix.miss
    t.sent.clear()
    other = store.digests_for(list(range(50, 67)))[-1]
    fed.handle(9, "prefix.fetch", pickle.dumps({"rid": "r2",
                                                "digest": other}))
    deadline = time.monotonic() + 5.0
    while not t.sent and time.monotonic() < deadline:
        time.sleep(0.01)
    assert t.sent[0][1] == "prefix.miss"
    assert fed.handle(9, "weights.have", b"x") is False  # not ours
    store.close()


# ----------------------------------------------------- end-to-end loopback
@pytest.fixture(scope="module")
def tiny_model():
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return model, params


def _mk_host(tiny_model, dev_idx, *, with_store=True):
    model, params = tiny_model
    devices = jax.devices()
    eng = PipelineEngine(
        model, params, make_mesh(pp=1, devices=devices[dev_idx:dev_idx + 1]),
        microbatches=2, max_seq=64, cache_dtype=jnp.float32,
        prefill_chunk=8, pool_pages=10, page_size=PAGE,
    )
    store = PrefixStore(host_bytes=1 << 20) if with_store else None
    return ContinuousBatcher(eng, decode_block=3, prefix_store=store), store


@hard_timeout(120)
def test_pod_federation_end_to_end_one_prefill_pod_wide(tiny_model):
    """The acceptance shape: a prefix made hot on host A is continued on
    host B with exactly one counted blob fetch, reused (not re-prefilled)
    tokens, and a greedy stream bit-identical to a monolithic batcher —
    then a faulted fetch degrades to plain prefill with the same tokens."""
    b_a, store_a = _mk_host(tiny_model, 0)
    b_b, store_b = _mk_host(tiny_model, 1)
    mono, _ = _mk_host(tiny_model, 2, with_store=False)
    hub = LoopbackHub()
    f_a = PodFleet(0, hub.register(0), b_a, prefix_store=store_a)
    f_b = PodFleet(1, hub.register(1), b_b, prefix_store=store_b)
    try:
        # warm the prefix on A: stream completion demotes the pure-
        # prefix block into A's host tier
        list(b_a.generate_step(BASE + [5], max_tokens=12))
        assert store_a.stats()["demotions"] >= 1
        f_a.tick()  # gossip A's inventory
        f_b.tick()
        assert f_b.prefix.stats()["fetches"] == 0
        # continue on B: local miss -> pod view -> one blob fetch
        got = [t for t, _ in b_b.generate_step(BASE + [9], max_tokens=12)]
        ref = [t for t, _ in mono.generate_step(BASE + [9], max_tokens=12)]
        assert got == ref
        sb = f_b.prefix.stats()
        assert sb["fetches"] == 1 and sb["fetch_bytes"] > 0
        assert f_a.prefix.stats()["blobs_served"] == 1
        assert store_b.stats()["tokens_reused"] >= 2 * PAGE
        # the same prefix again on B: local host tier, no second fetch
        got2 = [t for t, _ in b_b.generate_step(BASE + [3], max_tokens=8)]
        ref2 = [t for t, _ in mono.generate_step(BASE + [3], max_tokens=8)]
        assert got2 == ref2
        assert f_b.prefix.stats()["fetches"] == 1
        # fault leg: a fresh hot prefix on A, fetch faulted on B ->
        # plain prefill, stream still bit-identical, fault counted
        base2 = [11, 3, 3, 1, 2, 8, 8, 5, 9, 1, 40, 6, 12, 7, 7, 2]
        list(b_a.generate_step(base2 + [5], max_tokens=12))
        f_a.tick()
        f_b.tick()
        faults.arm("pod.prefix_fetch", exc=faults.FaultError, times=4)
        got3 = [t for t, _ in b_b.generate_step(base2 + [9], max_tokens=12)]
        ref3 = [t for t, _ in mono.generate_step(base2 + [9],
                                                 max_tokens=12)]
        assert got3 == ref3
        assert f_b.prefix.stats()["fallbacks"]["fetch_fault"] >= 1
        assert f_b.prefix.stats()["fetches"] == 1  # no new fetch
    finally:
        faults.disarm()
        f_a.close(close_local=False)
        f_b.close(close_local=False)
        b_a.close()
        b_b.close()
        mono.close()
