"""End-user CLI paths, driven as subprocesses against a real on-disk
checkpoint + tokenizer (built offline by make_tiny_checkpoint)."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from tests.make_tiny_checkpoint import make_tiny_checkpoint

    return str(make_tiny_checkpoint(tmp_path_factory.mktemp("cli_ckpt")))


def _run(args, timeout=420):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # PYTHONPATH must NOT include the axon sitecustomize dir: its register
    # hook overrides jax_platforms to "axon,cpu" and the child would try to
    # claim the real TPU (or hang if the tunnel is down).
    env["PYTHONPATH"] = str(REPO)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return subprocess.run(
        [sys.executable, *args], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=REPO,
    )


def test_generate_cli(ckpt):
    r = _run(
        ["-m", "mlx_sharding_tpu.cli.generate", "--model", ckpt,
         "--prompt", "the quick", "--max-tokens", "8",
         "--max-seq", "128", "--prefill-chunk", "16"]
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "tokens-per-sec" in r.stderr
    assert "TTFT" in r.stderr


@pytest.mark.slow  # subprocess CLI sweep — test_generate_cli keeps the quick signal
def test_generate_cli_spmd_pipeline(ckpt):
    r = _run(
        ["-m", "mlx_sharding_tpu.cli.generate", "--model", ckpt,
         "--prompt", "hello", "--max-tokens", "4", "--num-stages", "4",
         "--max-seq", "64", "--prefill-chunk", "16"]
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Generation" in r.stderr


@pytest.mark.slow  # subprocess CLI sweep — test_generate_cli keeps the quick signal
def test_generate_cli_chained_pipeline(ckpt):
    r = _run(
        ["-m", "mlx_sharding_tpu.cli.generate", "--model", ckpt,
         "--prompt", "hello", "--max-tokens", "4", "--stage-bounds", "0-1,1-4",
         "--max-seq", "64", "--prefill-chunk", "16"]
    )
    assert r.returncode == 0, r.stderr[-2000:]


@pytest.mark.slow  # subprocess CLI sweep — test_generate_cli keeps the quick signal
def test_shard_tool_cli(ckpt, tmp_path):
    r = _run(
        ["-m", "mlx_sharding_tpu.shard_tool", "--model", ckpt,
         "--output-dir", str(tmp_path), "--num-stages", "2"]
    )
    assert r.returncode == 0, r.stderr[-2000:]
    for stage in ("stage_00", "stage_01"):
        cfg = json.loads((tmp_path / stage / "config.json").read_text())
        assert "start_layer" in cfg and "end_layer" in cfg
        assert (tmp_path / stage / "tokenizer.json").exists()
    # a stage checkpoint loads and generates via the CLI
    r = _run(
        ["-m", "mlx_sharding_tpu.cli.generate", "--model", str(tmp_path / "stage_00"),
         "--prompt", "x", "--max-tokens", "2", "--max-seq", "32",
         "--prefill-chunk", "8"]
    )
    # stage 0 alone has no head -> logits are hidden states; generation becomes
    # meaningless but the load path must still work end-to-end. It should fail
    # cleanly or produce output; either way no traceback-free crash:
    assert "Traceback" not in r.stderr or r.returncode != 0


@pytest.mark.slow  # subprocess CLI sweep — test_generate_cli keeps the quick signal
def test_kv_share_calibrate_cli(ckpt, tmp_path):
    """The offline KVSharer calibration path (ISSUE 19): checkpoint in,
    validated share-map artifact out, loadable by the engine loader."""
    out = str(tmp_path / "share_map.json")
    r = _run(
        ["-m", "mlx_sharding_tpu.cli.kv_share_calibrate", "--model", ckpt,
         "--num-share", "2", "--output", out]
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "2 groups" in r.stdout and "50.0%" in r.stdout
    doc = json.loads(Path(out).read_text())
    assert doc["format"] == "mst-kv-share-map-v1"
    assert doc["num_layers"] == 4 and max(doc["group_of"]) + 1 == 2
    assert doc["share_hash"]
    assert doc["meta"]["calibration"]["pairs"]
    from mlx_sharding_tpu.kv_share import load_share_map

    assert load_share_map(out, num_layers=4).share_hash == doc["share_hash"]
