"""Fleet simulator + chaos campaign suite (mlx_sharding_tpu/sim/).

Quick tier: determinism (same seed → identical event-log digests),
virtual-clock/simkit mechanics, every invariant checker catching a seeded
violation, the ddmin shrinker reducing a 20-event failing storm to ≤ 3
events, repro-file round-trip, and the fault-site coverage gate
cross-checking ``lifecycle.REQUIRED_FAULT_SITES`` against the scenario
library. The 100-host 10×-surge acceptance campaign is ``slow``-marked.

Everything here runs in virtual time — zero wall-clock sleeps — so the
hard timeouts are generous bounds on pure CPU work, not waits.
"""

from __future__ import annotations

import json
import logging
import random

import pytest

from mlx_sharding_tpu.sim.chaos import (
    SCENARIOS,
    Campaign,
    FaultEvent,
    load_repro,
    run_campaign,
    scenario_host_death,
    scenario_site_storm,
    scenario_surge_100,
    shrink,
    write_repro,
)
from mlx_sharding_tpu.sim.fleetsim import (
    SimReplica,
    build_fleet,
    drive_arrivals,
    token_at,
)
from mlx_sharding_tpu.sim.simkit import (
    SeededScheduleExplorer,
    SimRng,
    Simulation,
    ddmin_trace,
)
from mlx_sharding_tpu.utils.clock import MONOTONIC, Clock, VirtualClock
from tests.helpers import hard_timeout


@pytest.fixture(autouse=True)
def _quiet_chaos_logs():
    # campaigns exercise failure paths that log exceptions on purpose;
    # keep the suite output readable
    logging.disable(logging.ERROR)
    yield
    logging.disable(logging.NOTSET)


# ------------------------------------------------------------ utils/clock
def test_virtual_clock_is_monotonic_and_injectable():
    clk = VirtualClock()
    assert isinstance(clk, Clock)
    assert isinstance(MONOTONIC, Clock)
    assert clk() == 0.0
    clk.advance(1.5)
    assert clk() == clk.now == 1.5
    clk.set(1.0)  # no-op: time never runs backward
    assert clk() == 1.5
    with pytest.raises(ValueError):
        clk.advance(-0.1)


# ----------------------------------------------------------------- simkit
def test_sim_rng_streams_are_independent():
    a, b = SimRng(1), SimRng(1)
    assert [a.stream("x").random() for _ in range(5)] == [
        b.stream("x").random() for _ in range(5)
    ]
    # a draw on one stream must not shift another
    c = SimRng(1)
    c.stream("y").random()
    assert c.stream("x").random() == SimRng(1).stream("x").random()
    assert SimRng(1).stream("x").random() != SimRng(2).stream("x").random()


@hard_timeout(10)
def test_sim_event_ordering_and_actor_sleep():
    sim = Simulation(seed=3)
    order = []
    sim.schedule(2.0, lambda: order.append(("call", sim.now())))

    def actor():
        order.append(("a0", sim.now()))
        sim.sleep(1.0)
        order.append(("a1", sim.now()))
        sim.sleep(3.0)
        order.append(("a2", sim.now()))

    sim.spawn(actor, name="a")
    sim.run()
    assert order == [("a0", 0.0), ("a1", 1.0), ("call", 2.0), ("a2", 4.0)]
    sim.close()


@hard_timeout(10)
def test_sim_digest_replays_bit_identically():
    def build(seed):
        sim = Simulation(seed=seed)
        rng = sim.rng.stream("load")
        for i in range(20):
            t = rng.random() * 5

            def work(i=i):
                sim.record("evt", i=i)

            sim.schedule(t, work)
        sim.run()
        d = sim.digest()
        sim.close()
        return d

    assert build(7) == build(7)
    assert build(7) != build(8)


# ------------------------------------------------- schedule exploration
def _racy_counter(explorer=None):
    """Two actors doing a read-modify-write the default schedule
    happens to serialize; a reordering inside the quantum loses an
    update.  Returns the final count (6 when race-free, 5 when lost)."""
    sim = Simulation(seed=0, explorer=explorer)
    state = {"n": 0}

    def worker(off):
        sim.sleep(off)
        for _ in range(3):
            v = state["n"]
            sim.sleep(0.0005)
            state["n"] = v + 1
            sim.sleep(0.0015)

    for i in range(2):
        sim.spawn(lambda off=i * 0.001: worker(off), name=f"w{i}")
    sim.run()
    n = state["n"]
    trace = list(explorer.trace) if explorer is not None else []
    sim.close()
    return n, trace


@hard_timeout(60)
def test_explorer_catches_and_shrinks_seeded_race():
    # the default schedule masks the race, deterministically
    assert _racy_counter()[0] == 6
    assert _racy_counter()[0] == 6

    caught = None
    for seed in range(32):
        n, trace = _racy_counter(SeededScheduleExplorer(random.Random(seed)))
        if n != 6:
            caught = (seed, trace)
            break
    assert caught is not None, "no explorer seed exposed the lost update"
    seed, trace = caught
    assert trace, "a diverging schedule must leave a non-empty trace"

    # replay of the full trace reproduces the failure exactly
    def fails(t):
        ex = SeededScheduleExplorer(random.Random(0), replay=list(t))
        return _racy_counter(ex)[0] != 6

    assert fails(trace)

    # ddmin shrinks to a handful of forced picks, still failing
    minimal = ddmin_trace(trace, fails)
    assert len(minimal) <= 3
    assert fails(minimal)

    # and the empty trace (pure default schedule) stays green
    assert not fails([])


# --------------------------------------------------------------- fleetsim
@hard_timeout(30)
def test_small_fleet_serves_deterministically():
    def run(seed):
        sim = Simulation(seed=seed)
        fs = build_fleet(sim, n_hosts=2, horizon_s=10.0)
        n = drive_arrivals(fs, kind="diurnal", duration_s=8.0,
                           base_rate=2.0)
        sim.run()
        digest = sim.digest()
        outcomes = sorted(
            (r["rid"], r["outcome"], tuple(r["tokens"]))
            for r in fs.requests.values()
        )
        sim.close()
        for h in fs.hosts:
            h.rs.close()
        return n, digest, outcomes

    n1, d1, o1 = run(5)
    n2, d2, o2 = run(5)
    assert n1 == n2 and d1 == d2 and o1 == o2
    assert n1 > 0
    for _, outcome, _toks in o1:
        assert outcome == "completed"


@hard_timeout(30)
def test_resume_is_token_exact_across_replica_crash():
    sim = Simulation(seed=9)
    fs = build_fleet(sim, n_hosts=2, horizon_s=20.0)
    prompt = [3, 1, 4, 1, 5]
    fs.submit("r0", prompt, 8, host=0)
    # crash host 0's engines mid-stream (8 tokens take ~0.4s virtual)
    sim.schedule(
        0.17,
        lambda: [rep.crash() for rep in fs.hosts[0].replicas],
    )
    sim.run()
    rec = fs.requests["r0"]
    assert rec["outcome"] == "completed"
    assert rec["tokens"] == [token_at(prompt, i) for i in range(8)]
    assert any(d.startswith("failover:") for d in rec["degradations"])
    sim.close()
    for h in fs.hosts:
        h.rs.close()


# --------------------------------------------------- campaigns: happy path
@hard_timeout(60)
def test_site_storm_campaign_green_and_replayable():
    r1 = run_campaign(scenario_site_storm())
    r2 = run_campaign(scenario_site_storm())
    assert r1.ok, r1.violations
    assert r1.digest == r2.digest
    assert r1.n_requests > 0
    assert set(r1.outcomes) <= {"completed", "shed", "client_aborted"}


@hard_timeout(60)
def test_host_death_campaign_never_drops_streams():
    res = run_campaign(scenario_host_death())
    assert res.ok, res.violations
    assert res.outcomes.get("completed", 0) > 0


# ------------------------------------------- invariants catch seeded bugs
@hard_timeout(60)
def test_no_dropped_streams_catches_disabled_resume():
    camp = scenario_host_death()
    camp.resume_streams = False  # the deliberately broken variant
    res = run_campaign(camp)
    assert not res.ok
    assert any(v.startswith("no_dropped_streams:") for v in res.violations)


@hard_timeout(60)
def test_token_exact_catches_corrupted_history(monkeypatch):
    # corrupt the resume path: a replica that seeds its history one token
    # short re-emits a duplicate — exactly the class of bug the invariant
    # exists for
    orig = SimReplica.generate_step

    def corrupting(self, prompt_tokens, **kw):
        resume = kw.get("_resume")
        if resume is not None and resume.history:
            resume.history = list(resume.history)[:-1]
        return orig(self, prompt_tokens, **kw)

    monkeypatch.setattr(SimReplica, "generate_step", corrupting)
    camp = scenario_host_death()
    res = run_campaign(camp)
    assert not res.ok
    assert any(v.startswith("token_exact:") for v in res.violations)


@hard_timeout(60)
def test_ledger_clean_catches_leaked_handle():
    from mlx_sharding_tpu.analysis import runtime as mst_runtime

    camp = Campaign(name="leaky", seed=3, n_hosts=2, duration_s=4.0,
                    settle_s=3.0, base_rate=1.0)
    camp.schedule = [FaultEvent(t=1.0, kind="site", site="scheduler.tick",
                                exc="runtime", times=1)]
    orig = run_campaign.__globals__["_apply_event"]

    def leaky(fs, ev):
        mst_runtime.note_acquire("faults.arm", ("leaked", id(ev)))
        orig(fs, ev)

    run_campaign.__globals__["_apply_event"] = leaky
    try:
        res = run_campaign(camp)
    finally:
        run_campaign.__globals__["_apply_event"] = orig
    assert not res.ok
    assert any(v.startswith("ledger_clean:") for v in res.violations)


@hard_timeout(60)
def test_convergence_catches_unhealed_breaker():
    # a breaker storm whose victim never heals and gets no settle traffic:
    # the breaker opens inside the storm and nothing ever probes it closed
    camp = Campaign(
        name="stuck_breaker", seed=13, n_hosts=2, duration_s=6.0,
        settle_s=0.5, base_rate=2.0, arrival="herd",
        schedule=[
            # every dispatch to replica 0 on any host fails, forever
            FaultEvent(t=0.0, kind="site", site="replica.dispatch",
                       exc="runtime", times=None, match={"replica": 0}),
        ],
        invariants=("convergence",),
    )
    res = run_campaign(camp)
    assert not res.ok
    assert any(v.startswith("convergence:") for v in res.violations)


@hard_timeout(60)
def test_queued_sane_catches_seeded_negative_gauge():
    camp = Campaign(name="neg_gauge", seed=3, n_hosts=2, duration_s=4.0,
                    settle_s=2.0, base_rate=1.0,
                    invariants=("queued_sane",))
    import mlx_sharding_tpu.sim.chaos as chaos_mod

    orig_build = chaos_mod.build_fleet

    def sabotaged(sim, **kw):
        fs = orig_build(sim, **kw)
        fs.queued_negative = 2  # as if the sampler saw a negative gauge
        return fs

    chaos_mod.build_fleet = sabotaged
    try:
        res = run_campaign(camp)
    finally:
        chaos_mod.build_fleet = orig_build
    assert not res.ok
    assert any("negative" in v for v in res.violations)


# ------------------------------------------------------------- shrinking
@hard_timeout(120)
def test_shrinker_reduces_20_event_storm_to_minimal_repro(tmp_path):
    # 19 harmless site arms + one host_kill, with resume disabled so the
    # kill drops streams: ddmin must isolate a <= 3 event schedule
    camp = scenario_host_death(seed=11)
    camp.resume_streams = False
    camp.schedule = [
        FaultEvent(t=2.0 + 0.2 * i, kind="site", site="spec.draft",
                   exc="fault", times=1)
        for i in range(19)
    ] + [FaultEvent(t=7.0, kind="host_kill", host=1)]
    assert len(camp.schedule) == 20
    full = run_campaign(camp)
    assert not full.ok

    shrunk = shrink(camp)
    assert not shrunk.ok
    assert len(shrunk.campaign.schedule) <= 3
    assert any(ev.kind == "host_kill" for ev in shrunk.campaign.schedule)

    # repro file round-trips and replays to the same digest
    path = tmp_path / "repro.json"
    write_repro(str(path), shrunk)
    doc = json.loads(path.read_text())
    assert doc["format"] == "mst-chaos-repro-v1"
    replayed = run_campaign(load_repro(str(path)))
    assert replayed.digest == shrunk.digest
    assert not replayed.ok


# -------------------------------------------------------- coverage gate
def test_every_required_fault_site_has_a_chaos_scenario():
    """Registry-drift gate: a newly REQUIRED fault site must be exercised
    by at least one chaos scenario, or this fails at registration time —
    the dynamic complement of the MST30x static checks."""
    from mlx_sharding_tpu.analysis.lifecycle import REQUIRED_FAULT_SITES

    required = {s for sites in REQUIRED_FAULT_SITES.values() for s in sites}
    covered = set()
    for factory in SCENARIOS.values():
        covered |= factory().sites()
    missing = sorted(required - covered)
    assert not missing, (
        f"required fault sites with no chaos scenario arming them: "
        f"{missing} — add them to a scenario in sim/chaos.py (the storm "
        "schedules pick up lifecycle.REQUIRED_FAULT_SITES automatically; "
        "rebuild SCENARIOS or extend one)"
    )


def test_campaign_provenance_stamped_into_snapshots():
    from mlx_sharding_tpu import tracing

    tracing.configure(mode="on")
    try:
        res = run_campaign(
            Campaign(name="prov", seed=21, n_hosts=2, duration_s=4.0,
                     settle_s=2.0, base_rate=1.5,
                     schedule=[FaultEvent(t=1.0, kind="site",
                                          site="scheduler.tick",
                                          exc="runtime", times=1)])
        )
        assert res.ok, res.violations
        tr = tracing.get_tracer()
        snaps = [s for s in tr.snapshots() if "campaign" in s]
        assert snaps, "no campaign-stamped snapshot recorded"
        camp = snaps[-1]["campaign"]
        assert camp["name"] == "prov" and camp["seed"] == 21
        assert camp["t_virtual"] >= 0.0
    finally:
        tracing.configure(mode="off")


# ------------------------------------------------------------- slow tier
@pytest.mark.slow
@hard_timeout(300)
def test_surge_100_hosts_acceptance_campaign():
    """The acceptance criterion verbatim: a seeded 100-host 10×-surge
    campaign (host deaths + transport kills + fault-site storm) with zero
    wall-clock sleeps, bit-identical across two runs, zero dropped
    streams, clean ledger — and the broken variant shrinks to ≤ 3."""
    r1 = run_campaign(scenario_surge_100())
    r2 = run_campaign(scenario_surge_100())
    assert r1.ok, r1.violations
    assert r1.digest == r2.digest
    assert r1.n_requests > 500

    broken = scenario_surge_100()
    broken.resume_streams = False
    broken.n_hosts = 20  # shrink probes re-run the sim; keep them honest
    broken.schedule = broken.schedule[:6]
    res = run_campaign(broken)
    assert not res.ok
    shrunk = shrink(broken)
    assert len(shrunk.campaign.schedule) <= 3
    assert not shrunk.ok
