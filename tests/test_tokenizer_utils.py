"""Detokenizer + stop machinery, driven with a fake byte-level tokenizer so
no network/tokenizer downloads are needed."""

import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.tokenizer_utils import (
    StreamingDetokenizer,
    sequence_overlap,
    stopping_criteria,
)


class ByteTokenizer:
    """Token id == one UTF-8 byte. Exercises the mid-codepoint edge case
    (multi-byte chars split across tokens) that real byte-level BPEs hit."""

    eos_token_id = 256

    def decode(self, ids):
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")

    def encode(self, text):
        return list(text.encode("utf-8"))


def test_detokenizer_ascii_stream():
    d = StreamingDetokenizer(ByteTokenizer())
    out = []
    for t in ByteTokenizer().encode("hello world"):
        d.add_token(t)
        out.append(d.last_segment)
    assert "".join(out) == "hello world"
    assert d.text == "hello world"


def test_detokenizer_multibyte_held_until_complete():
    tok = ByteTokenizer()
    d = StreamingDetokenizer(tok)
    emoji_bytes = "🎉".encode("utf-8")  # 4 bytes
    segments = []
    for b in emoji_bytes:
        d.add_token(b)
        segments.append(d.last_segment)
    assert segments[:3] == ["", "", ""]  # nothing emitted mid-codepoint
    assert segments[3] == "🎉"


def test_detokenizer_newline_region_reset():
    tok = ByteTokenizer()
    d = StreamingDetokenizer(tok)
    text = "a\nbb\nccc"
    for t in tok.encode(text):
        d.add_token(t)
    d.finalize()
    assert d.text == text


def test_detokenizer_finalize_drops_dangling_bytes():
    tok = ByteTokenizer()
    d = StreamingDetokenizer(tok)
    d.add_token("é".encode("utf-8")[0])  # first half of a 2-byte char
    d.finalize()
    assert d.text == ""


def test_detokenizer_long_output_region_caps():
    """A long newline-free stream stays correct across region restarts."""
    tok = ByteTokenizer()
    d = StreamingDetokenizer(tok)
    text = ("abcdefghij" * 40) + "é🎉 end"  # 400+ chars, multibyte near the end
    for t in tok.encode(text):
        d.add_token(t)
    d.finalize()
    assert d.text == text


class MetaspaceTokenizer:
    """SentencePiece-style fake: words carry a leading-space marker and a
    decode that STRIPS the leading space at sequence start — the behavior
    that would drop spaces at region restarts without the prefix-token
    scheme. Vocabulary: id = index into the word list."""

    words = ["▁the", "▁quick", "▁brown", "▁fox", "▁jumps", "▁over", "▁lazy", "▁dog"]
    eos_token_id = None

    def decode(self, ids):
        s = "".join(self.words[i] for i in ids).replace("▁", " ")
        return s[1:] if s.startswith(" ") else s


def test_detokenizer_metaspace_spaces_survive_restarts():
    tok = MetaspaceTokenizer()
    d = StreamingDetokenizer(tok)
    d.MAX_REGION_TOKENS = 3  # force frequent restarts
    ids = [0, 1, 2, 3, 4, 5, 0, 6, 7] * 4
    for t in ids:
        d.add_token(t)
    d.finalize()
    expected = tok.decode(ids)
    assert d.text == expected, f"{d.text!r} != {expected!r}"


def test_detokenizer_dirty_region_bounded():
    """A flood of lone continuation bytes can't grow the region forever."""
    tok = ByteTokenizer()
    d = StreamingDetokenizer(tok)
    d.MAX_DIRTY_REGION_TOKENS = 16
    for _ in range(100):
        d.add_token(0xBD)  # UTF-8 continuation byte, never decodes cleanly
    assert len(d.tokens) - d._region_start <= 16
    # recovery: clean text after the garbage still streams
    for t in tok.encode("ok"):
        d.add_token(t)
    d.finalize()
    assert d.text.endswith("ok")


def test_stopping_criteria_eos():
    s = stopping_criteria([1, 2, 3], [], eos_token_id=3)
    assert s.stop_met and s.trim_length == 0


def test_stopping_criteria_sequence_trims():
    s = stopping_criteria([5, 6, 7, 8], [[7, 8]], eos_token_id=None)
    assert s.stop_met and s.trim_length == 2


def test_stopping_criteria_no_match():
    s = stopping_criteria([5, 6, 7], [[9, 9]], eos_token_id=0)
    assert not s.stop_met


def test_sequence_overlap():
    assert sequence_overlap("hello wo", "world")  # "wo" is a prefix of "world"
    assert not sequence_overlap("hello", "xyz")
    assert sequence_overlap([1, 2], [2, 3])
    assert not sequence_overlap([], [1])
