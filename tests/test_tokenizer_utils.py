"""Detokenizer + stop machinery, driven with a fake byte-level tokenizer so
no network/tokenizer downloads are needed."""

import pytest

from mlx_sharding_tpu.tokenizer_utils import (
    StreamingDetokenizer,
    sequence_overlap,
    stopping_criteria,
)


class ByteTokenizer:
    """Token id == one UTF-8 byte. Exercises the mid-codepoint edge case
    (multi-byte chars split across tokens) that real byte-level BPEs hit."""

    eos_token_id = 256

    def decode(self, ids):
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")

    def encode(self, text):
        return list(text.encode("utf-8"))


def test_detokenizer_ascii_stream():
    d = StreamingDetokenizer(ByteTokenizer())
    out = []
    for t in ByteTokenizer().encode("hello world"):
        d.add_token(t)
        out.append(d.last_segment)
    assert "".join(out) == "hello world"
    assert d.text == "hello world"


def test_detokenizer_multibyte_held_until_complete():
    tok = ByteTokenizer()
    d = StreamingDetokenizer(tok)
    emoji_bytes = "🎉".encode("utf-8")  # 4 bytes
    segments = []
    for b in emoji_bytes:
        d.add_token(b)
        segments.append(d.last_segment)
    assert segments[:3] == ["", "", ""]  # nothing emitted mid-codepoint
    assert segments[3] == "🎉"


def test_detokenizer_newline_region_reset():
    tok = ByteTokenizer()
    d = StreamingDetokenizer(tok)
    text = "a\nbb\nccc"
    for t in tok.encode(text):
        d.add_token(t)
    d.finalize()
    assert d.text == text


def test_detokenizer_finalize_drops_dangling_bytes():
    tok = ByteTokenizer()
    d = StreamingDetokenizer(tok)
    d.add_token("é".encode("utf-8")[0])  # first half of a 2-byte char
    d.finalize()
    assert d.text == ""


def test_stopping_criteria_eos():
    s = stopping_criteria([1, 2, 3], [], eos_token_id=3)
    assert s.stop_met and s.trim_length == 0


def test_stopping_criteria_sequence_trims():
    s = stopping_criteria([5, 6, 7, 8], [[7, 8]], eos_token_id=None)
    assert s.stop_met and s.trim_length == 2


def test_stopping_criteria_no_match():
    s = stopping_criteria([5, 6, 7], [[9, 9]], eos_token_id=0)
    assert not s.stop_met


def test_sequence_overlap():
    assert sequence_overlap("hello wo", "world")  # "wo" is a prefix of "world"
    assert not sequence_overlap("hello", "xyz")
    assert sequence_overlap([1, 2], [2, 3])
    assert not sequence_overlap([], [1])
