import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator, stream_generate
from mlx_sharding_tpu.models.llama import LlamaModel

TINY = dict(
    vocab_size=300,  # > 256 so the ByteTokenizer ids fit
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


@pytest.fixture(scope="module")
def gen():
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )


def test_greedy_deterministic(gen):
    a = [t for t, _ in gen.generate_step([1, 2, 3], max_tokens=10)]
    b = [t for t, _ in gen.generate_step([1, 2, 3], max_tokens=10)]
    assert a == b
    assert len(a) == 10


def test_chunked_prefill_matches_unchunked(gen):
    """Prompt longer than the prefill chunk (8) must give the same greedy
    continuation as a generator with a chunk large enough to take it whole."""
    prompt = list(range(1, 20))  # 19 tokens -> chunks 8+8+3(padded)
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    big = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=32)
    a = [t for t, _ in gen.generate_step(prompt, max_tokens=5)]
    b = [t for t, _ in big.generate_step(prompt, max_tokens=5)]
    assert a == b


def test_seeded_sampling_deterministic(gen):
    a = [t for t, _ in gen.generate_step([1], temperature=1.0, seed=7, max_tokens=8)]
    b = [t for t, _ in gen.generate_step([1], temperature=1.0, seed=7, max_tokens=8)]
    assert a == b


def test_capacity_guard(gen):
    with pytest.raises(ValueError, match="exceeds KV"):
        list(gen.generate_step(list(range(60)), max_tokens=10))


def test_stream_generate_stops_and_reports(gen):
    from tests.test_tokenizer_utils import ByteTokenizer

    tok = ByteTokenizer()
    chunks = list(
        stream_generate(gen, tok, tok.encode("hi"), max_tokens=12, eos_token_ids=[])
    )
    final = chunks[-1]
    assert final.finish_reason == "length"
    assert final.generation_tokens == 12
    assert final.prompt_tokens == 2
    assert final.generation_tps > 0
    assert final.ttft > 0


def test_stream_generate_stop_sequence(gen):
    from tests.test_tokenizer_utils import ByteTokenizer

    tok = ByteTokenizer()
    # find what greedy decode produces, then use its 3rd token as a stop token
    toks = [t for t, _ in gen.generate_step(tok.encode("hi"), max_tokens=5)]
    stop = [[toks[2]]]
    chunks = list(
        stream_generate(
            gen, tok, tok.encode("hi"), max_tokens=12,
            stop_id_sequences=stop, eos_token_ids=[],
        )
    )
    assert chunks[-1].finish_reason == "stop"
    # stops at the *first* occurrence of the stop token, which is itself
    # trimmed from the reported output
    assert chunks[-1].generation_tokens == toks.index(toks[2])


def test_want_logprobs_topk(gen):
    """TokenLogprobs summaries (device-side lax.top_k) must agree with a full
    log-softmax recomputation: chosen == logprob of the emitted token, top-k
    descending and containing the greedy choice."""
    out = list(gen.generate_step([1, 2, 3], max_tokens=6, want_logprobs=True))
    assert len(out) == 6
    for tok, lp in out:
        assert lp is not None
        assert lp.top_values.shape == lp.top_indices.shape
        vals = np.asarray(lp.top_values)
        assert (np.diff(vals) <= 1e-6).all()  # descending
        assert vals[0] <= 0 + 1e-6
        # greedy decode: emitted token is the argmax -> top-1 index
        assert int(lp.top_indices[0]) == tok
        assert lp.chosen == pytest.approx(float(vals[0]), abs=1e-5)


def test_want_logprobs_token_parity(gen):
    """Asking for logprobs must not change the token stream (the summary is
    computed from the same in-scan logits)."""
    a = [t for t, _ in gen.generate_step([4, 5], max_tokens=9, seed=3, temperature=0.8)]
    b = [
        t
        for t, _ in gen.generate_step(
            [4, 5], max_tokens=9, seed=3, temperature=0.8, want_logprobs=True
        )
    ]
    assert a == b


def test_decode_block_sizes_agree(gen):
    """Different decode_block sizes are pure batching — token streams must be
    identical (greedy and seeded)."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    one = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
        decode_block=1,
    )
    five = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
        decode_block=5,
    )
    for kw in (dict(), dict(temperature=1.0, seed=11)):
        want = [t for t, _ in gen.generate_step([1, 2, 3], max_tokens=10, **kw)]
        assert [t for t, _ in one.generate_step([1, 2, 3], max_tokens=10, **kw)] == want
        assert [t for t, _ in five.generate_step([1, 2, 3], max_tokens=10, **kw)] == want
