import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator, stream_generate
from mlx_sharding_tpu.models.llama import LlamaModel

TINY = dict(
    vocab_size=300,  # > 256 so the ByteTokenizer ids fit
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


@pytest.fixture(scope="module")
def gen():
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )


def test_greedy_deterministic(gen):
    a = [t for t, _ in gen.generate_step([1, 2, 3], max_tokens=10)]
    b = [t for t, _ in gen.generate_step([1, 2, 3], max_tokens=10)]
    assert a == b
    assert len(a) == 10


def test_chunked_prefill_matches_unchunked(gen):
    """Prompt longer than the prefill chunk (8) must give the same greedy
    continuation as a generator with a chunk large enough to take it whole."""
    prompt = list(range(1, 20))  # 19 tokens -> chunks 8+8+3(padded)
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    big = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=32)
    a = [t for t, _ in gen.generate_step(prompt, max_tokens=5)]
    b = [t for t, _ in big.generate_step(prompt, max_tokens=5)]
    assert a == b


def test_seeded_sampling_deterministic(gen):
    a = [t for t, _ in gen.generate_step([1], temperature=1.0, seed=7, max_tokens=8)]
    b = [t for t, _ in gen.generate_step([1], temperature=1.0, seed=7, max_tokens=8)]
    assert a == b


def test_capacity_guard(gen):
    with pytest.raises(ValueError, match="exceeds KV"):
        list(gen.generate_step(list(range(60)), max_tokens=10))


def test_stream_generate_stops_and_reports(gen):
    from tests.test_tokenizer_utils import ByteTokenizer

    tok = ByteTokenizer()
    chunks = list(
        stream_generate(gen, tok, tok.encode("hi"), max_tokens=12, eos_token_ids=[])
    )
    final = chunks[-1]
    assert final.finish_reason == "length"
    assert final.generation_tokens == 12
    assert final.prompt_tokens == 2
    assert final.generation_tps > 0
    assert final.ttft > 0


def test_stream_generate_stop_sequence(gen):
    from tests.test_tokenizer_utils import ByteTokenizer

    tok = ByteTokenizer()
    # find what greedy decode produces, then use its 3rd token as a stop token
    toks = [t for t, _ in gen.generate_step(tok.encode("hi"), max_tokens=5)]
    stop = [[toks[2]]]
    chunks = list(
        stream_generate(
            gen, tok, tok.encode("hi"), max_tokens=12,
            stop_id_sequences=stop, eos_token_ids=[],
        )
    )
    assert chunks[-1].finish_reason == "stop"
    # stops at the *first* occurrence of the stop token, which is itself
    # trimmed from the reported output
    assert chunks[-1].generation_tokens == toks.index(toks[2])
