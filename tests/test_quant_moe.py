"""Packed 4-bit residency for the MoE models (VERDICT r2 item 3).

The BASELINE primary checkpoint (DeepSeek-Coder-V2-Lite-4bit) must load with
--keep-quantized: MLA projections and the (E, …) expert stacks stay packed
in HBM and dequantize inside the matmuls; the router (fp32 routing einsum)
and — in compressed cache mode — kv_b (absorbed into einsums as a tensor)
load dense via packed_keep_dense_re. Reference quant predicate:
shard/utils.py:54-65. Parity contract: packed load produces the exact token
stream of the dequantize-at-load path, solo and on every engine/mesh.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.ops.quant import is_quantized, quantize


def _write_quantized(tmp_path: Path, cfg: dict, spec, gs: int):
    """spec: iterable of (name, shape, quantized?) — quantized entries write
    MLX triples, including the routers/kv_b (the loader must decide what
    stays packed, not the checkpoint)."""
    from safetensors.numpy import save_file

    rng = np.random.default_rng(11)
    tensors = {}
    for name, shape, quant in spec:
        w = (rng.normal(size=shape) * 0.05).astype(np.float32)
        if quant:
            q, s, b = quantize(w, group_size=gs, bits=4)
            tensors[name] = q
            tensors[name.replace(".weight", ".scales")] = s
            tensors[name.replace(".weight", ".biases")] = b
        else:
            tensors[name] = w
    save_file(tensors, str(tmp_path / "model.safetensors"))
    (tmp_path / "config.json").write_text(json.dumps(cfg))
    return tmp_path


def _quantized_tiny_deepseek(tmp_path: Path, gs: int = 16, cache_mode="compressed"):
    hd, rank, heads = 64, 32, 4
    nope, rope, v_d = 16, 8, 16
    inter, mi, n_exp = 64, 32, 4
    cfg = dict(
        model_type="deepseek_v2", vocab_size=128, hidden_size=hd,
        intermediate_size=inter, moe_intermediate_size=mi,
        num_hidden_layers=3, num_attention_heads=heads,
        num_key_value_heads=heads, kv_lora_rank=rank, q_lora_rank=None,
        qk_rope_head_dim=rope, qk_nope_head_dim=nope, v_head_dim=v_d,
        n_routed_experts=n_exp, n_shared_experts=1, num_experts_per_tok=2,
        first_k_dense_replace=1, max_position_embeddings=256,
        rms_norm_eps=1e-5, rope_theta=10000.0,
        mla_cache_mode=cache_mode,
        quantization={"group_size": gs, "bits": 4},
    )
    spec = [
        ("model.embed_tokens.weight", (128, hd), False),
        ("model.norm.weight", (hd,), False),
        ("lm_head.weight", (128, hd), False),
    ]
    for i in range(3):
        p = f"model.layers.{i}"
        spec += [
            (f"{p}.input_layernorm.weight", (hd,), False),
            (f"{p}.post_attention_layernorm.weight", (hd,), False),
            (f"{p}.self_attn.kv_a_layernorm.weight", (rank,), False),
            (f"{p}.self_attn.q_proj.weight", (heads * (nope + rope), hd), True),
            (f"{p}.self_attn.kv_a_proj_with_mqa.weight", (rank + rope, hd), True),
            (f"{p}.self_attn.kv_b_proj.weight", (heads * (nope + v_d), rank), True),
            (f"{p}.self_attn.o_proj.weight", (hd, heads * v_d), True),
        ]
        if i < 1:  # dense layer
            spec += [
                (f"{p}.mlp.gate_proj.weight", (inter, hd), True),
                (f"{p}.mlp.up_proj.weight", (inter, hd), True),
                (f"{p}.mlp.down_proj.weight", (hd, inter), True),
            ]
        else:  # moe layer — router is quantized in the checkpoint too;
            # the loader must dequantize it (packed_keep_dense_re)
            spec += [
                (f"{p}.mlp.gate.weight", (n_exp, hd), True),
                (f"{p}.mlp.shared_experts.gate_proj.weight", (mi, hd), True),
                (f"{p}.mlp.shared_experts.up_proj.weight", (mi, hd), True),
                (f"{p}.mlp.shared_experts.down_proj.weight", (hd, mi), True),
            ]
            for e in range(n_exp):
                spec += [
                    (f"{p}.mlp.experts.{e}.gate_proj.weight", (mi, hd), True),
                    (f"{p}.mlp.experts.{e}.up_proj.weight", (mi, hd), True),
                    (f"{p}.mlp.experts.{e}.down_proj.weight", (hd, mi), True),
                ]
    return _write_quantized(tmp_path, cfg, spec, gs)


def _quantized_tiny_mixtral(tmp_path: Path, gs: int = 32):
    hd, inter, heads, hkv, d, n_exp = 64, 64, 4, 2, 16, 4
    cfg = dict(
        model_type="mixtral", vocab_size=128, hidden_size=hd,
        intermediate_size=inter, num_hidden_layers=2,
        num_attention_heads=heads, num_key_value_heads=hkv,
        num_local_experts=n_exp, num_experts_per_tok=2,
        max_position_embeddings=256, rms_norm_eps=1e-5, rope_theta=10000.0,
        quantization={"group_size": gs, "bits": 4},
    )
    spec = [
        ("model.embed_tokens.weight", (128, hd), False),
        ("model.norm.weight", (hd,), False),
        ("lm_head.weight", (128, hd), False),
    ]
    for i in range(2):
        p = f"model.layers.{i}"
        spec += [
            (f"{p}.input_layernorm.weight", (hd,), False),
            (f"{p}.post_attention_layernorm.weight", (hd,), False),
            (f"{p}.self_attn.q_proj.weight", (heads * d, hd), True),
            (f"{p}.self_attn.k_proj.weight", (hkv * d, hd), True),
            (f"{p}.self_attn.v_proj.weight", (hkv * d, hd), True),
            (f"{p}.self_attn.o_proj.weight", (hd, heads * d), True),
            (f"{p}.block_sparse_moe.gate.weight", (n_exp, hd), True),
        ]
        for e in range(n_exp):
            spec += [
                (f"{p}.block_sparse_moe.experts.{e}.w1.weight", (inter, hd), True),
                (f"{p}.block_sparse_moe.experts.{e}.w2.weight", (hd, inter), True),
                (f"{p}.block_sparse_moe.experts.{e}.w3.weight", (inter, hd), True),
            ]
    return _write_quantized(tmp_path, cfg, spec, gs)


def _tokens(model, params, prompt, max_tokens=8):
    from mlx_sharding_tpu.generate import Generator

    gen = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    return [t for t, _ in gen.generate_step(prompt, max_tokens=max_tokens)]


@pytest.mark.parametrize(
    "cache_mode",
    # decompressed rides the slow tier; compressed is the deployed MLA mode
    # and exercises the same packed MoE dispatch
    ["compressed", pytest.param("decompressed", marks=pytest.mark.slow)],
)
def test_deepseek_keep_quantized_matches_dense(tmp_path, cache_mode):
    from mlx_sharding_tpu.loading import load_model

    path = _quantized_tiny_deepseek(tmp_path, cache_mode=cache_mode)
    model_d, params_d = load_model(str(path), dtype=jnp.float32)
    model_p, params_p = load_model(str(path), dtype=jnp.float32, keep_quantized=True)

    moe = params_p["layers"]["moe"]
    assert is_quantized(moe["w_gate"])  # expert stacks stay packed
    assert moe["w_gate"]["q"].shape[:2] == (2, 4)  # (L_moe, E) leading dims
    assert not is_quantized(moe["router"])  # router forced dense
    kv_b = moe["kv_b_proj"]
    if cache_mode == "compressed":
        assert not is_quantized(kv_b)  # consumed as a tensor → dense
    else:
        assert is_quantized(kv_b)

    prompt = [3, 17, 42, 9]
    assert _tokens(model_p, params_p, prompt) == _tokens(model_d, params_d, prompt)


@pytest.mark.slow  # ~15s arch-matrix combo (packed x pipeline x EP)
def test_deepseek_packed_fused_pipeline_and_ep(tmp_path):
    """Packed grouped stacks through the fused SPMD engine: pp2 (uneven
    dense/moe split) and pp1 x ep2 (packed expert stacks sharded on their E
    axis) — exact parity with the solo packed run."""
    from mlx_sharding_tpu.loading import load_model
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

    path = _quantized_tiny_deepseek(tmp_path)
    model, params = load_model(str(path), dtype=jnp.float32, keep_quantized=True)
    prompt = [5, 9, 2, 61]
    want = _tokens(model, params, prompt)

    for mesh_kw in (dict(pp=2), dict(pp=1, ep=2)):
        eng = PipelineEngine(
            model, params, make_mesh(**mesh_kw), max_seq=64,
            cache_dtype=jnp.float32, prefill_chunk=8,
        )
        got = [t for t, _ in eng.generate_step(prompt, max_tokens=8)]
        assert got == want, f"{mesh_kw} diverged"
    # in the ep engine the packed E axis is the sharded one
    wq = eng.layer_params["moe"]["w_gate"]["q"]
    assert wq.sharding.shard_shape(wq.shape)[2] == wq.shape[2] // 2


@pytest.mark.slow  # ~12s arch-matrix combo (packed x TP)
def test_deepseek_packed_tensor_parallel(tmp_path):
    """TP x packed for MLA + experts: kv_b/q column-parallel (whole heads),
    o row-parallel, expert stacks split their intermediate dim — gs=16 keeps
    every row-split on a quant-group boundary."""
    from mlx_sharding_tpu.loading import load_model
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

    path = _quantized_tiny_deepseek(tmp_path, gs=16, cache_mode="decompressed")
    model, params = load_model(str(path), dtype=jnp.float32, keep_quantized=True)
    prompt = [7, 3, 99, 12]
    want = _tokens(model, params, prompt)
    eng = PipelineEngine(
        model, params, make_mesh(pp=1, tp=2), max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    assert [t for t, _ in eng.generate_step(prompt, max_tokens=8)] == want
    # column-parallel packed expert gate: out (= mi) dim sharded
    wq = eng.layer_params["moe"]["w_gate"]["q"]
    assert wq.sharding.shard_shape(wq.shape)[3] == wq.shape[3] // 2


@pytest.mark.slow  # ~11s all-engine sweep; dense-parity gates stay tier-1
def test_mixtral_keep_quantized_all_engines(tmp_path):
    from mlx_sharding_tpu.loading import load_model
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

    path = _quantized_tiny_mixtral(tmp_path)
    model_d, params_d = load_model(str(path), dtype=jnp.float32)
    model_p, params_p = load_model(str(path), dtype=jnp.float32, keep_quantized=True)
    assert is_quantized(params_p["layers"]["w_gate"])
    assert not is_quantized(params_p["layers"]["router"])

    prompt = [9, 4, 120, 33]
    want = _tokens(model_d, params_d, prompt)
    assert _tokens(model_p, params_p, prompt) == want

    for mesh_kw in (dict(pp=2), dict(pp=1, ep=2)):
        eng = PipelineEngine(
            model_p, params_p, make_mesh(**mesh_kw), max_seq=64,
            cache_dtype=jnp.float32, prefill_chunk=8,
        )
        got = [t for t, _ in eng.generate_step(prompt, max_tokens=8)]
        assert got == want, f"{mesh_kw} diverged"


def test_packed_gather_and_scan_paths_agree(tmp_path):
    """Decode (gather over packed leaves) and prefill (scan with fused
    dequant linears) must produce identical expert outputs."""
    from mlx_sharding_tpu.ops.moe import (
        GATHER_PATH_MAX_TOKENS,
        _apply_gather_packed,
        _apply_scan,
        mixtral_routing,
    )

    rng = np.random.default_rng(5)
    n, h, mi, e, k, gs = 8, 64, 32, 4, 2, 16
    assert n <= GATHER_PATH_MAX_TOKENS
    x = jnp.asarray(rng.normal(size=(n, h)), jnp.float32)
    router = jnp.asarray(rng.normal(size=(h, e)), jnp.float32)

    def packed_stack(out_d, in_d):
        ws = [
            quantize((rng.normal(size=(out_d, in_d)) * 0.1).astype(np.float32), gs, 4)
            for _ in range(e)
        ]
        return {
            "q": jnp.stack([jnp.asarray(w[0]) for w in ws]),
            "scales": jnp.stack([jnp.asarray(w[1], jnp.float32) for w in ws]),
            "biases": jnp.stack([jnp.asarray(w[2], jnp.float32) for w in ws]),
        }

    wg, wu = packed_stack(mi, h), packed_stack(mi, h)
    wd = packed_stack(h, mi)
    weights, idx = mixtral_routing(x, router, k)
    got_g = _apply_gather_packed(x, weights, idx, wg, wu, wd, gs, 4)
    got_s = _apply_scan(x, weights, idx, wg, wu, wd, gs, 4)
    np.testing.assert_allclose(np.asarray(got_g), np.asarray(got_s), rtol=1e-4, atol=1e-5)
