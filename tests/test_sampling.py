import jax
import pytest

pytestmark = pytest.mark.quick
import jax.numpy as jnp
import numpy as np

from mlx_sharding_tpu.sample import (
    apply_logit_bias,
    apply_repetition_penalty,
    init_recent_tokens,
    make_sampler_params,
    sample_token,
    top_p_filter,
    update_recent_tokens,
)


def test_greedy_at_zero_temperature():
    logits = jnp.asarray([[0.1, 5.0, -1.0, 2.0]])
    sp = make_sampler_params(temperature=0.0)
    tok, logprobs = sample_token(jax.random.PRNGKey(0), logits, sp)
    assert int(tok[0]) == 1
    np.testing.assert_allclose(
        np.asarray(logprobs), np.asarray(jax.nn.log_softmax(logits)), rtol=1e-5
    )


def test_categorical_respects_distribution():
    logits = jnp.asarray([[0.0, 10.0, 0.0, 0.0]])
    sp = make_sampler_params(temperature=1.0)
    toks = [
        int(sample_token(jax.random.PRNGKey(i), logits, sp)[0][0]) for i in range(20)
    ]
    assert toks.count(1) >= 18  # overwhelming mass on token 1


def test_top_p_filter_masks_tail():
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    filtered = top_p_filter(logits, jnp.asarray(0.7))
    # 0.5 kept (0 mass before); 0.3 kept (0.5 < 0.7); 0.15 dropped (0.8 >= 0.7)
    f = np.asarray(filtered[0])
    assert np.isfinite(f[0]) and np.isfinite(f[1])
    assert np.isinf(f[2]) and np.isinf(f[3])


def test_top_p_one_keeps_all():
    logits = jnp.asarray([[1.0, 2.0, 3.0]])
    filtered = top_p_filter(logits, jnp.asarray(1.0))
    assert np.isfinite(np.asarray(filtered)).all()


def test_logit_bias():
    logits = jnp.zeros((1, 8))
    sp = make_sampler_params(temperature=0.0, logit_bias={5: 100.0})
    tok, _ = sample_token(jax.random.PRNGKey(0), logits, sp)
    assert int(tok[0]) == 5


def test_logit_bias_padding_is_noop():
    logits = jnp.asarray([[3.0, 1.0, 2.0]])
    biased = apply_logit_bias(
        logits, jnp.zeros((4,), jnp.int32), jnp.zeros((4,), jnp.float32)
    )
    np.testing.assert_array_equal(np.asarray(biased), np.asarray(logits))


def test_repetition_penalty_matches_reference_rule():
    logits = jnp.asarray([[2.0, -2.0, 1.0, 0.5]])
    recent = jnp.asarray([[0, 1, -1, -1]])  # tokens 0 and 1 seen; -1 = empty
    out = np.asarray(apply_repetition_penalty(logits, recent, jnp.asarray(2.0)))[0]
    np.testing.assert_allclose(out, [1.0, -4.0, 1.0, 0.5])  # pos/2, neg*2, rest same


def test_repetition_penalty_via_sampler_changes_choice():
    logits = jnp.asarray([[5.0, 4.9, 0.0]])
    sp = make_sampler_params(temperature=0.0, repetition_penalty=2.0)
    recent = update_recent_tokens(init_recent_tokens(1, 4), jnp.asarray([0]))
    tok, _ = sample_token(jax.random.PRNGKey(0), logits, sp, recent)
    assert int(tok[0]) == 1  # token 0 penalized 5.0 -> 2.5


def test_recent_tokens_window_slides():
    r = init_recent_tokens(1, 3)
    for t in [7, 8, 9, 10]:
        r = update_recent_tokens(r, jnp.asarray([t]))
    np.testing.assert_array_equal(np.asarray(r), [[8, 9, 10]])
