"""Paged KV pool + reservation admission (VERDICT r2 item 4): slots address
pages out of a shared pool instead of owning dense max_seq allocations;
the scheduler reserves a request's full page need at admission (no
mid-stream allocation, no oversubscription deadlock) and queues what
doesn't fit. Parity contract: token streams identical to the serial path
whatever the interleaving or pool pressure."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.scheduler import ContinuousBatcher

TINY = dict(
    vocab_size=300,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


def make_engine(pool_pages, **kw):
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
        pool_pages=pool_pages, page_size=8, **kw,
    )
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    return eng, ref


@pytest.fixture(scope="module")
def setup():
    # pool of 10 pages = 80 rows, vs the dense layout's 2 slots x 64 rows
    eng, ref = make_engine(pool_pages=10)
    batcher = ContinuousBatcher(eng, decode_block=3)
    yield batcher, ref
    batcher.close()


def _run(batcher, prompt, **kw):
    return [t for t, _ in batcher.generate_step(prompt, **kw)]


def _concurrent(batcher, jobs):
    results = [None] * len(jobs)

    def work(i, prompt, kw):
        results[i] = _run(batcher, prompt, **kw)

    threads = [
        threading.Thread(target=work, args=(i, p, kw))
        for i, (p, kw) in enumerate(jobs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert all(r is not None for r in results)
    return results


def test_paged_serial_parity(setup):
    batcher, ref = setup
    prompt = [3, 17, 42]
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=10)]
    assert _run(batcher, prompt, max_tokens=10) == want


def test_paged_seeded_parity(setup):
    batcher, ref = setup
    kw = dict(temperature=0.9, top_p=0.7, seed=5, max_tokens=8)
    want = [t for t, _ in ref.generate_step([9, 1], **kw)]
    assert _run(batcher, [9, 1], **kw) == want


def test_n_much_greater_than_m_mixed_lengths(setup):
    """6 mixed-length requests through 2 slots and a 10-page pool: every
    stream must match its solo serial run exactly. With reservation
    admission some requests WAIT for pages, not just for slots."""
    batcher, ref = setup
    rng = np.random.default_rng(7)
    jobs = []
    for i in range(6):
        plen = int(rng.integers(2, 20))
        prompt = [int(t) for t in rng.integers(1, 300, size=plen)]
        jobs.append((prompt, dict(max_tokens=int(rng.integers(4, 16)), seed=i,
                                  temperature=0.5)))
    want = [
        [t for t, _ in ref.generate_step(p, **kw)] for p, kw in jobs
    ]
    got = _concurrent(batcher, jobs)
    assert got == want


def test_page_stats_and_high_water(setup):
    batcher, _ = setup
    total, in_use, _ = batcher.page_stats()
    assert total == 10
    assert in_use == 0  # nothing active between tests
    _run(batcher, [1, 2, 3], max_tokens=12)  # needs 2 pages of 8
    total, in_use, high = batcher.page_stats()
    assert in_use == 0  # freed at finish
    assert high >= 2  # the reservation registered on the high-water mark


def test_pool_pressure_queues_not_fails():
    """Pool of 3 pages: a 2-page request + another 2-page request cannot
    coexist — the second must WAIT and still complete correctly."""
    eng, ref = make_engine(pool_pages=3)
    batcher = ContinuousBatcher(eng, decode_block=2)
    try:
        jobs = [
            ([5, 6, 7], dict(max_tokens=10, seed=1)),   # 13 rows → 2 pages
            ([8, 9], dict(max_tokens=11, seed=2)),      # 13 rows → 2 pages
        ]
        want = [[t for t, _ in ref.generate_step(p, **kw)] for p, kw in jobs]
        got = _concurrent(batcher, jobs)
        assert got == want
    finally:
        batcher.close()


def test_oversized_request_rejected():
    eng, _ = make_engine(pool_pages=3)
    batcher = ContinuousBatcher(eng)
    try:
        with pytest.raises(ValueError, match="could never be admitted"):
            list(batcher.generate_step([1] * 30, max_tokens=30))
    finally:
        batcher.close()


@pytest.mark.slow  # waiting-line policy sweep — fifo pressure test stays quick
def test_first_fit_overtakes_blocked_head():
    """first_fit: while a big request occupies most of the pool, a waiting
    BIG request blocks a fifo line but a later small one may be admitted
    under first_fit. Verify both finish with correct streams."""
    eng, ref = make_engine(pool_pages=4)
    batcher = ContinuousBatcher(eng, decode_block=2, policy="first_fit")
    try:
        hog_prompt = [2] * 10
        hog_kw = dict(max_tokens=14, seed=3)      # 24 rows → 3 pages
        big_kw = dict(max_tokens=20, seed=4)      # 3 pages — won't fit yet
        small_kw = dict(max_tokens=5, seed=5)     # 1 page — fits alongside
        want_hog = [t for t, _ in ref.generate_step(hog_prompt, **hog_kw)]
        want_big = [t for t, _ in ref.generate_step([4] * 3, **big_kw)]
        want_small = [t for t, _ in ref.generate_step([6], **small_kw)]
        got = _concurrent(
            batcher,
            [(hog_prompt, hog_kw), ([4] * 3, big_kw), ([6], small_kw)],
        )
        assert got == [want_hog, want_big, want_small]
    finally:
        batcher.close()
