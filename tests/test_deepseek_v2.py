"""DeepSeek-V2 (MLA + MoE) parity vs HF transformers — the BASELINE.json
primary config's architecture."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.loading import load_model

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

TINY_HF = dict(
    vocab_size=160,
    hidden_size=64,
    intermediate_size=128,
    moe_intermediate_size=32,
    num_hidden_layers=4,
    num_attention_heads=4,
    num_key_value_heads=4,
    kv_lora_rank=16,
    q_lora_rank=None,
    qk_rope_head_dim=8,
    qk_nope_head_dim=16,
    v_head_dim=12,
    n_routed_experts=8,
    n_shared_experts=2,
    num_experts_per_tok=3,
    first_k_dense_replace=1,
    moe_layer_freq=1,
    routed_scaling_factor=1.0,
    norm_topk_prob=False,
    topk_method="greedy",
    n_group=1,
    topk_group=1,
    max_position_embeddings=256,
    rms_norm_eps=1e-6,
    rope_theta=10000.0,
    tie_word_embeddings=False,
    aux_loss_alpha=0.0,
)


def _make_checkpoint(tmp_path, **overrides):
    torch.manual_seed(13)
    cfg = transformers.DeepseekV2Config(**{**TINY_HF, **overrides})
    model = transformers.DeepseekV2ForCausalLM(cfg)
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("tiny_dsv2")
    model = _make_checkpoint(path)
    return path, model


def test_logits_parity_full(hf_checkpoint):
    path, hf_model = hf_checkpoint
    tokens = [[2, 45, 99, 3, 27, 81, 5, 150]]
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.numpy()
    model, params = load_model(str(path), dtype=jnp.float32)
    got, _ = model(
        params, jnp.asarray(tokens, jnp.int32), model.make_cache(1, 16, jnp.float32)
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-3, atol=3e-3)


def test_cache_head_dims(hf_checkpoint):
    path, _ = hf_checkpoint
    # default: compressed MLA cache — one shared latent head
    model, _ = load_model(str(path), dtype=jnp.float32)
    cache = model.make_cache(1, 8, jnp.float32)
    assert cache.k.shape[-2:] == (1, 16 + 8)  # kv_lora_rank + qk_rope
    # full mode keeps the reference's decompressed tuple head dims
    from mlx_sharding_tpu.models import build_model
    import json

    cfg = json.loads((path / "config.json").read_text())
    cfg["mla_cache_mode"] = "full"
    model_f, _ = build_model(cfg)
    cache_f = model_f.make_cache(1, 8, jnp.float32)
    assert cache_f.k.shape[-1] == 16 + 8  # qk_nope + qk_rope
    assert cache_f.v.shape[-1] == 12  # v_head_dim


def test_prefill_equals_decode(hf_checkpoint):
    path, _ = hf_checkpoint
    model, params = load_model(str(path), dtype=jnp.float32)
    tokens = jnp.asarray([[2, 17, 42, 9, 77, 23, 55, 12]], jnp.int32)
    full, _ = model(params, tokens, model.make_cache(1, 16, jnp.float32))
    cache = model.make_cache(1, 16, jnp.float32)
    outs = []
    for i in range(tokens.shape[1]):
        logits, cache = model(params, tokens[:, i : i + 1], cache)
        outs.append(logits[:, 0])
    got = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(got), rtol=2e-3, atol=2e-3)


def test_two_stage_parity_baseline_split(hf_checkpoint):
    """The BASELINE.json primary config splits DeepSeek at a layer boundary;
    here 4 layers split 0-2/2-4 (stage 0 holds the dense layer + 1 MoE)."""
    path, hf_model = hf_checkpoint
    tokens = [[5, 9, 2, 7, 33]]
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.numpy()
    s0, p0 = load_model(str(path), start_layer=0, end_layer=2, dtype=jnp.float32)
    s1, p1 = load_model(str(path), start_layer=2, end_layer=4, dtype=jnp.float32)
    assert "dense" in p0["layers"] and "moe" in p0["layers"]
    assert "dense" not in p1["layers"]  # stage 1 is all-MoE
    h, _ = s0(p0, jnp.asarray(tokens, jnp.int32), s0.make_cache(1, 16, jnp.float32))
    got, _ = s1(p1, h, s1.make_cache(1, 16, jnp.float32))
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-3, atol=3e-3)


def test_q_lora_variant(tmp_path):
    """Full-size DeepSeek-V2 factors queries through a LoRA bottleneck."""
    hf = _make_checkpoint(tmp_path, q_lora_rank=24)
    tokens = [[4, 9, 2]]
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    model, params = load_model(str(tmp_path), dtype=jnp.float32)
    assert "q_a_proj" in params["layers"]["moe"]
    got, _ = model(
        params, jnp.asarray(tokens, jnp.int32), model.make_cache(1, 8, jnp.float32)
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-3, atol=3e-3)


def test_group_limited_routing(tmp_path):
    hf = _make_checkpoint(
        tmp_path, topk_method="group_limited_greedy", n_group=4, topk_group=2
    )
    tokens = [[8, 3, 91, 14]]
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    model, params = load_model(str(tmp_path), dtype=jnp.float32)
    got, _ = model(
        params, jnp.asarray(tokens, jnp.int32), model.make_cache(1, 8, jnp.float32)
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-3, atol=3e-3)


def test_yarn_rope(tmp_path):
    """DeepSeek-Coder-V2-Lite ships yarn rope scaling.

    HF's native DeepseekV2 port omits DeepSeek's mscale_all_dim softmax-scale
    correction (mlx_lm DeepseekV2Attention and DeepSeek's remote code apply
    ``yarn_get_mscale(factor, mscale_all_dim)**2``; HF keeps a bare
    ``qk_head_dim**-0.5``). The reference's behavior comes from mlx_lm, so we
    implement the correction — and patch HF's per-layer scale here so the
    parity check targets the corrected math."""
    from mlx_sharding_tpu.ops.rope import yarn_get_mscale

    hf = _make_checkpoint(
        tmp_path,
        rope_scaling=dict(
            type="yarn", factor=4.0, original_max_position_embeddings=64,
            beta_fast=32, beta_slow=1, mscale=0.707, mscale_all_dim=0.707,
        ),
        max_position_embeddings=256,
    )
    mscale_sq = yarn_get_mscale(4.0, 0.707) ** 2
    assert mscale_sq > 1.05  # the correction must be material for this test
    for layer in hf.model.layers:
        layer.self_attn.scaling *= mscale_sq
    tokens = [[2, 45, 99, 3, 27, 81, 5, 150, 7, 9]]
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    model, params = load_model(str(tmp_path), dtype=jnp.float32)
    got, _ = model(
        params, jnp.asarray(tokens, jnp.int32), model.make_cache(1, 16, jnp.float32)
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-3, atol=3e-3)
