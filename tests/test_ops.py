import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.ops import apply_rope, causal_attention, rms_norm, rope_frequencies


def test_rms_norm_matches_numpy():
    x = np.random.default_rng(0).normal(size=(2, 5, 16)).astype(np.float32)
    w = np.random.default_rng(1).normal(size=(16,)).astype(np.float32)
    got = rms_norm(jnp.asarray(x), jnp.asarray(w), eps=1e-5)
    ref = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-5) * w
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5, atol=1e-5)


def test_rms_norm_gemma_offset():
    x = np.random.default_rng(0).normal(size=(1, 3, 8)).astype(np.float32)
    w = np.zeros((8,), np.float32)
    got = rms_norm(jnp.asarray(x), jnp.asarray(w), eps=1e-6, offset=1.0)
    ref = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5, atol=1e-5)


def test_rope_offset_consistency():
    """Rotating positions [0..8) in one call == two calls split at 3."""
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 2, 16))
    inv = jnp.asarray(rope_frequencies(16, 10000.0))
    full = apply_rope(x, inv, 0)
    a = apply_rope(x[:, :3], inv, 0)
    b = apply_rope(x[:, 3:], inv, 3)
    np.testing.assert_allclose(
        np.asarray(full), np.asarray(jnp.concatenate([a, b], axis=1)), rtol=1e-5, atol=1e-5
    )


def test_rope_matches_hf_rotate_half():
    """Against the HF transformers convention computed by hand in numpy."""
    d = 8
    x = np.random.default_rng(2).normal(size=(1, 4, 1, d)).astype(np.float32)
    inv = rope_frequencies(d, 10000.0)
    pos = np.arange(4)
    ang = pos[:, None] * inv[None, :]
    cos = np.cos(np.concatenate([ang, ang], -1))[None, :, None, :]
    sin = np.sin(np.concatenate([ang, ang], -1))[None, :, None, :]
    rot = np.concatenate([-x[..., d // 2:], x[..., : d // 2]], -1)
    ref = x * cos + rot * sin
    got = apply_rope(jnp.asarray(x), jnp.asarray(inv), 0)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5, atol=1e-5)


def _naive_attention(q, k, v, scale, causal_from=0, window=None):
    """Dense reference: q (B,T,H,D) vs k/v (B,S,H,D), queries at causal_from."""
    b, t, h, d = q.shape
    s = k.shape[1]
    scores = np.einsum("bthd,bshd->bhts", q, k) * scale
    qpos = causal_from + np.arange(t)[:, None]
    kpos = np.arange(s)[None, :]
    mask = kpos <= qpos
    if window is not None:
        mask &= kpos > qpos - window
    scores = np.where(mask[None, None], scores, -np.inf)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhts,bshd->bthd", p, v)


def test_causal_attention_matches_naive():
    rng = np.random.default_rng(3)
    b, t, h, d, s = 2, 5, 4, 8, 5
    q = rng.normal(size=(b, t, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, h, d)).astype(np.float32)
    v = rng.normal(size=(b, s, h, d)).astype(np.float32)
    got = causal_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(0), 1 / np.sqrt(d)
    )
    ref = _naive_attention(q, k, v, 1 / np.sqrt(d))
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4, atol=1e-4)


def test_causal_attention_gqa_and_offset():
    rng = np.random.default_rng(4)
    b, t, hq, hkv, d, s = 1, 1, 8, 2, 4, 10
    offset = 6  # decode step at position 6; cache has 7 valid slots after write
    q = rng.normal(size=(b, t, hq, d)).astype(np.float32)
    k = rng.normal(size=(b, s, hkv, d)).astype(np.float32)
    v = rng.normal(size=(b, s, hkv, d)).astype(np.float32)
    got = causal_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(offset), 0.5
    )
    # repeat kv to full heads for the naive path
    k_r = np.repeat(k, hq // hkv, axis=2)
    v_r = np.repeat(v, hq // hkv, axis=2)
    ref = _naive_attention(q, k_r, v_r, 0.5, causal_from=offset)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4, atol=1e-4)


def test_sliding_window_attention():
    rng = np.random.default_rng(5)
    b, t, h, d = 1, 6, 2, 4
    q = rng.normal(size=(b, t, h, d)).astype(np.float32)
    k = rng.normal(size=(b, t, h, d)).astype(np.float32)
    v = rng.normal(size=(b, t, h, d)).astype(np.float32)
    got = causal_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(0), 0.5,
        sliding_window=3,
    )
    ref = _naive_attention(q, k, v, 0.5, window=3)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4, atol=1e-4)


def test_softcap_changes_scores():
    rng = np.random.default_rng(6)
    q = jnp.asarray(rng.normal(size=(1, 2, 2, 4)).astype(np.float32)) * 10
    k = jnp.asarray(rng.normal(size=(1, 2, 2, 4)).astype(np.float32)) * 10
    v = jnp.asarray(rng.normal(size=(1, 2, 2, 4)).astype(np.float32))
    plain = causal_attention(q, k, v, jnp.asarray(0), 1.0)
    capped = causal_attention(q, k, v, jnp.asarray(0), 1.0, logit_softcap=5.0)
    assert not np.allclose(np.asarray(plain), np.asarray(capped))


def test_yarn_mscale_conventions():
    """DeepSeek remote-code convention: the cos/sin attention factor is the
    unconditional ratio get(f, mscale=1)/get(f, mscale_all_dim=0), and the
    model-side softmax-scale correction (get(f, mscale_all_dim)**2) fires
    whenever mscale_all_dim is set — so the net logit scale is get(f, mscale)^2
    in every key combination."""
    import math

    from mlx_sharding_tpu.ops.rope import yarn_frequencies, yarn_get_mscale

    f = 40.0
    base = dict(type="yarn", factor=f, original_max_position_embeddings=64,
                beta_fast=32, beta_slow=1)

    def factor_of(**keys):
        _, af = yarn_frequencies(8, 10000.0, {**base, **keys}, 256)
        return af

    g = yarn_get_mscale
    assert math.isclose(factor_of(), g(f, 1.0))
    assert math.isclose(factor_of(mscale=0.707, mscale_all_dim=0.707), 1.0)
    assert math.isclose(
        factor_of(mscale_all_dim=0.707), g(f, 1.0) / g(f, 0.707)
    )
    assert math.isclose(factor_of(mscale=0.8), g(f, 0.8))
    # net check for the mscale_all_dim-only shape: (ratio applied to q AND k)
    # times the model-side correction == reference's get(f, 1)^2
    net = factor_of(mscale_all_dim=0.707) ** 2 * g(f, 0.707) ** 2
    assert math.isclose(net, g(f, 1.0) ** 2)
    # explicit attention_factor overrides the ratio entirely
    assert factor_of(attention_factor=2.5) == 2.5
