"""Qwen3 (per-head Q/K RMSNorm) parity vs HF transformers, plus the fused
pipeline/TP paths inherited from the Llama family."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.loading import load_model

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

TINY = dict(
    vocab_size=160,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=4,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=24,  # decoupled from hidden/heads — Qwen3 signature
    max_position_embeddings=256,
    rms_norm_eps=1e-6,
    rope_theta=10000.0,
    tie_word_embeddings=False,
)


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("tiny_qwen3")
    torch.manual_seed(11)
    cfg = transformers.Qwen3Config(**TINY)
    model = transformers.Qwen3ForCausalLM(cfg)
    model.eval()
    model.save_pretrained(path, safe_serialization=True)
    return path, model


def test_logits_parity_full(hf_checkpoint):
    path, hf = hf_checkpoint
    tokens = [[2, 45, 99, 3, 27, 81, 5, 150]]
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    model, params = load_model(str(path), dtype=jnp.float32)
    assert "q_norm" in params["layers"]
    got, _ = model(
        params, jnp.asarray(tokens, jnp.int32), model.make_cache(1, 16, jnp.float32)
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-3, atol=3e-3)


def test_prefill_equals_decode(hf_checkpoint):
    path, _ = hf_checkpoint
    model, params = load_model(str(path), dtype=jnp.float32)
    tokens = jnp.asarray([[2, 17, 42, 9, 77]], jnp.int32)
    full, _ = model(params, tokens, model.make_cache(1, 16, jnp.float32))
    cache = model.make_cache(1, 16, jnp.float32)
    outs = []
    for i in range(tokens.shape[1]):
        logits, cache = model(params, tokens[:, i : i + 1], cache)
        outs.append(logits[:, 0])
    np.testing.assert_allclose(
        np.asarray(full), np.asarray(jnp.stack(outs, axis=1)), rtol=2e-3, atol=2e-3
    )


def test_two_stage_parity(hf_checkpoint):
    path, hf = hf_checkpoint
    tokens = [[5, 9, 2, 7]]
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    s0, p0 = load_model(str(path), 0, 2, dtype=jnp.float32)
    s1, p1 = load_model(str(path), 2, 4, dtype=jnp.float32)
    h, _ = s0(p0, jnp.asarray(tokens, jnp.int32), s0.make_cache(1, 16, jnp.float32))
    got, _ = s1(p1, h, s1.make_cache(1, 16, jnp.float32))
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-3, atol=3e-3)


def test_fused_pipeline_and_tp(hf_checkpoint):
    from mlx_sharding_tpu.generate import Generator
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

    path, _ = hf_checkpoint
    model, params = load_model(str(path), dtype=jnp.float32)
    prompt = [3, 17, 42, 9]
    ref = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=8)]
    eng = PipelineEngine(
        model, params, make_mesh(pp=2, tp=2), max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=8)]
    assert got == want


def test_attention_bias_variant(tmp_path):
    """Qwen3 fine-tunes may ship attention_bias=true — biases must be
    APPLIED, not just loaded."""
    torch.manual_seed(5)
    cfg = transformers.Qwen3Config(**{**TINY, "attention_bias": True})
    hf = transformers.Qwen3ForCausalLM(cfg)
    # make the biases material so an unapplied-bias bug changes logits
    with torch.no_grad():
        for layer in hf.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.normal_(std=0.5)
    hf.eval()
    hf.save_pretrained(tmp_path, safe_serialization=True)
    tokens = [[4, 9, 2, 91]]
    with torch.no_grad():
        ref = hf(torch.tensor(tokens)).logits.numpy()
    model, params = load_model(str(tmp_path), dtype=jnp.float32)
    assert "q_bias" in params["layers"]
    got, _ = model(
        params, jnp.asarray(tokens, jnp.int32), model.make_cache(1, 8, jnp.float32)
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-3, atol=3e-3)


def test_qwen3_training_specs():
    """llama_param_specs must cover the q/k norm params for the GSPMD
    training path (prune_specs would KeyError otherwise)."""
    from mlx_sharding_tpu.config import Qwen3Config
    from mlx_sharding_tpu.models.qwen3 import Qwen3Model
    from mlx_sharding_tpu.parallel.tp import llama_param_specs, prune_specs

    model = Qwen3Model(Qwen3Config(**{**TINY, "model_type": "qwen3"}))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    specs = prune_specs(llama_param_specs(), params)
    assert "q_norm" in specs["layers"] and "k_norm" in specs["layers"]
