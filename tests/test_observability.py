import http.client
import json

import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.utils.observability import ServingMetrics, _Reservoir, profile_trace


def test_reservoir_percentiles():
    r = _Reservoir(capacity=100)
    for i in range(100):
        r.add(float(i))
    assert abs(r.percentile(50) - 50) <= 2
    assert abs(r.percentile(95) - 95) <= 2


def test_metrics_render():
    m = ServingMetrics()
    m.record_request(prompt_tokens=10, generation_tokens=20, ttft_s=0.5, decode_tps=40.0)
    m.record_failure()
    out = m.render()
    assert "mst_requests_total 2" in out
    assert "mst_requests_failed_total 1" in out
    assert "mst_generation_tokens_total 20" in out
    assert 'mst_decode_tokens_per_second{quantile="0.5"} 40.000' in out


def test_profile_trace_noop():
    with profile_trace(None):
        pass  # must not require jax


def test_metrics_endpoint(tmp_path):
    """/metrics live on the server after a request."""
    import threading

    import jax
    import jax.numpy as jnp

    from mlx_sharding_tpu.config import LlamaConfig
    from mlx_sharding_tpu.generate import Generator
    from mlx_sharding_tpu.models.llama import LlamaModel
    from mlx_sharding_tpu.server.openai_api import ModelProvider, make_server
    from tests.test_tokenizer_utils import ByteTokenizer

    model = LlamaModel(
        LlamaConfig(
            vocab_size=300, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        )
    )
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    gen = Generator(model, params, max_seq=128, cache_dtype=jnp.float32, prefill_chunk=16)
    provider = ModelProvider.__new__(ModelProvider)
    provider.default_model = "tiny"
    provider.trust_remote_paths = False
    provider._key = None
    provider._load_lock = threading.Lock()
    provider._set("tiny", gen, ByteTokenizer())
    srv = make_server(provider, "127.0.0.1", 0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request(
            "POST", "/v1/completions",
            json.dumps({"prompt": "hi", "max_tokens": 5}),
            {"Content-Type": "application/json"},
        )
        conn.getresponse().read()
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode()
        assert resp.status == 200
        assert "mst_requests_total 1" in body
        assert "mst_generation_tokens_total 5" in body
        conn.close()
    finally:
        srv.shutdown()


def test_metrics_expose_batcher_slots():
    """/metrics reports slot occupancy and queue depth when the server runs
    a ContinuousBatcher (stats() contract; the real batcher integration is
    covered by the scheduler/server suites)."""
    from mlx_sharding_tpu.utils.observability import ServingMetrics

    class _FakeBatcher:
        def stats(self):
            return (2, 1, 3)

    text = ServingMetrics(batcher_fn=lambda: _FakeBatcher()).render()
    assert "mst_batch_slots 2" in text
    assert "mst_batch_slots_active 1" in text
    assert "mst_batch_queue_depth 3" in text
    # and none of it when no batcher is live
    assert "mst_batch_slots" not in ServingMetrics().render()


def test_metrics_expose_tick_timing():
    """/metrics reports the scheduler path (sync vs async tick pipeline)
    and the per-tick host/device-blocked split (tick_timing_stats()
    contract)."""
    from mlx_sharding_tpu.utils.observability import ServingMetrics

    class _FakeBatcher:
        def stats(self):
            return (2, 1, 0)

        def tick_timing_stats(self):
            return {
                "path": "async",
                "host_ms_last": 1.25,
                "device_blocked_ms_last": 0.5,
                "host_ms_avg": 1.0,
                "device_blocked_ms_avg": 0.75,
                "ticks": 7,
            }

    text = ServingMetrics(batcher_fn=lambda: _FakeBatcher()).render()
    assert "mst_sched_async 1" in text
    assert 'mst_tick_host_ms{path="async"} 1.250' in text
    assert 'mst_tick_device_blocked_ms{path="async"} 0.500' in text

    class _SyncBatcher(_FakeBatcher):
        def tick_timing_stats(self):
            return dict(_FakeBatcher.tick_timing_stats(self), path="sync")

    text = ServingMetrics(batcher_fn=lambda: _SyncBatcher()).render()
    assert "mst_sched_async 0" in text
    assert 'mst_tick_host_ms{path="sync"} 1.250' in text

    class _NoTickBatcher:
        def stats(self):
            return (2, 1, 0)

    # a batcher without the accessor (or a plain fake) emits no tick gauges
    text = ServingMetrics(batcher_fn=lambda: _NoTickBatcher()).render()
    assert "mst_tick_host_ms" not in text
    assert "mst_sched_async" not in text

def test_metrics_expose_kv_residency_and_prefetch():
    """/metrics reports the proactive-residency split: cold-spill/wake
    activity, tier lookup quality, reject reasons, the prefetch-vs-demand
    resume counters, and the per-tick kv_import stall gauge
    (spill_stats() / tick_timing_stats() contracts)."""
    from mlx_sharding_tpu.utils.observability import ServingMetrics

    class _FakeBatcher:
        def stats(self):
            return (2, 1, 0)

        def spill_stats(self):
            return {
                "enabled": True, "spills": 4, "spill_hits": 3,
                "spill_fallbacks": 1, "evictions": 0, "bytes_in_use": 1024,
                "budget_bytes": 4096, "migrations_out": 0,
                "migrations_in": 0, "reprefill_tokens": 7,
                "cold_spills": 5, "cold_wakes": 4, "parked": 2,
                "hit_rate": 0.875, "rejects_oversize": 1,
                "rejects_closed": 2, "prefetch_enabled": True,
                "prefetches": 4, "prefetch_hits": 3, "demand_imports": 1,
                "prefetch_faults": 1,
            }

        def tick_timing_stats(self):
            return {
                "path": "async", "host_ms_last": 1.0,
                "device_blocked_ms_last": 0.5, "host_ms_avg": 1.0,
                "device_blocked_ms_avg": 0.5, "ticks": 3,
                "kv_import_ms_last": 2.125,
            }

    text = ServingMetrics(batcher_fn=lambda: _FakeBatcher()).render()
    assert "mst_kv_spill_cold_total 5" in text
    assert "mst_kv_spill_wakes_total 4" in text
    assert "mst_kv_spill_parked 2" in text
    assert "mst_kv_spill_hit_rate 0.8750" in text
    assert 'mst_kv_spill_rejects_total{reason="oversize"} 1' in text
    assert 'mst_kv_spill_rejects_total{reason="closed"} 2' in text
    assert "mst_kv_prefetch_enabled 1" in text
    assert "mst_kv_prefetch_total 4" in text
    assert "mst_kv_prefetch_hits_total 3" in text
    assert "mst_kv_prefetch_demand_total 1" in text
    assert "mst_kv_prefetch_faults_total 1" in text
    assert 'mst_tick_device_blocked_ms{path="kv_import"} 2.125' in text

    class _LegacySpill(_FakeBatcher):
        # a ReplicaSet aggregation that predates the residency keys
        def spill_stats(self):
            s = _FakeBatcher.spill_stats(self)
            for k in ("cold_spills", "cold_wakes", "parked", "hit_rate",
                      "rejects_oversize", "rejects_closed",
                      "prefetch_enabled", "prefetches", "prefetch_hits",
                      "demand_imports", "prefetch_faults"):
                del s[k]
            return s

        def tick_timing_stats(self):
            t = _FakeBatcher.tick_timing_stats(self)
            del t["kv_import_ms_last"]
            return t

    text = ServingMetrics(batcher_fn=lambda: _LegacySpill()).render()
    assert "mst_kv_spill_cold_total 0" in text
    assert "mst_kv_prefetch_enabled 0" in text
    assert 'mst_tick_device_blocked_ms{path="kv_import"} 0.000' in text


def _rich_metrics():
    """A ServingMetrics wired with every accessor the renderer reads,
    all returning data — the widest exposition we can produce offline."""
    from mlx_sharding_tpu.prefix_store import PrefixStore
    from mlx_sharding_tpu.utils.observability import (
        HANDOFF_BUCKETS_MS, ITL_BUCKETS_S, LATENCY_BUCKETS_S, Histogram,
        ServingMetrics,
    )

    itl = Histogram(ITL_BUCKETS_S)
    itl.observe(0.01)
    qw = Histogram(LATENCY_BUCKETS_S)
    qw.observe(0.2)
    hand = Histogram(HANDOFF_BUCKETS_MS)
    hand.observe(3.0)

    class _Batcher:
        def stats(self):
            return (2, 1, 3)

        def tick_timing_stats(self):
            return {"path": "async", "host_ms_last": 1.0,
                    "device_blocked_ms_last": 0.5, "host_ms_avg": 1.0,
                    "device_blocked_ms_avg": 0.5, "ticks": 3,
                    "kv_import_ms_last": 2.0}

        def spill_stats(self):
            return {"enabled": True, "spills": 4, "spill_hits": 3,
                    "spill_fallbacks": 1, "evictions": 0,
                    "bytes_in_use": 1024, "budget_bytes": 4096,
                    "migrations_out": 1, "migrations_in": 1,
                    "reprefill_tokens": 7, "cold_spills": 5,
                    "cold_wakes": 4, "parked": 2, "hit_rate": 0.875,
                    "rejects_oversize": 1, "rejects_closed": 2,
                    "prefetch_enabled": True, "prefetches": 4,
                    "prefetch_hits": 3, "demand_imports": 1,
                    "prefetch_faults": 1}

        def latency_stats(self):
            return {"itl": itl.to_dict(), "queue_wait": qw.to_dict()}

        def fleet_stats(self):
            return {"size": 2, "sticky_hits": 1, "affinity_hits": 2,
                    "store_hits": 3}

        def handoff_stats(self):
            return {"handoffs": 4, "bytes_total": 100, "ms_p50": 1.0,
                    "ms_p99": 2.0, "fallbacks": {"handoff_fault": 1},
                    "store_skips": 5, "ms_hist": hand.to_dict()}

    store = PrefixStore(host_bytes=1 << 20)
    m = ServingMetrics(batcher_fn=lambda: _Batcher(),
                       prefix_store_fn=lambda: store)
    m.record_request(prompt_tokens=10, generation_tokens=20, ttft_s=0.5,
                     decode_tps=40.0)
    m.record_failure()
    return m, store


def test_metrics_help_type():
    """Exposition coverage contract: EVERY sample family in the widest
    render carries ``# HELP`` and ``# TYPE`` ahead of its first sample,
    histogram suffixes (_bucket/_sum/_count) resolve to a family declared
    ``histogram``, and the latency families render as real cumulative
    histograms."""
    m, store = _rich_metrics()
    try:
        text = m.render()
    finally:
        store.close()
    helped, typed, hist = set(), {}, set()
    for ln in text.splitlines():
        if ln.startswith("# HELP "):
            helped.add(ln.split()[2])
            continue
        if ln.startswith("# TYPE "):
            fam = ln.split()[2]
            assert fam in helped, f"# TYPE {fam} without a preceding # HELP"
            assert fam not in typed, f"duplicate # TYPE for {fam}"
            typed[fam] = ln.split()[3]
            if typed[fam] == "histogram":
                hist.add(fam)
            continue
        if not ln or ln.startswith("#"):
            continue
        name = ln.split("{", 1)[0].split(" ", 1)[0]
        fam = name
        for sfx in ("_bucket", "_sum", "_count"):
            if name.endswith(sfx) and name[: -len(sfx)] in hist:
                fam = name[: -len(sfx)]
        assert fam in typed, f"sample {name} has no # TYPE"
        assert fam in helped, f"sample {name} has no # HELP"
    # the histogram-grade latency families are really histograms
    for fam in ("mst_ttft_seconds", "mst_itl_seconds",
                "mst_queue_wait_seconds", "mst_disagg_handoff_ms"):
        assert typed.get(fam) == "histogram", f"{fam} should be a histogram"
        assert f'{fam}_bucket{{le="+Inf"}}' in text
        assert f"{fam}_sum " in text and f"{fam}_count " in text
    # counters follow the Prometheus naming/type convention
    for fam, ty in typed.items():
        if fam.endswith("_total"):
            assert ty == "counter", f"{fam} typed {ty}, want counter"


def test_metrics_render_never_500():
    """Every accessor raising at scrape time still yields a parseable
    exposition with the core request counters — a sick engine must not
    take down the monitoring that would diagnose it."""
    from mlx_sharding_tpu.utils.observability import ServingMetrics

    def _boom():
        raise RuntimeError("accessor gone")

    class _BrokenBatcher:
        def __getattr__(self, name):
            def method(*a, **kw):
                raise RuntimeError("batcher gone")
            return method

    for m in (
        ServingMetrics(batcher_fn=_boom, prefix_store_fn=_boom),
        ServingMetrics(batcher_fn=lambda: _BrokenBatcher()),
    ):
        m.record_request(prompt_tokens=1, generation_tokens=1, ttft_s=0.1,
                         decode_tps=1.0)
        text = m.render()
        assert "mst_requests_total 1" in text


def test_metrics_expose_itl_and_queue_wait_histograms():
    """The scheduler's latency_stats() contract flows to /metrics as
    cumulative bucketed histograms; a batcher without the accessor (or a
    fleet with nothing recorded) emits neither family."""
    from mlx_sharding_tpu.utils.observability import (
        ITL_BUCKETS_S, LATENCY_BUCKETS_S, Histogram, ServingMetrics,
    )

    itl = Histogram(ITL_BUCKETS_S)
    for v in (0.004, 0.009, 2.0):
        itl.observe(v)
    qw = Histogram(LATENCY_BUCKETS_S)
    qw.observe(0.03)

    class _B:
        def stats(self):
            return (2, 1, 0)

        def latency_stats(self):
            return {"itl": itl.to_dict(), "queue_wait": qw.to_dict()}

    text = ServingMetrics(batcher_fn=lambda: _B()).render()
    assert 'mst_itl_seconds_bucket{le="0.005"} 1' in text
    assert 'mst_itl_seconds_bucket{le="+Inf"} 3' in text
    assert "mst_itl_seconds_count 3" in text
    assert 'mst_queue_wait_seconds_bucket{le="' in text
    assert "mst_queue_wait_seconds_count 1" in text

    class _NoLat:
        def stats(self):
            return (2, 1, 0)

    text = ServingMetrics(batcher_fn=lambda: _NoLat()).render()
    assert "mst_itl_seconds" not in text
    assert "mst_queue_wait_seconds" not in text


def test_metrics_expose_prefix_store():
    """/metrics reports the fleet-wide prefix store family — residency by
    tier, lookup quality, COW forks, insertion damping, eviction reasons —
    against a REAL PrefixStore so the renderer's key reads stay in lock-step
    with stats(); plus the routing/disagg counters and the never-500 rule."""
    from mlx_sharding_tpu.prefix_store import PrefixStore
    from mlx_sharding_tpu.utils.observability import ServingMetrics

    store = PrefixStore(host_bytes=1 << 20)
    try:
        text = ServingMetrics(prefix_store_fn=lambda: store).render()
        assert 'mst_prefix_store_blocks{tier="device"} 0' in text
        assert 'mst_prefix_store_blocks{tier="host"} 0' in text
        assert 'mst_prefix_store_bytes{tier="host"} 0' in text
        assert f"mst_prefix_store_budget_bytes {1 << 20}" in text
        assert 'mst_prefix_store_hits_total{tier="device"} 0' in text
        assert 'mst_prefix_store_hits_total{tier="host"} 0' in text
        assert "mst_prefix_store_misses_total 0" in text
        assert "mst_prefix_store_hit_rate 0.0000" in text
        assert "mst_prefix_store_tokens_reused_total 0" in text
        assert "mst_prefix_store_cow_forks_total 0" in text
        assert "mst_prefix_store_inserts_total 0" in text
        assert "mst_prefix_store_inserts_damped_total 0" in text
        assert "mst_prefix_store_inserts_paused 0" in text
        assert "mst_prefix_store_demotions_total 0" in text
        assert "mst_prefix_store_demote_drops_total 0" in text
        assert 'mst_prefix_store_evictions_total{reason="budget"} 0' in text
        assert 'mst_prefix_store_evictions_total{reason="oversize"} 0' in text
        assert 'mst_prefix_store_evictions_total{reason="reset"} 0' in text
        assert 'mst_prefix_store_imports_total{kind="staged"} 0' in text
        assert 'mst_prefix_store_imports_total{kind="demand"} 0' in text
        assert 'mst_prefix_store_faults_total{kind="lookup"} 0' in text
        assert 'mst_prefix_store_faults_total{kind="import"} 0' in text
    finally:
        store.close()

    # no store wired -> no family
    assert "mst_prefix_store_" not in ServingMetrics().render()

    # a broken accessor must not 500 the scrape
    def _boom():
        raise RuntimeError("store gone")

    text = ServingMetrics(prefix_store_fn=_boom).render()
    assert "mst_requests_total" in text
    assert "mst_prefix_store_" not in text

    # routing + disagg counters ride the existing fleet/handoff blocks
    class _FakeFleet:
        def stats(self):
            return (2, 1, 0)

        def fleet_stats(self):
            return {"size": 2, "sticky_hits": 1, "affinity_hits": 2,
                    "store_hits": 3}

        def handoff_stats(self):
            return {"handoffs": 4, "bytes_total": 100, "ms_p50": 1.0,
                    "ms_p99": 2.0, "fallbacks": {}, "store_skips": 5}

    text = ServingMetrics(batcher_fn=lambda: _FakeFleet()).render()
    assert "mst_route_store_hits_total 3" in text
    assert "mst_disagg_store_skips_total 5" in text

    class _OldFleet(_FakeFleet):
        # pre-store aggregations lack the new keys -> lines stay absent
        def fleet_stats(self):
            f = _FakeFleet.fleet_stats(self)
            del f["store_hits"]
            return f

        def handoff_stats(self):
            h = _FakeFleet.handoff_stats(self)
            del h["store_skips"]
            return h

    text = ServingMetrics(batcher_fn=lambda: _OldFleet()).render()
    assert "mst_route_store_hits_total" not in text
    assert "mst_disagg_store_skips_total" not in text
