"""Dynamic Eraser-style locksets vs the static MST50x race verdicts.

``analysis.runtime.enable_locksets()`` arms a recorder; ``watch_attrs``
swaps an instance's class for a shim whose ``__setattr__`` reports every
attribute write with the writing thread's *role* (the MST50x registry
keyed by thread name) and the instrumented locks it holds. Driving real
control-plane code under it yields per-``Cls.attr`` observations in the
same shape as ``analyze_paths(...).race_verdicts`` — so the two halves
can be compared key by key, the same static-vs-dynamic contract
``test_lock_order_dynamic.py`` enforces for lock ordering:

- an attr the recorder proves racy (written from two roles, candidate
  lockset emptied) must NOT carry a ``clean`` static verdict;
- the load-bearing overlap — ``FleetAutoscaler.ticks`` written from the
  ``api`` and ``autoscaler`` roles under ``FleetAutoscaler._lock`` — is
  observed dynamically with exactly the lockset the static pass computed.
"""

import threading
from pathlib import Path

import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.analysis import runtime as mst_runtime
from mlx_sharding_tpu.analysis.core import analyze_paths
from mlx_sharding_tpu.fleet import FleetAutoscaler
from mlx_sharding_tpu.replicas import ReplicaSet

PACKAGE = Path(__file__).resolve().parent.parent / "mlx_sharding_tpu"


class _Stub:
    concurrent = True

    def generate_step(self, prompt_tokens, **kw):
        yield from [(t, None) for t in (1, 2, 3)]

    def stats(self):
        return 1, 0, 0

    def close(self):
        pass


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _Unguarded:
    def __init__(self):
        self.n = 0


class _Guarded:
    def __init__(self):
        self.n = 0


def _on_named_thread(name: str, fn):
    """Run ``fn`` on a thread carrying a registered role name — the same
    attribution path a production ``Thread(name=...)`` gets."""
    exc: list = []

    def _run():
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            exc.append(e)

    t = threading.Thread(target=_run, name=name, daemon=True)
    t.start()
    t.join(30)
    assert not t.is_alive(), f"{name} thread wedged"
    if exc:
        raise exc[0]


@pytest.fixture(scope="module")
def static_verdicts():
    return analyze_paths([str(PACKAGE)], baseline=None).race_verdicts


def test_recorder_flags_unguarded_cross_role_write():
    mst_runtime.enable_tracing()
    rec = mst_runtime.enable_locksets()
    try:
        bad = mst_runtime.watch_attrs(_Unguarded())
        good = mst_runtime.watch_attrs(_Guarded())
        glock = mst_runtime.make_lock("_Guarded.lock")
        bad.n = 1
        with glock:
            good.n = 1
        def tick_side():
            bad.n = 2
            with glock:
                good.n = 2

        _on_named_thread("continuous-batcher", tick_side)
        obs = rec.observations()
        assert obs["_Unguarded.n"]["racy"], obs
        assert set(obs["_Unguarded.n"]["roles"]) == {"api", "tick"}
        assert not obs["_Guarded.n"]["racy"], obs
        assert obs["_Guarded.n"]["lockset"] == ["_Guarded.lock"]
    finally:
        mst_runtime.disable_locksets()
        mst_runtime.disable_tracing()


def test_watch_attrs_is_a_noop_when_disarmed():
    c = _Unguarded()
    assert mst_runtime.watch_attrs(c) is c
    assert type(c) is _Unguarded


def test_autoscaler_observations_agree_with_static(static_verdicts):
    # locks constructed AFTER enable_tracing are instrumented — they feed
    # the held-stack the lockset recorder snapshots at each write
    mst_runtime.enable_tracing()
    rec = mst_runtime.enable_locksets()
    try:
        rs = ReplicaSet([_Stub(), _Stub()])
        auto = mst_runtime.watch_attrs(
            FleetAutoscaler(rs, None, clock=_FakeClock()))
        auto.tick()                                   # api role
        _on_named_thread("mst-autoscaler", auto.tick)  # autoscaler role
        obs = rec.observations()
    finally:
        mst_runtime.disable_locksets()
        mst_runtime.disable_tracing()

    # the overlap has teeth: the tick counter was genuinely written from
    # both roles, under the exact lock the static pass computed
    ticks = obs.get("FleetAutoscaler.ticks")
    assert ticks is not None, sorted(obs)
    assert set(ticks["roles"]) >= {"api", "autoscaler"}
    assert not ticks["racy"]
    assert ticks["lockset"] == ["FleetAutoscaler._lock"]
    sv = static_verdicts.get("FleetAutoscaler.ticks")
    assert sv is not None and sv["verdict"] == "clean", sv
    assert sv["lockset"] == ticks["lockset"]

    # the contract: nothing observed racy at runtime may be statically
    # certified clean (keys the static pass never saw are fine — test
    # locals, attrs only reachable through containers)
    for key, o in obs.items():
        if o["racy"]:
            sv = static_verdicts.get(key)
            assert sv is None or sv["verdict"] != "clean", (key, o, sv)


def test_composed_sim_run_agrees_with_static(static_verdicts):
    """Criterion with teeth: a composed disagg + shared-prefix +
    autoscaler fleet-sim run (cross-host handoffs, a mid-run host kill)
    with the control-plane objects under ``watch_attrs`` — no attribute
    may be dynamically observed racy while statically certified clean."""
    from mlx_sharding_tpu.sim.fleetsim import build_fleet
    from mlx_sharding_tpu.sim.simkit import Simulation

    mst_runtime.enable_tracing()
    rec = mst_runtime.enable_locksets()
    try:
        sim = Simulation(seed=11)
        fs = build_fleet(sim, n_hosts=2, horizon_s=12.0)
        for host in fs.hosts:
            mst_runtime.watch_attrs(host.rs)
            mst_runtime.watch_attrs(host.ctrl)
            mst_runtime.watch_attrs(host.fleet)
        for i in range(6):
            fs.submit(f"r{i}", [1, 2, 3, i], 6, host=i % 2,
                      cross_host=(i % 3 == 0), two_phase=(i % 2 == 1),
                      shared_prefix=True)
        sim.schedule(5.0, lambda: fs.kill_host(1))
        sim.run()
        # the sim drives every periodic tick from its driver thread; one
        # more autoscaler tick from the production thread role makes the
        # control-plane counters genuinely cross-thread (Eraser's shared
        # phase) so their locksets are actually intersected
        _on_named_thread("mst-autoscaler", fs.hosts[0].ctrl.tick)
        obs = rec.observations()
        sim.close()
    finally:
        mst_runtime.disable_locksets()
        mst_runtime.disable_tracing()

    assert obs, "composed run produced no shared-write observations"
    ticks = obs.get("FleetAutoscaler.ticks")
    assert ticks is not None and not ticks["racy"], ticks
    assert ticks["lockset"] == ["FleetAutoscaler._lock"]
    for key, o in obs.items():
        if o["racy"]:
            sv = static_verdicts.get(key)
            assert sv is None or sv["verdict"] != "clean", (key, o, sv)
