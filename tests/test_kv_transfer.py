"""KV page-block migration (ISSUE 6): spill-don't-discard preemption,
graceful replica drain, and crash-safe re-placement.

The load-bearing property everywhere: a stream that gets preempted,
migrated, or crash-failed-over must deliver EXACTLY the tokens the
uninterrupted run would — greedy streams are compared bit-for-bit against
a solo reference. Failure injection at the three new sites
(``cache.export`` / ``cache.import`` / ``replica.drain``) must degrade to
the legacy discard/re-prefill behavior, never wedge or drop a stream.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.cache import KVCache
from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.kv_transfer import (
    BlockIntegrityError,
    KVPageBlock,
    KVSpillTier,
    export_block,
    import_block,
)
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh, pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.replicas import ReplicaSet
from mlx_sharding_tpu.resilience import RequestMigratedError, ResumeState
from mlx_sharding_tpu.scheduler import ContinuousBatcher
from mlx_sharding_tpu.testing import faults
from tests.helpers import hard_timeout, run_concurrent

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


# ------------------------------------------------------------ block units
def _pool_cache(pool_pages=6, page=4, int8=False):
    """A hand-built paged cache in the engine's pool layout
    ``(S, L, pool_pages+1, B, page, H, D)`` with distinct values per cell
    so gather/scatter mistakes show up as value mismatches."""
    shape = (1, 2, pool_pages + 1, 1, page, 2, 4)
    vals = jnp.arange(np.prod(shape), dtype=jnp.float32).reshape(shape)
    if int8:
        k = {"d": (vals % 127).astype(jnp.int8),
             "s": jnp.ones(shape[:-1] + (1,), jnp.float32)}
        v = {"d": ((vals + 3) % 127).astype(jnp.int8),
             "s": jnp.ones(shape[:-1] + (1,), jnp.float32)}
    else:
        k, v = vals, vals + 1000.0
    return KVCache(k=k, v=v, offset=jnp.zeros((), jnp.int32))


def _export(cache, pages=(2, 4), n_tokens=6, history=(5, 6, 7)):
    return export_block(
        cache, list(pages), page_size=4, n_tokens=n_tokens,
        prompt=[1, 2, 3], history=list(history), produced=len(history),
        resume_keys=None, resume_recent=None,
    )


@pytest.mark.parametrize("int8", [False, True])
def test_block_roundtrip_bitexact(int8):
    """export → to_host → verify → import into different pool pages is a
    bit-exact move for both the bf16 and the int8 (codes+scales) pools."""
    src = _pool_cache(int8=int8)
    blk = _export(src).to_host()
    assert blk.is_host and blk.n_pages == 2 and blk.nbytes > 0
    blk.verify()

    dst = KVCache(
        k=jax.tree.map(jnp.zeros_like, src.k),
        v=jax.tree.map(jnp.zeros_like, src.v),
        offset=jnp.zeros((), jnp.int32),
    )
    out = import_block(dst, blk, [1, 3])
    for leaf_src, leaf_out in zip(
        jax.tree.leaves((src.k, src.v)), jax.tree.leaves((out.k, out.v))
    ):
        np.testing.assert_array_equal(
            np.asarray(leaf_src)[:, :, [2, 4]],
            np.asarray(leaf_out)[:, :, [1, 3]],
        )


def test_block_tamper_and_degenerate_shapes_rejected():
    blk = _export(_pool_cache()).to_host()
    blk.k_pages = jax.tree.map(np.array, blk.k_pages)  # writable copy
    jax.tree.leaves(blk.k_pages)[0].flat[0] += 1  # corrupt one element
    with pytest.raises(BlockIntegrityError, match="checksum"):
        blk.verify()
    with pytest.raises(BlockIntegrityError, match="pages"):
        _export(_pool_cache(), n_tokens=99).verify()
    hollow = _export(_pool_cache())
    hollow.history = []
    with pytest.raises(BlockIntegrityError, match="history"):
        hollow.verify()


def test_cross_mode_and_geometry_imports_rejected():
    """An int8 block can never scatter into a bf16 pool (and vice versa),
    and a page-count mismatch is caught before any device write."""
    blk = _export(_pool_cache(int8=True)).to_host()
    pool = _pool_cache(int8=False)
    assert "mismatch" in blk.compatible_with(pool)
    with pytest.raises(BlockIntegrityError, match="mismatch"):
        import_block(pool, blk, [1, 3])
    ok = _export(_pool_cache()).to_host()
    with pytest.raises(BlockIntegrityError, match="pages"):
        import_block(_pool_cache(), ok, [1])  # block carries 2 pages


def test_export_import_fault_sites_fire():
    cache = _pool_cache()
    faults.arm("cache.export", exc=faults.FaultError, times=1)
    with pytest.raises(faults.FaultError):
        _export(cache)
    blk = _export(cache).to_host()  # times exhausted: export works again
    faults.arm("cache.import", exc=faults.FaultError, times=1)
    with pytest.raises(faults.FaultError):
        import_block(cache, blk, [1, 3])
    import_block(cache, blk, [1, 3])


# ------------------------------------------------------------- spill tier
def _fake_block(nbytes):
    payload = np.zeros(nbytes // 2, np.uint8)
    return KVPageBlock(
        k_pages=payload, v_pages=payload.copy(), n_tokens=1, page_size=4,
        prompt=np.array([1], np.int32), history=[7], produced=1, last_tok=7,
        resume_keys=None, resume_recent=None,
    )


def test_spill_tier_lru_budget_and_rejects():
    tier = KVSpillTier(100, flush_async=False)
    a, b, c = object(), object(), object()
    assert tier.put(a, _fake_block(40)) and tier.put(b, _fake_block(40))
    tier.put(a, tier.take(a))          # refresh: a becomes MRU
    assert tier.put(c, _fake_block(40))  # evicts b (LRU), not a
    assert tier.take(b) is None and tier.evictions == 1
    assert tier.contains(a) and tier.peek(c) is not None
    assert not tier.put(object(), _fake_block(200))  # alone over budget
    assert tier.rejects == 1
    s = tier.stats()
    assert s["blocks"] == 2 and s["bytes_in_use"] == 80
    assert s["budget_bytes"] == 100 and s["bytes_spilled_total"] == 160
    tier.close()
    assert not tier.put(object(), _fake_block(10))  # closed: reject
    with pytest.raises(ValueError):
        KVSpillTier(0)


def test_spill_tier_flusher_moves_block_to_host():
    tier = KVSpillTier(1 << 20)
    blk = _export(_pool_cache())
    assert not blk.is_host
    assert tier.put("req", blk)
    deadline = time.monotonic() + 10
    while not blk.is_host and time.monotonic() < deadline:
        time.sleep(0.01)
    assert blk.is_host and tier.take("req") is blk
    tier.close()


# ------------------------------------------- dispatcher re-placement (stubs)
class _ResumeStub:
    """Replica that can continue a migrated stream: emits the fixed tail of
    ``script`` starting at the resume state's ``produced`` offset."""

    concurrent = True
    supports_resume = True

    def __init__(self, script=(1, 2, 3, 4, 5)):
        self.script = list(script)
        self.resumes = []

    def generate_step(self, prompt_tokens, _resume=None, **kw):
        self.resumes.append(_resume)
        start = _resume.produced if _resume is not None else 0
        yield from [(t, None) for t in self.script[start:]]


class _MigratingStub:
    """Emits two tokens then ends the stream with RequestMigratedError, the
    way a draining batcher does."""

    concurrent = True
    supports_resume = True

    def generate_step(self, prompt_tokens, _resume=None, **kw):
        yield (1, None)
        yield (2, None)
        raise RequestMigratedError(ResumeState(
            prompt=np.asarray(prompt_tokens, np.int32),
            history=[1, 2], produced=2,
        ))


class _CrashStub:
    concurrent = True

    def generate_step(self, prompt_tokens, **kw):
        yield (1, None)
        yield (2, None)
        raise RuntimeError("replica died mid-stream")


@hard_timeout(60)
def test_dispatcher_replaces_migrated_stream_seamlessly():
    r1 = _ResumeStub()
    rs = ReplicaSet([_MigratingStub(), r1])
    assert [t for t, _ in rs.generate_step([9, 9])] == [1, 2, 3, 4, 5]
    state = r1.resumes[0]
    assert state is not None and state.produced == 2
    assert state.history == [1, 2]
    assert rs.migrated_streams == 1
    assert rs.failures[0] == 0  # migration is not a breaker strike


@hard_timeout(60)
def test_dispatcher_rebuilds_state_on_generic_crash():
    """A replica that dies mid-stream (no migration protocol) still hands
    the stream over: the dispatcher rebuilds a blockless ResumeState from
    its own record of delivered tokens."""
    r1 = _ResumeStub()
    rs = ReplicaSet([_CrashStub(), r1])
    assert [t for t, _ in rs.generate_step([9, 9])] == [1, 2, 3, 4, 5]
    state = r1.resumes[0]
    assert state.produced == 2 and state.history == [1, 2]
    assert state.block is None and state.resume_keys is None
    assert rs.failures[0] == 1  # a crash IS a breaker strike
    assert rs.migrated_streams == 1


@hard_timeout(60)
def test_crash_resume_disabled_raises_mid_stream():
    rs = ReplicaSet([_CrashStub(), _ResumeStub()], resume_streams=False)
    with pytest.raises(RuntimeError, match="died mid-stream"):
        list(rs.generate_step([9, 9]))


# ----------------------------------------------------- drain (stub replicas)
class _DrainableStub(_ResumeStub):
    def __init__(self, script=(1, 2, 3)):
        super().__init__(script)
        self.migrations = 0
        self.closed = False

    def migrate_out(self, deadline=30.0):
        self.migrations += 1
        return 2

    def close(self):
        self.closed = True


@hard_timeout(60)
def test_drain_lifecycle_and_validation():
    r0, r1 = _DrainableStub(), _DrainableStub()
    rs = ReplicaSet([r0, r1])
    out = rs.drain(0)
    assert out == {"replica": 0, "migrated": 2, "closed": True}
    assert r0.closed and r0.migrations == 1 and rs.drains == 1
    h = rs.health()
    assert h["replicas_retired"] == 1 and h["replicas"][0]["state"] == "retired"
    assert h["status"] == "ok" and h["serving"]  # 1 expected, 1 live
    # retired replica gets no traffic
    assert [t for t, _ in rs.generate_step([5])] == [1, 2, 3]
    assert len(r0.resumes) == 0 and len(r1.resumes) == 1
    # idempotent re-drain, and the last live replica is protected
    assert rs.drain(0)["already_retired"]
    with pytest.raises(ValueError, match="last live"):
        rs.drain(1)
    with pytest.raises(ValueError, match="replica index"):
        rs.drain(7)
    with pytest.raises(ValueError, match="replica index"):
        rs.drain(True)


@hard_timeout(60)
def test_drain_fault_quarantines_replica_then_retry_succeeds():
    """An injected ``replica.drain`` failure leaves the replica quarantined
    — out of routing but unclosed, streams intact — and a retried drain()
    completes the retirement."""
    r0, r1 = _DrainableStub(), _DrainableStub()
    rs = ReplicaSet([r0, r1])
    faults.arm("replica.drain", exc=faults.FaultError, times=1)
    with pytest.raises(faults.FaultError):
        rs.drain(0)
    assert not r0.closed and rs.drains == 0
    h = rs.health()
    assert h["status"] == "draining"
    assert h["replicas"][0]["state"] == "draining"
    assert [t for t, _ in rs.generate_step([5])] == [1, 2, 3]
    assert len(r1.resumes) == 1  # quarantined r0 got no traffic
    out = rs.drain(0)  # retry: fault exhausted, drain completes
    assert out["closed"] and r0.closed and rs.drains == 1


# --------------------------------------------- spill ↔ resume (real engine)
def _spill_batcher(pool_pages=8, spill_bytes=64 << 20, kv_dtype=None,
                   async_sched="auto", overcommit=True, **kw):
    """8-page pool where each request's full need is 6 pages: two can never
    be co-resident, so over-commit preempts under pressure — with a spill
    tier, preemption exports the victim's block instead of discarding."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
        pool_pages=pool_pages, page_size=8, kv_dtype=kv_dtype,
    )
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
    )
    batcher = ContinuousBatcher(
        eng, decode_block=3, overcommit=overcommit, spill_bytes=spill_bytes,
        async_sched=async_sched, **kw
    )
    return batcher, ref


SPILL_JOBS = [
    ([7, 7, 2, 1], dict(max_tokens=40)),  # greedy hog, admitted first
    ([9, 4, 4, 6], dict(temperature=0.9, top_p=0.85, seed=321,
                        repetition_penalty=1.3, repetition_context_size=8,
                        max_tokens=36)),
]


@pytest.fixture(scope="module")
def spill_setup():
    batcher, ref = _spill_batcher()
    yield batcher, ref
    batcher.close()


def _refs(ref, jobs):
    return [[t for t, _ in ref.generate_step(p, **kw)] for p, kw in jobs]


def test_spill_preempt_resume_streams_exact(spill_setup):
    """Tentpole parity: with the spill tier on, preempted-then-resumed
    streams (greedy AND seeded-stochastic) are bit-identical to the
    never-preempted solo runs, resumes are served by block re-import
    (spill_hits), and the pool drains fully afterwards."""
    batcher, ref = spill_setup
    refs = _refs(ref, SPILL_JOBS)
    got = run_concurrent(batcher, SPILL_JOBS)
    assert got == refs
    s = batcher.spill_stats()
    assert s["enabled"] and s["preemptions"] > 0
    assert s["spills"] > 0 and s["spill_hits"] > 0
    assert s["spill_fallbacks"] == 0 and s["rejects"] == 0
    total, in_use, _ = batcher.page_stats()
    assert in_use == 0 and s["bytes_in_use"] == 0  # tier drained too
    r = batcher.resilience_stats()
    assert r["spills"] == s["spills"] and r["spill_hits"] == s["spill_hits"]


def test_spill_export_fault_degrades_to_discard_exact(spill_setup):
    """cache.export armed: every spill attempt fails, so preemption falls
    back to yesterday's fold-and-re-prefill — streams still exact."""
    batcher, ref = spill_setup
    before = batcher.spill_stats()
    faults.arm("cache.export", exc=faults.FaultError)
    got = run_concurrent(batcher, SPILL_JOBS)
    faults.disarm()
    assert got == _refs(ref, SPILL_JOBS)
    after = batcher.spill_stats()
    assert after["preemptions"] > before["preemptions"]
    assert after["spills"] == before["spills"]  # no block ever left
    assert after["spill_fallbacks"] > before["spill_fallbacks"]
    assert after["reprefill_tokens"] > before["reprefill_tokens"]


def test_spill_import_fault_degrades_to_reprefill_exact(spill_setup):
    """cache.import armed once: the first resume's block re-import fails
    mid-flight; that request re-prefills from the folded history instead —
    stream content must not change."""
    batcher, ref = spill_setup
    before = batcher.spill_stats()
    faults.arm("cache.import", exc=faults.FaultError, times=1)
    got = run_concurrent(batcher, SPILL_JOBS)
    faults.disarm()
    assert got == _refs(ref, SPILL_JOBS)
    after = batcher.spill_stats()
    assert after["spill_fallbacks"] > before["spill_fallbacks"]
    total, in_use, _ = batcher.page_stats()
    assert in_use == 0  # the failed import released its freshly-held pages


_MATRIX_REFS: dict = {}


def _never_preempted_refs(kv_dtype):
    """The ISSUE's comparison baseline: the same jobs run solo (no pool
    pressure, no over-commit) on the same pool type. The bf16 Generator is
    NOT a valid reference for the int8 pool — quantization drift diverges
    the greedy stream after a few dozen tokens — so the baseline must come
    from an unpreempted run of the pool under test. Memoized: the baseline
    depends only on the pool dtype, not on spill/async settings."""
    if kv_dtype not in _MATRIX_REFS:
        batcher, _ = _spill_batcher(
            pool_pages=16, spill_bytes=None, kv_dtype=kv_dtype,
            overcommit=False,
        )
        try:
            _MATRIX_REFS[kv_dtype] = [
                [t for t, _ in batcher.generate_step(p, **kw)]
                for p, kw in SPILL_JOBS
            ]
            assert batcher.spill_stats()["preemptions"] == 0
        finally:
            batcher.close()
    return _MATRIX_REFS[kv_dtype]


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", [None, "int8"])
@pytest.mark.parametrize("async_sched", ["off", "on"])
@pytest.mark.parametrize("spill", [True, False])
def test_preemption_parity_matrix(kv_dtype, async_sched, spill):
    """Full S3 matrix: {spill, legacy discard} x {bf16, int8 pool} x
    {sync, async scheduling} — greedy + seeded streams all bit-identical
    to the never-preempted run on the same pool."""
    refs = _never_preempted_refs(kv_dtype)
    batcher, _ = _spill_batcher(
        kv_dtype=kv_dtype, async_sched=async_sched,
        spill_bytes=(64 << 20) if spill else None,
    )
    try:
        got = run_concurrent(batcher, SPILL_JOBS)
        assert got == refs
        s = batcher.spill_stats()
        assert s["preemptions"] > 0
        if spill:
            assert s["spills"] > 0 and s["spill_hits"] > 0
        else:
            assert not s["enabled"] and s["spills"] == 0
    finally:
        batcher.close()


@pytest.mark.slow
def test_spill_budget_exhaustion_falls_back_exact():
    """A tier too small for any block rejects every put; preemption
    degrades to discard (rejects counted) and streams stay exact."""
    batcher, ref = _spill_batcher(spill_bytes=64)  # smaller than any block
    try:
        got = run_concurrent(batcher, SPILL_JOBS)
        assert got == _refs(ref, SPILL_JOBS)
        s = batcher.spill_stats()
        assert s["preemptions"] > 0 and s["rejects"] > 0
        assert s["spill_hits"] == 0
        assert s["spill_fallbacks"] > 0
    finally:
        batcher.close()


# ------------------------------------------ drain & failover (real engines)
def _replica_pair():
    """Two single-stage paged batcher replicas with identical pool
    geometry (so drain can move blocks, not just histories)."""
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    devices = jax.devices()
    reps = []
    for i in range(2):
        eng = PipelineEngine(
            model, params, make_mesh(pp=1, devices=devices[i : i + 1]),
            microbatches=2, max_seq=64, cache_dtype=jnp.float32,
            prefill_chunk=8, pool_pages=10, page_size=8,
        )
        reps.append(ContinuousBatcher(eng, decode_block=3))
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    return ReplicaSet(reps), ref


def _drive_drain(rs, ref, *, arm_site=None):
    """One greedy stream lands on replica 0; after its first tokens arrive,
    drain replica 0 while the stream is mid-flight. Returns the collected
    stream and the solo reference."""
    prompt, kw = [3, 17, 42], dict(max_tokens=24)
    want = [t for t, _ in ref.generate_step(prompt, **kw)]
    toks, err = [], []
    started = threading.Event()

    def consume():
        try:
            for t, _ in rs.generate_step(prompt, **kw):
                toks.append(t)
                started.set()
        except Exception as e:  # noqa: BLE001 — assert in main thread
            err.append(e)
            started.set()

    th = threading.Thread(target=consume)
    th.start()
    assert started.wait(60), "stream produced no tokens"
    assert rs.served[0] == 1  # tie-break routed it to replica 0
    if arm_site:
        faults.arm(arm_site, exc=faults.FaultError)
    out = rs.drain(0)
    faults.disarm()
    th.join(timeout=60)
    assert not th.is_alive(), "stream hung across the drain"
    assert not err, err
    return toks, want, out


@hard_timeout(180)
def test_drain_migrates_live_stream_token_exact():
    """Graceful drain: the admitted stream moves to the healthy replica and
    the client sees one uninterrupted, token-exact stream; the drained
    replica retires cleanly with zero dropped requests."""
    rs, ref = _replica_pair()
    try:
        toks, want, out = _drive_drain(rs, ref)
        assert toks == want
        assert out["closed"] and out["migrated"] >= 1
        assert rs.migrated_streams >= 1 and rs.drains == 1
        h = rs.health()
        assert h["replicas_retired"] == 1 and h["status"] == "ok"
        b0 = rs.replicas[0]
        assert b0.resilience_stats()["migrations_out"] >= 1
        assert rs.replicas[1].resilience_stats()["migrations_in"] >= 1
    finally:
        rs.close()


@hard_timeout(180)
def test_drain_survives_export_failure_zero_drops():
    """Acceptance: kill the block export mid-drain (cache.export armed for
    the whole migration) — migration degrades to blockless fold states, the
    stream still completes token-exact on the survivor, nothing drops."""
    rs, ref = _replica_pair()
    try:
        toks, want, out = _drive_drain(rs, ref, arm_site="cache.export")
        assert toks == want
        assert out["migrated"] >= 1
        assert rs.health()["replicas_retired"] == 1
        # the degraded path was actually taken: export failed, fold shipped
        assert rs.replicas[0].resilience_stats()["spill_fallbacks"] >= 1
    finally:
        rs.close()


@hard_timeout(180)
def test_crash_failover_resumes_stream_token_exact():
    """A replica whose scheduler tick dies mid-stream: the dispatcher
    rebuilds the stream from its own delivered-token record and the
    survivor continues it greedily bit-exact."""
    rs, ref = _replica_pair()
    try:
        prompt, kw = [3, 17, 42], dict(max_tokens=16)
        want = [t for t, _ in ref.generate_step(prompt, **kw)]
        # match on the engine id: other live batchers' ticks (e.g. the
        # module-scoped spill fixture) must not consume the fault
        faults.arm("scheduler.tick", exc=RuntimeError("injected crash"),
                   after=3, times=1,
                   match={"engine": id(rs.replicas[0])})
        got = [t for t, _ in rs.generate_step(prompt, **kw)]
        assert got == want
        assert rs.served == [1, 1]  # started on r0, finished on r1
        assert rs.migrated_streams == 1 and rs.failures[0] == 1
    finally:
        rs.close()
