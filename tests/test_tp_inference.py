"""Tensor parallelism in the fused inference engine — heads/MLP columns
sharded over tp WITHIN each pipeline stage (Megatron column/row split, two
psums per layer over ICI). The reference has no TP at all (SURVEY §2.3)."""

import jax
import jax.numpy as jnp
import pytest

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=4,
    num_attention_heads=4,
    num_key_value_heads=2,
)


@pytest.fixture(scope="module")
def model_and_params():
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return model, params


def _ref(model, params, prompt, **kw):
    gen = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    return [t for t, _ in gen.generate_step(prompt, **kw)]


def test_pp2_tp2_matches_single_device(model_and_params):
    model, params = model_and_params
    prompt = [3, 17, 42, 9]
    want = _ref(model, params, prompt, max_tokens=10)
    eng = PipelineEngine(
        model, params, make_mesh(pp=2, tp=2), max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=10)]
    assert got == want


def test_pp1_tp2_seeded_sampling(model_and_params):
    model, params = model_and_params
    prompt = [5, 9, 2, 7]
    kw = dict(temperature=0.9, top_p=0.85, seed=31, max_tokens=8)
    want = _ref(model, params, prompt, **kw)
    eng = PipelineEngine(
        model, params, make_mesh(pp=1, tp=2), max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    assert [t for t, _ in eng.generate_step(prompt, **kw)] == want


def test_tp_with_uneven_pp_and_microbatches(model_and_params):
    model, params = model_and_params
    prompt = list(range(1, 14))
    want = _ref(model, params, prompt, max_tokens=6)
    eng = PipelineEngine(
        model, params, make_mesh(pp=2, tp=2), stage_bounds=[(0, 3), (3, 4)],
        microbatches=2, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
    )
    assert [t for t, _ in eng.generate_step(prompt, max_tokens=6)] == want


def test_tp_cache_is_head_sharded(model_and_params):
    model, params = model_and_params
    eng = PipelineEngine(
        model, params, make_mesh(pp=2, tp=2), max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    cache = eng.init_cache()
    shard = cache.k.sharding.shard_shape(cache.k.shape)
    assert shard[0] == 1  # stage-local
    assert shard[5] == TINY["num_key_value_heads"] // 2  # head-sharded
    # q_proj columns sharded, norms replicated
    qs = eng.layer_params["q_proj"].sharding.shard_shape(
        eng.layer_params["q_proj"].shape
    )
    assert qs[-1] == eng.layer_params["q_proj"].shape[-1] // 2
    ns = eng.layer_params["input_norm"].sharding.shard_shape(
        eng.layer_params["input_norm"].shape
    )
    assert ns[-1] == eng.layer_params["input_norm"].shape[-1]


DEEPSEEK_TINY = dict(
    vocab_size=128, hidden_size=32, intermediate_size=64,
    moe_intermediate_size=16, num_hidden_layers=4,
    num_attention_heads=4, num_key_value_heads=4, kv_lora_rank=16,
    q_lora_rank=None, qk_rope_head_dim=8, qk_nope_head_dim=16,
    v_head_dim=12, n_routed_experts=4, n_shared_experts=1,
    num_experts_per_tok=2, first_k_dense_replace=1,
)


def _deepseek(mla_cache_mode, q_lora_rank=None):
    from mlx_sharding_tpu.config import DeepseekV2Config
    from mlx_sharding_tpu.models.deepseek_v2 import DeepseekV2Model

    cfg = DeepseekV2Config(
        **{**DEEPSEEK_TINY, "q_lora_rank": q_lora_rank},
        mla_cache_mode=mla_cache_mode,
    )
    model = DeepseekV2Model(cfg)
    return model, model.init_params(jax.random.PRNGKey(1), jnp.float32)


# rides the slow tier: heavy cross-config sweep — mixtral pp2xtp2/tp2xep2
# and the deepseek pp2 chained test keep the quick composition signal
@pytest.mark.slow
@pytest.mark.parametrize("cache_mode", ["decompressed", "compressed"])
def test_deepseek_pp2_tp2_matches_single_device(cache_mode):
    """MLA TP: per-head q/kv_b/o shard over tp around the replicated
    low-rank latent; in compressed mode the single-latent-head cache
    replicates over tp while query heads stay sharded. Exact token parity
    across an uneven dense/moe split proves both cache modes."""
    model, params = _deepseek(cache_mode, q_lora_rank=24)
    prompt = [7, 3, 99, 12]
    want = _ref(model, params, prompt, max_tokens=8)
    eng = PipelineEngine(
        model, params, make_mesh(pp=2, tp=2), max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    assert [t for t, _ in eng.generate_step(prompt, max_tokens=8)] == want


@pytest.mark.slow  # tp x ep composition stays quick via the mixtral variant
def test_deepseek_tp2_ep2_matches_single_device():
    """tp x ep composition: expert stacks shard over ep (the engine's merge
    lets ep override tp for those stacks), attention + shared experts shard
    over tp — only the tp-sharded shared-expert partials join the tp psum."""
    model, params = _deepseek("decompressed")
    prompt = [5, 88, 2, 61]
    want = _ref(model, params, prompt, max_tokens=8)
    eng = PipelineEngine(
        model, params, make_mesh(pp=2, tp=2, ep=2), max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    assert [t for t, _ in eng.generate_step(prompt, max_tokens=8)] == want
    # expert stacks sharded over ep, replicated over tp
    wg = eng.layer_params["moe"]["w_gate"]
    assert wg.sharding.shard_shape(wg.shape)[2] == 2  # 4 experts / ep=2


def test_mixtral_pp2_tp2_and_tp2_ep2():
    from mlx_sharding_tpu.config import MixtralConfig
    from mlx_sharding_tpu.models.mixtral import MixtralModel

    cfg = MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=48,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
    )
    model = MixtralModel(cfg)
    params = model.init_params(jax.random.PRNGKey(2), jnp.float32)
    prompt = [9, 4, 120, 33]
    want = _ref(model, params, prompt, max_tokens=8)
    for mesh_kw in (dict(pp=2, tp=2), dict(tp=2, ep=2)):
        eng = PipelineEngine(
            model, params, make_mesh(**mesh_kw), max_seq=64,
            cache_dtype=jnp.float32, prefill_chunk=8,
        )
        got = [t for t, _ in eng.generate_step(prompt, max_tokens=8)]
        assert got == want, f"{mesh_kw} diverged"


def test_tp_unsupported_arch_raises():
    """Models that declare no tp_layer_axes still fail loudly."""
    from mlx_sharding_tpu.models.base import BaseModel

    class NoTP(LlamaModel):
        def tp_layer_axes(self):
            return {}

    model = NoTP(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(1), jnp.float32)
    with pytest.raises(ValueError, match="tensor parallelism"):
        PipelineEngine(
            model, params, make_mesh(pp=1, tp=2), max_seq=32,
            cache_dtype=jnp.float32, prefill_chunk=8,
        )


def test_gemma2_pp2_tp2_matches_single_device():
    """Gemma-2 TP: the post-attention/post-ffw norms are nonlinear, so the
    row-parallel partial products must psum BEFORE them — exact parity
    proves the placement (and the alternating window survives head
    sharding)."""
    from mlx_sharding_tpu.config import Gemma2Config
    from mlx_sharding_tpu.models.gemma2 import Gemma2Model

    cfg = Gemma2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, sliding_window=4, query_pre_attn_scalar=8,
    )
    model = Gemma2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    prompt = list(range(2, 12))  # > sliding_window so the window matters
    ref = Generator(model, params, max_seq=32, cache_dtype=jnp.float32, prefill_chunk=16)
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=6)]
    eng = PipelineEngine(
        model, params, make_mesh(pp=2, tp=2), max_seq=32,
        cache_dtype=jnp.float32, prefill_chunk=16,
    )
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=6)]
    assert got == want


def test_pp1_tp2_continuous_batching(model_and_params):
    """S=1 x tp: the VECTORIZED batched step (one vmapped forward for all
    slots) with tp psums inside the vmap — interleaved requests must match
    the serial generator exactly."""
    from mlx_sharding_tpu.scheduler import ContinuousBatcher
    from tests.helpers import run_concurrent

    model, params = model_and_params
    eng = PipelineEngine(
        model, params, make_mesh(pp=1, tp=2), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    batcher = ContinuousBatcher(eng, decode_block=4)
    try:
        jobs = [
            ([3, 17, 42], dict(max_tokens=8, seed=1)),
            ([5, 9, 2, 7], dict(max_tokens=8, temperature=0.9, top_p=0.85,
                                seed=31)),
        ]
        for (p, kw), got in zip(jobs, run_concurrent(batcher, jobs)):
            assert got == _ref(model, params, p, **kw)
    finally:
        batcher.close()
