import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.ops import causal_attention
from mlx_sharding_tpu.parallel.mesh import make_mesh
from mlx_sharding_tpu.parallel.ring_attention import ring_attention


@pytest.mark.parametrize("sp,hq,hkv", [(4, 4, 4), (8, 8, 2), (2, 4, 2)])
def test_ring_matches_dense_causal(sp, hq, hkv):
    b, t, d = 1, 32, 8
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, t, hq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, t, hkv, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, t, hkv, d)), jnp.float32)
    scale = d**-0.5

    dense = causal_attention(q, k, v, jnp.asarray(0), scale)
    mesh = make_mesh(sp=sp)
    ring = ring_attention(q, k, v, scale, mesh)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-4, atol=2e-4)


def test_ring_single_device_degenerate():
    b, t, h, d = 2, 8, 2, 4
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    mesh = make_mesh(sp=1)
    dense = causal_attention(q, k, v, jnp.asarray(0), 0.5)
    ring = ring_attention(q, k, v, 0.5, mesh)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-4, atol=2e-4)
