"""Dynamic lock-order graph vs the static one (mstcheck's MST203 family).

``analysis.runtime.enable_tracing()`` makes every ``make_lock`` in the
serving layer hand out instrumented locks, so driving a real
ContinuousBatcher + ReplicaSet + ServingMetrics workload records the lock
orderings the stack ACTUALLY exercises. The contract with the static graph
(``analyze_paths(...).lock_edges``):

- the dynamic graph is acyclic;
- the union of static and dynamic edges is acyclic (a dynamic edge that
  reverses a static one is a latent ABBA deadlock even if neither graph
  has a cycle alone);
- the cross-class edge the stack depends on — metrics ``render()`` holding
  ``ServingMetrics.lock`` while calling the batcher's locked accessors —
  shows up dynamically exactly as the static analyzer predicted.
"""

import threading
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.analysis.core import analyze_paths
from mlx_sharding_tpu.analysis import runtime as lock_runtime
from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.replicas import ReplicaSet
from mlx_sharding_tpu.scheduler import ContinuousBatcher
from mlx_sharding_tpu.utils.observability import ServingMetrics
from tests.helpers import hard_timeout

PACKAGE = Path(__file__).resolve().parent.parent / "mlx_sharding_tpu"

TINY = dict(
    vocab_size=300,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


class SerialStub:
    """Non-concurrent replica: forces ReplicaSet onto its serial locks."""

    concurrent = False

    def generate_step(self, prompt_tokens, **kw):
        yield from ((t, None) for t in (5, 6, 7))

    def stats(self):
        return 1, 0, 0


@pytest.fixture(scope="module")
def traced_stack():
    """A real batcher + replica set + metrics, all built under tracing."""
    recorder = lock_runtime.enable_tracing()
    try:
        model = LlamaModel(LlamaConfig(**TINY))
        params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
        eng = PipelineEngine(
            model, params, pipeline_mesh(1), microbatches=2, max_seq=64,
            cache_dtype=jnp.float32, prefill_chunk=8,
        )
        batcher = ContinuousBatcher(eng, decode_block=4, max_queue=8)
        rs = ReplicaSet([SerialStub(), SerialStub()])
        metrics = ServingMetrics(batcher_fn=lambda: batcher)

        @hard_timeout(120)
        def drive():
            # exercise the real admission/decode/close paths...
            assert len(list(batcher.generate_step([1, 2, 3],
                                                  max_tokens=3))) == 3
            # ...the replica dispatch path under a serial lock...
            assert [t for t, _ in rs.generate_step([1])] == [5, 6, 7]
            rs.stats()
            rs.health()
            # ...and /metrics + /health while the engine is live: render()
            # holds ServingMetrics.lock across the batcher's locked
            # accessors — the nesting under test
            metrics.record_request(prompt_tokens=3, generation_tokens=3,
                                   ttft_s=0.1, decode_tps=30.0)
            assert "mst_batch_queue_depth" in metrics.render()
            batcher.health()
            batcher.close()

        drive()
        return recorder.edges()
    finally:
        lock_runtime.disable_tracing()


def test_dynamic_lock_graph_is_acyclic(traced_stack):
    cycle = lock_runtime.LockOrderRecorder().find_cycle(
        extra_edges=traced_stack)
    assert cycle is None, f"dynamic lock-order cycle: {' -> '.join(cycle)}"


def test_dynamic_graph_matches_static(traced_stack):
    static = {(e.src, e.dst)
              for e in analyze_paths([str(PACKAGE)], baseline=None).lock_edges}
    # no dynamic ordering may reverse a statically predicted one, and the
    # combined graph must stay acyclic — either breach is a latent ABBA
    # deadlock between code paths that haven't collided yet
    reversed_edges = {(a, b) for a, b in traced_stack if (b, a) in static}
    assert not reversed_edges, f"dynamic edges reverse static: {reversed_edges}"
    combined = static | traced_stack
    cycle = lock_runtime.LockOrderRecorder().find_cycle(extra_edges=combined)
    assert cycle is None, (
        f"static ∪ dynamic lock-order cycle: {' -> '.join(cycle)}"
    )
    # the load-bearing cross-class nesting was actually exercised AND
    # statically predicted
    edge = ("ServingMetrics.lock", "ContinuousBatcher._admission_lock")
    assert edge in traced_stack and edge in static


def test_instrumented_lock_is_a_real_lock():
    rec = lock_runtime.enable_tracing()
    try:
        lk = lock_runtime.make_lock("test.lock")
        assert isinstance(lk, lock_runtime.InstrumentedLock)
        assert lk.acquire(blocking=False)
        assert lk.locked()
        # a second thread must NOT get it (and must not deadlock trying)
        got = []
        t = threading.Thread(
            target=lambda: got.append(lk.acquire(blocking=False)))
        t.start()
        t.join(5)
        assert got == [False]
        lk.release()
        assert not lk.locked()
        with lock_runtime.make_lock("test.other"), lk:
            pass
        assert ("test.other", "test.lock") in rec.edges()
    finally:
        lock_runtime.disable_tracing()
    assert isinstance(lock_runtime.make_lock("test.plain"),
                      type(threading.Lock()))
