"""SPMD pipeline vs single-device parity — the sharded-vs-unsharded
equivalence the reference never tested (SURVEY §4 (c)), on a virtual
multi-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine, split_stage_stacks

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=8,
    num_attention_heads=4,
    num_key_value_heads=2,
)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return model, params


def _engine(model, params, stages, micro=1, **kw):
    mesh = pipeline_mesh(stages)
    return PipelineEngine(
        model, params, mesh, microbatches=micro, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8, **kw,
    )


class _Homog:
    """Minimal model stub for split_stage_stacks: homogeneous 8-layer group."""

    class config:
        num_hidden_layers = 8

    def layer_group_ranges(self):
        return {None: (0, 8)}


def test_split_stage_stacks_even():
    p = {"w": jnp.arange(24).reshape(8, 3)}
    s, mask, slots = split_stage_stacks(_Homog(), p, [(0, 2), (2, 4), (4, 6), (6, 8)])
    assert s["w"].shape == (4, 2, 3) and slots == 2
    assert bool(mask.all())
    np.testing.assert_array_equal(np.asarray(s["w"][1, 0]), np.asarray(p["w"][2]))


def test_split_stage_stacks_uneven_pads_and_masks():
    p = {"w": jnp.arange(16).reshape(8, 2)}
    s, mask, slots = split_stage_stacks(_Homog(), p, [(0, 5), (5, 7), (7, 8)])
    assert s["w"].shape == (3, 5, 2) and slots == 5
    np.testing.assert_array_equal(
        np.asarray(mask),
        [[True] * 5, [True, True, False, False, False], [True] + [False] * 4],
    )
    np.testing.assert_array_equal(np.asarray(s["w"][2, 0]), np.asarray(p["w"][7]))
    assert not np.asarray(s["w"][2, 1:]).any()  # zero padding


def test_split_stage_stacks_rejects_bad_bounds():
    p = {"w": jnp.zeros((8, 2))}
    with pytest.raises(ValueError, match="cover all layers"):
        split_stage_stacks(_Homog(), p, [(0, 4), (4, 7)])
    with pytest.raises(ValueError, match="contiguous"):
        split_stage_stacks(_Homog(), p, [(0, 4), (5, 8)])
    with pytest.raises(ValueError, match="empty stage"):
        split_stage_stacks(_Homog(), p, [(0, 8), (8, 8)])


def test_pipeline_matches_single_device_greedy(model_and_params):
    model, params = model_and_params
    prompt = [3, 17, 42, 9]
    ref_gen = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=12)]

    eng = _engine(model, params, stages=4)
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=12)]
    assert got == ref


def test_pipeline_long_prompt_chunked(model_and_params):
    """Prompt spanning multiple prefill chunks through the pipeline."""
    model, params = model_and_params
    prompt = list(range(1, 21))  # 20 tokens, chunk=8 -> 8+8+4(padded)
    ref_gen = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=6)]
    eng = _engine(model, params, stages=4)
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=6)]
    assert got == ref


def test_pipeline_two_stages(model_and_params):
    model, params = model_and_params
    prompt = [5, 6]
    ref_gen = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=8)]
    eng = _engine(model, params, stages=2)
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=8)]
    assert got == ref


def test_pipeline_eight_stages_one_layer_each(model_and_params):
    model, params = model_and_params
    prompt = [11, 7]
    ref_gen = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=5)]
    eng = _engine(model, params, stages=8)
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=5)]
    assert got == ref


def test_pipeline_seeded_sampling_matches_single_device(model_and_params):
    """Replicated sampling on psum'd logits must reproduce the single-device
    sampler exactly (same PRNG path, same tempered nucleus)."""
    model, params = model_and_params
    prompt = [3, 1, 4]
    ref_gen = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    ref = [
        t for t, _ in ref_gen.generate_step(
            prompt, temperature=0.9, top_p=0.8, seed=11, max_tokens=8
        )
    ]
    eng = _engine(model, params, stages=4)
    got = [
        t for t, _ in eng.generate_step(
            prompt, temperature=0.9, top_p=0.8, seed=11, max_tokens=8
        )
    ]
    assert got == ref


def test_pipeline_microbatched_multichunk_prefill(model_and_params):
    """M=2 microbatches with a prompt spanning several prefill chunks."""
    model, params = model_and_params
    prompt = list(range(1, 20))  # chunks of 8: 8+8+4(padded)
    ref_gen = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=5)]
    eng = _engine(model, params, stages=2, micro=2)
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=5)]
    assert got == ref


def test_pipeline_microbatched_decode(model_and_params):
    """M=3 microbatches: every microbatch decodes the same greedy sequence
    the single-request path produces (independent caches, filled bubble)."""
    model, params = model_and_params
    prompt = [9, 1, 4]
    ref_gen = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=6)]

    eng = _engine(model, params, stages=4, micro=3)
    from mlx_sharding_tpu.sample import init_recent_tokens, make_sampler_params

    sp = make_sampler_params(0.0, 1.0)
    key = jax.random.PRNGKey(0)
    M = 3
    prompt_arr = np.broadcast_to(np.asarray(prompt, np.int32), (M, 1, len(prompt)))
    cache = eng.init_cache()
    chunk = np.pad(prompt_arr, ((0, 0), (0, 0), (0, 8 - len(prompt))))
    logits, cache = eng._prefill(
        eng.layer_params, eng.layer_masks, eng.vocab_parts, eng.shared_params,
        jnp.asarray(chunk), cache, jnp.asarray(len(prompt), jnp.int32),
    )
    recent = init_recent_tokens(M, 20)
    tok, _, recent, key = eng._sample(logits, recent, key, sp)
    seqs = [[int(tok[m, 0])] for m in range(M)]
    for _ in range(5):
        tok, _, cache, recent, key = eng._decode(
            eng.layer_params, eng.layer_masks, eng.vocab_parts,
            eng.shared_params, tok[..., None], cache, recent, key, sp,
            jnp.asarray(1, jnp.int32),
        )
        for m in range(M):
            seqs[m].append(int(tok[m, 0]))
    for m in range(M):
        assert seqs[m] == ref, f"microbatch {m} diverged"


def test_vocab_sharded_embed_head(model_and_params):
    """VERDICT r1 item 5: embed/head must NOT be replicated per pp device —
    each device holds vocab/S rows of the table (and of the head when not
    tied), cutting ~1 GB/device at Llama-3 vocab."""
    model, params = model_and_params
    eng = _engine(model, params, stages=4)
    assert "embed" not in eng.shared_params
    assert "lm_head" not in eng.shared_params
    S, V, H = 4, TINY["vocab_size"], TINY["hidden_size"]
    Vs = -(-V // S)
    assert eng.vocab_parts[0].shape == (S, Vs, H)
    assert not eng._head_tied
    assert eng.vocab_parts[1].shape == (S, H, Vs)
    # per-device shard is 1/S of the table
    shard_shape = eng.vocab_parts[0].sharding.shard_shape(eng.vocab_parts[0].shape)
    assert shard_shape == (1, Vs, H)
