"""Tests run on CPU with 8 virtual devices so the full multi-stage mesh
machinery is exercised without TPU hardware (SURVEY §4 implication (b)).

Environment wrinkle: this container's sitecustomize imports jax and registers
the ``axon`` TPU-tunnel plugin before pytest starts, with JAX_PLATFORMS=axon
in the env. Setting env vars here is therefore too late for jax's config —
but backends initialize lazily, so ``jax.config.update`` still redirects to
CPU (and avoids a hard deadlock: the axon C-API client hangs at init when
torch is loaded in the same process)."""

import os

# For any subprocesses tests may spawn.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax: the XLA_FLAGS host-platform flag above already forces 8
    pass
jax.config.update("jax_default_matmul_precision", "highest")
