"""Fused SPMD pipeline with non-Llama architectures: Gemma-2 (global
layer-index alternation must survive stage slicing) and Mixtral (MoE expert
stacks ride the stage split)."""

import jax
import jax.numpy as jnp
import pytest

from mlx_sharding_tpu.config import Gemma2Config, MixtralConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.gemma2 import Gemma2Model
from mlx_sharding_tpu.models.mixtral import MixtralModel
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

pytestmark = pytest.mark.slow  # arch-matrix sweep; excluded from tier-1


def test_gemma2_pipeline_odd_layers_per_stage():
    """4 stages x 1 layer: stages 1 and 3 hold GLOBAL odd (non-sliding)
    layers — with per-stage-local indices they would wrongly apply the
    window."""
    cfg = Gemma2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, sliding_window=4, query_pre_attn_scalar=8,
    )
    model = Gemma2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    prompt = list(range(2, 12))  # > sliding_window so the window matters

    ref_gen = Generator(model, params, max_seq=32, cache_dtype=jnp.float32, prefill_chunk=16)
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=6)]

    eng = PipelineEngine(
        model, params, pipeline_mesh(4), max_seq=32,
        cache_dtype=jnp.float32, prefill_chunk=16,
    )
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=6)]
    assert got == ref


def test_mixtral_pipeline():
    cfg = MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=48,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
    )
    model = MixtralModel(cfg)
    params = model.init_params(jax.random.PRNGKey(1), jnp.float32)
    prompt = [5, 9, 2]

    ref_gen = Generator(model, params, max_seq=32, cache_dtype=jnp.float32, prefill_chunk=8)
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=6)]

    eng = PipelineEngine(
        model, params, pipeline_mesh(2), max_seq=32,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=6)]
    assert got == ref


def _dsv2_model(seed=3, first_k_dense=1, layers=4):
    from mlx_sharding_tpu.config import DeepseekV2Config
    from mlx_sharding_tpu.models.deepseek_v2 import DeepseekV2Model

    cfg = DeepseekV2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=16, num_hidden_layers=layers,
        num_attention_heads=4, num_key_value_heads=4, kv_lora_rank=16,
        q_lora_rank=None, qk_rope_head_dim=8, qk_nope_head_dim=16,
        v_head_dim=12, n_routed_experts=4, n_shared_experts=1,
        num_experts_per_tok=2, first_k_dense_replace=first_k_dense,
    )
    model = DeepseekV2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(seed), jnp.float32)
    return model, params


def test_deepseek_fused_pipeline_two_stages():
    """The VERDICT r1 gap: DeepSeek-V2 (heterogeneous dense+MoE layer tree,
    MLA single-latent-head cache) through the fused SPMD engine — the
    BASELINE primary architecture as ONE compiled program per token, with
    stage 0 holding the dense prefix and stage 1 all-MoE (the shape of the
    reference's 0-14/14-27 split, /root/reference/shard/utils.py:162-164)."""
    model, params = _dsv2_model()
    prompt = [7, 3, 99, 12]
    ref_gen = Generator(model, params, max_seq=32, cache_dtype=jnp.float32, prefill_chunk=8)
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=6)]

    eng = PipelineEngine(
        model, params, pipeline_mesh(2), max_seq=32,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=6)]
    assert got == ref


def test_deepseek_fused_uneven_baseline_shape():
    """Uneven split (0-3/3-4) where stage 0 = dense+2 MoE and stage 1 = 1 MoE
    (padded+masked slots): fused engine must match single-device decode."""
    model, params = _dsv2_model(seed=5)
    prompt = [4, 88, 23]
    ref_gen = Generator(model, params, max_seq=32, cache_dtype=jnp.float32, prefill_chunk=8)
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=6)]

    eng = PipelineEngine(
        model, params, pipeline_mesh(2), stage_bounds=[(0, 3), (3, 4)],
        max_seq=32, cache_dtype=jnp.float32, prefill_chunk=8,
    )
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=6)]
    assert got == ref


def test_llama_fused_uneven_split():
    """Homogeneous model, non-divisible split: 8 layers over 3 stages
    (3/3/2 balanced default) and an explicit skewed 5/2/1."""
    from mlx_sharding_tpu.config import LlamaConfig
    from mlx_sharding_tpu.models.llama import LlamaModel

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=8, num_attention_heads=4, num_key_value_heads=2,
    )
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(2), jnp.float32)
    prompt = [3, 17, 42]
    ref_gen = Generator(model, params, max_seq=32, cache_dtype=jnp.float32, prefill_chunk=8)
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=6)]

    eng = PipelineEngine(
        model, params, pipeline_mesh(3), max_seq=32,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    assert eng.stage_bounds == [(0, 3), (3, 6), (6, 8)]
    assert [t for t, _ in eng.generate_step(prompt, max_tokens=6)] == ref

    eng2 = PipelineEngine(
        model, params, pipeline_mesh(3), stage_bounds=[(0, 5), (5, 7), (7, 8)],
        max_seq=32, cache_dtype=jnp.float32, prefill_chunk=8,
    )
    assert [t for t, _ in eng2.generate_step(prompt, max_tokens=6)] == ref
