"""Fused SPMD pipeline with non-Llama architectures: Gemma-2 (global
layer-index alternation must survive stage slicing) and Mixtral (MoE expert
stacks ride the stage split)."""

import jax
import jax.numpy as jnp
import pytest

from mlx_sharding_tpu.config import Gemma2Config, MixtralConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.gemma2 import Gemma2Model
from mlx_sharding_tpu.models.mixtral import MixtralModel
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine


def test_gemma2_pipeline_odd_layers_per_stage():
    """4 stages x 1 layer: stages 1 and 3 hold GLOBAL odd (non-sliding)
    layers — with per-stage-local indices they would wrongly apply the
    window."""
    cfg = Gemma2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, sliding_window=4, query_pre_attn_scalar=8,
    )
    model = Gemma2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    prompt = list(range(2, 12))  # > sliding_window so the window matters

    ref_gen = Generator(model, params, max_seq=32, cache_dtype=jnp.float32, prefill_chunk=16)
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=6)]

    eng = PipelineEngine(
        model, params, pipeline_mesh(4), max_seq=32,
        cache_dtype=jnp.float32, prefill_chunk=16,
    )
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=6)]
    assert got == ref


def test_mixtral_pipeline():
    cfg = MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=48,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
    )
    model = MixtralModel(cfg)
    params = model.init_params(jax.random.PRNGKey(1), jnp.float32)
    prompt = [5, 9, 2]

    ref_gen = Generator(model, params, max_seq=32, cache_dtype=jnp.float32, prefill_chunk=8)
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=6)]

    eng = PipelineEngine(
        model, params, pipeline_mesh(2), max_seq=32,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    got = [t for t, _ in eng.generate_step(prompt, max_tokens=6)]
    assert got == ref
