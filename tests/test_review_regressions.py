"""Regression tests for code-review findings."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator, stream_generate
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.sample import make_sampler_params, sample_token, init_recent_tokens

TINY = dict(
    vocab_size=300,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


def _gen(**kw):
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return Generator(model, params, cache_dtype=jnp.float32, **kw)


def test_non_multiple_max_seq_rounds_up_and_stays_correct():
    """max_seq=20 with chunk=8 rounds to 24; a 19-token prompt + decode must
    match a generator whose chunk swallows the prompt whole."""
    g1 = _gen(max_seq=20, prefill_chunk=8)
    assert g1.max_seq == 24
    g2 = _gen(max_seq=24, prefill_chunk=32)
    prompt = list(range(1, 20))
    a = [t for t, _ in g1.generate_step(prompt, max_tokens=4)]
    b = [t for t, _ in g2.generate_step(prompt, max_tokens=4)]
    assert a == b


def test_repetition_penalty_sees_prompt():
    """The window is seeded with the prompt tail: a token prominent in the
    prompt gets penalized on the very first generated token."""
    recent = init_recent_tokens(1, 8, np.asarray([[7, 7, 7]], np.int32))
    np.testing.assert_array_equal(np.asarray(recent)[0, -3:], [7, 7, 7])
    logits = jnp.zeros((1, 16)).at[0, 7].set(1.0).at[0, 3].set(0.9)
    sp = make_sampler_params(temperature=0.0, repetition_penalty=3.0)
    tok, _ = sample_token(jax.random.PRNGKey(0), logits, sp, recent)
    assert int(tok[0]) == 3  # 7 would win without the prompt-seeded penalty


@pytest.mark.slow  # ~21s: statistical many-sample sweep (runs in full suite)
def test_top_p_applies_after_temperature():
    """At high temperature the tempered distribution is flatter, so more
    tokens stay inside the nucleus than at temp≈0+."""
    logits = jnp.log(jnp.asarray([[0.70, 0.20, 0.06, 0.04]]))
    sp_hot = make_sampler_params(temperature=4.0, top_p=0.8)
    # sample many times at hot temperature; token 2 (outside the temp=1
    # nucleus {0,1}: 0.9 >= 0.8) must appear because tempering flattens mass
    toks = {
        int(sample_token(jax.random.PRNGKey(i), logits, sp_hot)[0][0])
        for i in range(64)
    }
    assert 2 in toks


def test_logit_bias_beyond_16_entries():
    bias = {i: -100.0 for i in range(24)}  # ban tokens 0..23
    bias[25] = 50.0
    sp = make_sampler_params(temperature=0.0, logit_bias=bias)
    logits = jnp.zeros((1, 32)).at[0, 23].set(10.0)  # would win if bias dropped
    tok, _ = sample_token(jax.random.PRNGKey(0), logits, sp)
    assert int(tok[0]) == 25


def test_stream_stop_prefix_never_leaks():
    """A multi-token stop sequence's prefix must not be emitted."""
    from tests.test_tokenizer_utils import ByteTokenizer

    g = _gen(max_seq=64, prefill_chunk=8)
    tok = ByteTokenizer()
    prompt = tok.encode("m")
    ref = [t for t, _ in g.generate_step(prompt, max_tokens=8)]
    # stop on tokens 2..3 of the greedy continuation
    stop = [ref[2], ref[3]]
    chunks = list(
        stream_generate(
            g, tok, prompt, max_tokens=8,
            stop_id_sequences=[stop], eos_token_ids=[],
        )
    )
    streamed = "".join(c.text for c in chunks)
    stop_text = tok.decode(stop)
    if stop_text.strip():  # only meaningful when stop decodes to visible text
        assert stop_text not in streamed
    assert chunks[-1].finish_reason == "stop"


def test_qwen2_bias_parity():
    """Qwen2 (attention_bias=True) checkpoints load their QKV biases and
    match HF logits."""
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")
    import tempfile

    from mlx_sharding_tpu.loading import load_model

    with tempfile.TemporaryDirectory() as td:
        torch.manual_seed(3)
        cfg = transformers.Qwen2Config(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, rope_theta=10000.0,
            tie_word_embeddings=False,
        )
        hf = transformers.Qwen2ForCausalLM(cfg)
        hf.eval()
        hf.save_pretrained(td, safe_serialization=True)

        tokens = [[5, 77, 23, 9]]
        with torch.no_grad():
            ref = hf(torch.tensor(tokens)).logits.numpy()
        model, params = load_model(td, dtype=jnp.float32)
        assert "q_bias" in params["layers"]
        got, _ = model(
            params, jnp.asarray(tokens, jnp.int32), model.make_cache(1, 16, jnp.float32)
        )
        np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-3, atol=2e-3)


def test_control_plane_concurrent_exchanges_do_not_cross_replies(monkeypatch):
    """ControlPlane.exchange's timed path (submit to the broadcast thread,
    collect the reply) must be atomic: two callers racing it could collect
    each other's broadcast results (or spawn duplicate broadcast threads).
    A slow fake broadcast makes the race window wide; every caller must get
    its own header back."""
    import threading
    import time as _time

    from mlx_sharding_tpu.parallel.multihost import ControlPlane

    def slow_echo(buf):
        _time.sleep(0.01)
        return buf

    monkeypatch.setattr(ControlPlane, "_broadcast", staticmethod(slow_echo))
    plane = ControlPlane(max_prompt=8, timeout_s=30)
    results = {}

    def caller(i):
        out = plane.exchange({"header": np.full(8, i, np.int32)})
        results[i] = int(out["header"][0])

    threads = [threading.Thread(target=caller, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert results == {i: i for i in range(8)}
    assert not plane.dead
