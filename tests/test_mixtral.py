import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.loading import load_model
from mlx_sharding_tpu.ops.moe import _apply_gather, _apply_scan, mixtral_routing

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

TINY_HF = dict(
    vocab_size=160,
    hidden_size=64,
    intermediate_size=96,
    num_hidden_layers=3,
    num_attention_heads=4,
    num_key_value_heads=2,
    num_local_experts=4,
    num_experts_per_tok=2,
    max_position_embeddings=128,
    rms_norm_eps=1e-5,
)


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("tiny_mixtral")
    torch.manual_seed(11)
    model = transformers.MixtralForCausalLM(transformers.MixtralConfig(**TINY_HF))
    model.eval()
    model.save_pretrained(path, safe_serialization=True)
    return path, model


def test_moe_gather_matches_scan():
    rng = np.random.default_rng(0)
    n, h, i, e, k = 4, 8, 16, 4, 2
    x = jnp.asarray(rng.normal(size=(n, h)), jnp.float32)
    router = jnp.asarray(rng.normal(size=(h, e)), jnp.float32)
    wg = jnp.asarray(rng.normal(size=(e, h, i)), jnp.float32) * 0.1
    wu = jnp.asarray(rng.normal(size=(e, h, i)), jnp.float32) * 0.1
    wd = jnp.asarray(rng.normal(size=(e, i, h)), jnp.float32) * 0.1
    weights, idx = mixtral_routing(x, router, k)
    a = _apply_gather(x, weights, idx, wg, wu, wd)
    b = _apply_scan(x, weights, idx, wg, wu, wd)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_routing_normalizes_topk():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(3, 8)), jnp.float32)
    router = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
    weights, idx = mixtral_routing(x, router, 2)
    np.testing.assert_allclose(np.asarray(weights.sum(-1)), np.ones(3), rtol=1e-5)
    assert np.asarray(idx).max() < 4


def test_logits_parity_full(hf_checkpoint):
    path, hf_model = hf_checkpoint
    tokens = [[2, 45, 99, 3, 27, 81, 5]]
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.numpy()
    model, params = load_model(str(path), dtype=jnp.float32)
    got, _ = model(
        params, jnp.asarray(tokens, jnp.int32), model.make_cache(1, 16, jnp.float32)
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-3, atol=3e-3)


def test_decode_path_matches_prefill_path(hf_checkpoint):
    """The gather (decode) and scan (prefill) MoE paths must agree through
    the full model: feeding tokens one-by-one == one prefill call."""
    path, _ = hf_checkpoint
    model, params = load_model(str(path), dtype=jnp.float32)
    tokens = jnp.asarray([list(range(2, 2 + 20))], jnp.int32)  # 20 > gather cap
    full, _ = model(params, tokens, model.make_cache(1, 32, jnp.float32))
    cache = model.make_cache(1, 32, jnp.float32)
    outs = []
    for i in range(tokens.shape[1]):
        logits, cache = model(params, tokens[:, i : i + 1], cache)
        outs.append(logits[:, 0])
    got = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(got), rtol=2e-3, atol=2e-3)


def test_two_stage_parity(hf_checkpoint):
    path, hf_model = hf_checkpoint
    tokens = [[5, 9, 2, 7]]
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.numpy()
    s0, p0 = load_model(str(path), start_layer=0, end_layer=2, dtype=jnp.float32)
    s1, p1 = load_model(str(path), start_layer=2, end_layer=3, dtype=jnp.float32)
    h, _ = s0(p0, jnp.asarray(tokens, jnp.int32), s0.make_cache(1, 16, jnp.float32))
    got, _ = s1(p1, h, s1.make_cache(1, 16, jnp.float32))
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-3, atol=3e-3)
