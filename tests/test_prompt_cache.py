"""Prompt-prefix caching (generate.py): a new request reuses the previous
request's KV rows for the longest common token prefix and prefills only the
rest. Streams must be EXACTLY what an uncached generator produces — prefix
reuse is a pure prefill shortcut, never a semantic change."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel

TINY = dict(
    vocab_size=300,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


@pytest.fixture(scope="module")
def pair():
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    cached = Generator(
        model, params, max_seq=128, cache_dtype=jnp.float32,
        prefill_chunk=8, decode_block=4, prompt_cache=True,
    )
    plain = Generator(
        model, params, max_seq=128, cache_dtype=jnp.float32,
        prefill_chunk=8, decode_block=4,
    )
    return cached, plain


def run(gen, prompt, **kw):
    return [t for t, _ in gen.generate_step(prompt, **kw)]


def test_chat_turn_pattern(pair):
    """Turn 2 re-sends turn 1's prompt + reply + new text — the realistic
    chat shape. The hit must cover at least the whole first prompt and the
    stream must match an uncached generator exactly."""
    cached, plain = pair
    p1 = [5, 9, 2, 44, 17, 80, 3, 14, 9, 9, 31]
    reply = run(cached, p1, max_tokens=9)
    assert cached.last_prefix_hit == 0  # cold start

    p2 = p1 + reply + [77, 12, 5]
    want = run(plain, p2, max_tokens=8)
    got = run(cached, p2, max_tokens=8)
    assert got == want
    assert cached.last_prefix_hit >= len(p1)


def test_exact_repeat(pair):
    cached, plain = pair
    p = [8, 1, 99, 42, 6, 13, 27]
    run(cached, p, max_tokens=5)
    want = run(plain, p, max_tokens=5)
    got = run(cached, p, max_tokens=5)
    assert got == want
    assert cached.last_prefix_hit == len(p) - 1  # one token must prefill


def test_mismatched_prompt_is_safe(pair):
    """A completely different prompt: no reuse, stream still exact (the old
    buffer is recycled at offset 0, stale rows never attended)."""
    cached, plain = pair
    run(cached, [5, 9, 2, 44, 17], max_tokens=6)
    p = [200, 201, 202, 203]
    want = run(plain, p, max_tokens=6)
    got = run(cached, p, max_tokens=6)
    assert got == want
    assert cached.last_prefix_hit == 0


def test_partial_prefix(pair):
    """Divergence mid-prompt: reuse exactly the common part."""
    cached, plain = pair
    p1 = [5, 9, 2, 44, 17, 80, 3, 14]
    run(cached, p1, max_tokens=4)
    p2 = p1[:5] + [150, 151, 152]
    want = run(plain, p2, max_tokens=6)
    got = run(cached, p2, max_tokens=6)
    assert got == want
    assert cached.last_prefix_hit == 5


def test_sampled_with_cache(pair):
    """Seeded sampling over a reused prefix: the PRNG chain starts fresh per
    request, so streams match the uncached generator token-for-token."""
    cached, plain = pair
    p1 = [5, 9, 2, 44, 17, 80]
    run(cached, p1, max_tokens=5)
    p2 = p1 + [60, 61]
    kw = dict(max_tokens=7, temperature=0.8, top_p=0.9, seed=3,
              repetition_penalty=1.2)
    want = run(plain, p2, **kw)
    got = run(cached, p2, **kw)
    assert got == want
    assert cached.last_prefix_hit >= len(p1) - 1


def test_early_close_then_reuse(pair):
    """Abandoning a stream mid-generation (stop sequence / disconnect) must
    leave a usable, correctly-accounted cache."""
    cached, plain = pair
    p1 = [5, 9, 2, 44, 17, 80, 3]
    g = cached.generate_step(p1, max_tokens=12)
    first = [next(g) for _ in range(3)]
    g.close()  # consumer walks away after 3 tokens
    taken = [t for t, _ in first]

    p2 = p1 + taken + [90]
    want = run(plain, p2, max_tokens=6)
    got = run(cached, p2, max_tokens=6)
    assert got == want
    assert cached.last_prefix_hit >= len(p1)


def test_logprobs_with_cache(pair):
    cached, plain = pair
    p1 = [5, 9, 2, 44]
    run(cached, p1, max_tokens=4)
    p2 = p1 + [10, 11]
    want = list(plain.generate_step(p2, max_tokens=5, want_logprobs=True))
    got = list(cached.generate_step(p2, max_tokens=5, want_logprobs=True))
    assert [t for t, _ in got] == [t for t, _ in want]
    for (_, a), (_, b) in zip(got, want):
        assert a.chosen == pytest.approx(b.chosen, abs=1e-5)
        assert list(a.top_indices) == list(b.top_indices)


def test_capacity_edge_unaligned_hit():
    """A non-chunk-aligned prefix hit whose padded suffix would cross
    max_seq must not clamp-overwrite valid rows (the hit aligns down to a
    chunk boundary instead). prefill_chunk=8, max_seq=16: 5-token shared
    prefix + 15-token prompt was the exact overflow shape."""
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    cached = Generator(
        model, params, max_seq=16, cache_dtype=jnp.float32,
        prefill_chunk=8, decode_block=4, prompt_cache=True,
    )
    plain = Generator(
        model, params, max_seq=16, cache_dtype=jnp.float32,
        prefill_chunk=8, decode_block=4,
    )
    p1 = [5, 9, 2, 44, 17]
    run(cached, p1, max_tokens=2)
    p2 = p1 + [30, 31, 32, 33, 34, 35, 36, 37, 38, 39]  # 15 tokens
    want = run(plain, p2, max_tokens=1)
    got = run(cached, p2, max_tokens=1)
    assert got == want
    # the 5-token hit would overflow (5 + 2*8 > 16); it must align to 0
    assert cached.last_prefix_hit == 0
