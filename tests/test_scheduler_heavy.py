"""Scheduler composition tests that each build their own engines (compile
cost ~40-70s apiece on CPU) — correctness-critical but excluded from the
quick tier, which keeps one representative per feature (see
tests/test_scheduler.py: over-commit preempt/resume exactness, spec greedy
exactness + sampled stability) and stays within its time budget."""

import jax
import jax.numpy as jnp
import pytest

from mlx_sharding_tpu.scheduler import ContinuousBatcher  # noqa: F401

from tests.test_scheduler import (  # noqa: F401 — shared tiny-model helpers
    _concurrent,
    _paged_batcher,
    _run,
    _spec_batcher,
)

pytestmark = pytest.mark.slow  # compile-bound combos; excluded from tier-1


def test_overcommit_interleaves_where_reserve_serializes():
    """Two requests whose reserved needs (6 pages each) exceed the 8-page
    pool: reserve admission runs them strictly one-after-another, over-commit
    runs them concurrently (higher slot occupancy) and stays token-exact
    through the preemption the pool pressure eventually forces."""
    jobs = [
        ([3, 17, 42, 9], dict(max_tokens=40)),   # full need ceil(44/8)=6
        ([5, 11, 2, 8], dict(max_tokens=40)),
    ]
    # reserve-mode control: same pool, no overcommit — strict serialization
    reserve, ref = _paged_batcher(pool_pages=8)
    try:
        refs = [_run(ref, p, **kw) for p, kw in jobs]
        got_r, times_r = _concurrent(reserve, jobs)
        assert got_r == refs
        # one request's stream finished entirely before the other started
        starts = [t[0] for t in times_r]
        ends = [t[-1] for t in times_r]
        assert min(ends) <= max(starts), (
            "reserve admission co-ran 2x6 pages in an 8-page pool"
        )
    finally:
        reserve.close()

    batcher, _ = _paged_batcher(pool_pages=8, overcommit=True)
    try:
        before = batcher.preemptions
        got, times = _concurrent(batcher, jobs)
        assert got == refs  # token-exact through preemption + resume
        # genuine interleaving: each produced a token before the other ended
        assert times[0][0] < times[1][-1] and times[1][0] < times[0][-1]
        assert batcher.preemptions > before  # pool pressure forced a preempt
    finally:
        batcher.close()


def test_overcommit_prefix_cache_compose():
    """Over-commit + prefix cache: a preempted request's registered prompt
    pages survive as cache entries and its resume re-prefill hits them;
    streams stay exact."""
    batcher, ref = _paged_batcher(
        pool_pages=8, overcommit=True, prefix_cache=True
    )
    try:
        shared = [((7 * i) % 251) + 1 for i in range(12)]  # 1 full page + 4
        jobs = [
            (shared + [61, 62], dict(max_tokens=30)),
            (shared + [71], dict(max_tokens=30)),
        ]
        refs = [_run(ref, p, **kw) for p, kw in jobs]
        got, _ = _concurrent(batcher, jobs)
        assert got == refs
        assert batcher.prefix_stats()[0] >= 2  # both queried the index
    finally:
        batcher.close()


def test_spec_cb_perfect_draft_accepts_k():
    """A draft identical to the target agrees at every position: every
    round emits the full window K (the acceptance gauge's upper bound)."""
    batcher, ref = _spec_batcher(microbatches=2, spec_k=3, draft_seed=0)
    try:
        jobs = [([3, 17, 42], dict(max_tokens=13)),
                ([5, 11, 2], dict(max_tokens=13))]
        refs = [_run(ref, p, **kw) for p, kw in jobs]
        got, _ = _concurrent(batcher, jobs)
        assert got == refs
        assert batcher.accepted_tokens == batcher.spec_k * batcher.rounds
    finally:
        batcher.close()


def test_spec_cb_paged_overcommit_compose():
    """Speculation x paged pool x over-commit: verify writes straddle page
    boundaries (multi-page writeback) and pool pressure preempts + resumes
    a request mid-speculation; greedy streams stay exact throughout."""
    batcher, ref = _spec_batcher(microbatches=2, spec_k=3, pool_pages=8,
                                 overcommit=True)
    try:
        jobs = [
            ([3, 17, 42, 9], dict(max_tokens=40)),  # full need 6 pages
            ([5, 11, 2, 8], dict(max_tokens=40)),
        ]
        refs = [_run(ref, p, **kw) for p, kw in jobs]
        before = batcher.preemptions
        got, _ = _concurrent(batcher, jobs)
        assert got == refs
        assert batcher.preemptions > before
        total, in_use, _ = batcher.page_stats()
        assert in_use == 0 and len(batcher._free_pages) == total
    finally:
        batcher.close()


def test_spec_cb_prefix_cache_compose():
    """Speculation x prefix cache: a prefix hit skips TARGET prefill chunks
    while the draft — which has no page sharing — catches up from 0 on its
    own position; activation waits for both, streams stay token-exact and
    the hit is real."""
    from mlx_sharding_tpu.config import LlamaConfig
    from mlx_sharding_tpu.generate import Generator
    from mlx_sharding_tpu.models.llama import LlamaModel
    from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

    from tests.test_scheduler import TINY

    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    dparams = model.init_params(jax.random.PRNGKey(7), jnp.float32)
    mesh = pipeline_mesh(1)
    eng = PipelineEngine(
        model, params, mesh, microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8, pool_pages=16, page_size=8,
    )
    deng = PipelineEngine(
        model, dparams, mesh, microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    batcher = ContinuousBatcher(
        eng, decode_block=3, draft_engine=deng, spec_k=3, prefix_cache=True
    )
    try:
        shared = [((7 * i) % 251) + 1 for i in range(20)]  # 2 full pages + 4
        first = _run(batcher, shared + [61], max_tokens=8)
        assert first == _run(ref, shared + [61], max_tokens=8)
        # second request prefix-hits (16 reused tokens) while its draft
        # prefills all 3 chunks — token-exact vs the serial generator
        second = _run(batcher, shared + [71, 72], max_tokens=8)
        assert second == _run(ref, shared + [71, 72], max_tokens=8)
        _, hits, reused, _, _ = batcher.prefix_stats()
        assert hits >= 1 and reused >= 16
        assert batcher.rounds > 0  # speculation ran on the hit request too
    finally:
        batcher.close()
