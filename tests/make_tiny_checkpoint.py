"""Create a fully self-contained tiny Llama checkpoint + byte-level BPE
tokenizer on disk (no network): the fixture that lets the CLI / server /
loader run the exact end-user path offline.

Usage: python tests/make_tiny_checkpoint.py [outdir]
"""

import json
import sys
from pathlib import Path


def make_tiny_checkpoint(outdir: str | Path, vocab_size: int = 384) -> Path:
    outdir = Path(outdir)
    outdir.mkdir(parents=True, exist_ok=True)

    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    corpus = [
        "the quick brown fox jumps over the lazy dog. ",
        "hello world, this is a tiny corpus for a tiny tokenizer. ",
        "pipelines run on meshes; stages pass activations over rings. ",
        "0123456789 !?,.:;()[]{}<>+-*/=\n",
    ] * 50
    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size,
        special_tokens=["<eos>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False,
    )
    tok.train_from_iterator(corpus, trainer)
    tok.save(str(outdir / "tokenizer.json"))
    (outdir / "tokenizer_config.json").write_text(
        json.dumps(
            {"tokenizer_class": "PreTrainedTokenizerFast", "eos_token": "<eos>"}
        )
    )

    import torch
    import transformers

    torch.manual_seed(7)
    cfg = transformers.LlamaConfig(
        vocab_size=tok.get_vocab_size(),
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=1024,
        eos_token_id=0,
    )
    model = transformers.LlamaForCausalLM(cfg)
    model.save_pretrained(outdir, safe_serialization=True)
    return outdir


if __name__ == "__main__":
    out = make_tiny_checkpoint(sys.argv[1] if len(sys.argv) > 1 else "/tmp/tiny_llama_ckpt")
    print(out)
