"""Compressed MLA cache: identical logits to the decompressed path and to
HF, at a fraction of the KV memory."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.loading import load_model

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from tests.test_deepseek_v2 import TINY_HF, _make_checkpoint  # noqa: E402


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("tiny_dsv2_mla")
    model = _make_checkpoint(path)
    return path, model


def _load(path, mode, **kw):
    import json

    cfg = json.loads((path / "config.json").read_text())
    cfg["mla_cache_mode"] = mode
    (path / "config.json").write_text(json.dumps(cfg))
    return load_model(str(path), dtype=jnp.float32, **kw)


def test_compressed_matches_hf_and_full(hf_checkpoint):
    path, hf_model = hf_checkpoint
    tokens = [[2, 45, 99, 3, 27, 81, 5, 150]]
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.numpy()

    model_c, params_c = _load(path, "compressed")
    got_c, _ = model_c(
        params_c, jnp.asarray(tokens, jnp.int32), model_c.make_cache(1, 16, jnp.float32)
    )
    np.testing.assert_allclose(np.asarray(got_c), ref, rtol=3e-3, atol=3e-3)

    model_f, params_f = _load(path, "full")
    got_f, _ = model_f(
        params_f, jnp.asarray(tokens, jnp.int32), model_f.make_cache(1, 16, jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(got_c), np.asarray(got_f), rtol=1e-4, atol=1e-4
    )


def test_compressed_cache_is_smaller(hf_checkpoint):
    path, _ = hf_checkpoint
    model_c, _ = _load(path, "compressed")
    model_f, _ = _load(path, "full")
    cache_c = model_c.make_cache(1, 32, jnp.float32)
    cache_f = model_f.make_cache(1, 32, jnp.float32)
    size = lambda c: c.k.size + c.v.size
    assert size(cache_c) < size(cache_f) / 2
    # latent head: rank + rope dims, one shared head
    assert cache_c.k.shape[-2:] == (1, TINY_HF["kv_lora_rank"] + TINY_HF["qk_rope_head_dim"])


def test_compressed_prefill_equals_decode(hf_checkpoint):
    path, _ = hf_checkpoint
    model, params = _load(path, "compressed")
    tokens = jnp.asarray([[2, 17, 42, 9, 77, 23]], jnp.int32)
    full, _ = model(params, tokens, model.make_cache(1, 16, jnp.float32))
    cache = model.make_cache(1, 16, jnp.float32)
    outs = []
    for i in range(tokens.shape[1]):
        logits, cache = model(params, tokens[:, i : i + 1], cache)
        outs.append(logits[:, 0])
    got = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(got), rtol=2e-3, atol=2e-3)
