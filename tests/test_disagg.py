"""Disaggregated prefill/decode serving (disagg.py): role-split replica
pools with KVPageBlock handoff.

Parity contract: every stream a client sees through the DisaggCoordinator
is bit-identical to the same request served by one monolithic batcher of
the same pool geometry — across greedy and seeded-stochastic sampling,
across bf16/fp32 and int8 KV pools, and under every injected handoff
fault. The degradation matrix (``disagg.handoff`` / ``cache.export`` /
``cache.import`` / a pool dying mid-plan) must degrade to serve-in-place
or a blockless resume, never a dropped stream."""

import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.disagg import DisaggCoordinator
from mlx_sharding_tpu.fleet import FleetAutoscaler, pool_pressure
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.replicas import ReplicaSet
from mlx_sharding_tpu.resilience import QueueFullError
from mlx_sharding_tpu.scheduler import ContinuousBatcher
from mlx_sharding_tpu.testing import faults
from mlx_sharding_tpu.utils.observability import ServingMetrics
from tests.helpers import hard_timeout, run_concurrent

TINY = dict(vocab_size=256, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2)

# greedy, seeded-stochastic, and the degenerate stream that completes
# inside prefill (max_tokens=1 never reaches the decode pool)
JOBS = [
    ([3, 17, 42], dict(max_tokens=24)),
    ([9, 4, 4, 6], dict(temperature=0.9, top_p=0.85, seed=321,
                        repetition_penalty=1.3, repetition_context_size=8,
                        max_tokens=20)),
    ([7, 7, 2, 1], dict(max_tokens=1)),
]


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


@pytest.fixture(scope="module")
def tiny_model():
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return model, params


def _mk_batcher(tiny_model, dev_idx, kv_dtype=None):
    model, params = tiny_model
    devices = jax.devices()
    eng = PipelineEngine(
        model, params, make_mesh(pp=1, devices=devices[dev_idx:dev_idx + 1]),
        microbatches=2, max_seq=64, cache_dtype=jnp.float32,
        prefill_chunk=8, pool_pages=10, page_size=8, kv_dtype=kv_dtype,
    )
    return ContinuousBatcher(eng, decode_block=3)


@pytest.fixture(scope="module")
def disagg_setup(tiny_model):
    """One prefill + one decode replica behind a coordinator, plus a
    monolithic batcher of identical geometry as the parity reference."""
    co = DisaggCoordinator(
        ReplicaSet([_mk_batcher(tiny_model, 0)], role="prefill"),
        ReplicaSet([_mk_batcher(tiny_model, 1)], role="decode"),
    )
    mono = _mk_batcher(tiny_model, 2)
    yield co, mono
    co.close()
    mono.close()


def _refs(gen, jobs):
    return [[t for t, _ in gen.generate_step(p, **kw)] for p, kw in jobs]


# ------------------------------------------------------------ tentpole
@hard_timeout(120)
def test_handoff_streams_bit_identical_to_monolithic(disagg_setup):
    """Greedy, seeded-stochastic, and prefill-complete streams through the
    split pools match the monolithic batcher token for token, and the
    bookkeeping shows the handoffs actually happened (this is not
    serve-in-place parity by accident)."""
    co, mono = disagg_setup
    before = co.handoff_stats()
    assert _refs(co, JOBS) == _refs(mono, JOBS)
    h = co.handoff_stats()
    # two handoffs (the max_tokens=1 job finishes inside prefill) with a
    # real shipped payload and a measured DMA+control latency window
    assert h["handoffs"] - before["handoffs"] == 2
    assert h["bytes_total"] > before["bytes_total"]
    assert h["window"] >= 2 and h["ms_p50"] is not None
    assert h["fallbacks"] == before["fallbacks"]
    r = co.resilience_stats()
    assert r["handoffs"] == h["handoffs"]
    assert r["handoffs_out"] >= 2  # prefill pool exported the parked slots
    assert r["migrations_in"] >= 2  # decode pool admitted via resume
    health = co.health()
    assert health["status"] == "ok" and health["serving"] and health["disagg"]
    assert set(health["pools"]) == {"prefill", "decode"}
    fs = co.fleet_stats()
    assert [p["role"] for p in fs["pools"]] == ["prefill", "decode"]


@hard_timeout(120)
@pytest.mark.slow  # the slow fault sweep also runs concurrent handoffs
def test_concurrent_handoffs_stay_exact(disagg_setup):
    """Interleaved requests handing off while other streams tick keep
    exact content — the handoff overlaps ongoing prefill/decode work."""
    co, mono = disagg_setup
    jobs = [JOBS[0], JOBS[1], JOBS[0]]
    assert run_concurrent(co, jobs) == _refs(mono, jobs)


# ------------------------------------------------- degradation matrix
@hard_timeout(120)
def test_handoff_fault_serves_in_place(disagg_setup):
    """disagg.handoff armed: the control point fails, the prefill pool
    finishes the stream it started — same tokens, zero dropped streams,
    no handoff counted."""
    co, mono = disagg_setup
    before = co.handoff_stats()
    faults.arm("disagg.handoff", exc=faults.FaultError, times=1)
    got = [t for t, _ in co.generate_step(*JOBS[0][:1], **JOBS[0][1])]
    assert got == _refs(mono, JOBS[:1])[0]
    h = co.handoff_stats()
    assert h["handoffs"] == before["handoffs"]
    assert h["fallbacks"].get("handoff_fault", 0) \
        == before["fallbacks"].get("handoff_fault", 0) + 1


@hard_timeout(120)
def test_export_fault_degrades_to_blockless_handoff(disagg_setup):
    """cache.export armed on the prefill scheduler: the block never forms,
    the handoff ships history only, and the decode replica re-prefills
    from the fold — still token-exact."""
    co, mono = disagg_setup
    before = co.handoff_stats()
    faults.arm("cache.export", exc=faults.FaultError, times=1)
    got = [t for t, _ in co.generate_step(*JOBS[0][:1], **JOBS[0][1])]
    assert got == _refs(mono, JOBS[:1])[0]
    h = co.handoff_stats()
    assert h["handoffs"] == before["handoffs"] + 1
    assert h["bytes_total"] == before["bytes_total"]  # nothing shipped


@hard_timeout(120)
def test_import_fault_degrades_to_reprefill(disagg_setup):
    """cache.import armed on the decode replica: the block import fails at
    admission and the scheduler's own fallback re-prefills — the
    coordinator never notices, the stream never changes."""
    co, mono = disagg_setup
    faults.arm("cache.import", exc=faults.FaultError, times=1)
    got = [t for t, _ in co.generate_step(*JOBS[1][:1], **JOBS[1][1])]
    assert got == _refs(mono, JOBS[1:2])[0]


@hard_timeout(120)
def test_decode_pool_down_serves_in_place(disagg_setup):
    """The decode leg's dispatch fails (prefill's passed: after=1): the
    coordinator falls back to the prefill pool, which resumes the stream
    it prefilled — token-exact, decode_failed counted."""
    co, mono = disagg_setup
    before = co.handoff_stats()
    faults.arm("replica.dispatch", exc=faults.FaultError, after=1, times=1)
    got = [t for t, _ in co.generate_step(*JOBS[0][:1], **JOBS[0][1])]
    assert got == _refs(mono, JOBS[:1])[0]
    h = co.handoff_stats()
    assert h["fallbacks"].get("decode_failed", 0) \
        == before["fallbacks"].get("decode_failed", 0) + 1


@hard_timeout(120)
def test_prefill_pool_down_decode_serves_monolithically(disagg_setup):
    """The prefill dispatch fails before any token: the decode pool serves
    the whole request (prefill included) — degraded, never dropped."""
    co, mono = disagg_setup
    before = co.handoff_stats()
    faults.arm("replica.dispatch", exc=faults.FaultError, times=1)
    got = [t for t, _ in co.generate_step(*JOBS[0][:1], **JOBS[0][1])]
    assert got == _refs(mono, JOBS[:1])[0]
    h = co.handoff_stats()
    assert h["fallbacks"].get("prefill_unavailable", 0) \
        == before["fallbacks"].get("prefill_unavailable", 0) + 1


def test_queue_full_before_tokens_is_not_remapped():
    """Admission saturation on the prefill pool re-raises (429 +
    Retry-After is the correct answer) — spilling the overflow onto the
    decode pool would break the SLO isolation disaggregation exists for."""

    class FullPool:
        role = "prefill"
        supports_prefill_only = True

        def generate_step(self, prompt_tokens, **kw):
            raise QueueFullError(4, 4)
            yield  # pragma: no cover — make this a generator function

    class IdlePool:
        role = "decode"
        supports_resume = True
        served = 0

        def generate_step(self, prompt_tokens, **kw):
            self.served += 1
            yield from [(1, None)]

    decode = IdlePool()
    co = DisaggCoordinator(FullPool(), decode)
    with pytest.raises(QueueFullError):
        list(co.generate_step([1, 2, 3], max_tokens=4))
    assert decode.served == 0 and co.handoff_stats()["fallbacks"] == {}


def test_pool_capabilities_validated_at_construction():
    """A prefill pool that can't park prefill-only requests (or a decode
    pool without the resume protocol) is rejected up front, not at the
    first handoff."""

    class Plain:
        # no .replicas attr → the coordinator validates the pool object itself

        def generate_step(self, prompt_tokens, **kw):
            yield from ()

    ok = type("Cap", (Plain,), {"supports_prefill_only": True,
                                "supports_resume": True})()
    with pytest.raises(ValueError):
        DisaggCoordinator(Plain(), ok)
    with pytest.raises(ValueError):
        DisaggCoordinator(ok, Plain())


# ------------------------------------------- per-pool autoscaling split
class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _LoadStub:
    concurrent = True

    def __init__(self):
        self.load = (1, 0, 0)
        self.closed = False

    def stats(self):
        return self.load

    def generate_step(self, prompt_tokens, **kw):
        yield from [(t, None) for t in (1, 2, 3)]

    def close(self):
        self.closed = True


def test_pool_pressure_is_per_pool_and_shed_capped():
    # (active + queued) / slots, plus a capped shed-burst term — one
    # pool's queue never leaks into the other's scalar by construction
    assert pool_pressure(2, 1, 3, 0) == 2.0
    assert pool_pressure(1, 0, 0, 100) == 1.0  # shed term saturates
    assert pool_pressure(0, 0, 0, 0) == 0.0  # empty pool: no div-by-zero


def test_prefill_storm_cannot_spawn_decode_replicas():
    """The satellite bugfix, end to end: two role pools, two controllers,
    a storm on the prefill pool only. The prefill controller spawns; the
    decode controller — reading only its own pool's signals — stays put."""
    clk = _Clock()
    spawned = {"prefill": 0, "decode": 0}
    pools = {}
    ctrls = {}
    for role in ("prefill", "decode"):
        pools[role] = ReplicaSet([_LoadStub()], role=role)

        def factory(role=role):
            spawned[role] += 1
            return _LoadStub()

        ctrls[role] = FleetAutoscaler(
            pools[role], factory, max_replicas=3, clock=clk,
            scale_up_sustain_s=5.0, cooldown_s=0.0, enable_brownout=False,
        )
        assert ctrls[role].state()["role"] == role
    # storm hits ONLY the prefill pool
    pools["prefill"].replicas[0].load = (1, 1, 4)  # pressure 5.0
    for ctrl in ctrls.values():
        ctrl.tick()  # anchors each sustain window
    clk.t += 5.0
    assert ctrls["prefill"].tick()["action"] == "spawn"
    assert ctrls["decode"].tick()["action"] is None
    assert spawned == {"prefill": 1, "decode": 0}
    assert pools["prefill"].fleet_stats()["size"] == 2
    assert pools["decode"].fleet_stats()["size"] == 1
    for pool in pools.values():
        pool.close()


# --------------------------------------------------------- observability
@hard_timeout(120)
def test_metrics_render_role_labels_and_handoff_counters(disagg_setup):
    """/metrics through the coordinator: role-labeled fleet and replica
    gauges plus the mst_disagg_handoff_* family; the monolithic render
    (test_fleet) stays unlabeled — both shapes coexist scrape-side."""
    co, _ = disagg_setup
    # ensure at least one handoff and one counted fallback are on the books
    faults.arm("disagg.handoff", exc=faults.FaultError, times=1)
    list(co.generate_step(*JOBS[0][:1], **JOBS[0][1]))
    faults.disarm()
    list(co.generate_step(*JOBS[0][:1], **JOBS[0][1]))
    text = ServingMetrics(batcher_fn=lambda: co).render()
    assert 'mst_fleet_size{role="prefill"} 1' in text
    assert 'mst_fleet_size{role="decode"} 1' in text
    assert 'mst_replica_inflight{replica="0",role="prefill"} 0' in text
    assert 'mst_replica_inflight{replica="0",role="decode"} 0' in text
    assert "mst_disagg_handoff_total " in text
    assert "mst_disagg_handoff_bytes_total " in text
    # cumulative histogram form (the windowed quantile summary was
    # superseded by Histogram in the coordinator's handoff_stats)
    assert 'mst_disagg_handoff_ms_bucket{le="' in text
    assert "mst_disagg_handoff_ms_sum " in text
    assert "mst_disagg_handoff_ms_count " in text
    assert 'mst_disagg_fallbacks_total{kind="handoff_fault"} ' in text


# ------------------------------------------------------- heavy parity
@pytest.mark.slow
@hard_timeout(300)
@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_disagg_parity_matrix(tiny_model, kv_dtype):
    """Acceptance matrix: fp32 AND int8 KV pools, sequential AND
    concurrent, greedy AND seeded — every disagg stream bit-identical to
    the monolithic batcher with the same pool dtype (the monolithic run
    is the only valid baseline for a quantized pool; see
    test_kv_transfer's matrix note)."""
    mono = _mk_batcher(tiny_model, 3, kv_dtype=kv_dtype)
    co = DisaggCoordinator(
        ReplicaSet([_mk_batcher(tiny_model, 4, kv_dtype=kv_dtype)],
                   role="prefill"),
        ReplicaSet([_mk_batcher(tiny_model, 5, kv_dtype=kv_dtype)],
                   role="decode"),
    )
    try:
        refs = _refs(mono, JOBS)
        assert _refs(co, JOBS) == refs
        assert run_concurrent(co, JOBS) == refs
        assert co.handoff_stats()["fallbacks"] == {}
        assert co.handoff_stats()["handoffs"] == 4  # 2 per pass
    finally:
        co.close()
        mono.close()


@pytest.mark.slow
@hard_timeout(300)
def test_fault_sweep_under_concurrency_zero_dropped_streams(disagg_setup):
    """Every handoff-path fault armed across a concurrent burst: streams
    all complete with exact content — the degradation ladder never drops
    one — and the fallback counters account for each armed fault."""
    co, mono = disagg_setup
    jobs = [JOBS[0], JOBS[1]] * 2
    refs = _refs(mono, jobs)
    for site, kw in [
        ("disagg.handoff", dict(exc=faults.FaultError, times=2)),
        ("cache.export", dict(exc=faults.FaultError, times=2)),
        ("cache.import", dict(exc=faults.FaultError, times=1)),
    ]:
        before = sum(co.handoff_stats()["fallbacks"].values())
        faults.arm(site, **kw)
        assert run_concurrent(co, jobs) == refs
        faults.disarm()
        if site == "disagg.handoff":
            after = sum(co.handoff_stats()["fallbacks"].values())
            assert after == before + 2  # both armed firings serve in place
