"""Content-addressed prefix KV store (ISSUE 12): fleet-wide COW reuse.

The load-bearing properties: (1) greedy streams are bit-identical with the
store on or off — on a device hit (COW fork), a host-tier import, and
EVERY fault-degradation path; (2) a hot prefix is prefilled roughly once:
later same-prefix admissions reuse its pages (device) or import its block
(host) instead of re-running prefill; (3) the disagg coordinator's
full-coverage probe skips the prefill pool entirely; (4) every
``cache.prefix_lookup`` / import / export fault degrades to plain
prefill — never a dropped stream.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.cache import KVCache
from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.kv_transfer import export_block
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh, pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.prefix_store import PrefixStore
from mlx_sharding_tpu.scheduler import ContinuousBatcher
from mlx_sharding_tpu.testing import faults
from mlx_sharding_tpu.utils.digests import chunk_digests
from tests.helpers import hard_timeout

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)

PAGE = 8
# one shared 2-page prefix, divergent tails: the hot-prefix traffic shape
BASE = [7, 7, 2, 1, 9, 4, 4, 6, 3, 17, 42, 5, 11, 2, 2, 8]
JOB_A = (BASE + [5], dict(max_tokens=40))
JOB_B = (BASE + [9], dict(max_tokens=12))
JOB_C = (BASE + [3], dict(max_tokens=12))


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


# ------------------------------------------------------- keying + LPM units
def test_chunk_digests_chain_addresses_whole_prefix():
    """digests[k] depends on every token before it (chained seeding), and
    equal prefixes agree digest-for-digest regardless of the tails."""
    a = chunk_digests(BASE + [5, 5, 5, 5, 5, 5, 5, 5], PAGE)
    b = chunk_digests(BASE + [9, 9, 9, 9, 9, 9, 9, 9], PAGE)
    assert len(a) == len(b) == 3
    assert a[:2] == b[:2] and a[2] != b[2]
    # perturbing an EARLY token changes every later digest (the chain)
    c = chunk_digests([1] + BASE[1:], PAGE)
    assert c[0] != a[0] and c[1] != a[1]


def test_digests_for_caps_one_token_short_of_prompt():
    """The last prompt token must go through prefill (it produces the
    first sample's logits), so a page-exact prompt yields one fewer chunk."""
    store = PrefixStore(host_bytes=1 << 20)
    assert store.digests_for(list(range(17))) == []  # unbound: no geometry
    store.bind_page_size(PAGE)
    assert store.digests_for(list(range(8))) == []
    assert len(store.digests_for(list(range(16)))) == 1
    assert len(store.digests_for(list(range(17)))) == 2
    store.close()


def test_bind_page_size_is_write_once():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(8)
    store.bind_page_size(8)  # idempotent
    with pytest.raises(ValueError, match="chained at page_size=8"):
        store.bind_page_size(16)
    store.close()


def test_constructor_validation():
    with pytest.raises(ValueError, match="host_bytes"):
        PrefixStore(host_bytes=0)
    with pytest.raises(ValueError, match="insert_min_hits"):
        PrefixStore(insert_min_hits=0)
    with pytest.raises(ValueError, match="insert_burst"):
        PrefixStore(insert_burst=0)


def _primed(store, owner, prompt):
    """Register ``prompt``'s chain after the one counted miss the default
    insert_min_hits=1 policy needs; returns (digests, lease)."""
    digests = store.digests_for(prompt)
    store.count_lookup("miss", digests)
    lease = store.register(owner, digests, list(range(len(digests))),
                           prompt[: len(digests) * PAGE], 1024)
    return digests, lease


def test_device_lookup_is_longest_prefix_match():
    """A 3-chunk probe against a 2-chunk entry hits at cover=2 — the
    chained digest makes the longest single probe exact — and acquire is
    the counted COW fork whose LAST release returns the entry."""
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    owner = object()
    digests, lease = _primed(store, owner, BASE + [5])
    assert lease is not None and lease.cover == 2
    probe = store.digests_for(BASE + [9] * 9)  # 3 chunks, shares 2
    assert len(probe) == 3
    assert store.lookup(owner, probe) == ("device", 2)
    assert store.lookup(object(), probe) is None  # other pool: no entry
    fork = store.acquire(owner, probe, 2)
    assert fork is not None and fork.pages == lease.pages[:2]
    assert store.stats()["cow_forks"] == 1
    assert store.stats()["tokens_reused"] == 16
    assert fork.release() is None       # the entry's first lease survives
    entry = lease.release()
    assert entry is not None            # last out: caller demotes
    assert store.lookup(owner, probe) is None
    with pytest.raises(RuntimeError, match="released twice"):
        lease.release()
    store.close()


def test_insertion_policy_min_hits_bucket_and_pause():
    store = PrefixStore(host_bytes=1 << 20, insert_min_hits=2,
                        insert_burst=1)
    store.bind_page_size(PAGE)
    owner = object()
    digests = store.digests_for(BASE + [5])
    store.count_lookup("miss", digests)
    assert store.register(owner, digests, [0, 1], BASE, 64) is None
    assert store.stats()["inserts_damped"] == 1  # one miss < min_hits=2
    store.count_lookup("miss", digests)
    lease = store.register(owner, digests, [0, 1], BASE, 64)
    assert lease is not None  # demand proven; burst token spent
    other = store.digests_for(list(range(100, 117)))
    store.count_lookup("miss", other)
    store.count_lookup("miss", other)
    assert store.register(owner, other, [2, 3], list(range(100, 116)),
                          64) is None  # bucket empty
    store.note_admission()  # one admission = one insert credit
    lease2 = store.register(owner, other, [2, 3], list(range(100, 116)), 64)
    assert lease2 is not None
    store.pause_inserts(True)  # the brownout rung
    third = store.digests_for(list(range(200, 217)))
    store.count_lookup("miss", third)
    store.count_lookup("miss", third)
    store.note_admission()
    assert store.register(owner, third, [4, 5], list(range(200, 216)),
                          64) is None
    assert store.stats()["inserts_paused"] is True
    store.pause_inserts(False)
    lease.release(), lease2.release()
    store.close()


def _pure_prefix_block(tokens, pages=(0, 1)):
    shape = (1, 2, 4, 1, PAGE, 2, 4)
    vals = jnp.arange(np.prod(shape), dtype=jnp.float32).reshape(shape)
    cache = KVCache(k=vals, v=vals + 1000.0, offset=jnp.zeros((), jnp.int32))
    return export_block(
        cache, list(pages), page_size=PAGE, n_tokens=len(pages) * PAGE,
        prompt=list(tokens), history=[], produced=0,
        resume_keys=None, resume_recent=None,
    )


def test_host_tier_covers_full_and_owner_hint():
    """host_block() is non-consuming (any number of admissions import the
    same prefix), covers_full() sees both tiers, and owner_hint() names
    only a DEVICE holder (host blocks import anywhere)."""
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    prompt = BASE + [5]
    digests = store.digests_for(prompt)
    assert store.host_put(digests[-1], _pure_prefix_block(BASE))
    assert store.host_block(digests[-1]) is not None
    assert store.host_block(digests[-1]) is not None  # still there
    assert store.lookup(object(), digests) == ("host", 2)
    assert store.covers_full(prompt)
    assert not store.covers_full(BASE + [9] * 9)  # 3rd chunk unknown
    assert store.owner_hint(prompt) is None  # host tier: no placement pull
    owner = object()
    # a chain the host tier already serves is never duplicated on device
    digests2, dup = _primed(store, owner, prompt)
    assert dup is None
    other = BASE[::-1] + [5]
    _, lease = _primed(store, owner, other)
    assert lease is not None
    assert store.owner_hint(other) is owner
    assert store.stats()["demotions"] == 1
    lease.release()
    store.close()


def test_drop_owner_orphans_outstanding_leases():
    """Pool reset / close: entries vanish without export, outstanding
    leases release as no-ops, and the reset is counted."""
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    owner = object()
    digests, lease = _primed(store, owner, BASE + [5])
    store.drop_owner(owner)
    assert store.lookup(owner, digests) is None
    assert lease.release() is None  # orphan: nothing to demote
    assert store.stats()["evictions_reset"] == 1
    store.close()


def test_lookup_fault_site_fires_on_both_probes():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    faults.arm("cache.prefix_lookup", exc=faults.FaultError)
    with pytest.raises(faults.FaultError):
        store.lookup(object(), store.digests_for(BASE + [5]))
    with pytest.raises(faults.FaultError):
        store.covers_full(BASE + [5])
    store.close()


# --------------------------------------------- engine-level happy/degraded
@pytest.fixture(scope="module")
def store_env():
    """One shared pp=2 paged engine + solo reference; each test wraps it
    in its own batcher + store (the policy knobs differ, the engine
    doesn't)."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
        pool_pages=8, page_size=PAGE,
    )
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
    )
    return eng, ref


def _store_batcher(eng, store, **kw):
    return ContinuousBatcher(eng, decode_block=3, prefix_store=store, **kw)


def _collect(gen_like, job):
    prompt, kw = job
    return [t for t, _ in gen_like.generate_step(prompt, **kw)]


def test_store_requires_paged_engine_and_excludes_prompt_cache(store_env):
    eng, _ = store_env
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    dense = PipelineEngine(
        model, params, make_mesh(pp=1, devices=jax.devices()[:1]),
        microbatches=2, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
    )
    store = PrefixStore(host_bytes=1 << 20)
    with pytest.raises(ValueError, match="paged engine"):
        ContinuousBatcher(dense, prefix_store=store)
    with pytest.raises(ValueError, match="mutually exclusive"):
        ContinuousBatcher(eng, prefix_cache=True, prefix_store=store)
    store.close()


@hard_timeout(420)
def test_sequential_hot_prefix_served_from_host_tier_exact(store_env):
    """The fleet traffic shape, serialized: A prefills + registers the
    prefix, A's finish demotes it to the host tier, B and C import it —
    every stream bit-identical to the solo reference, the prefix
    prefilled once, and the pool fully drained at the end."""
    eng, ref = store_env
    jobs = (JOB_A, JOB_B, JOB_C)
    want = [_collect(ref, j) for j in jobs]
    store = PrefixStore(host_bytes=64 << 20)
    batcher = _store_batcher(eng, store)
    try:
        for job, expect in zip(jobs, want):
            assert _collect(batcher, job) == expect
        s = store.stats()
        assert s["inserts"] >= 1 and s["demotions"] >= 1
        assert s["hits_host"] >= 2  # B and C both imported
        assert s["tokens_reused"] >= 2 * len(BASE)
        assert s["imports_staged"] + s["imports_demand"] >= 2
        assert s["import_faults"] == 0 and s["lookup_faults"] == 0
        # all leases released + demoted: nothing device-resident remains
        assert s["device_blocks"] == 0
        total, in_use, _ = batcher.page_stats()
        assert in_use == 0
    finally:
        batcher.close()
        store.close()


@hard_timeout(420)
def test_concurrent_same_prefix_cow_forks_device_pages(store_env):
    """B admits while A still decodes on the same prefix: B leases A's
    registered pages copy-on-write (zero-copy, no import) and both
    streams stay bit-identical — divergent tails prove the shared pages
    were never rewritten."""
    eng, ref = store_env
    # A must leave pool room for B: admission reserves pages for the whole
    # max_tokens budget (no overcommit), so A takes 5 of 8 pages and B's
    # fork needs only 2 fresh ones past the 2 it shares
    job_a = (BASE + [5], dict(max_tokens=16))
    want_a, want_b = _collect(ref, job_a), _collect(ref, JOB_B)
    store = PrefixStore(host_bytes=64 << 20)
    batcher = _store_batcher(eng, store)
    got_a: list = []
    done_a = threading.Event()

    def consume_a():
        prompt, kw = job_a
        for t, _ in batcher.generate_step(prompt, **kw):
            got_a.append(t)
        done_a.set()

    # throttle every tick: the tiny model decodes A's whole 40-token tail
    # in milliseconds, which would demote the entry before B could even be
    # submitted — the delay keeps A live across B's admission without
    # changing a single token
    faults.arm("scheduler.tick", delay=0.05)
    th = threading.Thread(target=consume_a, daemon=True)
    th.start()
    try:
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if store.stats()["inserts"] >= 1:
                break
            time.sleep(0.005)
        assert store.stats()["inserts"] >= 1, "A's prefill never registered"
        assert not done_a.is_set(), "A finished before B could fork"
        assert _collect(batcher, JOB_B) == want_b
        faults.disarm("scheduler.tick")  # let A's tail run at full speed
        th.join(timeout=90)
        assert not th.is_alive(), "stream A hung"
        assert got_a == want_a
        s = store.stats()
        assert s["cow_forks"] >= 1 and s["hits_device"] >= 1
        assert s["imports_staged"] + s["imports_demand"] == 0
    finally:
        faults.disarm()
        batcher.close()
        store.close()


@hard_timeout(420)
def test_lookup_fault_degrades_to_plain_prefill_exact(store_env):
    """cache.prefix_lookup armed for the whole run: every probe becomes a
    counted no-hit, every stream plain-prefills, nothing drops."""
    eng, ref = store_env
    jobs = (JOB_A, JOB_B)
    want = [_collect(ref, j) for j in jobs]
    store = PrefixStore(host_bytes=64 << 20)
    batcher = _store_batcher(eng, store)
    faults.arm("cache.prefix_lookup", exc=faults.FaultError)
    try:
        for job, expect in zip(jobs, want):
            assert _collect(batcher, job) == expect
        s = store.stats()
        assert s["lookup_faults"] >= 2
        assert s["hits"] == 0 and s["tokens_reused"] == 0
    finally:
        faults.disarm()
        batcher.close()
        store.close()


@hard_timeout(420)
def test_import_fault_reprefills_from_token_zero_exact(store_env):
    """A primes the host tier; cache.import armed: B's host-hit admission
    fails mid-import, keeps its pages, and re-prefills the whole prompt —
    stream still exact, fault counted, no import recorded."""
    eng, ref = store_env
    want_a, want_b = _collect(ref, JOB_A), _collect(ref, JOB_B)
    store = PrefixStore(host_bytes=64 << 20)
    batcher = _store_batcher(eng, store)
    try:
        assert _collect(batcher, JOB_A) == want_a
        assert store.stats()["demotions"] >= 1
        faults.arm("cache.import", exc=faults.FaultError)
        assert _collect(batcher, JOB_B) == want_b
        s = store.stats()
        assert s["import_faults"] >= 1
        assert s["imports_staged"] + s["imports_demand"] == 0
    finally:
        faults.disarm()
        batcher.close()
        store.close()


@hard_timeout(420)
def test_export_fault_drops_prefix_never_stream(store_env):
    """cache.export armed: A's finish-time demotion fails, the prefix is
    simply gone (counted), and A's own stream is untouched."""
    eng, ref = store_env
    want = _collect(ref, JOB_A)
    store = PrefixStore(host_bytes=64 << 20)
    batcher = _store_batcher(eng, store)
    faults.arm("cache.export", exc=faults.FaultError)
    try:
        assert _collect(batcher, JOB_A) == want
        s = store.stats()
        assert s["demote_drops"] >= 1 and s["demotions"] == 0
        assert s["host_blocks"] == 0
    finally:
        faults.disarm()
        batcher.close()
        store.close()


@hard_timeout(420)
def test_brownout_pressure_pauses_insertion_not_hits(store_env):
    """set_pressure(1) (the fleet ladder's first rung) closes the store to
    NEW prefixes while already-resident ones keep serving hits."""
    eng, ref = store_env
    want_a, want_b = _collect(ref, JOB_A), _collect(ref, JOB_B)
    store = PrefixStore(host_bytes=64 << 20)
    batcher = _store_batcher(eng, store)
    try:
        assert _collect(batcher, JOB_A) == want_a  # registers + demotes
        batcher.set_pressure(1)
        assert store.inserts_paused
        assert _collect(batcher, JOB_B) == want_b
        assert store.stats()["hits_host"] >= 1  # hits still serve
        # (B's host-import PROMOTION registers force=True — promotion of
        # an already-proven prefix is exempt from the pause by design)
        base_inserts = store.stats()["inserts"]
        cold = ([23, 31] * 9, dict(max_tokens=4))  # a NEW prefix under
        want_cold = _collect(ref, cold)            # pressure
        assert _collect(batcher, cold) == want_cold
        s = store.stats()
        assert s["inserts"] == base_inserts  # the new prefix was refused
        assert s["inserts_damped"] >= 1
        batcher.set_pressure(0)
        assert not store.inserts_paused
    finally:
        batcher.close()
        store.close()


# ------------------------------------------------------------------ disagg
@hard_timeout(420)
def test_disagg_full_hit_skips_prefill_pool():
    """A store that fully covers the prompt's page-aligned prefix lets the
    coordinator skip phase 1 outright: the decode pool serves from token
    0 (store-hit admission), no handoff happens, and the stream matches
    the two-phase run of the same request."""
    from mlx_sharding_tpu.disagg import DisaggCoordinator
    from mlx_sharding_tpu.replicas import ReplicaSet

    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    devices = jax.devices()

    def mk(dev_idx):
        eng = PipelineEngine(
            model, params,
            make_mesh(pp=1, devices=devices[dev_idx:dev_idx + 1]),
            microbatches=2, max_seq=64, cache_dtype=jnp.float32,
            prefill_chunk=8, pool_pages=10, page_size=PAGE,
        )
        return ContinuousBatcher(eng, decode_block=3, prefix_store=store)

    store = PrefixStore(host_bytes=64 << 20)
    co = DisaggCoordinator(
        ReplicaSet([mk(0)], role="prefill", prefix_store=store),
        ReplicaSet([mk(1)], role="decode", prefix_store=store),
        prefix_store=store,
    )
    job = (BASE + [5], dict(max_tokens=16))
    try:
        first = _collect(co, job)
        h0 = co.handoff_stats()
        assert h0["store_skips"] == 0  # cold: the normal two-phase path
        assert store.stats()["demotions"] >= 1  # handoff demoted the prefix
        second = _collect(co, job)
        assert second == first
        h1 = co.handoff_stats()
        assert h1["store_skips"] == 1
        assert h1["handoffs"] == h0["handoffs"]  # phase 1 never ran
        # the fault site also guards the coverage probe: armed, the
        # coordinator falls back to the normal two-phase plan
        faults.arm("cache.prefix_lookup", exc=faults.FaultError)
        third = _collect(co, job)
        assert third == first
        assert co.handoff_stats()["store_skips"] == 1  # no new skip
    finally:
        faults.disarm()
        co.close()
        store.close()


# -------------------------------------------------- slow parity sweeps
@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", [None, "int8"])
@pytest.mark.parametrize("async_sched", ["off", "on"])
@pytest.mark.parametrize("fault", [None, "cache.prefix_lookup",
                                   "cache.import", "cache.export"])
def test_store_parity_sweep(kv_dtype, async_sched, fault):
    """Full matrix: {bf16, int8 pool} x {sync, async} x {happy, lookup
    fault, import fault, export fault} — hot-prefix streams through the
    store are always bit-identical to the same engine geometry with the
    store off (the int8 pool's quantization drift makes the fp32 stream
    an invalid reference)."""
    eng_kw = dict(kv_dtype=kv_dtype) if kv_dtype else {}
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)

    def mk_engine():
        return PipelineEngine(
            model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
            cache_dtype=jnp.float32, prefill_chunk=8,
            pool_pages=8, page_size=PAGE, **eng_kw,
        )

    plain = ContinuousBatcher(mk_engine(), decode_block=3)
    try:
        want = [_collect(plain, j) for j in (JOB_A, JOB_B, JOB_C)]
    finally:
        plain.close()
    store = PrefixStore(host_bytes=64 << 20)
    batcher = ContinuousBatcher(
        mk_engine(), decode_block=3, prefix_store=store,
        async_sched=async_sched,
    )
    if fault:
        faults.arm(fault, exc=faults.FaultError)
    try:
        got = [_collect(batcher, j) for j in (JOB_A, JOB_B, JOB_C)]
        assert got == want
        s = store.stats()
        if fault is None:
            assert s["hits"] >= 2 and s["tokens_reused"] >= 2 * len(BASE)
        elif fault == "cache.prefix_lookup":
            assert s["lookup_faults"] >= 2 and s["hits"] == 0
        elif fault == "cache.import":
            assert s["import_faults"] >= 1
        else:
            assert s["demote_drops"] >= 1 and s["host_blocks"] == 0
    finally:
        faults.disarm()
        batcher.close()
        store.close()
