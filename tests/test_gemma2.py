import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.config import Gemma2Config
from mlx_sharding_tpu.loading import load_model
from mlx_sharding_tpu.models.gemma2 import Gemma2Model

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

TINY_HF = dict(
    vocab_size=160,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=4,  # covers both sliding (even) and global (odd) layers
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    max_position_embeddings=128,
    rms_norm_eps=1e-6,
    query_pre_attn_scalar=16,
    sliding_window=8,  # small so the window actually bites in tests
    attn_logit_softcapping=50.0,
    final_logit_softcapping=30.0,
)


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("tiny_gemma2")
    torch.manual_seed(5)
    model = transformers.Gemma2ForCausalLM(transformers.Gemma2Config(**TINY_HF))
    model.eval()
    model.save_pretrained(path, safe_serialization=True)
    return path, model


def test_logits_parity_full(hf_checkpoint):
    path, hf_model = hf_checkpoint
    tokens = [[2, 45, 99, 3, 27, 81, 5, 9, 101, 33, 72, 4]]  # > sliding_window
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.numpy()
    model, params = load_model(str(path), dtype=jnp.float32)
    got, _ = model(
        params, jnp.asarray(tokens, jnp.int32), model.make_cache(1, 32, jnp.float32)
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-3, atol=3e-3)


def test_two_stage_parity_tied_embed_on_last(hf_checkpoint):
    """Gemma-2's tied head means the LAST stage needs the embedding too
    (ref gemma2.py:23-24)."""
    path, hf_model = hf_checkpoint
    tokens = [[7, 8, 9, 10]]
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.numpy()
    s0, p0 = load_model(str(path), start_layer=0, end_layer=2, dtype=jnp.float32)
    s1, p1 = load_model(str(path), start_layer=2, end_layer=4, dtype=jnp.float32)
    assert "embed" in p0 and "embed" in p1  # both stages carry it
    h, _ = s0(p0, jnp.asarray(tokens, jnp.int32), s0.make_cache(1, 16, jnp.float32))
    got, _ = s1(p1, h, s1.make_cache(1, 16, jnp.float32))
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-3, atol=3e-3)


def test_prefill_equals_decode(hf_checkpoint):
    path, _ = hf_checkpoint
    model, params = load_model(str(path), dtype=jnp.float32)
    tokens = jnp.asarray([[2, 17, 42, 9, 77, 23, 55, 12, 90, 31]], jnp.int32)
    full, _ = model(params, tokens, model.make_cache(1, 16, jnp.float32))
    cache = model.make_cache(1, 16, jnp.float32)
    outs = []
    for i in range(tokens.shape[1]):
        logits, cache = model(params, tokens[:, i : i + 1], cache)
        outs.append(logits[:, 0])
    got = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(got), rtol=2e-3, atol=2e-3)
