"""Multi-host continuous batching: 2-process jax.distributed deployment.

`--concurrent N` under `--coordinator`: rank 0 runs the real scheduler and
broadcasts each device op; rank 1 mirrors them on an identical batcher
(parallel/multihost.py batched protocol). Every response must match the
identical request served by a single-process `--concurrent` server —
including seeded sampling, slot reuse across requests, interleaved
admission, and early stream termination (stop sequences → OP_B_CANCEL).
"""

import signal
import threading

import pytest

from tests.test_multihost import (
    _env,
    _free_port,
    _post_completion,
    _spawn_server,
    _wait_health,
    ckpt,  # noqa: F401 — module-scoped fixture reused
)

CONCURRENT = [
    "--concurrent", "2", "--paged-pool", "12", "--page-size", "16",
    # prefix cache ON deployment-wide: worker mirrors must rebuild the
    # identical content-addressed index from the op stream alone (the
    # round-4 multi-host fence, lifted in round 5) — every parity check
    # below now also proves the mirrored page tables never diverge
    "--prompt-cache",
]


def _forced_token(ckpt_dir):
    """A (token_id, text) pair the battery can force via logit_bias so a
    stop sequence deterministically truncates the stream mid-request —
    exercising consumer abandonment (OP_B_CANCEL in the batched protocol)."""
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(ckpt_dir)
    for tid in range(1, tok.vocab_size):
        text = tok.decode([tid])
        # the stop string must re-encode to [tid, tid] (stop sequences are
        # matched on raw ids) — BPE may merge a doubled token into another id
        if (
            text.strip() and text.isprintable()
            and tok(text + text, add_special_tokens=False)["input_ids"]
            == [tid, tid]
        ):
            return tid, text
    raise AssertionError("no printable self-doubling token in the tiny vocab")


def _run_requests(port, forced):
    """The request battery, identical against either deployment."""
    tid, ttext = forced
    out = {}
    # stop sequence matched mid-stream: the consumer abandons the request
    # with 8 tokens unproduced → slot cancel; the next requests prove the
    # deployment stayed aligned afterwards
    s, r = _post_completion(
        port,
        {"prompt": "the quick", "max_tokens": 10, "seed": 9,
         "logit_bias": {str(tid): 100.0}, "stop": [ttext + ttext]},
    )
    assert s == 200
    out["cancelled"] = r["choices"][0]["text"]
    # greedy, slot 0
    s, r = _post_completion(
        port, {"prompt": "the quick brown fox", "max_tokens": 8, "seed": 3})
    assert s == 200
    out["greedy"] = r["choices"][0]["text"]
    # seeded sampling — exercises the replicated PRNG chain
    s, r = _post_completion(
        port,
        {"prompt": "hello world", "max_tokens": 8, "seed": 11,
         "temperature": 0.8, "top_p": 0.9},
    )
    assert s == 200
    out["sampled"] = r["choices"][0]["text"]
    # multi-chunk prefill (prompt longer than --prefill-chunk 16)
    s, r = _post_completion(
        port,
        {"prompt": "one two three four five six seven eight nine ten "
                   "eleven twelve thirteen fourteen fifteen sixteen "
                   "seventeen eighteen", "max_tokens": 6, "seed": 4},
    )
    assert s == 200
    out["long"] = r["choices"][0]["text"]
    # two interleaved requests — mid-decode admission into the second slot
    results = [None, None]

    def post(i, body):
        results[i] = _post_completion(port, body)

    threads = [
        threading.Thread(target=post, args=(0, {
            "prompt": "alpha beta", "max_tokens": 10, "seed": 21})),
        threading.Thread(target=post, args=(1, {
            "prompt": "gamma delta", "max_tokens": 10, "seed": 22})),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    for i in (0, 1):
        assert results[i] is not None and results[i][0] == 200
    out["inter_a"] = results[0][1]["choices"][0]["text"]
    out["inter_b"] = results[1][1]["choices"][0]["text"]
    # shared system prompt: the later requests prefix-hit the pages the
    # first registered (page_size 16 → the long shared head spans a full
    # page); token-exactness across deployments proves the hit path
    sys_p = ("one two three four five six seven eight nine ten eleven "
             "twelve thirteen fourteen fifteen sixteen seventeen ")
    s, r = _post_completion(
        port, {"prompt": sys_p + "alpha", "max_tokens": 6, "seed": 31})
    assert s == 200
    out["pc_a"] = r["choices"][0]["text"]
    s, r = _post_completion(
        port, {"prompt": sys_p + "beta", "max_tokens": 6, "seed": 32})
    assert s == 200
    out["pc_b"] = r["choices"][0]["text"]
    return out


def _metric(port, name):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", "/metrics")
    body = conn.getresponse().read().decode()
    conn.close()
    for line in body.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    return None


@pytest.mark.slow  # ~75s: spawns a live 2-process deployment
def test_two_process_concurrent_matches_single_process(ckpt, tmp_path):  # noqa: F811
    forced = _forced_token(ckpt)
    # reference: single process, 4 local devices, same batching config
    port1 = _free_port()
    log1 = open(tmp_path / "single.log", "w")
    p_single = _spawn_server(ckpt, port1, CONCURRENT, 4, log1)
    try:
        _wait_health(port1, [p_single])
        ref = _run_requests(port1, forced)
    finally:
        p_single.send_signal(signal.SIGTERM)
        p_single.wait(timeout=30)

    # deployment under test: 2 processes x 2 devices, same 4-stage mesh
    port0 = _free_port()
    coord = f"localhost:{_free_port()}"
    mh = [*CONCURRENT, "--coordinator", coord, "--num-processes", "2"]
    log_r0 = open(tmp_path / "rank0.log", "w")
    log_r1 = open(tmp_path / "rank1.log", "w")
    r0 = _spawn_server(ckpt, port0, [*mh, "--process-id", "0"], 2, log_r0)
    r1 = _spawn_server(ckpt, _free_port(), [*mh, "--process-id", "1"], 2, log_r1)
    try:
        _wait_health(port0, [r0, r1])
        got = _run_requests(port0, forced)
        assert got == ref
        # the deployment's prefix cache actually hit (not just didn't break)
        hits = _metric(port0, "mst_prefix_cache_hits_total")
        assert hits is not None and hits >= 1
    finally:
        for p in (r0, r1):
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in (r0, r1):
            try:
                p.wait(timeout=30)
            except Exception:
                p.kill()
