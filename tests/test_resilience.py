"""Serving resilience: deadlines, load shedding, circuit breakers and the
fault-injection harness (testing/faults.py) that makes each failure mode
happen deterministically on CPU — a wedged engine tick, an overloaded
queue, a dead replica, a dropped multi-host collective, a client that
vanishes mid-SSE-stream. Every test is bounded by an alarm (pytest-timeout
is not available here): a reclamation bug must fail one test, not hang
tier-1."""

import http.client
import json
import threading
import time

import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.replicas import ReplicaSet
from mlx_sharding_tpu.resilience import (
    Deadlines,
    QueueFullError,
    ReplicasUnavailableError,
    RequestTimeoutError,
)
from mlx_sharding_tpu.scheduler import ContinuousBatcher
from mlx_sharding_tpu.server.openai_api import ModelProvider, make_server
from mlx_sharding_tpu.testing import faults
from mlx_sharding_tpu.utils.observability import ServingMetrics
from tests.helpers import hard_timeout
from tests.test_tokenizer_utils import ByteTokenizer

TINY = dict(
    vocab_size=300,  # covers the byte tokenizer's id range
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


@pytest.fixture(autouse=True)
def _disarm_after():
    """No fault may leak into the next test (or the rest of tier-1)."""
    yield
    faults.disarm()


@pytest.fixture(scope="module")
def mp():
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return model, params


def _batcher(mp, *, slots=2, paged=False, **kw):
    model, params = mp
    extra = dict(pool_pages=8, page_size=8) if paged else {}
    eng = PipelineEngine(
        model, params, pipeline_mesh(1), microbatches=slots, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8, **extra,
    )
    return ContinuousBatcher(eng, decode_block=4, **kw)


def _wait_for(cond, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _wedge(gate):
    """Arm a gate fault at scheduler.tick and block until the scheduler
    thread is provably parked on it (fired implies it is inside trigger(),
    and it cannot drain/admit anything until the gate is released)."""
    f = faults.arm("scheduler.tick", gate=gate)
    _wait_for(lambda: f.fired >= 1, msg="scheduler thread to hit the gate")
    return f


# ------------------------------------------------------------- unit: faults
def test_fault_match_times_after():
    f = faults.arm("site.x", exc=faults.FaultError, times=1, after=1,
                   match={"replica": 2})
    faults.inject("site.x", replica=1)  # match miss
    faults.inject("site.x", replica=2)  # consumed by `after`
    with pytest.raises(faults.FaultError):
        faults.inject("site.x", replica=2)
    faults.inject("site.x", replica=2)  # times exhausted: no-op
    assert f.fired == 1 and f.skipped == 1
    faults.disarm("site.x")
    faults.inject("site.x", replica=2)  # disarmed: no-op


def test_fault_env_parsing():
    faults._parse_env(
        "scheduler.tick:delay=0.5:times=2, ,bogus:exc=nosuch,"
        "replica.dispatch:exc=runtime"
    )
    try:
        armed = faults._ARMED
        (f,) = armed["scheduler.tick"]
        assert f.delay == 0.5 and f.times == 2
        assert armed["replica.dispatch"][0].exc is RuntimeError
        # the malformed entry is dropped, not fatal
        assert "bogus" not in armed
    finally:
        faults.disarm()


# ---------------------------------------------------------- unit: deadlines
def test_deadline_validation():
    for bad in (0, -1, "2", True):
        with pytest.raises(ValueError):
            Deadlines.start(request_timeout=bad)
    d = Deadlines.start(ttft_timeout=1.5)
    # the stall watchdog inherits the TTFT budget by default
    assert d.stall_timeout == 1.5
    assert d.total_deadline is None and d.ttft_deadline is not None
    d2 = Deadlines.start(request_timeout=3.0, stall_timeout=0.5)
    assert d2.stall_timeout == 0.5 and d2.ttft_deadline is None


def test_batcher_rejects_bad_deadlines(mp):
    b = _batcher(mp)
    try:
        with pytest.raises(ValueError):
            b.generate_step([1, 2], max_tokens=4, ttft_timeout=-1)
    finally:
        b.close()


# ------------------------------------------------------- unit: empty prompt
def test_empty_prompt_rejected_everywhere(mp):
    model, params = mp
    gen = Generator(model, params, max_seq=64, cache_dtype=jnp.float32,
                    prefill_chunk=8)
    with pytest.raises(ValueError, match="empty prompt"):
        next(gen.generate_step([], max_tokens=4))
    eng = PipelineEngine(
        model, params, pipeline_mesh(1), max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    with pytest.raises(ValueError, match="empty prompt"):
        next(eng.generate_step([], max_tokens=4))
    b = _batcher(mp)
    try:
        # eager admission: raises at call time, before any request exists
        with pytest.raises(ValueError, match="empty prompt"):
            b.generate_step([], max_tokens=4)
    finally:
        b.close()


def test_empty_prompt_rejected_chained():
    from mlx_sharding_tpu.parallel.chained import ChainedPipeline
    from tests.test_chained_pipeline import TINY as CH_TINY, _stage

    full = LlamaModel(LlamaConfig(**CH_TINY))
    params = full.init_params(jax.random.PRNGKey(0), jnp.float32)
    m, p = _stage(CH_TINY, params, 0, CH_TINY["num_hidden_layers"])
    chain = ChainedPipeline(
        [m], [p], max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    with pytest.raises(ValueError, match="empty prompt"):
        next(chain.generate_step([], max_tokens=4))


# --------------------------------------------- wedged tick → structured 504
@hard_timeout(180)
def test_wedged_tick_ttft_timeout_and_reclaim(mp):
    """Acceptance #1: wedge the engine mid-serving — the waiting client gets
    a structured TTFT timeout immediately (not a hang), and once the engine
    revives, the cancelled request's slot and KV pages are reclaimed."""
    b = _batcher(mp, paged=True)
    gate = threading.Event()
    try:
        list(b.generate_step([1, 2, 3], max_tokens=4))  # compile + warm
        _wait_for(lambda: b.stats()[1] == 0, msg="warmup slot reclaim")
        _, baseline_in_use, _ = b.page_stats()

        _wedge(gate)
        t0 = time.monotonic()
        it = b.generate_step([9, 8, 7], max_tokens=8, ttft_timeout=0.3)
        with pytest.raises(RequestTimeoutError) as ei:
            next(it)
        assert ei.value.kind == "ttft"
        assert ei.value.budget_s == pytest.approx(0.3)
        # released at the deadline, not after the wedge cleared
        assert time.monotonic() - t0 < 5.0
        assert b.timeouts == 1
        assert b.resilience_stats()["timeouts"] == 1

        gate.set()
        faults.disarm()
        _wait_for(
            lambda: b.stats()[1] == 0 and b.stats()[2] == 0
            and b.page_stats()[1] <= baseline_in_use,
            msg="slot + page reclaim after the wedge cleared",
        )
        # the engine is fully serviceable again
        assert len(list(b.generate_step([4, 5], max_tokens=3))) == 3
    finally:
        gate.set()
        faults.disarm()
        b.close()


@hard_timeout(180)
def test_stall_watchdog_mid_stream(mp):
    """A stream that produced tokens and then stalls trips the inter-token
    watchdog with kind='stall' (not ttft — the stream had started)."""
    b = _batcher(mp, slots=1)
    gate = threading.Event()
    try:
        list(b.generate_step([1, 2], max_tokens=4))  # compile + warm
        # slow the ticks so the stream is still mid-flight when the gate
        # engages (the engine decodes regardless of consumer pace)
        faults.arm("scheduler.tick", delay=0.05)
        it = b.generate_step(
            [3, 4], max_tokens=30, ttft_timeout=10.0, stall_timeout=0.3
        )
        first = next(it)  # stream is live
        assert isinstance(first, tuple)
        _wedge(gate)  # now the engine stops producing
        with pytest.raises(RequestTimeoutError) as ei:
            for _ in it:
                pass
        assert ei.value.kind == "stall"
        assert b.timeouts == 1
    finally:
        gate.set()
        faults.disarm()
        b.close()


# ------------------------------------------------- admission control / shed
@hard_timeout(180)
def test_queue_full_sheds_synchronously(mp):
    b = _batcher(mp, slots=1, max_queue=1)
    gate = threading.Event()
    try:
        list(b.generate_step([1, 2], max_tokens=4))  # compile + warm
        _wedge(gate)  # nothing drains: submissions pile up at the bound
        it1 = b.generate_step([5, 6], max_tokens=4)  # depth 1 == max_queue
        with pytest.raises(QueueFullError) as ei:
            b.generate_step([7, 8], max_tokens=4)
        assert ei.value.retry_after_s > 0
        assert b.shed_queue_full == 1
        assert b.resilience_stats()["shed_queue_full"] == 1
        gate.set()
        faults.disarm()
        # the admitted request is unharmed by its neighbor's rejection
        assert len(list(it1)) == 4
        m = ServingMetrics(batcher_fn=lambda: b)
        out = m.render()
        assert 'mst_requests_shed_total{reason="queue_full"} 1' in out
        assert "mst_max_queue 1" in out
    finally:
        gate.set()
        faults.disarm()
        b.close()


@hard_timeout(180)
def test_queue_wait_shed_before_prefill(mp):
    """A queued request whose TTFT budget expires while waiting for a slot
    is shed by the scheduler (kind='queue') before any prefill is spent."""
    b = _batcher(mp, slots=1)
    try:
        list(b.generate_step([1, 2], max_tokens=4))  # compile + warm
        # slow every tick so request A holds the only slot long enough
        faults.arm("scheduler.tick", delay=0.03)
        it_a = b.generate_step([1, 2], max_tokens=40)
        next(it_a)  # A admitted and producing
        it_b = b.generate_step([3, 4], max_tokens=4, ttft_timeout=0.25)
        _wait_for(lambda: b.shed_deadline == 1, msg="queued request shed")
        time.sleep(0.1)  # let the scheduler's error delivery land
        with pytest.raises(RequestTimeoutError) as ei:
            next(it_b)
        assert ei.value.kind == "queue"
        assert b.timeouts == 0  # shed scheduler-side, not a consumer timeout
        faults.disarm()
        assert len(list(it_a)) == 39  # A unaffected
    finally:
        faults.disarm()
        b.close()


@hard_timeout(180)
def test_stall_timeout_alone_bounds_first_token(mp):
    """With ONLY stall_timeout set, the watchdog must also bound the wait
    for the FIRST token — a wedged engine can't hang a caller who asked
    for an inter-token watchdog but set no TTFT budget."""
    b = _batcher(mp, slots=1)
    gate = threading.Event()
    try:
        list(b.generate_step([1, 2], max_tokens=4))  # compile + warm
        _wedge(gate)
        t0 = time.monotonic()
        it = b.generate_step([3, 4], max_tokens=4, stall_timeout=0.3)
        with pytest.raises(RequestTimeoutError) as ei:
            next(it)
        assert ei.value.kind == "stall"
        assert ei.value.budget_s == pytest.approx(0.3)
        assert time.monotonic() - t0 < 5.0
        assert b.timeouts == 1
    finally:
        gate.set()
        faults.disarm()
        b.close()


@hard_timeout(180)
def test_admission_bound_exact_under_concurrent_submits(mp):
    """Check-then-enqueue is atomic across handler threads: with the
    scheduler wedged (nothing drains), N concurrent submits against
    max_queue=2 admit EXACTLY 2 and shed the rest."""
    b = _batcher(mp, slots=1, max_queue=2)
    gate = threading.Event()
    try:
        list(b.generate_step([1, 2], max_tokens=4))  # compile + warm
        _wedge(gate)
        results = []

        def submit():
            try:
                results.append(b.generate_step([5, 6], max_tokens=2))
            except QueueFullError:
                results.append(None)

        threads = [threading.Thread(target=submit) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        admitted = [r for r in results if r is not None]
        assert len(admitted) == 2  # never over the bound
        assert b.shed_queue_full == 6  # no lost counter increments
        gate.set()
        faults.disarm()
        for it in admitted:  # admitted requests drain normally after revive
            assert len(list(it)) == 2
    finally:
        gate.set()
        faults.disarm()
        b.close()


# ------------------------------------------------------ close() wedge leak
@hard_timeout(180)
def test_close_reports_wedged_scheduler_thread(mp):
    b = _batcher(mp, slots=1)
    gate = threading.Event()
    try:
        list(b.generate_step([1, 2], max_tokens=4))  # start + warm the thread
        _wedge(gate)
        b.close(timeout=0.3)
        assert b.thread_wedged
        assert not b.scheduler_thread_live()
        h = b.health()
        assert h["status"] == "degraded" and not h["serving"]
        out = ServingMetrics(batcher_fn=lambda: b).render()
        assert "mst_scheduler_thread_live 0" in out
    finally:
        gate.set()
        faults.disarm()
        # the revived tick must observe _stop and exit — no leaked threads
        if b._thread is not None:
            b._thread.join(timeout=20)
            assert not b._thread.is_alive()


def test_healthy_close_and_health_states(mp):
    b = _batcher(mp, slots=1)
    assert b.health()["status"] == "ok"  # never started is healthy
    list(b.generate_step([1, 2], max_tokens=3))
    assert b.health() == {
        "status": "ok", "serving": True, "scheduler_thread_live": True,
    }
    b.close()
    h = b.health()
    assert h["status"] == "draining" and not h["serving"]
    assert b.scheduler_thread_live()  # clean exit, not a wedge


# ------------------------------------------------------------ replica stubs
class StubReplica:
    """Scriptable replica: fails on demand, else yields a fixed stream."""

    concurrent = True
    supports_deadlines = True

    def __init__(self, tokens=(1, 2, 3)):
        self.tokens = list(tokens)
        self.fail = False
        self.exc = RuntimeError("injected replica crash")
        self.calls = 0

    def generate_step(self, prompt_tokens, **kw):
        self.calls += 1
        if self.fail:
            raise self.exc
        yield from [(t, None) for t in self.tokens]


@hard_timeout(60)
def test_failover_breaker_opens_and_recovers():
    """Acceptance #3: requests keep succeeding on the survivor while the
    sick replica circuit-breaks out of routing; health says degraded (not
    dead); a half-open probe closes the breaker once the replica heals."""
    r0, r1 = StubReplica(), StubReplica()
    rs = ReplicaSet([r0, r1], breaker_threshold=2, probe_interval=0.2)
    r0.fail = True
    for _ in range(2):  # ties route to r0 first; both fail over to r1
        assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]
    assert rs.failures[0] == 2 and rs.breaker_opens[0] == 1
    h = rs.health()
    assert h["status"] == "degraded" and h["serving"]
    assert h["replicas_live"] == 1
    assert h["replicas"][0]["breaker"] == "open"
    # breaker open: traffic skips r0 entirely
    calls0 = r0.calls
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]
    assert r0.calls == calls0
    # past the probe interval the healed replica gets ONE probe and rejoins
    r0.fail = False
    time.sleep(0.25)
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]
    assert r0.calls == calls0 + 1
    assert rs.health()["status"] == "ok"
    assert rs.breaker_opens[0] == 1  # recovery didn't re-open


@hard_timeout(60)
def test_failed_probe_reopens_breaker():
    r0, r1 = StubReplica(), StubReplica()
    rs = ReplicaSet([r0, r1], breaker_threshold=1, probe_interval=0.15)
    r0.fail = True
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]
    assert rs.breaker_opens[0] == 1
    time.sleep(0.2)  # half-open
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]  # probe fails
    assert rs._breaker_state(0, time.monotonic()) == "open"
    assert rs.breaker_opens[0] == 1  # a re-opened probe is not a new open
    time.sleep(0.2)
    r0.fail = False
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]
    assert rs.health()["status"] == "ok"


@hard_timeout(60)
def test_all_replicas_down_raises_concrete_error():
    r0, r1 = StubReplica(), StubReplica()
    rs = ReplicaSet([r0, r1], breaker_threshold=1, probe_interval=60)
    r0.fail = r1.fail = True
    with pytest.raises(RuntimeError, match="injected replica crash"):
        list(rs.generate_step([1]))
    # both breakers now open; a fresh request has no concrete failure to
    # report and gets the structured 503
    with pytest.raises(ReplicasUnavailableError):
        list(rs.generate_step([1]))
    h = rs.health()
    assert not h["serving"] and h["replicas_live"] == 0


@hard_timeout(60)
def test_started_stream_never_migrates():
    class HalfStream:
        concurrent = True

        def generate_step(self, prompt_tokens, **kw):
            yield (1, None)
            raise RuntimeError("replica died mid-stream")

    rs = ReplicaSet([HalfStream(), StubReplica()])
    it = rs.generate_step([1])
    assert next(it) == (1, None)
    with pytest.raises(RuntimeError, match="mid-stream"):
        list(it)
    assert rs.failures[0] == 1
    assert rs.replicas[1].calls == 0  # no silent retry with KV lost


@hard_timeout(60)
def test_replica_error_classification():
    # queue-full: saturation — retried on the other replica, no strike
    r0, r1 = StubReplica(), StubReplica()
    rs = ReplicaSet([r0, r1])
    r0.fail, r0.exc = True, QueueFullError(4, 4)
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]
    assert rs.failures == [0, 0] and rs._fails_consec == [0, 0]
    # both full: the client's 429 comes through
    r1.fail, r1.exc = True, QueueFullError(4, 4)
    with pytest.raises(QueueFullError):
        list(rs.generate_step([1]))
    # ValueError: the request is bad, not the replica — no retry, no strike
    r0.exc = ValueError("empty prompt")
    r1.fail = False
    calls1 = r1.calls
    with pytest.raises(ValueError):
        list(rs.generate_step([1]))
    assert rs.failures == [0, 0] and r1.calls == calls1  # no retry happened
    # ttft/queue timeouts: saturation (queue wait against a client-settable
    # budget) — propagate, but a healthy-but-busy replica takes NO strike,
    # or tight-budget clients could circuit-break the whole fleet
    for kind in ("ttft", "queue"):
        r0.exc = RequestTimeoutError(kind, 1.0, 1.0)
        with pytest.raises(RequestTimeoutError):
            list(rs.generate_step([1]))
    assert rs.failures == [0, 0] and rs._fails_consec == [0, 0]
    # stall/total timeouts mark a wedged engine: propagate AND strike
    for n, kind in enumerate(("stall", "total"), start=1):
        r0.exc = RequestTimeoutError(kind, 1.0, 1.0)
        with pytest.raises(RequestTimeoutError):
            list(rs.generate_step([1]))
        assert rs.failures[0] == n


@hard_timeout(60)
def test_early_closed_stream_counts_as_success():
    """The server it.close()es every stream it stops reading (eos / stop
    word) — GeneratorExit at the yield must record SUCCESS: sporadic
    failures interleaved with early-closed successes must never accumulate
    into an open breaker."""
    r0, r1 = StubReplica(), StubReplica()
    rs = ReplicaSet([r0, r1], breaker_threshold=2, probe_interval=0.15)
    for _ in range(3):
        r0.fail = True
        assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]  # failover
        r0.fail = False
        it = rs.generate_step([1])  # ties route back to r0
        assert next(it) == (1, None)
        it.close()  # eos/stop-word: the stream is closed mid-iteration
    assert rs.breaker_opens[0] == 0 and rs._fails_consec[0] == 0
    assert rs.health()["status"] == "ok"


@hard_timeout(60)
def test_probe_closed_early_still_closes_breaker():
    """A half-open probe whose consumer stops reading after the first token
    is a SUCCESSFUL probe — the breaker closes and the replica rejoins."""
    r0, r1 = StubReplica(), StubReplica()
    rs = ReplicaSet([r0, r1], breaker_threshold=1, probe_interval=0.15)
    r0.fail = True
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]
    assert rs.breaker_opens[0] == 1
    r0.fail = False
    time.sleep(0.2)  # half-open
    it = rs.generate_step([1])  # routed as the probe
    assert next(it) == (1, None)
    it.close()  # early close must not leave the probe dangling
    assert not rs._probing[0]
    assert rs._breaker_state(0, time.monotonic()) == "closed"
    assert rs.health()["status"] == "ok"


@hard_timeout(60)
def test_probe_ticket_returned_on_queue_full_and_bad_request():
    """A probe that exits via QueueFullError or ValueError takes no verdict
    on replica health, but must hand the probe ticket back — a leaked
    ticket would bar the replica from ever being probed again."""
    r0, r1 = StubReplica(), StubReplica()
    rs = ReplicaSet([r0, r1], breaker_threshold=1, probe_interval=0.1)
    r0.fail = True
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]
    time.sleep(0.15)  # half-open
    r0.exc = QueueFullError(4, 4)
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]  # probe → r1
    assert not rs._probing[0]
    assert rs._breaker_state(0, time.monotonic()) == "half_open"
    r0.exc = ValueError("empty prompt")
    with pytest.raises(ValueError):
        list(rs.generate_step([1]))
    assert not rs._probing[0]
    # still probeable: heal it and the next request closes the breaker
    r0.fail = False
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]
    assert rs._breaker_state(0, time.monotonic()) == "closed"
    assert rs.health()["status"] == "ok"


@hard_timeout(60)
def test_replica_dispatch_fault_site():
    """The replica.dispatch injection point fails one targeted replica."""
    r0, r1 = StubReplica(), StubReplica()
    rs = ReplicaSet([r0, r1], breaker_threshold=1, probe_interval=60)
    faults.arm("replica.dispatch", exc=faults.FaultError, match={"replica": 0})
    assert [t for t, _ in rs.generate_step([1])] == [1, 2, 3]
    assert r0.calls == 0  # died at dispatch, before the replica ran
    assert rs.breaker_opens[0] == 1 and rs.health()["status"] == "degraded"


# --------------------------------------------------------- multihost faults
@hard_timeout(60)
def test_multihost_exchange_drop_marks_plane_dead():
    from mlx_sharding_tpu.parallel.multihost import (
        ControlPlane,
        WorkerTimeoutError,
    )

    cp = ControlPlane(max_prompt=8, timeout_s=30)
    cp.exchange({"header": [1]})  # healthy single-process collective
    assert cp.last_ok is not None and not cp.dead
    faults.arm("multihost.exchange", exc=faults.DropExchange, times=1)
    with pytest.raises(WorkerTimeoutError):
        cp.exchange({"header": [1]})
    assert cp.dead
    faults.disarm()
    with pytest.raises(WorkerTimeoutError):  # dead plane fails fast forever
        cp.exchange({"header": [1]})


# --------------------------------------------------------------- HTTP layer
@pytest.fixture()
def cb_server(mp):
    b = _batcher(mp, slots=1, max_queue=1)
    provider = ModelProvider.__new__(ModelProvider)
    provider.default_model = "tiny"
    provider.trust_remote_paths = False
    provider._key = None
    provider._load_lock = threading.Lock()
    provider._set("tiny", b, ByteTokenizer())
    srv = make_server(provider, "127.0.0.1", 0)
    port = srv.server_address[1]
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield port, b
    srv.shutdown()
    faults.disarm()
    b.close()


def _request(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request(
        method, path,
        json.dumps(body) if body is not None else None,
        {"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    data = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, headers, data


@hard_timeout(300)
def test_http_504_then_429_retry_after(cb_server):
    """Acceptance #2: a wedged engine answers a TTFT-bounded request with a
    structured 504 (not a hang), and once the bounded queue is full every
    further request — buffered or streaming — gets 429 with Retry-After
    (the stream primes its first token before committing to SSE, so the
    429 is a real status code)."""
    port, b = cb_server
    status, _, _ = _request(
        port, "POST", "/v1/completions", {"prompt": "hi", "max_tokens": 4}
    )
    assert status == 200  # compiled + warm
    gate = threading.Event()
    _wedge(gate)
    # wedged engine + TTFT budget → structured 504; the timed-out request
    # stays in the (wedged) submit queue until the scheduler revives, so
    # the queue is now at its --max-queue bound of 1
    status, _, body = _request(
        port, "POST", "/v1/completions",
        {"prompt": "yo", "max_tokens": 4, "ttft_timeout": 0.3},
    )
    assert status == 504, body
    assert json.loads(body)["error"]["type"] == "timeout_error"
    for stream in (False, True):
        status, headers, body = _request(
            port, "POST", "/v1/completions",
            {"prompt": "hi", "max_tokens": 4, "stream": stream},
        )
        assert status == 429, body
        assert int(headers["Retry-After"]) >= 1
        assert json.loads(body)["error"]["type"] == "overloaded_error"
    assert b.shed_queue_full == 2 and b.timeouts == 1
    gate.set()
    faults.disarm()
    # revived: the cancelled request is reaped and the server serves again.
    # Reaping takes the revived scheduler one tick, and a request racing
    # that tick legitimately sees the still-full queue — retry 429s briefly
    # instead of racing the reap.
    deadline = time.monotonic() + 30
    while True:
        status, _, _ = _request(
            port, "POST", "/v1/completions", {"prompt": "hi", "max_tokens": 4}
        )
        if status != 429 or time.monotonic() > deadline:
            break
        time.sleep(0.05)
    assert status == 200


@hard_timeout(300)
def test_http_deadline_param_validation(cb_server):
    port, _ = cb_server
    for bad in (-1, 0, "x", True):
        status, _, body = _request(
            port, "POST", "/v1/completions",
            {"prompt": "hi", "max_tokens": 4, "request_timeout": bad},
        )
        assert status == 400, (bad, body)


@hard_timeout(300)
def test_sse_client_disconnect_reclaims_slot(cb_server):
    """Satellite: a client that vanishes mid-SSE (BrokenPipeError on write)
    must cancel the batcher request — the slot frees within a tick instead
    of decoding to max_tokens for nobody."""
    port, b = cb_server
    status, _, _ = _request(
        port, "POST", "/v1/completions", {"prompt": "hi", "max_tokens": 4}
    )
    assert status == 200  # compiled + warm
    f = faults.arm("server.sse_write", exc=BrokenPipeError, times=1)
    status, _, body = _request(
        port, "POST", "/v1/completions",
        {"prompt": "abcdefgh", "max_tokens": 50, "stream": True},
    )
    # headers went out before the first write died; the body is truncated
    assert status == 200
    assert b"[DONE]" not in body
    assert f.fired == 1
    _wait_for(
        lambda: b.stats()[1] == 0 and b.stats()[2] == 0,
        msg="slot reclaim after client disconnect",
    )
    # well under the 50 requested tokens were generated for the dead client
    faults.disarm()
    status, _, _ = _request(
        port, "POST", "/v1/completions", {"prompt": "hi", "max_tokens": 4}
    )
    assert status == 200  # the server kept serving


@hard_timeout(300)
def test_http_health_replica_degradation():
    """/health over HTTP: degraded-but-200 on partial capacity, 503 when
    every replica is circuit-broken."""
    r0, r1 = StubReplica(), StubReplica()
    rs = ReplicaSet([r0, r1], breaker_threshold=1, probe_interval=60)
    provider = ModelProvider.__new__(ModelProvider)
    provider.default_model = "tiny"
    provider.trust_remote_paths = False
    provider._key = None
    provider._load_lock = threading.Lock()
    provider._set("tiny", rs, ByteTokenizer())
    srv = make_server(provider, "127.0.0.1", 0)
    port = srv.server_address[1]
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        status, _, body = _request(port, "GET", "/health")
        assert status == 200 and json.loads(body)["status"] == "ok"
        r0.fail = True
        list(rs.generate_step([1]))  # opens r0's breaker, succeeds on r1
        status, _, body = _request(port, "GET", "/health")
        payload = json.loads(body)
        assert status == 200  # degraded is still serving
        assert payload["status"] == "degraded"
        assert payload["replicas_live"] == 1
        r1.fail = True
        with pytest.raises(RuntimeError):
            list(rs.generate_step([1]))  # opens r1's breaker too
        status, _, body = _request(port, "GET", "/health")
        assert status == 503
        assert json.loads(body)["replicas_live"] == 0
    finally:
        srv.shutdown()
