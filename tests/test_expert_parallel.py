import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.ops.moe import apply_experts, mixtral_routing
from mlx_sharding_tpu.parallel.expert_parallel import expert_parallel_apply
from mlx_sharding_tpu.parallel.mesh import make_mesh


@pytest.mark.parametrize("ep", [2, 4, 8])
def test_expert_parallel_matches_local(ep):
    rng = np.random.default_rng(0)
    n, h, i, e, k = 32, 16, 24, 8, 2
    x = jnp.asarray(rng.normal(size=(n, h)), jnp.float32)
    router = jnp.asarray(rng.normal(size=(h, e)), jnp.float32)
    wg = jnp.asarray(rng.normal(size=(e, h, i)), jnp.float32) * 0.1
    wu = jnp.asarray(rng.normal(size=(e, h, i)), jnp.float32) * 0.1
    wd = jnp.asarray(rng.normal(size=(e, i, h)), jnp.float32) * 0.1
    weights, idx = mixtral_routing(x, router, k)

    ref = apply_experts(x, weights, idx, wg, wu, wd)
    mesh = make_mesh(ep=ep)
    got = expert_parallel_apply(x, weights, idx, wg, wu, wd, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_expert_parallel_rejects_uneven():
    mesh = make_mesh(ep=4)
    x = jnp.zeros((4, 8))
    w = jnp.zeros((4, 2))
    idx = jnp.zeros((4, 2), jnp.int32)
    wg = jnp.zeros((6, 8, 8))  # 6 experts over ep=4
    with pytest.raises(ValueError, match="not divisible"):
        expert_parallel_apply(x, w, idx, wg, wg, jnp.zeros((6, 8, 8)), mesh)


@pytest.mark.slow  # fused-engine sweep — pp1_ep2 continuous batching stays quick
def test_mixtral_fused_engine_with_ep():
    """EP inside the MODEL FORWARD: Mixtral's expert stacks shard over the
    ep mesh axis within the fused engine (each device computes its resident
    experts for all tokens + one psum) — exact parity with single-device."""
    import jax.numpy as jnp

    from mlx_sharding_tpu.config import MixtralConfig
    from mlx_sharding_tpu.generate import Generator
    from mlx_sharding_tpu.models.mixtral import MixtralModel
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

    cfg = MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=48,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
    )
    model = MixtralModel(cfg)
    params = model.init_params(jax.random.PRNGKey(1), jnp.float32)
    prompt = [5, 9, 2, 44]
    ref = Generator(model, params, max_seq=32, cache_dtype=jnp.float32, prefill_chunk=8)
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=6)]

    for pp, ep in ((2, 2), (1, 4)):
        eng = PipelineEngine(
            model, params, make_mesh(pp=pp, ep=ep), max_seq=32,
            cache_dtype=jnp.float32, prefill_chunk=8,
        )
        got = [t for t, _ in eng.generate_step(prompt, max_tokens=6)]
        assert got == want, f"pp={pp} ep={ep} diverged"
        wg = eng.layer_params["w_gate"]
        assert wg.sharding.shard_shape(wg.shape)[2] == 4 // ep  # expert-sharded


def test_ep_unsupported_arch_raises():
    import jax.numpy as jnp

    from mlx_sharding_tpu.config import LlamaConfig
    from mlx_sharding_tpu.models.llama import LlamaModel
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

    model = LlamaModel(
        LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        )
    )
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    with pytest.raises(ValueError, match="expert parallelism"):
        PipelineEngine(
            model, params, make_mesh(pp=1, ep=2), max_seq=32,
            cache_dtype=jnp.float32, prefill_chunk=8,
        )


@pytest.mark.slow  # ~16s arch-matrix combo; EP parity itself is pinned above
def test_deepseek_fused_engine_with_ep():
    """DeepSeek grouped stacks: only the moe group's routed experts shard
    over ep (nested ep_layer_axes); shared experts/router/attention
    replicate. Exact parity incl. an uneven dense/moe split."""
    import jax.numpy as jnp

    from mlx_sharding_tpu.config import DeepseekV2Config
    from mlx_sharding_tpu.generate import Generator
    from mlx_sharding_tpu.models.deepseek_v2 import DeepseekV2Model
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

    cfg = DeepseekV2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=16, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=4, kv_lora_rank=16,
        q_lora_rank=None, qk_rope_head_dim=8, qk_nope_head_dim=16,
        v_head_dim=12, n_routed_experts=4, n_shared_experts=1,
        num_experts_per_tok=2, first_k_dense_replace=1,
    )
    model = DeepseekV2Model(cfg)
    params = model.init_params(jax.random.PRNGKey(3), jnp.float32)
    prompt = [7, 3, 99, 12]
    ref = Generator(model, params, max_seq=32, cache_dtype=jnp.float32, prefill_chunk=8)
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=6)]

    for pp, ep, bounds in ((2, 2, None), (1, 4, None), (2, 2, [(0, 3), (3, 4)])):
        eng = PipelineEngine(
            model, params, make_mesh(pp=pp, ep=ep), stage_bounds=bounds,
            max_seq=32, cache_dtype=jnp.float32, prefill_chunk=8,
        )
        got = [t for t, _ in eng.generate_step(prompt, max_tokens=6)]
        assert got == want, f"pp={pp} ep={ep} bounds={bounds} diverged"
        wg = eng.layer_params["moe"]["w_gate"]
        assert wg.sharding.shard_shape(wg.shape)[2] == 4 // ep
        # shared experts: stage-sharded (pp) but fully replicated across ep
        sg = eng.layer_params["moe"]["shared_gate"]
        assert sg.sharding.shard_shape(sg.shape) == (1, *sg.shape[1:])


def test_pp1_ep2_continuous_batching():
    """S=1 x ep: the vectorized batched step with the expert psum inside the
    vmap — slot streams must match the serial generator exactly."""
    import jax.numpy as jnp

    from mlx_sharding_tpu.config import MixtralConfig
    from mlx_sharding_tpu.generate import Generator
    from mlx_sharding_tpu.models.mixtral import MixtralModel
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
    from mlx_sharding_tpu.scheduler import ContinuousBatcher
    from tests.helpers import run_concurrent

    cfg = MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
    )
    model = MixtralModel(cfg)
    params = model.init_params(jax.random.PRNGKey(1), jnp.float32)
    eng = PipelineEngine(
        model, params, make_mesh(pp=1, ep=2), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    batcher = ContinuousBatcher(eng, decode_block=4)
    try:
        jobs = [
            ([3, 17], dict(max_tokens=6, seed=4)),
            ([9, 2, 7], dict(max_tokens=6, temperature=0.7, seed=5)),
        ]
        ref = Generator(model, params, max_seq=64, cache_dtype=jnp.float32,
                        prefill_chunk=8)
        for (p, kw), got in zip(jobs, run_concurrent(batcher, jobs)):
            assert got == [t for t, _ in ref.generate_step(p, **kw)]
    finally:
        batcher.close()
