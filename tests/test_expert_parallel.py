import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.ops.moe import apply_experts, mixtral_routing
from mlx_sharding_tpu.parallel.expert_parallel import expert_parallel_apply
from mlx_sharding_tpu.parallel.mesh import make_mesh


@pytest.mark.parametrize("ep", [2, 4, 8])
def test_expert_parallel_matches_local(ep):
    rng = np.random.default_rng(0)
    n, h, i, e, k = 32, 16, 24, 8, 2
    x = jnp.asarray(rng.normal(size=(n, h)), jnp.float32)
    router = jnp.asarray(rng.normal(size=(h, e)), jnp.float32)
    wg = jnp.asarray(rng.normal(size=(e, h, i)), jnp.float32) * 0.1
    wu = jnp.asarray(rng.normal(size=(e, h, i)), jnp.float32) * 0.1
    wd = jnp.asarray(rng.normal(size=(e, i, h)), jnp.float32) * 0.1
    weights, idx = mixtral_routing(x, router, k)

    ref = apply_experts(x, weights, idx, wg, wu, wd)
    mesh = make_mesh(ep=ep)
    got = expert_parallel_apply(x, weights, idx, wg, wu, wd, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_expert_parallel_rejects_uneven():
    mesh = make_mesh(ep=4)
    x = jnp.zeros((4, 8))
    w = jnp.zeros((4, 2))
    idx = jnp.zeros((4, 2), jnp.int32)
    wg = jnp.zeros((6, 8, 8))  # 6 experts over ep=4
    with pytest.raises(ValueError, match="not divisible"):
        expert_parallel_apply(x, w, idx, wg, wg, jnp.zeros((6, 8, 8)), mesh)
