"""Fused dequant-matmul: packed 4-bit weights through the whole stack.

VERDICT r1 item 10: quantized checkpoints should decode with the weights
STILL PACKED in HBM (4x capacity + bandwidth). Kernel parity runs in Pallas
interpret mode; the end-to-end path loads a quantized tiny-llama checkpoint
with keep_quantized=True and must match the dequantize-at-load path.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.ops.quant import dequantize, is_quantized, linear, quantize
from mlx_sharding_tpu.ops.quant_matmul import quant_matmul_pallas


@pytest.mark.parametrize(
    "m,in_dim,out_dim,gs,bits",
    [
        (128, 512, 128, 64, 4),
        (1, 512, 256, 64, 4),  # decode-shaped: one row
        (64, 1024, 128, 128, 4),
        (8, 512, 128, 64, 8),
    ],
)
def test_pallas_kernel_matches_dense(m, in_dim, out_dim, gs, bits):
    rng = np.random.default_rng(3)
    w = rng.normal(size=(out_dim, in_dim)).astype(np.float32)
    q, s, b = quantize(w, group_size=gs, bits=bits)
    dense = np.asarray(
        dequantize(q, s, b, group_size=gs, bits=bits, dtype=jnp.float32)
    )
    x = rng.normal(size=(m, in_dim)).astype(np.float32)
    want = x @ dense.T

    got = quant_matmul_pallas(
        jnp.asarray(x), jnp.asarray(q), jnp.asarray(s, jnp.float32),
        jnp.asarray(b, jnp.float32), group_size=gs, bits=bits,
        block_m=64, block_out=64, block_in=256, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_linear_dispatch_packed_vs_dense():
    """ops.quant.linear must produce the same numbers whether the weight is
    a dense (in, out) array or the packed MLX triple."""
    rng = np.random.default_rng(4)
    w = rng.normal(size=(96, 128)).astype(np.float32)  # (out, in)
    q, s, b = quantize(w, group_size=64, bits=4)
    dense = np.asarray(dequantize(q, s, b, dtype=jnp.float32))

    x = jnp.asarray(rng.normal(size=(2, 5, 128)), jnp.float32)
    want = np.asarray(x @ jnp.asarray(dense.T))
    packed = {
        "q": jnp.asarray(q),
        "scales": jnp.asarray(s, jnp.float32),
        "biases": jnp.asarray(b, jnp.float32),
    }
    assert is_quantized(packed) and not is_quantized(jnp.asarray(dense))
    got = np.asarray(linear(x, packed, 64, 4))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def _quantized_tiny_llama(tmp_path: Path, group_size: int = 64):
    """Write a tiny llama checkpoint whose decoder projections AND vocab
    pair (embed_tokens / lm_head — published 4-bit checkpoints quantize
    both) are MLX-style 4-bit triples (config.quantization present)."""
    from safetensors.numpy import save_file

    cfg = dict(
        model_type="llama", vocab_size=128, hidden_size=64,
        intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=256,
        rms_norm_eps=1e-5, rope_theta=10000.0, tie_word_embeddings=False,
        quantization={"group_size": group_size, "bits": 4},
    )
    rng = np.random.default_rng(7)
    tensors = {}

    def dense(name, shape):
        tensors[name] = (rng.normal(size=shape) * 0.05).astype(np.float32)

    def quant(name, out_d, in_d):
        w = (rng.normal(size=(out_d, in_d)) * 0.05).astype(np.float32)
        q, s, b = quantize(w, group_size=group_size, bits=4)
        tensors[name] = q
        tensors[name.replace(".weight", ".scales")] = s
        tensors[name.replace(".weight", ".biases")] = b

    quant("model.embed_tokens.weight", 128, 64)
    dense("model.norm.weight", (64,))
    quant("lm_head.weight", 128, 64)
    for i in range(2):
        p = f"model.layers.{i}"
        dense(f"{p}.input_layernorm.weight", (64,))
        dense(f"{p}.post_attention_layernorm.weight", (64,))
        quant(f"{p}.self_attn.q_proj.weight", 64, 64)
        quant(f"{p}.self_attn.k_proj.weight", 32, 64)
        quant(f"{p}.self_attn.v_proj.weight", 32, 64)
        quant(f"{p}.self_attn.o_proj.weight", 64, 64)
        quant(f"{p}.mlp.gate_proj.weight", 128, 64)
        quant(f"{p}.mlp.up_proj.weight", 128, 64)
        quant(f"{p}.mlp.down_proj.weight", 64, 128)
    save_file(tensors, str(tmp_path / "model.safetensors"))
    (tmp_path / "config.json").write_text(json.dumps(cfg))
    return tmp_path


def _leaf_bytes(tree):
    return sum(x.nbytes for x in jax.tree.leaves(tree))


def test_keep_quantized_end_to_end(tmp_path):
    from mlx_sharding_tpu.generate import Generator
    from mlx_sharding_tpu.loading import load_model

    path = _quantized_tiny_llama(tmp_path)
    model_d, params_d = load_model(str(path), dtype=jnp.float32)
    model_p, params_p = load_model(
        str(path), dtype=jnp.float32, keep_quantized=True
    )
    # packed layers really are packed (and much smaller); the vocab pair
    # stays packed too — the head matmul is the biggest dense read of a
    # decode step
    assert is_quantized(
        jax.tree.map(
            lambda x: x, params_p["layers"]["q_proj"], is_leaf=is_quantized
        )
    )
    assert is_quantized(params_p["embed"]["weight"])
    assert is_quantized(params_p["lm_head"]["weight"])
    assert _leaf_bytes(params_p["layers"]) < _leaf_bytes(params_d["layers"]) / 2

    prompt = [3, 17, 42, 9, 77]
    ref = Generator(
        model_d, params_d, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    gen = Generator(
        model_p, params_p, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=10)]
    got = [t for t, _ in gen.generate_step(prompt, max_tokens=10)]
    assert got == want


def test_keep_quantized_tied_embedding(tmp_path):
    """Tied models project logits through the packed embed triple (MLX's
    (V, H) layout is already the head's packed orientation) and gather
    embed rows by dequantizing only the looked-up tokens."""
    import json as _json

    from mlx_sharding_tpu.generate import Generator
    from mlx_sharding_tpu.loading import load_model

    path = _quantized_tiny_llama(tmp_path)
    cfg = _json.loads((path / "config.json").read_text())
    cfg["tie_word_embeddings"] = True
    (path / "config.json").write_text(_json.dumps(cfg))

    model_d, params_d = load_model(str(path), dtype=jnp.float32)
    model_p, params_p = load_model(
        str(path), dtype=jnp.float32, keep_quantized=True
    )
    assert is_quantized(params_p["embed"]["weight"])
    assert "lm_head" not in params_p

    prompt = [3, 17, 42, 9, 77]
    ref = Generator(
        model_d, params_d, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    gen = Generator(
        model_p, params_p, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=10)]
    assert [t for t, _ in gen.generate_step(prompt, max_tokens=10)] == want


def _packed_ref(tmp_path):
    """Shared recipe: quantized checkpoint + packed load + reference tokens
    for the canonical prompt."""
    from mlx_sharding_tpu.generate import Generator
    from mlx_sharding_tpu.loading import load_model

    path = _quantized_tiny_llama(tmp_path)
    model, params = load_model(str(path), dtype=jnp.float32, keep_quantized=True)
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    want = [t for t, _ in ref.generate_step([5, 9, 2], max_tokens=8)]
    return path, model, params, want


def test_keep_quantized_fused_pipeline(tmp_path):
    """Packed params ride the fused SPMD engine (tree-aware stage split)."""
    from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

    path, model, params, want = _packed_ref(tmp_path)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    got = [t for t, _ in eng.generate_step([5, 9, 2], max_tokens=8)]
    assert got == want


def test_keep_quantized_gemma2(tmp_path):
    """Gemma-2 packed 4-bit: projections through _linear's quant dispatch,
    tied packed embedding (scaled row-gather dequant on lookup, softcapped
    packed head matmul) — token parity with the dequantize-at-load path."""
    import json as _json

    from safetensors.numpy import save_file

    from mlx_sharding_tpu.generate import Generator
    from mlx_sharding_tpu.loading import load_model

    gs = 32
    cfg = dict(
        model_type="gemma2", vocab_size=64, hidden_size=32,
        intermediate_size=64, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=8, sliding_window=8,
        query_pre_attn_scalar=8.0, rms_norm_eps=1e-6, rope_theta=10000.0,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        tie_word_embeddings=True, max_position_embeddings=128,
        quantization={"group_size": gs, "bits": 4},
    )
    rng = np.random.default_rng(11)
    tensors = {}

    def dense(name, shape):
        tensors[name] = (rng.normal(size=shape) * 0.05).astype(np.float32)

    def quant(name, out_d, in_d):
        w = (rng.normal(size=(out_d, in_d)) * 0.05).astype(np.float32)
        q, s, b = quantize(w, group_size=gs, bits=4)
        tensors[name] = q
        tensors[name.replace(".weight", ".scales")] = s
        tensors[name.replace(".weight", ".biases")] = b

    quant("model.embed_tokens.weight", 64, 32)
    dense("model.norm.weight", (32,))
    for i in range(2):
        p = f"model.layers.{i}"
        for n in ("input_layernorm", "post_attention_layernorm",
                  "pre_feedforward_layernorm", "post_feedforward_layernorm"):
            dense(f"{p}.{n}.weight", (32,))
        quant(f"{p}.self_attn.q_proj.weight", 32, 32)
        quant(f"{p}.self_attn.k_proj.weight", 16, 32)
        quant(f"{p}.self_attn.v_proj.weight", 16, 32)
        quant(f"{p}.self_attn.o_proj.weight", 32, 32)
        quant(f"{p}.mlp.gate_proj.weight", 64, 32)
        quant(f"{p}.mlp.up_proj.weight", 64, 32)
        quant(f"{p}.mlp.down_proj.weight", 32, 64)
    save_file(tensors, str(tmp_path / "model.safetensors"))
    (tmp_path / "config.json").write_text(_json.dumps(cfg))

    model_d, params_d = load_model(str(tmp_path), dtype=jnp.float32)
    model_p, params_p = load_model(
        str(tmp_path), dtype=jnp.float32, keep_quantized=True
    )
    assert is_quantized(params_p["layers"]["q_proj"])
    assert is_quantized(params_p["embed"]["weight"])

    prompt = [3, 17, 42, 9]
    ref = Generator(
        model_d, params_d, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    gen = Generator(
        model_p, params_p, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=10)]
    assert [t for t, _ in gen.generate_step(prompt, max_tokens=10)] == want


def test_keep_quantized_native_checkpoint_rejected(tmp_path):
    """Native (Orbax) checkpoints store dense weights; keep_quantized on
    one is a user error, not a silent no-op."""
    from mlx_sharding_tpu.loading import load_model

    d = tmp_path / "native"
    d.mkdir()
    (d / "native_checkpoint.json").write_text("{}")
    with pytest.raises(ValueError, match="keep_quantized"):
        load_model(str(d), dtype=jnp.float32, keep_quantized=True)


@pytest.mark.slow  # chained variant — fused-pipeline + tp keep the quick signal
def test_keep_quantized_chained_pipeline(tmp_path):
    """--engine chained with --keep-quantized: every stage loads packed."""
    from mlx_sharding_tpu.parallel.chained import load_chained_pipeline

    path, _, _, want = _packed_ref(tmp_path)
    chain = load_chained_pipeline(
        str(path), [(0, 1), (1, 2)], dtype=jnp.float32, keep_quantized=True,
        max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
    )
    for stage_params in chain.params:  # EVERY stage, not just stage 0
        assert is_quantized(stage_params["layers"]["q_proj"])
    got = [t for t, _ in chain.generate_step([5, 9, 2], max_tokens=8)]
    assert got == want


def test_keep_quantized_with_tensor_parallelism(tmp_path):
    """TP over packed 4-bit weights: column-parallel shards dim 0 of the
    (out, in/8) packed layout, row-parallel shards the packed in dim — the
    per-leaf divisibility checks guarantee nibble-word and quant-group
    alignment. Exact token parity at pp1xtp2 and pp2xtp2.

    group_size=32 so the row-parallel in-split (64/2=32) lands on a group
    boundary; gs=64 is the rejection test below."""
    from mlx_sharding_tpu.generate import Generator
    from mlx_sharding_tpu.loading import load_model
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

    path = _quantized_tiny_llama(tmp_path, group_size=32)
    model, params = load_model(str(path), dtype=jnp.float32, keep_quantized=True)
    ref = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    want = [t for t, _ in ref.generate_step([5, 9, 2], max_tokens=8)]

    for pp, tp in ((1, 2), (2, 2)):
        eng = PipelineEngine(
            model, params, make_mesh(pp=pp, tp=tp), max_seq=64,
            cache_dtype=jnp.float32, prefill_chunk=8,
        )
        got = [t for t, _ in eng.generate_step([5, 9, 2], max_tokens=8)]
        assert got == want, f"pp={pp} tp={tp} diverged"
        # column-parallel q_proj: packed dim 0 (out) sharded
        qp = eng.layer_params["q_proj"]["q"]
        assert qp.sharding.shard_shape(qp.shape)[2] == qp.shape[2] // tp
        # row-parallel o_proj: packed dim 1 (in/8) sharded
        op = eng.layer_params["o_proj"]["q"]
        assert op.sharding.shard_shape(op.shape)[3] == op.shape[3] // tp


def test_keep_quantized_tp_group_misalignment_rejected(tmp_path):
    """gs=64 with in=64 and tp=2 would split a quant group in half — the
    scales divisibility check must reject it loudly."""
    from mlx_sharding_tpu.loading import load_model
    from mlx_sharding_tpu.parallel.mesh import make_mesh
    from mlx_sharding_tpu.parallel.pipeline import PipelineEngine

    path = _quantized_tiny_llama(tmp_path)  # gs=64, o_proj in=64
    model, params = load_model(str(path), dtype=jnp.float32, keep_quantized=True)
    with pytest.raises(ValueError, match="not divisible"):
        PipelineEngine(
            model, params, make_mesh(pp=1, tp=2), max_seq=64,
            cache_dtype=jnp.float32, prefill_chunk=8,
        )


def test_keep_quantized_unsupported_arch_rejected(tmp_path, monkeypatch):
    """Architectures without packed wiring must reject keep_quantized
    loudly instead of silently loading dense (every in-tree family now
    supports packed, so the branch is exercised by flipping the flag)."""
    from mlx_sharding_tpu.loading import load_model
    from mlx_sharding_tpu.models.llama import LlamaModel

    path = _quantized_tiny_llama(tmp_path)
    monkeypatch.setattr(LlamaModel, "supports_packed", False)
    with pytest.raises(ValueError, match="keep_quantized is not supported"):
        load_model(str(path), dtype=jnp.float32, keep_quantized=True)


def test_speculative_rejects_mismatched_vocab():
    from mlx_sharding_tpu.config import LlamaConfig
    from mlx_sharding_tpu.models.llama import LlamaModel
    from mlx_sharding_tpu.speculative import SpeculativeGenerator

    tiny = dict(hidden_size=32, intermediate_size=64, num_hidden_layers=1,
                num_attention_heads=4, num_key_value_heads=2)
    model = LlamaModel(LlamaConfig(vocab_size=128, **tiny))
    draft = LlamaModel(LlamaConfig(vocab_size=64, **tiny))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    dparams = draft.init_params(jax.random.PRNGKey(1), jnp.float32)
    with pytest.raises(ValueError, match="vocab"):
        SpeculativeGenerator(model, params, draft, dparams, max_seq=64)


def test_keep_quantized_dense_checkpoint_rejected(tmp_path):
    """keep_quantized on a checkpoint with no quantization config must fail
    loudly — a silent dense load would quietly cost 4x the expected HBM."""
    import transformers

    cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    )
    transformers.LlamaForCausalLM(cfg).save_pretrained(
        tmp_path, safe_serialization=True
    )
    from mlx_sharding_tpu.loading import load_model

    with pytest.raises(ValueError, match="quantized checkpoint"):
        load_model(str(tmp_path), dtype=jnp.float32, keep_quantized=True)
