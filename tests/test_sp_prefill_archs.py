"""Sequence-parallel parity for the non-Llama architectures (VERDICT r4
ask #4): Gemma-2 — alternating sliding/global windows + attention-logit
softcap carried into the ring (with window-aware block skipping) — and
DeepSeek-V2 MLA — compressed-latent MQA via values_from_k, grouped
dense/moe layer scan. Mirrors tests/test_sp_prefill.py and
test_sp_decode.py: sp=4 must reproduce the dense single-device path
token-for-token, through both the gathered-cache decode (default) and the
sharded-KV decode (sp_decode=True)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.config import DeepseekV2Config, Gemma2Config
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.deepseek_v2 import DeepseekV2Model
from mlx_sharding_tpu.models.gemma2 import Gemma2Model
from mlx_sharding_tpu.parallel.mesh import make_mesh
from mlx_sharding_tpu.parallel.sp_prefill import supports_sp_prefill

pytestmark = pytest.mark.slow  # arch-matrix sweep; excluded from tier-1

GEMMA_TINY = dict(
    vocab_size=160,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=4,  # covers sliding (even) and global (odd) layers
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    query_pre_attn_scalar=16.0,
    sliding_window=8,  # small enough that the window bites in a 30-token prompt
)

DSV2_TINY = dict(
    vocab_size=128,
    hidden_size=32,
    intermediate_size=64,
    moe_intermediate_size=16,
    num_hidden_layers=3,
    num_attention_heads=4,
    num_key_value_heads=4,
    kv_lora_rank=16,
    q_lora_rank=None,
    qk_rope_head_dim=8,
    qk_nope_head_dim=16,
    v_head_dim=12,
    n_routed_experts=4,
    n_shared_experts=1,
    num_experts_per_tok=2,
    first_k_dense_replace=1,  # 1 dense + 2 moe: both sp groups scan
)


def _gens(model, params, sp_decode=False):
    dense = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8
    )
    sp = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
        sp_mesh=make_mesh(sp=4), sp_decode=sp_decode,
        decode_block=5 if sp_decode else 16,
    )
    return dense, sp


def _toks(gen, prompt, **kw):
    return [t for t, _ in gen.generate_step(prompt, **kw)]


# -------------------------------------------------------------------- Gemma-2
@pytest.fixture(scope="module")
def gemma():
    model = Gemma2Model(Gemma2Config(**GEMMA_TINY))
    params = model.init_params(jax.random.PRNGKey(3), jnp.float32)
    return model, params


def test_gemma2_sp_supported(gemma):
    assert supports_sp_prefill(gemma[0])


def test_gemma2_sp_prefill_parity(gemma):
    """30-token prompt, window 8: even layers see only a fraction of the
    ring's K/V blocks, so parity proves the window masking AND that block
    skipping drops exactly the blocks that contribute nothing."""
    model, params = gemma
    dense, sp = _gens(model, params)
    prompt = [int(x) for x in np.random.default_rng(1).integers(1, 160, 30)]
    assert _toks(sp, prompt, max_tokens=10) == _toks(
        dense, prompt, max_tokens=10
    )


def test_gemma2_sp_seeded_sampling(gemma):
    model, params = gemma
    dense, sp = _gens(model, params)
    prompt = [int(x) for x in np.random.default_rng(4).integers(1, 160, 27)]
    kw = dict(temperature=0.8, top_p=0.9, seed=42, max_tokens=8)
    assert _toks(sp, prompt, **kw) == _toks(dense, prompt, **kw)


def test_gemma2_sp_decode_parity(gemma):
    """Sharded-KV decode: the partial-softmax merge honors the per-layer
    window/softcap; generation crosses shard boundaries (45 + 12 > 48)."""
    model, params = gemma
    dense, sp = _gens(model, params, sp_decode=True)
    prompt = [int(x) for x in np.random.default_rng(2).integers(1, 160, 45)]
    assert _toks(sp, prompt, max_tokens=12) == _toks(
        dense, prompt, max_tokens=12
    )


# --------------------------------------------------------------- DeepSeek-V2
@pytest.fixture(scope="module", params=["compressed", "full"])
def dsv2(request):
    model = DeepseekV2Model(
        DeepseekV2Config(**DSV2_TINY, mla_cache_mode=request.param)
    )
    params = model.init_params(jax.random.PRNGKey(5), jnp.float32)
    return model, params


def test_dsv2_sp_supported(dsv2):
    assert supports_sp_prefill(dsv2[0])


def test_dsv2_sp_prefill_parity(dsv2):
    """MLA sp prefill (both cache modes): compressed rides the ring as MQA
    over the latent head with values taken from the key rows."""
    model, params = dsv2
    dense, sp = _gens(model, params)
    prompt = [int(x) for x in np.random.default_rng(7).integers(1, 128, 29)]
    assert _toks(sp, prompt, max_tokens=10) == _toks(
        dense, prompt, max_tokens=10
    )


def test_dsv2_sp_decode_parity(dsv2):
    model, params = dsv2
    dense, sp = _gens(model, params, sp_decode=True)
    prompt = [int(x) for x in np.random.default_rng(8).integers(1, 128, 40)]
    assert _toks(sp, prompt, max_tokens=12) == _toks(
        dense, prompt, max_tokens=12
    )


# ------------------------------------------------------------------- Mixtral
@pytest.fixture(scope="module")
def mixtral():
    from mlx_sharding_tpu.config import MixtralConfig
    from mlx_sharding_tpu.models.mixtral import MixtralModel

    model = MixtralModel(
        MixtralConfig(
            vocab_size=160, hidden_size=32, intermediate_size=48,
            num_hidden_layers=3, num_attention_heads=4,
            num_key_value_heads=2, num_local_experts=4,
            num_experts_per_tok=2,
            sliding_window=8,  # small so the window bites (None also valid)
        )
    )
    params = model.init_params(jax.random.PRNGKey(9), jnp.float32)
    return model, params


def test_mixtral_sp_prefill_parity(mixtral):
    """MoE + sliding window through the ring: routing runs replicated per
    sp device on its local rows; the window masks/skips blocks."""
    model, params = mixtral
    assert supports_sp_prefill(model)
    dense, sp = _gens(model, params)
    prompt = [int(x) for x in np.random.default_rng(10).integers(1, 160, 30)]
    assert _toks(sp, prompt, max_tokens=10) == _toks(
        dense, prompt, max_tokens=10
    )


def test_mixtral_sp_decode_parity(mixtral):
    model, params = mixtral
    dense, sp = _gens(model, params, sp_decode=True)
    prompt = [int(x) for x in np.random.default_rng(11).integers(1, 160, 42)]
    assert _toks(sp, prompt, max_tokens=10) == _toks(
        dense, prompt, max_tokens=10
    )
