"""Speculative decoding (speculative.py): greedy token streams must be
EXACTLY the plain-decode streams whatever the draft model is — a good
draft only changes throughput. Rollback is offset-only (rows past the
verified prefix are never attended), so no state can leak between rounds."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.speculative import SpeculativeGenerator

TINY = dict(
    vocab_size=300,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


def build(draft_seed, spec_k=4):
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    draft_cfg = LlamaConfig(**{**TINY, "num_hidden_layers": 1})
    draft = LlamaModel(draft_cfg)
    dparams = draft.init_params(jax.random.PRNGKey(draft_seed), jnp.float32)
    spec = SpeculativeGenerator(
        model, params, draft, dparams, spec_k=spec_k, max_seq=96,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    ref = Generator(
        model, params, max_seq=96, cache_dtype=jnp.float32, prefill_chunk=8
    )
    return spec, ref


@pytest.fixture(scope="module")
def pair():
    return build(draft_seed=1)


def test_exact_with_unrelated_draft(pair):
    """A randomly-initialized draft agrees with the target rarely — the
    stream must be identical anyway (acceptance only buys speed)."""
    spec, ref = pair
    prompt = [3, 17, 42, 9]
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=20)]
    got = [t for t, _ in spec.generate_step(prompt, max_tokens=20)]
    assert got == want


def test_exact_with_perfect_draft():
    """Draft == target: every round accepts the full window; stream still
    exact and the capacity-tail fallback still engages."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    spec = SpeculativeGenerator(
        model, params, model, params, spec_k=4, max_seq=96,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    ref = Generator(
        model, params, max_seq=96, cache_dtype=jnp.float32, prefill_chunk=8
    )
    prompt = [5, 9, 2]
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=30)]
    assert [t for t, _ in spec.generate_step(prompt, max_tokens=30)] == want


def test_exact_with_penalty_and_bias(pair):
    """Sampler transforms participate in verification: repetition penalty
    evolves the window token-by-token and logit_bias shifts the argmax —
    both must match plain decode exactly."""
    spec, ref = pair
    kw = dict(
        max_tokens=16, repetition_penalty=1.5, repetition_context_size=8,
        logit_bias={7: 4.0, 11: -2.0},
    )
    prompt = [1, 2, 3]
    want = [t for t, _ in ref.generate_step(prompt, **kw)]
    assert [t for t, _ in spec.generate_step(prompt, **kw)] == want


def test_spec_k_values(pair):
    """Every window size produces the same stream (K=1 degenerates to
    verify-only decode)."""
    _, ref = pair
    prompt = [8, 8, 4]
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=15)]
    for k in (1, 2, 7):
        spec, _ = build(draft_seed=2, spec_k=k)
        assert [t for t, _ in spec.generate_step(prompt, max_tokens=15)] == want


def test_sampled_requests_fall_back(pair):
    spec, ref = pair
    kw = dict(temperature=0.8, seed=42, max_tokens=10)
    want = [t for t, _ in ref.generate_step([4, 5], **kw)]
    assert [t for t, _ in spec.generate_step([4, 5], **kw)] == want


def test_capacity_edge(pair):
    """Generation that fills the cache to the brim: the spec loop must hand
    off to the blocked tail without overrunning capacity."""
    spec, ref = pair
    prompt = list(range(1, 60))  # 59 tokens, capacity 96
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=37)]
    assert [t for t, _ in spec.generate_step(prompt, max_tokens=37)] == want
