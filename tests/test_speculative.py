"""Speculative decoding (speculative.py): greedy token streams must be
EXACTLY the plain-decode streams whatever the draft model is — a good
draft only changes throughput. Rollback is offset-only (rows past the
verified prefix are never attended), so no state can leak between rounds."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.speculative import SpeculativeGenerator

TINY = dict(
    vocab_size=300,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


def build(draft_seed, spec_k=4):
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    draft_cfg = LlamaConfig(**{**TINY, "num_hidden_layers": 1})
    draft = LlamaModel(draft_cfg)
    dparams = draft.init_params(jax.random.PRNGKey(draft_seed), jnp.float32)
    spec = SpeculativeGenerator(
        model, params, draft, dparams, spec_k=spec_k, max_seq=96,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    ref = Generator(
        model, params, max_seq=96, cache_dtype=jnp.float32, prefill_chunk=8
    )
    return spec, ref


@pytest.fixture(scope="module")
def pair():
    return build(draft_seed=1)


def test_exact_with_unrelated_draft(pair):
    """A randomly-initialized draft agrees with the target rarely — the
    stream must be identical anyway (acceptance only buys speed)."""
    spec, ref = pair
    prompt = [3, 17, 42, 9]
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=20)]
    got = [t for t, _ in spec.generate_step(prompt, max_tokens=20)]
    assert got == want


def test_exact_with_perfect_draft():
    """Draft == target: every round accepts the full window; stream still
    exact and the capacity-tail fallback still engages."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    spec = SpeculativeGenerator(
        model, params, model, params, spec_k=4, max_seq=96,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    ref = Generator(
        model, params, max_seq=96, cache_dtype=jnp.float32, prefill_chunk=8
    )
    prompt = [5, 9, 2]
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=30)]
    assert [t for t, _ in spec.generate_step(prompt, max_tokens=30)] == want


def test_exact_with_penalty_and_bias(pair):
    """Sampler transforms participate in verification: repetition penalty
    evolves the window token-by-token and logit_bias shifts the argmax —
    both must match plain decode exactly."""
    spec, ref = pair
    kw = dict(
        max_tokens=16, repetition_penalty=1.5, repetition_context_size=8,
        logit_bias={7: 4.0, 11: -2.0},
    )
    prompt = [1, 2, 3]
    want = [t for t, _ in ref.generate_step(prompt, **kw)]
    assert [t for t, _ in spec.generate_step(prompt, **kw)] == want


@pytest.mark.slow  # ~14s K-sweep; single-K exactness tests stay tier-1
def test_spec_k_values(pair):
    """Every window size produces the same stream (K=1 degenerates to
    verify-only decode)."""
    _, ref = pair
    prompt = [8, 8, 4]
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=15)]
    for k in (1, 2, 7):
        spec, _ = build(draft_seed=2, spec_k=k)
        assert [t for t, _ in spec.generate_step(prompt, max_tokens=15)] == want


def test_logprobs_requests_fall_back(pair):
    spec, ref = pair
    kw = dict(seed=42, max_tokens=10, want_logprobs=True)
    want = [t for t, _ in ref.generate_step([4, 5], **kw)]
    assert [t for t, _ in spec.generate_step([4, 5], **kw)] == want


# ---------------------------------------------------------------- sampled
# temperature > 0: rejection sampling. The stream legitimately differs
# from non-speculative sampling with the same seed (PRNG consumed
# differently); what must hold is the DISTRIBUTION identity, the
# all-accept behavior for a perfect draft, and per-seed determinism.


@pytest.mark.slow  # statistical distribution check — greedy exactness stays quick
def test_rejection_round_emits_target_distribution():
    """The Leviathan et al. identity, tested on the pure round function:
    whatever q is, the slot-0 emitted token is distributed exactly as p."""
    from mlx_sharding_tpu.speculative import rejection_round

    V, K, N = 12, 3, 20000
    kp, kq, kd = jax.random.split(jax.random.PRNGKey(0), 3)
    p_logits = jax.random.normal(kp, (K, 1, V)) * 1.5
    q_logits = jax.random.normal(kq, (K, 1, V)) * 1.5
    plp = jax.nn.log_softmax(p_logits, axis=-1)
    qlp = jax.nn.log_softmax(q_logits, axis=-1)

    def one(key):
        k_draft, k_round = jax.random.split(key)
        # draft proposes from q, independently per slot (any proposal chain
        # is admissible for the slot-0 identity)
        drafts = jax.vmap(jax.random.categorical)(
            jax.random.split(k_draft, K), qlp[:, 0]
        ).astype(jnp.int32)[:, None]
        gs, m, count = rejection_round(k_round, drafts, qlp, plp)
        return gs[0, 0]

    toks = np.asarray(jax.vmap(one)(jax.random.split(jax.random.PRNGKey(7), N)))
    empirical = np.bincount(toks, minlength=V) / N
    expected = np.asarray(jnp.exp(plp[0, 0]))
    tv = 0.5 * np.abs(empirical - expected).sum()
    assert tv < 0.03, (tv, empirical, expected)


def test_sampled_perfect_draft_accepts_everything():
    """Draft == target ⇒ p == q at every slot ⇒ acceptance probability 1:
    every round must emit the full window."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    spec = SpeculativeGenerator(
        model, params, model, params, spec_k=4, max_seq=96,
        cache_dtype=jnp.float32, prefill_chunk=8,
    )
    toks = [t for t, _ in spec.generate_step(
        [5, 9, 2], max_tokens=21, temperature=0.9, top_p=0.95, seed=3
    )]
    assert len(toks) == 21
    assert spec.rounds > 0
    assert spec.accepted_tokens == spec.spec_k * spec.rounds


def test_sampled_deterministic_per_seed(pair):
    spec, _ = pair
    kw = dict(temperature=0.8, top_p=0.9, max_tokens=18, seed=11,
              repetition_penalty=1.3, logit_bias={7: 2.0})
    a = [t for t, _ in spec.generate_step([4, 5], **kw)]
    b = [t for t, _ in spec.generate_step([4, 5], **kw)]
    assert a == b
    c = [t for t, _ in spec.generate_step([4, 5], **{**kw, "seed": 12})]
    assert a != c  # a 300-vocab 18-token collision is astronomically unlikely


def test_sampled_capacity_edge(pair):
    """The blocked-decode tail engages for sampled requests too and the
    stream stays within capacity."""
    spec, _ = pair
    prompt = list(range(1, 60))
    toks = [t for t, _ in spec.generate_step(
        prompt, max_tokens=37, temperature=0.7, seed=5
    )]
    assert len(toks) == 37


def test_capacity_edge(pair):
    """Generation that fills the cache to the brim: the spec loop must hand
    off to the blocked tail without overrunning capacity."""
    spec, ref = pair
    prompt = list(range(1, 60))  # 59 tokens, capacity 96
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=37)]
    assert [t for t, _ in spec.generate_step(prompt, max_tokens=37)] == want
