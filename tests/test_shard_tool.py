"""Offline shard writer: placement rules + end-to-end reload parity."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.loading import load_model, load_raw_weights
from mlx_sharding_tpu.shard_tool import even_partition, shard_all_stages, save_sharded_weights

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from tests.test_checkpoint import TINY_HF  # noqa: E402


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("src_llama")
    torch.manual_seed(1)
    model = transformers.LlamaForCausalLM(transformers.LlamaConfig(**TINY_HF))
    model.eval()
    model.save_pretrained(path, safe_serialization=True)
    return path, model


def test_even_partition():
    assert even_partition(27, 2) == [(0, 14), (14, 27)]  # BASELINE config split
    assert even_partition(8, 4) == [(0, 2), (2, 4), (4, 6), (6, 8)]


def test_placement_rules(hf_checkpoint, tmp_path):
    path, _ = hf_checkpoint
    save_sharded_weights(path, tmp_path / "s0", 0, 2)
    save_sharded_weights(path, tmp_path / "s1", 2, 3)

    w0 = load_raw_weights(tmp_path / "s0")
    w1 = load_raw_weights(tmp_path / "s1")
    assert any("embed_tokens" in k for k in w0)
    assert not any("embed_tokens" in k for k in w1)
    assert not any("lm_head" in k or k == "model.norm.weight" for k in w0)
    assert any("lm_head" in k for k in w1)
    assert any(".layers.1." in k for k in w0) and not any(".layers.2." in k for k in w0)
    assert any(".layers.2." in k for k in w1) and not any(".layers.1." in k for k in w1)

    cfg0 = json.loads((tmp_path / "s0" / "config.json").read_text())
    assert cfg0["start_layer"] == 0 and cfg0["end_layer"] == 2
    idx = json.loads((tmp_path / "s0" / "model.safetensors.index.json").read_text())
    assert set(idx["weight_map"].values()) == {"model-00000-00002.safetensors"}


def test_sharded_reload_matches_full(hf_checkpoint, tmp_path):
    """Stages written by the tool, loaded back WITHOUT dynamic bounds (they
    self-describe via baked config), chain to the full model's logits."""
    path, hf_model = hf_checkpoint
    dirs = shard_all_stages(path, tmp_path, num_stages=2)
    tokens = [[4, 8, 15, 16]]
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.numpy()

    s0, p0 = load_model(str(dirs[0]), dtype=jnp.float32)
    s1, p1 = load_model(str(dirs[1]), dtype=jnp.float32)
    assert s0.config.start_layer == 0 and s1.config.is_last_stage
    h, _ = s0(p0, jnp.asarray(tokens, jnp.int32), s0.make_cache(1, 16, jnp.float32))
    got, _ = s1(p1, h, s1.make_cache(1, 16, jnp.float32))
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-3, atol=2e-3)


def test_emit_native_stage_loads_and_matches(hf_checkpoint, tmp_path):
    """--emit-native writes an Orbax stage restoreable through load_model
    with identical logits to the safetensors stage."""
    path, _ = hf_checkpoint
    out = save_sharded_weights(path, tmp_path / "s0", 0, 3, emit_native=True)
    m_st, p_st = load_model(str(out), dtype=jnp.bfloat16)
    m_nat, p_nat = load_model(str(out / "native"))
    tokens = jnp.asarray([[9, 4, 2]], jnp.int32)
    a, _ = m_st(p_st, tokens, m_st.make_cache(1, 8, jnp.bfloat16))
    b, _ = m_nat(p_nat, tokens, m_nat.make_cache(1, 8, jnp.bfloat16))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_aux_files_copied(hf_checkpoint, tmp_path):
    path, _ = hf_checkpoint
    (path / "tokenizer_config.json").write_text("{}")
    out = save_sharded_weights(path, tmp_path / "aux", 0, 3)
    assert (out / "tokenizer_config.json").exists()
    assert (out / "generation_config.json").exists()  # written by save_pretrained
