"""End-to-end API server tests: a real HTTP server over a tiny random model
with a byte-level tokenizer — every endpoint, streaming, validation."""

import http.client
import json
import threading

import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.server.openai_api import ModelProvider, convert_chat, make_server
from tests.test_tokenizer_utils import ByteTokenizer

TINY = dict(
    vocab_size=300,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


@pytest.fixture(scope="module")
def server():
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    gen = Generator(model, params, max_seq=512, cache_dtype=jnp.float32, prefill_chunk=16)
    provider = ModelProvider.__new__(ModelProvider)
    provider.default_model = "tiny"
    provider.trust_remote_paths = False
    provider._key = None
    provider._load_lock = threading.Lock()
    provider._set("tiny", gen, ByteTokenizer())
    srv = make_server(provider, "127.0.0.1", 0)
    port = srv.server_address[1]
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield port
    srv.shutdown()


def _request(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request(
        method, path,
        json.dumps(body) if body is not None else None,
        {"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, resp.getheader("Content-Type", ""), data


def _sse_chunks(data: bytes):
    out = []
    for block in data.decode().split("\n\n"):
        block = block.strip()
        if block.startswith("data: "):
            payload = block[6:]
            out.append(payload if payload == "[DONE]" else json.loads(payload))
    return out


def test_health_and_static(server):
    status, ctype, body = _request(server, "GET", "/health")
    assert status == 200 and json.loads(body)["status"] == "ok"
    status, ctype, body = _request(server, "GET", "/")
    assert status == 200 and ctype.startswith("text/html") and b"composer" in body
    status, _, body = _request(server, "GET", "/app.js")
    assert status == 200
    status, _, _ = _request(server, "GET", "/../../secrets")
    assert status == 404


def test_completion_non_stream(server):
    status, _, body = _request(
        server, "POST", "/v1/completions",
        {"prompt": "hi", "max_tokens": 8},
    )
    assert status == 200
    resp = json.loads(body)
    assert resp["object"] == "text_completion"
    assert resp["choices"][0]["finish_reason"] in ("length", "stop")
    assert resp["usage"]["prompt_tokens"] == 2
    assert resp["usage"]["completion_tokens"] <= 8
    assert isinstance(resp["choices"][0]["text"], str)


def test_completion_deterministic_greedy(server):
    a = _request(server, "POST", "/v1/completions", {"prompt": "abc", "max_tokens": 6})
    b = _request(server, "POST", "/v1/completions", {"prompt": "abc", "max_tokens": 6})
    assert json.loads(a[2])["choices"][0]["text"] == json.loads(b[2])["choices"][0]["text"]


def test_completion_stream(server):
    status, ctype, body = _request(
        server, "POST", "/v1/completions",
        {"prompt": "hi", "max_tokens": 6, "stream": True},
    )
    assert status == 200 and ctype.startswith("text/event-stream")
    chunks = _sse_chunks(body)
    assert chunks[-1] == "[DONE]"
    final = chunks[-2]
    assert final["choices"][0]["finish_reason"] in ("length", "stop")
    text = "".join(
        c["choices"][0].get("text", "") for c in chunks if isinstance(c, dict)
    )
    assert isinstance(text, str)


def test_chat_completion_fallback_template(server):
    status, _, body = _request(
        server, "POST", "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "hello"}], "max_tokens": 6},
    )
    assert status == 200
    resp = json.loads(body)
    assert resp["object"] == "chat.completion"
    assert resp["choices"][0]["message"]["role"] == "assistant"


def test_chat_completion_stream_role_then_content(server):
    status, _, body = _request(
        server, "POST", "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "hello"}], "max_tokens": 5,
         "stream": True},
    )
    chunks = _sse_chunks(body)
    assert chunks[0]["choices"][0]["delta"]["role"] == "assistant"
    assert chunks[0]["object"] == "chat.completion.chunk"
    assert chunks[-1] == "[DONE]"


def test_logprobs(server):
    status, _, body = _request(
        server, "POST", "/v1/completions",
        {"prompt": "xy", "max_tokens": 4, "logprobs": 3},
    )
    resp = json.loads(body)
    lp = resp["choices"][0]["logprobs"]
    assert len(lp["token_logprobs"]) == len(lp["tokens"]) == len(lp["top_logprobs"])
    assert all(len(t) == 3 for t in lp["top_logprobs"])
    assert all(v <= 0 for v in lp["token_logprobs"])


def test_logit_bias_forces_token(server):
    status, _, body = _request(
        server, "POST", "/v1/completions",
        {"prompt": "q", "max_tokens": 3, "logit_bias": {"65": 100.0}},
    )
    text = json.loads(body)["choices"][0]["text"]
    assert text == "AAA"  # byte 65 == 'A' forced every step


def test_stop_word(server):
    # discover greedy output, then stop on its second character
    _, _, body = _request(server, "POST", "/v1/completions", {"prompt": "m", "max_tokens": 6})
    full = json.loads(body)["choices"][0]["text"]
    if len(full) < 2:
        pytest.skip("greedy output too short to carve a stop word")
    stop = full[1]
    _, _, body = _request(
        server, "POST", "/v1/completions",
        {"prompt": "m", "max_tokens": 6, "stop": stop},
    )
    resp = json.loads(body)
    assert resp["choices"][0]["finish_reason"] == "stop"
    assert stop not in resp["choices"][0]["text"]


def test_validation_errors(server):
    cases = [
        {"prompt": "x", "temperature": -1},
        {"prompt": "x", "top_p": 0},
        {"prompt": "x", "max_tokens": "many"},
        {"prompt": "x", "logprobs": 50},
        {"messages": "not-a-list"},
        {},
    ]
    for i, payload in enumerate(cases):
        route = "/v1/chat/completions" if "messages" in payload else "/v1/completions"
        status, _, body = _request(server, "POST", route, payload)
        assert status == 400, f"case {i} gave {status}"
        assert "error" in json.loads(body)


def test_unknown_route(server):
    status, _, _ = _request(server, "POST", "/v2/nope", {})
    assert status == 404


def test_convert_chat_roles():
    text = convert_chat(
        [{"role": "system", "content": "be brief"},
         {"role": "user", "content": "hi"}]
    )
    assert "ASSISTANT's RULE: be brief" in text
    assert "USER: hi" in text
    assert text.endswith("ASSISTANT:")


def test_api_key_auth():
    """--api-key gates /v1/* with Bearer auth; static and health stay open."""
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    gen = Generator(model, params, max_seq=128, cache_dtype=jnp.float32, prefill_chunk=16)
    provider = ModelProvider.__new__(ModelProvider)
    provider.default_model = "tiny"
    provider.trust_remote_paths = False
    provider._key = None
    provider._load_lock = threading.Lock()
    provider._set("tiny", gen, ByteTokenizer())
    srv = make_server(provider, "127.0.0.1", 0, api_key="sekrit")
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        status, _, data = _request(port, "POST", "/v1/completions",
                                   {"prompt": "a", "max_tokens": 2})
        assert status == 401
        assert json.loads(data)["error"]["type"] == "authentication_error"

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt": "a", "max_tokens": 2}),
                     {"Content-Type": "application/json",
                      "Authorization": "Bearer sekrit"})
        assert conn.getresponse().status == 200
        conn.close()

        status, _, _ = _request(port, "GET", "/health")
        assert status == 200  # ungated
        status, _, _ = _request(port, "GET", "/index.html")
        assert status == 200  # static UI must load to let the user SET a key
    finally:
        srv.shutdown()


def test_speculative_server(server):
    """--draft-model serving path: a SpeculativeGenerator behind the same
    HTTP contract. Greedy completions must be byte-identical to the plain
    generator's (token-exact speculation), and sampled requests must work
    (rejection sampling)."""
    from mlx_sharding_tpu.speculative import SpeculativeGenerator

    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    draft = LlamaModel(LlamaConfig(**{**TINY, "num_hidden_layers": 1}))
    dparams = draft.init_params(jax.random.PRNGKey(5), jnp.float32)
    spec = SpeculativeGenerator(
        model, params, draft, dparams, spec_k=3, max_seq=512,
        cache_dtype=jnp.float32, prefill_chunk=16,
    )
    provider = ModelProvider.__new__(ModelProvider)
    provider.default_model = "tiny"
    provider.trust_remote_paths = False
    provider._key = None
    provider._load_lock = threading.Lock()
    provider._set("tiny", spec, ByteTokenizer())
    srv = make_server(provider, "127.0.0.1", 0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        body = {"prompt": "hello there", "max_tokens": 12}
        s1, _, ref = _request(server, "POST", "/v1/completions", body)
        s2, _, got = _request(port, "POST", "/v1/completions", body)
        assert s1 == s2 == 200
        assert (
            json.loads(got)["choices"][0]["text"]
            == json.loads(ref)["choices"][0]["text"]
        )
        s3, _, sampled = _request(
            port, "POST", "/v1/completions",
            {"prompt": "hi", "max_tokens": 8, "temperature": 0.9, "seed": 2},
        )
        assert s3 == 200
        assert isinstance(json.loads(sampled)["choices"][0]["text"], str)
        s4, _, metrics = _request(port, "GET", "/metrics")
        assert s4 == 200 and b"mst_spec_rounds_total" in metrics
        assert b"mst_spec_tokens_accepted_total" in metrics
    finally:
        srv.shutdown()
