"""Proactive KV residency tiers (ISSUE 9): cold-slot spill with
PRESERVE-style overlapped prefetch.

The load-bearing properties: (1) a decode slot whose consumer stops
pulling tokens is spilled to the host tier and its pool pages freed, and
the stream still delivers EXACTLY the tokens the never-spilled run would;
(2) with prefetch on, scheduled resumes consume a device-staged block
(the overlapped path) — the demand-import fallback count stays ~0 in the
happy path; (3) every ``cache.prefetch`` fault degrades to demand import,
then to re-prefill, never a dropped stream.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.cache import KVCache
from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.kv_transfer import KVSpillTier, export_block
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.resilience import RequestMigratedError
from mlx_sharding_tpu.scheduler import ContinuousBatcher
from mlx_sharding_tpu.testing import faults
from tests.helpers import hard_timeout

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    faults.disarm()


# ----------------------------------------------------- tier + block units
def _pool_cache(pool_pages=6, page=4):
    shape = (1, 2, pool_pages + 1, 1, page, 2, 4)
    vals = jnp.arange(np.prod(shape), dtype=jnp.float32).reshape(shape)
    return KVCache(k=vals, v=vals + 1000.0, offset=jnp.zeros((), jnp.int32))


def _block(history=(5, 6, 7)):
    return export_block(
        _pool_cache(), [2, 4], page_size=4, n_tokens=6,
        prompt=[1, 2, 3], history=list(history), produced=len(history),
        resume_keys=None, resume_recent=None,
    )


def test_tier_hit_miss_and_reject_reason_counters():
    """take() counts hits/misses, put() splits rejects by reason, drop()
    counts neither, and hit_rate reflects the lookup history."""
    tier = KVSpillTier(1 << 20)
    assert tier.put("a", _block())
    assert tier.take("a") is not None
    assert tier.take("a") is None  # gone: a counted miss
    tier.put("b", _block())
    tier.drop("b")  # cancelled-stream cleanup: not a lookup
    s = tier.stats()
    assert (s["hits"], s["misses"]) == (1, 1)
    assert s["hit_rate"] == 0.5
    small = KVSpillTier(8)  # smaller than any block
    assert not small.put("c", _block())
    assert small.stats()["rejects_oversize"] == 1
    small.close()
    tier.close()
    assert not tier.put("d", _block())
    assert tier.stats()["rejects_closed"] == 1
    assert tier.stats()["rejects"] == 1  # aggregate stays in sync


def test_tier_touch_refreshes_lru_order():
    """touch() moves a block to the LRU tail so budget pressure evicts a
    colder one instead of the block about to be re-imported."""
    one = _block().to_host()
    tier = KVSpillTier(3 * one.nbytes + 8)
    for key in ("a", "b", "c"):
        assert tier.put(key, _block().to_host())
    tier.touch("a")  # now the hottest; "b" is the LRU head
    assert tier.put("d", _block().to_host())  # forces one eviction
    assert tier.contains("a") and not tier.contains("b")
    assert tier.stats()["evictions"] == 1
    tier.touch("zzz")  # absent key: a no-op, not an error


def test_block_prefetch_stage_and_payload():
    """prefetch() stages device copies of a host block exactly once,
    payload() prefers the stage, drop_prefetch() releases it, and a
    still-device block never stages (nothing to upload)."""
    dev = _block()
    assert not dev.is_prefetched
    dev.prefetch()
    assert not dev.is_prefetched  # not host-resident: no-op
    host = _block().to_host()
    calls = []

    def put(x):
        calls.append(1)
        return jnp.asarray(x)

    host.prefetch(put=put)
    assert host.is_prefetched and calls
    n = len(calls)
    host.prefetch(put=put)  # idempotent: already staged
    assert len(calls) == n
    k_pages, v_pages = host.payload()
    assert all(
        isinstance(leaf, jax.Array)
        for leaf in jax.tree.leaves((k_pages, v_pages))
    )
    host.drop_prefetch()
    assert not host.is_prefetched
    k_pages, _ = host.payload()
    assert isinstance(jax.tree.leaves(k_pages)[0], np.ndarray)


def test_block_prefetch_fault_site():
    """The cache.prefetch fault site fires before any staging happens."""
    host = _block().to_host()
    faults.arm("cache.prefetch", exc=faults.FaultError)
    with pytest.raises(faults.FaultError):
        host.prefetch()
    faults.disarm()
    assert not host.is_prefetched


def test_tier_stats_blocks_host_tracks_flusher():
    """blocks_host counts host-materialized entries — what tests (and the
    prefetcher) use to know the async flush landed."""
    tier = KVSpillTier(1 << 20)
    tier.put("a", _block())
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if tier.stats()["blocks_host"] == 1:
            break
        time.sleep(0.01)
    assert tier.stats()["blocks_host"] == 1
    tier.close()


# --------------------------------------------- engine-level happy/degraded
@pytest.fixture(scope="module")
def residency_env():
    """One shared pp=2 paged engine + solo reference; each test wraps it in
    its own batcher (the policy knobs differ per test, the engine doesn't)."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
        pool_pages=8, page_size=8,
    )
    ref = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
    )
    return eng, ref


def _residency_batcher(eng, **kw):
    kw.setdefault("spill_bytes", 64 << 20)
    kw.setdefault("spill_cold_after", 2)
    kw.setdefault("kv_prefetch", "on")
    return ContinuousBatcher(eng, decode_block=3, overcommit=True, **kw)


JOB = ([7, 7, 2, 1], dict(max_tokens=40))


def _run_stalled(batcher, *, wait_host=True, prompt_kw=JOB, timeout=90.0):
    """Drive one stream with a consumer that stalls after the first token
    (backlog builds → the slot goes cold and parks), optionally waits for
    the flusher to host-materialize the block, then drains to completion.
    Returns the collected tokens."""
    prompt, kw = prompt_kw
    toks: list = []
    stall = threading.Event()

    def consume():
        for i, (t, _) in enumerate(batcher.generate_step(prompt, **kw)):
            toks.append(t)
            if i == 0:
                stall.wait()

    th = threading.Thread(target=consume, daemon=True)
    th.start()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if batcher.spill_stats()["cold_spills"] > 0:
            break
        time.sleep(0.02)
    assert batcher.spill_stats()["cold_spills"] > 0, "slot never went cold"
    if wait_host:
        while time.monotonic() < deadline:
            if batcher.spill_stats()["blocks_host"] > 0:
                break
            time.sleep(0.02)
        assert batcher.spill_stats()["blocks_host"] > 0, "flusher never ran"
    stall.set()
    th.join(timeout=timeout)
    assert not th.is_alive(), "stream hung after wake"
    return toks


@pytest.mark.parametrize("async_sched", ["off", "on"])
@hard_timeout(420)
def test_cold_spill_prefetch_resume_exact(residency_env, async_sched):
    """Tentpole happy path, sync AND async sched: an idle-consumer slot is
    cold-spilled (pool pages freed), the wake stages the block ahead of
    admission, the resume takes the overlapped path (prefetch_hits, zero
    demand imports), and the greedy stream is bit-identical to the
    never-spilled solo run."""
    eng, ref = residency_env
    prompt, kw = JOB
    want = [t for t, _ in ref.generate_step(prompt, **kw)]
    batcher = _residency_batcher(eng, async_sched=async_sched)
    try:
        toks = _run_stalled(batcher)
        assert toks == want
        s = batcher.spill_stats()
        assert s["cold_spills"] > 0 and s["cold_wakes"] > 0
        assert s["prefetches"] > 0 and s["prefetch_hits"] > 0
        assert s["demand_imports"] == 0 and s["prefetch_faults"] == 0
        assert s["spill_fallbacks"] == 0 and s["parked"] == 0
        assert s["hit_rate"] > 0.0
        total, in_use, _ = batcher.page_stats()
        assert in_use == 0 and s["bytes_in_use"] == 0
        # demand/prefetch wait time is folded into the tick gauges
        assert "kv_import_ms_last" in batcher.tick_timing_stats()
    finally:
        batcher.close()


@hard_timeout(420)
def test_prefetch_fault_degrades_to_demand_import_exact(residency_env):
    """cache.prefetch armed: every stage attempt fails, so the resume
    falls back to the counted demand import — stream still exact, nothing
    dropped."""
    eng, ref = residency_env
    prompt, kw = JOB
    want = [t for t, _ in ref.generate_step(prompt, **kw)]
    batcher = _residency_batcher(eng)
    faults.arm("cache.prefetch", exc=faults.FaultError)
    try:
        toks = _run_stalled(batcher)
        assert toks == want
        s = batcher.spill_stats()
        assert s["prefetch_faults"] > 0 and s["prefetch_hits"] == 0
        assert s["demand_imports"] > 0
        assert s["parked"] == 0
    finally:
        faults.disarm()
        batcher.close()


@hard_timeout(420)
def test_prefetch_and_import_faults_degrade_to_reprefill_exact(residency_env):
    """Both cache.prefetch and cache.import armed: the full degradation
    ladder lands on fold-and-re-prefill — stream still exact."""
    eng, ref = residency_env
    prompt, kw = JOB
    want = [t for t, _ in ref.generate_step(prompt, **kw)]
    batcher = _residency_batcher(eng)
    faults.arm("cache.prefetch", exc=faults.FaultError)
    faults.arm("cache.import", exc=faults.FaultError)
    try:
        toks = _run_stalled(batcher)
        assert toks == want
        s = batcher.spill_stats()
        assert s["spill_fallbacks"] > 0
        assert s["reprefill_tokens"] > 0
        assert s["prefetch_hits"] == 0
    finally:
        faults.disarm()
        batcher.close()


@hard_timeout(420)
def test_prefetch_off_counts_demand_imports(residency_env):
    """kv_prefetch='off': resumes demand-import (counted), never stage,
    and the stream is still exact — the fallback path is the whole path."""
    eng, ref = residency_env
    prompt, kw = JOB
    want = [t for t, _ in ref.generate_step(prompt, **kw)]
    batcher = _residency_batcher(eng, kv_prefetch="off")
    try:
        toks = _run_stalled(batcher)
        assert toks == want
        s = batcher.spill_stats()
        assert not s["prefetch_enabled"]
        assert s["prefetches"] == 0 and s["prefetch_hits"] == 0
        assert s["demand_imports"] > 0
    finally:
        batcher.close()


@hard_timeout(420)
def test_cancel_while_parked_reaps_cleanly(residency_env):
    """A consumer that abandons its stream while the slot is parked: the
    wake pass reaps the request, drops its tier block, and the tier
    drains — no wedge, no leak."""
    eng, _ = residency_env
    batcher = _residency_batcher(eng)
    try:
        gen = batcher.generate_step([9, 4, 4, 6], max_tokens=40)
        next(gen)  # first token, then stop pulling: the slot goes cold
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if batcher.spill_stats()["cold_spills"] > 0:
                break
            time.sleep(0.02)
        assert batcher.spill_stats()["cold_spills"] > 0
        gen.close()  # cancel the parked stream
        while time.monotonic() < deadline:
            s = batcher.spill_stats()
            if s["parked"] == 0 and s["bytes_in_use"] == 0:
                break
            time.sleep(0.02)
        s = batcher.spill_stats()
        assert s["parked"] == 0 and s["bytes_in_use"] == 0
        total, in_use, _ = batcher.page_stats()
        assert in_use == 0
    finally:
        batcher.close()


@hard_timeout(420)
def test_migrate_out_covers_parked_requests(residency_env):
    """Replica drain while a cold session is parked: the parked request's
    stream ends with a RequestMigratedError whose ResumeState carries the
    tokens already emitted (block or fold) — migration never forgets a
    parked session."""
    eng, _ = residency_env
    batcher = _residency_batcher(eng)
    caught: list = []
    stall = threading.Event()

    def consume():
        try:
            for i, _ in enumerate(
                batcher.generate_step([3, 17, 42], max_tokens=40)
            ):
                if i == 0:
                    stall.wait()
        except RequestMigratedError as e:
            caught.append(e)

    th = threading.Thread(target=consume, daemon=True)
    th.start()
    try:
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if batcher.spill_stats()["cold_spills"] > 0:
                break
            time.sleep(0.02)
        assert batcher.spill_stats()["cold_spills"] > 0
        moved = batcher.migrate_out(deadline=60)
        stall.set()
        th.join(timeout=60)
        assert not th.is_alive()
        assert moved >= 1 and caught
        state = caught[0].state
        assert state.produced > 0
        assert state.block is not None or state.history
    finally:
        batcher.close()


# -------------------------------------------------- slow parity sweeps
def _sweep_refs(eng_kw, prompt_kw):
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
        pool_pages=16, page_size=8, **eng_kw,
    )
    batcher = ContinuousBatcher(eng, decode_block=3)
    try:
        prompt, kw = prompt_kw
        return [t for t, _ in batcher.generate_step(prompt, **kw)]
    finally:
        batcher.close()


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", [None, "int8"])
@pytest.mark.parametrize("async_sched", ["off", "on"])
@pytest.mark.parametrize("fault", [None, "cache.prefetch", "cache.import"])
def test_cold_spill_parity_sweep(kv_dtype, async_sched, fault):
    """Full matrix: {bf16, int8 pool} x {sync, async} x {happy, prefetch
    fault, import fault} — the cold-spilled stream is always bit-identical
    to the never-spilled run on the same pool dtype (the int8 pool's
    quantization drift makes the bf16 stream an invalid reference)."""
    eng_kw = dict(kv_dtype=kv_dtype) if kv_dtype else {}
    want = _sweep_refs(eng_kw, JOB)
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=2, max_seq=64,
        cache_dtype=jnp.float32, prefill_chunk=8,
        pool_pages=8, page_size=8, **eng_kw,
    )
    batcher = _residency_batcher(eng, async_sched=async_sched)
    if fault:
        faults.arm(fault, exc=faults.FaultError)
    try:
        toks = _run_stalled(batcher, wait_host=(fault is None))
        assert toks == want
        s = batcher.spill_stats()
        assert s["cold_spills"] > 0 and s["parked"] == 0
        if fault is None:
            assert s["demand_imports"] == 0 and s["prefetch_hits"] > 0
    finally:
        faults.disarm()
        batcher.close()


def test_spill_cold_skips_candidate_unslotted_by_the_quiesce(residency_env):
    """Regression: the async tick scans cold candidates BEFORE quiescing,
    and the quiesce's harvest can finish a candidate (its max_tokens lands
    in the drained block), leaving ``req.slot == -1``. ``_spill_cold``
    must skip such a request — suspending it would release slot -1
    (clobbering ``self._slots[-1]``, i.e. whatever live stream holds the
    last slot) and park an already-finished request for ``_wake_parked``
    to re-admit. The window is harvest-timing dependent, so this pins the
    guard directly with an unslotted candidate."""
    from types import SimpleNamespace

    eng, _ = residency_env
    batcher = _residency_batcher(eng)
    try:
        finished = SimpleNamespace(slot=-1, _trace=None)
        before = batcher.spill_stats()
        batcher._spill_cold([finished])
        after = batcher.spill_stats()
        assert after["cold_spills"] == before["cold_spills"]
        assert after["parked"] == before["parked"]
    finally:
        batcher.close()
