"""KVSharer layer-wise KV sharing (ISSUE 19, arXiv:2410.18517).

The load-bearing properties: (1) an identity share map is a no-op — the
pool layout is byte-identical to unshared (``share_hash is None``) and
greedy streams are bit-identical with the map on or off; (2) a
non-identity map physically allocates ONE (k, v) buffer per share group,
cutting pool bytes by exactly ``1 - groups/layers`` while decode still
serves every stream; (3) the share-map layout identity (``share_hash``)
joins every KV export/import integrity check and the prefix store's
write-once binding, so two hosts with different layouts can never
exchange byte-compatible-but-wrong blocks; (4) calibration ranks layer
pairs most-dissimilar-first (KVSharer's safety ordering) and the saved
artifact round-trips, rejecting hand-edited hashes.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.cache import KVCache
from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.kv_share import (
    KVShareMap,
    ShareMapError,
    calibrate_share_map,
    load_share_map,
    rank_layer_pairs,
)
from mlx_sharding_tpu.kv_transfer import (
    BlockIntegrityError,
    export_block,
    import_block,
)
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.prefix_store import PrefixStore
from mlx_sharding_tpu.scheduler import ContinuousBatcher

TINY = dict(vocab_size=256, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2)

PAGE = 8
PROMPT = [7, 7, 2, 1, 9, 4, 4, 6, 3, 17, 42, 5, 11, 2, 2, 8, 5]


# ------------------------------------------------------------- map algebra
def test_share_map_canonicalizes_group_ids():
    a = KVShareMap(4, (2, 0, 2, 0))
    b = KVShareMap(4, (0, 1, 0, 1))
    assert a == b and a.share_hash == b.share_hash
    assert a.group_of == (0, 1, 0, 1)
    assert a.num_groups == 2
    assert a.owner_layers == (0, 1)
    assert a.owner_mask == (True, True, False, False)
    assert a.bytes_saved_fraction() == 0.5


def test_identity_map_is_unshared_layout():
    m = KVShareMap.identity(4)
    assert m.is_identity
    assert m.share_hash is None  # legacy blocks compose, no flag-day
    assert m.bytes_saved_fraction() == 0.0
    shared = KVShareMap(4, (0, 0, 1, 2))
    assert not shared.is_identity and shared.share_hash is not None


def test_from_pairs_union_find_chains():
    m = KVShareMap.from_pairs(6, [(0, 3), (3, 5), (1, 4)])
    assert m.group_of[0] == m.group_of[3] == m.group_of[5]
    assert m.group_of[1] == m.group_of[4]
    assert m.num_groups == 3
    with pytest.raises(ShareMapError):
        KVShareMap.from_pairs(4, [(0, 9)])


def test_validate_for_wrong_stage_split():
    with pytest.raises(ShareMapError, match="recalibrate"):
        KVShareMap(4, (0, 0, 1, 2)).validate_for(2)


def test_save_load_round_trip_and_tamper_rejection(tmp_path):
    m = KVShareMap(4, (0, 0, 1, 2), meta={"note": "t"})
    p = tmp_path / "share.json"
    m.save(str(p))
    back = KVShareMap.load(str(p))
    assert back == m and back.share_hash == m.share_hash
    assert back.meta["note"] == "t"
    doc = json.loads(p.read_text())
    doc["group_of"] = [0, 1, 1, 2]  # hand-edit under the stamped hash
    p.write_text(json.dumps(doc))
    with pytest.raises(ShareMapError, match="recalibrate"):
        KVShareMap.load(str(p))
    p.write_text("{not json")
    with pytest.raises(ShareMapError, match="not readable JSON"):
        KVShareMap.load(str(p))
    p.write_text(json.dumps({"format": "something-else"}))
    with pytest.raises(ShareMapError, match="artifact"):
        KVShareMap.load(str(p))
    assert load_share_map(None) is None
    m.save(str(p))
    assert load_share_map(str(p), num_layers=4) == m
    with pytest.raises(ShareMapError):
        load_share_map(str(p), num_layers=8)


# -------------------------------------------------------------- calibration
def _calib_buffers():
    """(L=4, B=1, S=8, H=2, D=4) dense buffers where layers 0/1 are
    near-identical and layers 2/3 point opposite ways — the dissimilar
    (safe-to-share) pairs all involve layer 3."""
    rng = np.random.default_rng(7)
    base = rng.standard_normal((1, 8, 2, 4)).astype(np.float32)
    k = np.stack([base, base + 1e-3, base * 0.5, -base])
    v = np.stack([base, base + 1e-3, base * 0.5, -base])
    return k, v


def test_rank_layer_pairs_most_dissimilar_first():
    k, v = _calib_buffers()
    ranked = rank_layer_pairs(k, v)
    assert len(ranked) == 6
    assert all(ranked[i][1] >= ranked[i + 1][1] for i in range(5))
    # the anti-aligned pairs (all involving layer 3) rank above the
    # aligned layer-0/1/2 cluster, whose dissimilarity is ~0
    assert ranked[0][0][1] == 3
    assert 3 not in ranked[-1][0] and ranked[-1][1] < 1e-3


def test_calibrate_merges_dissimilar_pairs_under_group_cap():
    k, v = _calib_buffers()
    m = calibrate_share_map(k, v, num_share=1)
    assert m.num_groups == 3
    merged = [i for i in range(4) if not m.owner_mask[i]]
    assert len(merged) == 1  # exactly one layer reads through its group
    cal = m.meta["calibration"]
    assert len(cal["pairs"]) == 1 and len(cal["dissimilarity"]) == 1
    # max_group=2 forces disjoint pairs: 2 merges -> 2 groups of 2
    m2 = calibrate_share_map(k, v, num_share=2)
    assert m2.num_groups == 2
    assert sorted(m2.group_of).count(0) == 2
    with pytest.raises(ShareMapError):
        calibrate_share_map(k, v, num_share=4)  # > L-1
    with pytest.raises(ShareMapError):
        calibrate_share_map(k, v, num_share=1, max_group=1)


# ----------------------------------------------- export/import layout joins
def _cache_and_block(share_hash=None):
    shape = (1, 2, 4, 1, PAGE, 2, 4)
    vals = jnp.arange(np.prod(shape), dtype=jnp.float32).reshape(shape)
    cache = KVCache(k=vals, v=vals + 1000.0, offset=jnp.zeros((), jnp.int32))
    block = export_block(
        cache, [0, 1], page_size=PAGE, n_tokens=2 * PAGE,
        prompt=PROMPT[:-1], history=[], produced=0,
        resume_keys=None, resume_recent=None, share_hash=share_hash,
    ).to_host()
    return cache, block


def test_block_round_trip_preserves_share_hash():
    _, block = _cache_and_block(share_hash="aa55")
    back = type(block).from_bytes(block.to_bytes())
    assert back.share_hash == "aa55"


def test_import_rejects_share_layout_mismatch():
    cache, block = _cache_and_block(share_hash="aa55")
    with pytest.raises(BlockIntegrityError, match="--kv-share-map"):
        import_block(cache, block, [0, 1], share_hash=None)
    with pytest.raises(BlockIntegrityError, match="layout mismatch"):
        import_block(cache, block, [0, 1], share_hash="bb66")
    # matching layouts import fine
    import_block(cache, block, [0, 1], share_hash="aa55")


def test_prefix_store_share_hash_binding():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    store.bind_share_hash("aa55")
    store.bind_share_hash("aa55")  # idempotent re-bind
    with pytest.raises(ValueError, match="cannot share"):
        store.bind_share_hash("bb66")
    # a block exported under another layout is refused (degrades to
    # re-prefill), never resident-but-unimportable
    digest = store.digests_for(PROMPT)[-1]
    _, block = _cache_and_block(share_hash="bb66")
    assert store.host_put(digest, block) is False
    assert store.stats()["demote_drops"] == 1
    _, good = _cache_and_block(share_hash="aa55")
    assert store.host_put(digest, good) is True
    store.close()


def test_prefix_store_first_bind_rejects_stale_resident_blocks():
    store = PrefixStore(host_bytes=1 << 20)
    store.bind_page_size(PAGE)
    digest = store.digests_for(PROMPT)[-1]
    _, block = _cache_and_block(share_hash=None)
    assert store.host_put(digest, block) is True
    with pytest.raises(ValueError, match="--kv-share-map"):
        store.bind_share_hash("aa55")
    store.close()


# ------------------------------------------------------------ engine wiring
@pytest.fixture(scope="module")
def tiny_model():
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return model, params


def _mk_engine(tiny_model, dev_idx, share_map=None, pool_pages=10):
    model, params = tiny_model
    devices = jax.devices()
    return PipelineEngine(
        model, params, make_mesh(pp=1, devices=devices[dev_idx:dev_idx + 1]),
        microbatches=2, max_seq=64, cache_dtype=jnp.float32,
        prefill_chunk=8, pool_pages=pool_pages, page_size=PAGE,
        kv_share_map=share_map,
    )


def test_engine_rejects_share_map_without_pool(tiny_model):
    model, params = tiny_model
    with pytest.raises(ValueError, match="paged"):
        PipelineEngine(
            model, params,
            make_mesh(pp=1, devices=jax.devices()[:1]),
            microbatches=2, max_seq=64, cache_dtype=jnp.float32,
            kv_share_map=KVShareMap(2, (0, 0)),
        )


def test_engine_rejects_share_map_on_stage_split(tiny_model):
    model, params = tiny_model
    with pytest.raises(ValueError, match="pp=1"):
        PipelineEngine(
            model, params, make_mesh(pp=2, devices=jax.devices()[:2]),
            microbatches=2, max_seq=64, cache_dtype=jnp.float32,
            prefill_chunk=8, pool_pages=10, page_size=PAGE,
            kv_share_map=KVShareMap(2, (0, 0)),
        )


def test_identity_map_greedy_parity_and_stats(tiny_model):
    """Acceptance: the identity map changes NOTHING — same bytes, same
    greedy tokens as no map at all."""
    b_plain = ContinuousBatcher(_mk_engine(tiny_model, 0), decode_block=3)
    b_ident = ContinuousBatcher(
        _mk_engine(tiny_model, 1, share_map=KVShareMap.identity(2)),
        decode_block=3)
    try:
        ref = [t for t, _ in b_plain.generate_step(PROMPT, max_tokens=16)]
        got = [t for t, _ in b_ident.generate_step(PROMPT, max_tokens=16)]
        assert got == ref
        s = b_ident.engine.kv_share_stats()
        assert s["enabled"] is False and s["share_hash"] is None
        assert s["bytes_saved"] == 0
    finally:
        b_plain.close()
        b_ident.close()


def test_shared_map_halves_pool_bytes_and_serves(tiny_model):
    """Acceptance: a 2-layers-into-1-group map cuts KV pool bytes by 50%
    (>= the 25% criterion) at identical pool_pages, and decode still
    completes every stream."""
    eng_plain = _mk_engine(tiny_model, 2)
    eng_shared = _mk_engine(tiny_model, 3,
                            share_map=KVShareMap(2, (0, 0)))
    b = ContinuousBatcher(eng_shared, decode_block=3)
    try:
        s = eng_shared.kv_share_stats()
        assert s["enabled"] is True and s["groups"] == 1 and s["layers"] == 2
        assert s["share_hash"] == KVShareMap(2, (0, 0)).share_hash
        got = [t for t, _ in b.generate_step(PROMPT, max_tokens=16)]
        assert len(got) == 16
        # the physical claim, measured on the engines' own pools
        def pool_bytes(eng):
            c, _table = eng.init_cache_paged()
            leaves = jax.tree_util.tree_leaves((c.k, c.v))
            return sum(x.nbytes for x in leaves)
        assert pool_bytes(eng_shared) * 2 == pool_bytes(eng_plain)
        assert s["bytes_saved"] > 0
    finally:
        b.close()
        eng_plain.close()
