"""Multi-host serving: 2-process jax.distributed deployment on CPU.

The reference's whole premise is one shard process per machine
(/root/reference/shard/main.py:4-14). This test deploys the TPU-native
equivalent end-to-end: rank 0 = HTTP server + driver, rank 1 = worker
mirroring the step sequence over the broadcast control plane, model mesh
spanning both processes (2 CPU devices each, 4 pipeline stages). Output
must match the identical request served by a single-process server.
"""

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env(n_local_devices):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)  # no axon site: pure-CPU subprocess
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_local_devices}"
    )
    return env


def _wait_health(port, procs, timeout=420):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for p in procs:
            if p.poll() is not None:
                raise RuntimeError(
                    f"server process exited rc={p.returncode}"
                )
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
            conn.request("GET", "/health")
            if conn.getresponse().status == 200:
                conn.close()
                return
        except OSError:
            pass
        time.sleep(2)
    raise TimeoutError("server did not become healthy")


def _post_completion(port, body, timeout=300):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(
        "POST", "/v1/completions", json.dumps(body),
        {"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    data = json.loads(resp.read())
    conn.close()
    return resp.status, data


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    from tests.make_tiny_checkpoint import make_tiny_checkpoint

    return str(make_tiny_checkpoint(tmp_path_factory.mktemp("mh_ckpt")))


def _spawn_server(ckpt, port, extra, n_local_devices, log, env_extra=None):
    env = _env(n_local_devices)
    if env_extra:
        env.update(env_extra)
    return subprocess.Popen(
        [
            sys.executable, "-m", "mlx_sharding_tpu.server.openai_api",
            "--model", ckpt, "--host", "127.0.0.1", "--port", str(port),
            "--num-stages", "4", "--max-seq", "128", "--prefill-chunk", "16",
            *extra,
        ],
        env=env, cwd=str(REPO),
        stdout=log, stderr=subprocess.STDOUT,
    )


@pytest.mark.quick
@pytest.mark.slow  # ~55s: spawns a live 2-process deployment
def test_worker_death_fails_cleanly_not_hang(ckpt, tmp_path):
    """SIGKILL rank 1 of a live 2-process deployment (VERDICT r4 ask #5):
    the in-flight/next request must get a structured 5xx within the
    liveness budget — NOT hang rank 0 in the broadcast collective forever —
    /health must flip to degraded (503, workers_responsive false), and
    later requests must fail fast off the dead-plane flag. Rank 0 stays
    alive throughout: the driver is restartable, not wedged.

    (Also the quick tier's one cross-process protocol case — VERDICT r4
    ask #8: it exercises deployment, the broadcast control plane, a full
    request, and the failure path in a single 2-process spawn.)"""
    port0 = _free_port()
    coord = f"localhost:{_free_port()}"
    mh = ["--coordinator", coord, "--num-processes", "2"]
    env_extra = {"MST_MULTIHOST_TIMEOUT_S": "60"}
    log_r0 = open(tmp_path / "rank0.log", "w")
    log_r1 = open(tmp_path / "rank1.log", "w")
    r0 = _spawn_server(
        ckpt, port0, [*mh, "--process-id", "0"], 2, log_r0, env_extra
    )
    r1 = _spawn_server(
        ckpt, _free_port(), [*mh, "--process-id", "1"], 2, log_r1, env_extra
    )
    try:
        _wait_health(port0, [r0, r1])
        # one good request first: programs compiled, protocol healthy
        status, ok = _post_completion(
            port0, {"prompt": "the quick", "max_tokens": 4, "seed": 3}
        )
        assert status == 200 and isinstance(ok["choices"][0]["text"], str)

        r1.kill()  # SIGKILL: no cleanup, no goodbye
        r1.wait(timeout=10)

        status, err = _post_completion(
            port0, {"prompt": "hello", "max_tokens": 4}, timeout=240
        )
        assert status >= 500
        assert "error" in err

        # /health degrades instead of lying
        conn = http.client.HTTPConnection("127.0.0.1", port0, timeout=10)
        conn.request("GET", "/health")
        resp = conn.getresponse()
        health = json.loads(resp.read())
        conn.close()
        assert resp.status == 503
        assert health["status"] == "degraded"
        assert health["multihost"]["workers_responsive"] is False

        # later requests fail FAST off the dead flag (no fresh 60s wait)
        t0 = time.time()
        status2, err2 = _post_completion(
            port0, {"prompt": "again", "max_tokens": 4}, timeout=60
        )
        assert status2 >= 500 and "error" in err2
        assert time.time() - t0 < 30
        assert r0.poll() is None  # the driver never wedged or died
    finally:
        for p in (r0, r1):
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in (r0, r1):
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow  # ~65s: spawns a live 2-process deployment
def test_two_process_serving_matches_single_process(ckpt, tmp_path):
    body = {"prompt": "the quick brown fox", "max_tokens": 8, "seed": 5}

    # reference: single process, 4 local devices
    port1 = _free_port()
    log1 = open(tmp_path / "single.log", "w")
    p_single = _spawn_server(ckpt, port1, [], 4, log1)
    try:
        _wait_health(port1, [p_single])
        status, ref = _post_completion(port1, body)
        assert status == 200
    finally:
        p_single.send_signal(signal.SIGTERM)
        p_single.wait(timeout=30)

    # deployment under test: 2 processes x 2 devices, same 4-stage mesh
    port0 = _free_port()
    coord = f"localhost:{_free_port()}"
    mh = ["--coordinator", coord, "--num-processes", "2"]
    log_r0 = open(tmp_path / "rank0.log", "w")
    log_r1 = open(tmp_path / "rank1.log", "w")
    r0 = _spawn_server(ckpt, port0, [*mh, "--process-id", "0"], 2, log_r0)
    r1 = _spawn_server(ckpt, _free_port(), [*mh, "--process-id", "1"], 2, log_r1)
    try:
        _wait_health(port0, [r0, r1])
        status, got = _post_completion(port0, body)
        assert status == 200
        assert got["choices"][0]["text"] == ref["choices"][0]["text"]
        # a second request through the same workers (protocol returns to
        # the idle loop cleanly after STOP)
        body2 = {"prompt": "hello world", "max_tokens": 5, "seed": 7}
        s1, a = _post_completion(port0, body2)
        assert s1 == 200 and isinstance(a["choices"][0]["text"], str)
    finally:
        for p in (r0, r1):
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in (r0, r1):
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
