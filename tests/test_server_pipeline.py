"""API server backed by the fused SPMD pipeline engine — the serving-over-
mesh path (BASELINE config #3 shape: MoE-capable API serving on a pipeline)."""

import http.client
import json
import threading

import jax
import jax.numpy as jnp
import pytest

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.server.openai_api import ModelProvider, make_server
from tests.test_tokenizer_utils import ByteTokenizer


@pytest.fixture(scope="module")
def server():
    model = LlamaModel(
        LlamaConfig(
            vocab_size=300, hidden_size=32, intermediate_size=64,
            num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        )
    )
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(4), max_seq=256,
        cache_dtype=jnp.float32, prefill_chunk=16,
    )
    provider = ModelProvider.__new__(ModelProvider)
    provider.default_model = "tiny-pp"
    provider.trust_remote_paths = False
    provider._key = None
    provider._load_lock = threading.Lock()
    provider._set("tiny-pp", eng, ByteTokenizer())
    srv = make_server(provider, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


def _post(port, path, body):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
    conn.request("POST", path, json.dumps(body), {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_completion_over_pipeline(server):
    status, body = _post(server, "/v1/completions", {"prompt": "abc", "max_tokens": 6})
    assert status == 200
    resp = json.loads(body)
    assert resp["usage"]["completion_tokens"] <= 6


def test_streaming_chat_over_pipeline(server):
    status, body = _post(
        server, "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 4,
         "stream": True},
    )
    assert status == 200
    assert b"[DONE]" in body


@pytest.fixture(scope="module")
def concurrent_server():
    """Server backed by a 2-slot ContinuousBatcher — requests are NOT
    serialized by the generation lock."""
    from mlx_sharding_tpu.scheduler import ContinuousBatcher

    model = LlamaModel(
        LlamaConfig(
            vocab_size=300, hidden_size=32, intermediate_size=64,
            num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        )
    )
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(2), microbatches=2, max_seq=256,
        cache_dtype=jnp.float32, prefill_chunk=16,
    )
    batcher = ContinuousBatcher(eng)
    provider = ModelProvider.__new__(ModelProvider)
    provider.default_model = "tiny-cb"
    provider.trust_remote_paths = False
    provider._key = None
    provider._load_lock = threading.Lock()
    provider._set("tiny-cb", batcher, ByteTokenizer())
    srv = make_server(provider, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()
    batcher.close()


def test_concurrent_http_requests_interleave(concurrent_server):
    """Two HTTP requests in flight at once both complete, and their outputs
    equal the same requests run one at a time (slot isolation end-to-end
    through the HTTP layer)."""
    port = concurrent_server
    bodies = [
        {"prompt": "abc", "max_tokens": 8, "seed": 3},
        {"prompt": "xyzw", "max_tokens": 8, "seed": 4},
    ]
    serial = [
        json.loads(_post(port, "/v1/completions", b)[1])["choices"][0]["text"]
        for b in bodies
    ]

    results = [None, None]

    def worker(i):
        status, data = _post(port, "/v1/completions", bodies[i])
        assert status == 200
        results[i] = json.loads(data)["choices"][0]["text"]

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
        assert not t.is_alive()
    assert results == serial


def test_concurrent_stop_sequence_frees_slot(concurrent_server):
    """A request ended early by a stop sequence releases its slot; a
    follow-up request still runs (generator close -> slot reclaim)."""
    port = concurrent_server
    status, data = _post(
        port, "/v1/completions",
        {"prompt": "abc", "max_tokens": 30, "stop": ["a"], "seed": 9},
    )
    assert status == 200
    # slot must be free again: run 2 more concurrently
    results = []

    def worker():
        s, d = _post(port, "/v1/completions", {"prompt": "pq", "max_tokens": 5})
        results.append(s)

    ts = [threading.Thread(target=worker) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=300)
    assert results == [200, 200]
