"""API server backed by the fused SPMD pipeline engine — the serving-over-
mesh path (BASELINE config #3 shape: MoE-capable API serving on a pipeline)."""

import http.client
import json
import threading

import jax
import jax.numpy as jnp
import pytest

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import pipeline_mesh
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine
from mlx_sharding_tpu.server.openai_api import ModelProvider, make_server
from tests.test_tokenizer_utils import ByteTokenizer


@pytest.fixture(scope="module")
def server():
    model = LlamaModel(
        LlamaConfig(
            vocab_size=300, hidden_size=32, intermediate_size=64,
            num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        )
    )
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    eng = PipelineEngine(
        model, params, pipeline_mesh(4), max_seq=256,
        cache_dtype=jnp.float32, prefill_chunk=16,
    )
    provider = ModelProvider.__new__(ModelProvider)
    provider.default_model = "tiny-pp"
    provider.trust_remote_paths = False
    provider._key = None
    provider._load_lock = threading.Lock()
    provider._set("tiny-pp", eng, ByteTokenizer())
    srv = make_server(provider, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


def _post(port, path, body):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
    conn.request("POST", path, json.dumps(body), {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_completion_over_pipeline(server):
    status, body = _post(server, "/v1/completions", {"prompt": "abc", "max_tokens": 6})
    assert status == 200
    resp = json.loads(body)
    assert resp["usage"]["completion_tokens"] <= 6


def test_streaming_chat_over_pipeline(server):
    status, body = _post(
        server, "/v1/chat/completions",
        {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 4,
         "stream": True},
    )
    assert status == 200
    assert b"[DONE]" in body
