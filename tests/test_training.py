import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh
from mlx_sharding_tpu.training import lm_loss, make_train_step

TINY = dict(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


def test_loss_decreases_under_training():
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    mesh = make_mesh()  # single device
    init, step = make_train_step(model, optax.adamw(1e-2), mesh)
    state = init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 12), 0, 64, jnp.int32)
    first = None
    for _ in range(10):
        state, loss = step(state, tokens)
        if first is None:
            first = float(loss)
    assert float(loss) < first, f"loss did not decrease: {first} -> {float(loss)}"


def test_sharded_train_step_matches_single_device():
    """dp2/pp2/tp2 sharded step produces the same loss as unsharded."""
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 12), 0, 64, jnp.int32)

    ref = float(lm_loss(model, params, tokens))

    mesh = make_mesh(dp=2, pp=2, tp=2)
    init, step = make_train_step(model, optax.adamw(1e-3), mesh)
    state = init(params)
    _, loss = step(state, tokens)
    np.testing.assert_allclose(float(loss), ref, rtol=1e-4)


@pytest.mark.slow  # ~45s: full dry-run compile of the graft entry point
def test_graft_entry_dryrun():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


@pytest.mark.slow  # ~17s: graft entry compile, same tier as the dry-run
def test_graft_entry_forward():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    tok, cache = jax.jit(fn)(*args)
    assert tok.shape == (1,)
    assert int(cache.offset) == 1
