"""Cross-replica shared weights (weights.py): one resident packed tree per
host, refcount-leased to every replica.

Contracts pinned here:

- Store semantics: one build per key under concurrent acquires, aliasing
  returns the SAME resident object, last release frees the entry, unknown
  releases and double-released leases raise.
- Alias-fast engines: ``PipelineEngine(..., weights=...)`` executes against
  the same device arrays (leaf identity), greedy streams are bit-identical
  shared vs private, and fleet weight bytes stay ~W instead of N×W.
- Lifecycle: ``engine.close()`` (via drain / ReplicaSet.close / disagg
  teardown) releases exactly one ref; a faulted spawn releases its lease
  before the error propagates — refcounts are consistent either way.
- The spawn-path device-slice free list recycles drained replicas' slices
  (the old next-index factories leaked them).
"""

import threading

import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.disagg import DisaggCoordinator
from mlx_sharding_tpu.fleet import FleetAutoscaler
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh, mesh_fingerprint
from mlx_sharding_tpu.parallel.pipeline import PipelineEngine, place_weights
from mlx_sharding_tpu.replicas import ReplicaSet
from mlx_sharding_tpu.scheduler import ContinuousBatcher
from mlx_sharding_tpu.server.openai_api import _SliceAllocator
from mlx_sharding_tpu.utils.observability import ServingMetrics
from mlx_sharding_tpu.weights import (
    WeightKey,
    WeightStore,
    aliased_spawn,
    key_digest,
    weight_store,
)
from tests.helpers import run_concurrent
from tests.test_fleet import FakeClock, _LoadStub

TINY = dict(vocab_size=256, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2)

KEY = WeightKey(checkpoint="ck", stage_bounds=(("auto", 1),),
                dtype="float32", quant="tp1", placement="pp=1|0")


def _key(**kw):
    base = dict(checkpoint="ck", stage_bounds=(("auto", 1),),
                dtype="float32", quant="tp1", placement="pp=1|0")
    base.update(kw)
    return WeightKey(**base)


class _Tree:
    def __init__(self, nbytes=100):
        self.weight_bytes = nbytes


@pytest.fixture(scope="module")
def tiny_model():
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return model, params


# ------------------------------------------------------------ store semantics
def test_acquire_builds_once_and_aliases():
    store, built = WeightStore(), []

    def build():
        built.append(1)
        return _Tree()

    a = store.acquire(KEY, build)
    b = store.acquire(KEY, build)
    assert len(built) == 1  # ONE placement, however many spawns
    assert a.weights is b.weights
    assert store.refs(KEY) == 2
    st = store.stats()
    assert st == {
        "trees": 1, "refs": 2, "bytes": 100,
        "entries": [{"checkpoint": "ck", "placement": "pp=1|0",
                     "refs": 2, "bytes": 100,
                     "digest": key_digest(KEY)}],
    }


def test_distinct_keys_build_distinct_trees():
    store = WeightStore()
    a = store.acquire(_key(dtype="float32"), _Tree)
    b = store.acquire(_key(dtype="bfloat16"), _Tree)
    assert a.weights is not b.weights
    assert store.stats()["trees"] == 2


def test_last_release_frees_and_errors_raise():
    store = WeightStore()
    a = store.acquire(KEY, _Tree)
    b = store.acquire(KEY, _Tree)
    assert a.release() is False  # a ref remains — tree stays resident
    assert store.refs(KEY) == 1
    assert b.release() is True  # last ref out frees the entry
    assert store.stats() == {"trees": 0, "refs": 0, "bytes": 0, "entries": []}
    with pytest.raises(RuntimeError, match="released twice"):
        b.release()
    with pytest.raises(RuntimeError, match="does not hold"):
        store.release(KEY)


def test_concurrent_acquires_build_once():
    store, built = WeightStore(), []

    def build():
        built.append(1)
        return _Tree()

    leases = [None] * 8

    def go(i):
        leases[i] = store.acquire(KEY, build)

    threads = [threading.Thread(target=go, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(built) == 1 and store.refs(KEY) == 8
    assert all(ls.weights is leases[0].weights for ls in leases)


def test_aliased_spawn_fault_leaves_refcounts_consistent():
    store = WeightStore()
    holder = store.acquire(KEY, _Tree)  # a live replica's lease

    def boom(lease):
        raise RuntimeError("engine construction failed")

    with pytest.raises(RuntimeError, match="construction failed"):
        aliased_spawn(store, KEY, _Tree, boom)
    # the faulted spawn's ref is gone, the live replica's is intact —
    # nothing leaked, nothing freed in use
    assert store.refs(KEY) == 1
    assert holder.release() is True
    # and a first-spawn fault leaves the store empty (build not leaked)
    with pytest.raises(RuntimeError, match="construction failed"):
        aliased_spawn(store, KEY, _Tree, boom)
    assert store.stats()["trees"] == 0


def test_module_singleton_is_shared():
    assert weight_store() is weight_store()


# ------------------------------------------------- alias-fast engine builds
def test_engines_alias_one_resident_tree(tiny_model):
    """Two engines over one placed tree execute against the SAME device
    arrays (leaf identity), and greedy streams are bit-identical to a
    private-upload engine of the same geometry."""
    model, params = tiny_model
    devices = jax.devices()
    rw = place_weights(model, params, make_mesh(pp=1, devices=devices[:1]))
    kw = dict(max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    shared = [
        PipelineEngine(model, None, rw.mesh, weights=rw, **kw)
        for _ in range(2)
    ]
    private = PipelineEngine(
        model, params, make_mesh(pp=1, devices=devices[1:2]), **kw
    )
    assert all(e.weights_shared for e in shared)
    assert not private.weights_shared
    a_leaves = jax.tree.leaves(shared[0].layer_params)
    b_leaves = jax.tree.leaves(shared[1].layer_params)
    assert all(x is y for x, y in zip(a_leaves, b_leaves))
    prompt = [3, 17, 42]
    want = [t for t, _ in private.generate_step(prompt, max_tokens=10)]
    for eng in shared:
        assert [t for t, _ in eng.generate_step(prompt, max_tokens=10)] == want


def test_fleet_weight_bytes_stay_flat(tiny_model):
    """The headline number: N aliased engines hold ~W resident weight
    bytes where N private engines hold N×W (unique-buffer accounting)."""
    model, params = tiny_model
    devices = jax.devices()

    def unique_bytes(engines):
        seen, total = set(), 0
        for e in engines:
            for leaf in jax.tree.leaves(
                (e.layer_params, e.vocab_parts, e.shared_params)
            ):
                if id(leaf) not in seen:
                    seen.add(id(leaf))
                    total += leaf.nbytes
        return total

    rw = place_weights(model, params, make_mesh(pp=1, devices=devices[:1]))
    kw = dict(max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    shared = [PipelineEngine(model, None, rw.mesh, weights=rw, **kw)
              for _ in range(3)]
    private = [
        PipelineEngine(model, params,
                       make_mesh(pp=1, devices=devices[i:i + 1]), **kw)
        for i in range(3)
    ]
    w = unique_bytes(shared[:1])
    assert unique_bytes(shared) == w  # ~W, however many replicas alias it
    assert unique_bytes(private) == 3 * w  # N×W without the store
    assert rw.weight_bytes == w


def test_alias_rejects_foreign_mesh_and_bounds(tiny_model):
    model, params = tiny_model
    devices = jax.devices()
    rw = place_weights(model, params, make_mesh(pp=2, devices=devices[:2]))
    kw = dict(max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    with pytest.raises(ValueError, match="different device grid"):
        PipelineEngine(model, None, make_mesh(pp=2, devices=devices[2:4]),
                       weights=rw, **kw)
    with pytest.raises(ValueError, match="disagree"):
        PipelineEngine(model, None, rw.mesh, weights=rw,
                       stage_bounds=[(0, 2), (2, 2)], **kw)


def test_close_hook_releases_exactly_once(tiny_model):
    model, params = tiny_model
    store = WeightStore()
    rw_key = _key(checkpoint="close-hook")
    mesh = make_mesh(pp=1, devices=jax.devices()[:1])
    lease = store.acquire(
        rw_key, lambda: place_weights(model, params, mesh)
    )
    eng = PipelineEngine(
        model, None, lease.weights.mesh, weights=lease.weights,
        max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
    )
    eng.on_close(lease.release)
    assert store.refs(rw_key) == 1
    eng.close()
    assert store.refs(rw_key) == 0 and lease.released
    eng.close()  # idempotent — the hook ran once, no double release


# --------------------------------------------- fleet lifecycle with real refs
def _shared_batcher(tiny_model, store, key, concurrent=2, **pool_kw):
    model, params = tiny_model
    mesh = make_mesh(pp=1, devices=jax.devices()[:1])
    lease = store.acquire(
        key, lambda: place_weights(model, params, mesh)
    )
    eng = PipelineEngine(
        model, None, lease.weights.mesh, weights=lease.weights,
        microbatches=concurrent, max_seq=64, cache_dtype=jnp.float32,
        prefill_chunk=8, **pool_kw,
    )
    eng.on_close(lease.release)
    return ContinuousBatcher(eng, decode_block=3)


def test_drain_releases_ref_close_frees_tree(tiny_model):
    """ReplicaSet.drain → batcher.close → engine close hook → one ref out;
    ReplicaSet.close releases the rest and the LAST release frees the
    store's tree. Streams before/through are token-exact vs private."""
    model, params = tiny_model
    store, key = WeightStore(), _key(checkpoint="drain")
    rs = ReplicaSet([_shared_batcher(tiny_model, store, key)
                     for _ in range(3)])
    assert store.refs(key) == 3
    assert rs.fleet_stats()["weights_shared"] == 3
    private = PipelineEngine(
        model, params, make_mesh(pp=1, devices=jax.devices()[1:2]),
        max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
    )
    jobs = [([3, 17, 42], dict(max_tokens=8, seed=i + 1)) for i in range(3)]
    got = run_concurrent(rs, jobs)
    for (p, kw), toks in zip(jobs, got):
        assert toks == [t for t, _ in private.generate_step(p, **kw)]
    rs.drain(2, deadline=5.0)
    assert store.refs(key) == 2  # retirement released exactly one ref
    assert rs.fleet_stats()["weights_shared"] == 2
    rs.close()
    assert store.stats()["trees"] == 0  # last engine out freed the tree


def test_disagg_pools_share_one_tree_with_parity(tiny_model):
    """Prefill and decode pools alias the same resident tree; coordinated
    streams stay bit-identical to a private monolithic batcher; teardown
    frees the tree."""
    model, params = tiny_model
    store, key = WeightStore(), _key(checkpoint="disagg")
    pool_kw = dict(pool_pages=10, page_size=8)
    co = DisaggCoordinator(
        ReplicaSet([_shared_batcher(tiny_model, store, key, **pool_kw)],
                   role="prefill"),
        ReplicaSet([_shared_batcher(tiny_model, store, key, **pool_kw)],
                   role="decode"),
    )
    mono_eng = PipelineEngine(
        model, params, make_mesh(pp=1, devices=jax.devices()[1:2]),
        microbatches=2, max_seq=64, cache_dtype=jnp.float32,
        prefill_chunk=8, **pool_kw,
    )
    mono = ContinuousBatcher(mono_eng, decode_block=3)
    try:
        assert store.refs(key) == 2
        assert co.fleet_stats()["weights_shared"] == 2
        jobs = [([3, 17, 42], dict(max_tokens=12)),
                ([9, 4, 4, 6], dict(max_tokens=10, seed=7, temperature=0.8))]
        got = run_concurrent(co, jobs)
        want = run_concurrent(mono, jobs)
        assert got == want
    finally:
        co.close()
        mono.close()
    assert store.stats()["trees"] == 0


@pytest.mark.slow
def test_async_batcher_parity_shared_vs_private(tiny_model):
    """Async tick pipelining over aliased weights stays token-exact vs a
    private synchronous batcher."""
    model, params = tiny_model
    store, key = WeightStore(), _key(checkpoint="async")
    eng_shared = _shared_batcher(tiny_model, store, key)
    mesh = make_mesh(pp=1, devices=jax.devices()[:1])
    lease = store.acquire(key, lambda: place_weights(model, params, mesh))
    async_eng = PipelineEngine(
        model, None, lease.weights.mesh, weights=lease.weights,
        microbatches=2, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
    )
    async_eng.on_close(lease.release)
    shared_async = ContinuousBatcher(async_eng, decode_block=3,
                                     async_sched="on")
    private = ContinuousBatcher(
        PipelineEngine(
            model, params, make_mesh(pp=1, devices=jax.devices()[1:2]),
            microbatches=2, max_seq=64, cache_dtype=jnp.float32,
            prefill_chunk=8,
        ),
        decode_block=3,
    )
    try:
        jobs = [([3, 17, 42], dict(max_tokens=10)),
                ([5, 5, 9], dict(max_tokens=8, seed=3, temperature=0.7))]
        want = run_concurrent(private, jobs)
        assert run_concurrent(eng_shared, jobs) == want
        assert run_concurrent(shared_async, jobs) == want
    finally:
        eng_shared.close()
        shared_async.close()
        private.close()
    assert store.stats()["trees"] == 0


def test_autoscaler_spawn_fault_keeps_store_consistent():
    """A replica.spawn fault through aliased_spawn degrades the controller
    to the static fleet with refcounts exactly as they were."""
    store, key = WeightStore(), _key(checkpoint="fleet")
    holder = store.acquire(key, _Tree)  # the static fleet's resident tree

    def factory():
        return aliased_spawn(
            store, key, _Tree,
            lambda lease: (_ for _ in ()).throw(RuntimeError("spawn boom")),
        )

    clk = FakeClock()
    reps = [_LoadStub() for _ in range(2)]
    rs = ReplicaSet(reps)
    ctrl = FleetAutoscaler(rs, factory, clock=clk, max_replicas=3,
                           scale_up_sustain_s=5.0, cooldown_s=20.0)
    for r in reps:
        r.load = (1, 1, 2)
    ctrl.tick()
    clk.advance(5.0)
    assert ctrl.tick()["action"] == "spawn_failed"
    assert ctrl.state()["degraded"]
    assert store.refs(key) == 1  # the fault neither leaked nor freed
    assert holder.release() is True


def test_autoscaler_spawn_records_latency():
    clk = FakeClock()
    reps = [_LoadStub() for _ in range(2)]
    rs = ReplicaSet(reps)
    ctrl = FleetAutoscaler(rs, _LoadStub, clock=clk, max_replicas=3,
                           scale_up_sustain_s=5.0, cooldown_s=20.0)
    assert ctrl.state()["last_spawn_s"] is None
    for r in reps:
        r.load = (1, 1, 2)
    ctrl.tick()
    clk.advance(5.0)
    assert ctrl.tick()["action"] == "spawn"
    # the aliased-vs-full-reload A/B number the bench reads
    assert ctrl.state()["last_spawn_s"] >= 0.0


# ------------------------------------------------- device-slice free list
def test_slice_allocator_recycles_lowest_first():
    alloc = _SliceAllocator(list("abcdef"), per=2)
    assert alloc.total == 3
    assert [alloc.take() for _ in range(3)] == [0, 1, 2]
    assert alloc.slice_for(1) == ["c", "d"]
    with pytest.raises(RuntimeError, match="no free device slice"):
        alloc.take()
    alloc.give(2)
    alloc.give(0)
    alloc.give(0)  # double-give must not hand one slice to two replicas
    assert alloc.free_count() == 2
    assert [alloc.take(), alloc.take()] == [0, 2]


def test_drain_recycles_slice_through_on_retire():
    """Regression for the spawn-factory device-slice leak: a drained
    replica's slice returns to the free list, so a later spawn reuses it
    instead of failing on a 'consumed' grid."""
    class _Rep:
        def generate_step(self, prompt_tokens, **kw):
            yield from ((t, None) for t in prompt_tokens)

        def close(self):
            pass

    alloc = _SliceAllocator([0, 1], per=1)
    reps = [_Rep(), _Rep()]
    for r in reps:
        r._mst_slice = alloc.take()
    with pytest.raises(RuntimeError, match="no free device slice"):
        alloc.take()  # the old factories were stuck here forever
    rs = ReplicaSet(reps)
    rs.on_retire = lambda rep: alloc.give(
        getattr(rep, "_mst_slice", None)
    ) if getattr(rep, "_mst_slice", None) is not None else None
    rs.drain(1, deadline=2.0)
    assert alloc.free_count() == 1
    assert alloc.take() == 1  # the drained replica's slice, reused


# ------------------------------------------------------------- observability
def test_metrics_weight_store_gauges():
    store = WeightStore()
    store.acquire(KEY, lambda: _Tree(nbytes=2048))
    store.acquire(KEY, lambda: _Tree(nbytes=2048))
    m = ServingMetrics(weight_store_fn=lambda: store)
    out = m.render()
    assert "mst_weight_store_trees 1" in out
    assert "mst_weight_store_refs 2" in out
    assert "mst_weight_store_bytes 2048" in out


def test_metrics_per_replica_shared_gauge():
    shared, private = _LoadStub(), _LoadStub()
    shared.weights_shared = True
    rs = ReplicaSet([shared, private])
    m = ServingMetrics(batcher_fn=lambda: rs,
                       weight_store_fn=lambda: WeightStore())
    out = m.render()
    assert 'mst_replica_weights_shared{replica="0"} 1' in out
    assert 'mst_replica_weights_shared{replica="1"} 0' in out
    assert "mst_weight_store_trees 0" in out


def test_provider_shared_weights_resolution():
    from mlx_sharding_tpu.server.openai_api import ModelProvider

    p = ModelProvider.__new__(ModelProvider)
    p.multihost = False
    for mode, replicas, disagg, want in (
        ("auto", 3, False, True),
        ("auto", 1, True, True),
        ("auto", 1, False, False),
        ("off", 3, False, False),
        ("on", 1, False, True),
    ):
        p.shared_weights, p.replicas, p.disagg = mode, replicas, disagg
        assert p._shared_weights_on() is want, (mode, replicas, disagg)


def test_weight_key_placement_is_identity():
    devices = jax.devices()
    a = mesh_fingerprint(make_mesh(pp=1, devices=devices[:1]))
    b = mesh_fingerprint(make_mesh(pp=1, devices=devices[1:2]))
    assert a != b  # same geometry, different devices → different trees
    assert _key(placement=a) != _key(placement=b)
