"""Golden fixture for MLX grouped-affine 4-bit compatibility.

SURVEY §7 hard-part (a): published ``*-4bit-mlx`` checkpoints must decode
bit-exactly. ``mlx`` itself is Apple-silicon-only and cannot run in this
environment, so the fixture below encodes the format contract *independently
of the implementation under test*, following mlx.core.quantize's documented
layout (MLX docs "Quantization"; mlx/ops.cpp::quantize; reference applies it
via nn.quantize at /root/reference/shard/utils.py:54-65):

- every ``32/bits`` consecutive elements along the input dim pack into one
  uint32, FIRST element in the LEAST significant bits;
- per ``group_size`` elements, ``value = q * scale + bias`` with
  scales/biases stored in the checkpoint dtype (fp16 for published 4-bit
  checkpoints).

The packed words are written as literal hex constants and the expected
dequantized values are computed by scalar arithmetic in this file — NOT by
calling the repo's own packer — so a nibble-order or group-mapping drift in
ops/quant.py fails these tests even if quantize/dequantize stay mutually
consistent.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.ops.quant import dequantize, quantize


def test_nibble_order_is_lsb_first():
    """q = [1,2,...,8] must pack to 0x87654321 (element 0 in the low nibble).
    An MSB-first implementation would produce 0x12345678 and corrupt every
    published checkpoint silently."""
    q = np.arange(1, 9, dtype=np.uint32)  # one uint32 worth of nibbles
    word = np.uint32(0)
    for k, v in enumerate(q):
        word |= np.uint32(v) << np.uint32(4 * k)
    assert word == np.uint32(0x87654321)

    # group_size=8 is not a real MLX option but isolates the packing check
    packed = np.array([[0x87654321]], np.uint32)
    scales = np.array([[1.0]], np.float16)
    biases = np.array([[0.0]], np.float16)
    got = np.asarray(
        dequantize(packed, scales, biases, group_size=8, bits=4, dtype=np.float32)
    )
    np.testing.assert_array_equal(got[0], q.astype(np.float32))


def test_golden_dequant_group64_fp16():
    """Full golden fixture at the published-checkpoint layout: group_size=64,
    bits=4, fp16 scales/biases, 2 output rows x 128 input dims (2 groups per
    row). Expected values computed by scalar affine math on the hand-chosen
    nibble sequence."""
    rng = np.random.RandomState(42)
    out_dim, in_dim, gs = 2, 128, 64
    q = rng.randint(0, 16, size=(out_dim, in_dim)).astype(np.uint32)

    # pack LSB-first, 8 nibbles per word — spelled out longhand
    packed = np.zeros((out_dim, in_dim // 8), np.uint32)
    for r in range(out_dim):
        for w in range(in_dim // 8):
            word = 0
            for k in range(8):
                word |= int(q[r, w * 8 + k]) << (4 * k)
            packed[r, w] = word

    scales = np.array([[0.5, 0.25], [0.125, 2.0]], np.float16)
    biases = np.array([[-1.0, 2.0], [0.5, -8.0]], np.float16)

    expected = np.empty((out_dim, in_dim), np.float32)
    for r in range(out_dim):
        for c in range(in_dim):
            g = c // gs
            expected[r, c] = float(q[r, c]) * float(scales[r, g]) + float(
                biases[r, g]
            )

    got = np.asarray(
        dequantize(packed, scales, biases, group_size=gs, bits=4, dtype=np.float32)
    )
    np.testing.assert_array_equal(got, expected)


def test_golden_dequant_8bit():
    """8-bit variant (MLX supports bits in {2,4,8}): 4 bytes per word,
    byte 0 in the low byte."""
    q = np.array([[7, 255, 0, 128, 1, 2, 3, 4]], np.uint32)
    packed = np.array(
        [[7 | 255 << 8 | 0 << 16 | 128 << 24, 1 | 2 << 8 | 3 << 16 | 4 << 24]],
        np.uint32,
    )
    scales = np.array([[0.5]], np.float16)
    biases = np.array([[-4.0]], np.float16)
    expected = q.astype(np.float32) * 0.5 - 4.0
    got = np.asarray(
        dequantize(packed, scales, biases, group_size=8, bits=8, dtype=np.float32)
    )
    np.testing.assert_array_equal(got, expected)


def test_packer_agrees_with_golden_layout():
    """The repo's own packer must produce the golden layout (it writes
    native-quantized shard checkpoints that MLX-side tooling should be able
    to read back)."""
    w = np.array([[float(v) for v in range(64)]], np.float32)  # one group
    packed, scales, biases = quantize(w, group_size=64, bits=4)
    # scale = (max-min)/15 = 63/15 = 4.2, bias = 0; q = round(v/4.2)
    assert scales.shape == (1, 1) and biases.shape == (1, 1)
    q_expected = np.clip(np.round(w / float(scales[0, 0])), 0, 15).astype(np.uint32)
    word0 = 0
    for k in range(8):
        word0 |= int(q_expected[0, k]) << (4 * k)
    assert int(packed[0, 0]) == word0
    # and the round trip through the independent dequant math is tight
    got = np.asarray(
        dequantize(packed, scales, biases, group_size=64, bits=4, dtype=np.float32)
    )
    assert np.abs(got - w).max() <= float(scales[0, 0]) / 2 + 1e-6


def test_dequant_rejects_non_uint32():
    with pytest.raises(ValueError, match="uint32"):
        dequantize(
            np.zeros((2, 4), np.int32), np.ones((2, 1)), np.zeros((2, 1)),
            group_size=16,
        )


def test_quantize_jax_matches_numpy_packer():
    """Device-side packer must produce the identical mlx-layout triple as the
    host packer (bench and tests both rely on it)."""
    import jax.numpy as jnp

    from mlx_sharding_tpu.ops.quant import quantize, quantize_jax

    rng = np.random.default_rng(5)
    w = rng.standard_normal((16, 128)).astype(np.float32)
    q_np, s_np, b_np = quantize(w, group_size=64, bits=4)
    q_j, s_j, b_j = quantize_jax(jnp.asarray(w), group_size=64, bits=4)
    np.testing.assert_array_equal(np.asarray(q_j), q_np)
    np.testing.assert_allclose(np.asarray(s_j), s_np.astype(np.float32), rtol=1e-3)
    np.testing.assert_allclose(np.asarray(b_j), b_np.astype(np.float32), rtol=1e-3)


def test_quantize_jax_roundtrip():
    from mlx_sharding_tpu.ops.quant import dequantize, quantize_jax
    import jax.numpy as jnp

    rng = np.random.default_rng(6)
    w = rng.standard_normal((3, 8, 128)).astype(np.float32)  # stacked layers
    q, s, b = quantize_jax(jnp.asarray(w))
    back = np.asarray(dequantize(q, s, b, dtype=jnp.float32))
    # 4-bit grouped affine: max error is half a quantization step per group
    step = np.asarray(s)[..., None].repeat(64, -1).reshape(w.shape)
    assert (np.abs(back - w) <= step * 0.51 + 1e-6).all()
