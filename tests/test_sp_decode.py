"""Decode over sp-sharded KV (parallel/sp_decode.py): the cache's sequence
dim stays sharded over sp for the whole generation — round 2's post-prefill
all-gather (VERDICT weak #5) is gone. Parity contract: identical tokens to
the dense single-device path (greedy and seeded sampling), since the
distributed partial-softmax merge is exact."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.quick

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh

TINY = dict(
    vocab_size=300,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig(**TINY)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    dense = Generator(
        model, params, max_seq=128, cache_dtype=jnp.float32, prefill_chunk=8
    )
    sp = Generator(
        model, params, max_seq=128, cache_dtype=jnp.float32, prefill_chunk=8,
        sp_mesh=make_mesh(sp=4), sp_decode=True, decode_block=5,
    )
    return dense, sp


def test_sharded_cache_stays_sharded(setup):
    _, sp = setup
    cache = sp._sp_decode.make_cache(1, 128, jnp.float32)
    # sequence axis sharded over the 4 sp devices: 32 rows per shard
    shard_shapes = {s.data.shape for s in cache.k.addressable_shards}
    assert shard_shapes == {(2, 1, 32, 2, 8)}


def test_greedy_parity_long_prompt(setup):
    dense, sp = setup
    prompt = list(np.random.default_rng(0).integers(1, 300, size=45))
    want = [t for t, _ in dense.generate_step(prompt, max_tokens=12)]
    got = [t for t, _ in sp.generate_step(prompt, max_tokens=12)]
    assert got == want


def test_greedy_parity_short_prompt(setup):
    """Short prompts route through sp too (padded to the quantum)."""
    dense, sp = setup
    want = [t for t, _ in dense.generate_step([5, 9, 2], max_tokens=10)]
    got = [t for t, _ in sp.generate_step([5, 9, 2], max_tokens=10)]
    assert got == want


def test_seeded_sampling_parity(setup):
    dense, sp = setup
    kw = dict(temperature=0.9, top_p=0.8, seed=13, max_tokens=9)
    want = [t for t, _ in dense.generate_step([7, 3, 1, 8], **kw)]
    got = [t for t, _ in sp.generate_step([7, 3, 1, 8], **kw)]
    assert got == want


def test_decode_past_prefill_boundary(setup):
    """Generate enough tokens that new KV rows land on a LATER shard than the
    prompt ended on — the owner-write must follow the position across
    devices. Prompt 30 (pad 32; shard size 32) + 40 tokens crosses into
    shard 1 and beyond."""
    dense, sp = setup
    prompt = list(np.random.default_rng(1).integers(1, 300, size=30))
    want = [t for t, _ in dense.generate_step(prompt, max_tokens=40)]
    got = [t for t, _ in sp.generate_step(prompt, max_tokens=40)]
    assert got == want


def test_logprobs_summaries(setup):
    _, sp = setup
    out = list(sp.generate_step([4, 2], max_tokens=6, want_logprobs=True))
    for tok, lp in out:
        assert lp is not None
        assert int(lp.top_indices[0]) == tok  # greedy
        assert lp.chosen <= 1e-6
