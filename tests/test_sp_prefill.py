"""Sequence-parallel (ring attention) prefill at the MODEL level — long
prompts sharded over sp=4 must reproduce the dense single-device prefill
exactly (VERDICT r1 item 8: ring attention wired into a reachable path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.mesh import make_mesh
from mlx_sharding_tpu.parallel.sp_prefill import SpPrefill, supports_sp_prefill

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=4,
    num_attention_heads=4,
    num_key_value_heads=2,
)


@pytest.fixture(scope="module")
def model_and_params():
    model = LlamaModel(LlamaConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0), jnp.float32)
    return model, params


def test_sp_prefill_logits_and_cache_match_dense(model_and_params):
    model, params = model_and_params
    prompt = np.arange(1, 33, dtype=np.int32).reshape(1, 32)  # 8 tokens/device
    dense, dense_cache = model(
        params, jnp.asarray(prompt), model.make_cache(1, 64, jnp.float32)
    )

    sp = SpPrefill(model, params, make_mesh(sp=4), prefill_chunk=8)
    logits, cache = sp(prompt, model.make_cache(1, 64, jnp.float32))
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(dense[:, -1]), rtol=2e-5, atol=2e-5
    )
    assert int(cache.offset) == 32
    np.testing.assert_allclose(
        np.asarray(cache.k[:, :, :32]), np.asarray(dense_cache.k[:, :, :32]),
        rtol=2e-5, atol=2e-5,
    )


def test_sp_prefill_cache_continues_decode(model_and_params):
    """Generation after sp prefill must match the chunked-prefill path token
    for token (the gathered ring K/V is the same cache the dense path
    builds)."""
    model, params = model_and_params
    prompt = list(range(1, 33))
    ref = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=8)]

    gen = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
        sp_mesh=make_mesh(sp=4),
    )
    got = [t for t, _ in gen.generate_step(prompt, max_tokens=8)]
    assert got == want


def test_sp_prefill_unaligned_prompt(model_and_params):
    """Prompt not divisible by sp: right-padded; padded K/V rows are beyond
    the offset and never attended."""
    model, params = model_and_params
    prompt = list(range(1, 30))  # 29 tokens, sp=4 -> padded to 32
    ref = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    want = [t for t, _ in ref.generate_step(prompt, max_tokens=8)]
    gen = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
        sp_mesh=make_mesh(sp=4),
    )
    got = [t for t, _ in gen.generate_step(prompt, max_tokens=8)]
    assert got == want


def test_sp_prefill_seeded_sampling(model_and_params):
    model, params = model_and_params
    prompt = list(range(3, 30))
    kw = dict(temperature=0.8, top_p=0.9, seed=42, max_tokens=8)
    ref = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    want = [t for t, _ in ref.generate_step(prompt, **kw)]
    gen = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
        sp_mesh=make_mesh(sp=4),
    )
    assert [t for t, _ in gen.generate_step(prompt, **kw)] == want


def test_short_prompt_stays_on_chunked_path(model_and_params):
    """Prompts within one chunk skip the sp program entirely."""
    model, params = model_and_params
    gen = Generator(
        model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
        sp_mesh=make_mesh(sp=4),
    )
    ref = Generator(model, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    p = [5, 9, 2]
    assert [t for t, _ in gen.generate_step(p, max_tokens=5)] == [
        t for t, _ in ref.generate_step(p, max_tokens=5)
    ]


def test_unsupported_arch_raises():
    """An architecture without sp wiring (supports_sp False) is rejected up
    front, not deep inside a program. All five shipped families carry sp
    hooks as of round 5 (see test_sp_prefill_archs.py), so the case is a
    stub — the gate is the flag + hook contract, not a family list."""

    class NoSpModel(LlamaModel):
        supports_sp = False

    model = NoSpModel(LlamaConfig(**TINY))
    assert not supports_sp_prefill(model)
    params = model.init_params(jax.random.PRNGKey(1), jnp.float32)
    with pytest.raises(ValueError, match="sequence-parallel"):
        Generator(
            model, params, max_seq=32, cache_dtype=jnp.float32,
            sp_mesh=make_mesh(sp=2),
        )


def test_sp_quantum_overflow_falls_back_to_chunked(model_and_params):
    """A prompt that fits KV capacity must not fail just because quantum
    padding (sp * prefill_chunk) would exceed it — it falls back to the
    chunked path."""
    model, params = model_and_params
    # max_seq=40 rounds to 40 (chunk 8); quantum = 4*8=32 -> 33 tokens pad to 64
    gen = Generator(
        model, params, max_seq=40, cache_dtype=jnp.float32, prefill_chunk=8,
        sp_mesh=make_mesh(sp=4),
    )
    ref = Generator(model, params, max_seq=40, cache_dtype=jnp.float32, prefill_chunk=8)
    prompt = list(range(1, 34))  # 33 tokens
    assert [t for t, _ in gen.generate_step(prompt, max_tokens=7)] == [
        t for t, _ in ref.generate_step(prompt, max_tokens=7)
    ]
