"""Shared test utilities."""

from __future__ import annotations

import functools
import signal
import threading


def hard_timeout(seconds: float):
    """Fail the decorated test if it runs longer than ``seconds``.

    pytest-timeout is not installed in this environment, and the resilience
    suite deliberately wedges threads — a bug in the reclamation paths would
    otherwise hang the whole tier-1 run instead of failing one test. Uses
    SIGALRM/setitimer, so it only arms in the main thread on platforms that
    have it (everywhere we run tests); elsewhere it is a no-op rather than
    a crash.
    """

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if (
                not hasattr(signal, "SIGALRM")
                or threading.current_thread() is not threading.main_thread()
            ):
                return fn(*args, **kwargs)

            def on_alarm(signum, frame):
                raise TimeoutError(
                    f"{fn.__name__} exceeded the {seconds}s hard timeout"
                )

            prev = signal.signal(signal.SIGALRM, on_alarm)
            signal.setitimer(signal.ITIMER_REAL, seconds)
            try:
                return fn(*args, **kwargs)
            finally:
                signal.setitimer(signal.ITIMER_REAL, 0.0)
                signal.signal(signal.SIGALRM, prev)

        return wrapper

    return deco


def run_concurrent(gen_like, jobs, timeout: float = 600.0):
    """Run ``generate_step`` for every (prompt, kwargs) job in parallel
    threads and return the token lists in job order. Worker exceptions
    re-raise in the caller; a hung worker fails loudly instead of leaving
    a non-daemon thread blocking interpreter exit."""
    outs: list = [None] * len(jobs)

    def run(i, prompt, kw):
        try:
            outs[i] = [t for t, _ in gen_like.generate_step(prompt, **kw)]
        except Exception as e:  # noqa: BLE001 — surface in the caller
            outs[i] = e

    threads = [
        threading.Thread(target=run, args=(i, p, kw), daemon=True)
        for i, (p, kw) in enumerate(jobs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    assert not any(t.is_alive() for t in threads), "generation thread hung"
    for o in outs:
        if isinstance(o, Exception):
            raise o
    return outs
