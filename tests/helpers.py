"""Shared test utilities."""

from __future__ import annotations

import threading


def run_concurrent(gen_like, jobs, timeout: float = 600.0):
    """Run ``generate_step`` for every (prompt, kwargs) job in parallel
    threads and return the token lists in job order. Worker exceptions
    re-raise in the caller; a hung worker fails loudly instead of leaving
    a non-daemon thread blocking interpreter exit."""
    outs: list = [None] * len(jobs)

    def run(i, prompt, kw):
        try:
            outs[i] = [t for t, _ in gen_like.generate_step(prompt, **kw)]
        except Exception as e:  # noqa: BLE001 — surface in the caller
            outs[i] = e

    threads = [
        threading.Thread(target=run, args=(i, p, kw), daemon=True)
        for i, (p, kw) in enumerate(jobs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    assert not any(t.is_alive() for t in threads), "generation thread hung"
    for o in outs:
        if isinstance(o, Exception):
            raise o
    return outs
