"""Pallas flash-attention kernel vs the XLA reference path (interpret mode
on CPU; the same kernel compiles for TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.ops import causal_attention
from mlx_sharding_tpu.ops.flash_attention import flash_attention


@pytest.mark.parametrize(
    "b,t,s,hq,hkv,dk,offset",
    [
        (1, 128, 256, 4, 4, 64, 0),  # plain prefill from empty cache
        (1, 128, 256, 8, 2, 64, 64),  # GQA + continuation chunk at offset
        (2, 256, 256, 4, 2, 32, 0),  # batch, full-capacity prompt
    ],
)
def test_flash_matches_xla(b, t, s, hq, hkv, dk, offset):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, t, hq, dk)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, hkv, dk)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, hkv, dk)), jnp.float32)
    scale = dk**-0.5
    ref = causal_attention(q, k, v, jnp.asarray(offset), scale)
    got = flash_attention(
        q, k, v, jnp.asarray(offset), scale, block_q=64, block_k=64, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_flash_rejects_ragged_blocks():
    q = jnp.zeros((1, 100, 2, 16))
    k = jnp.zeros((1, 128, 2, 16))
    v = jnp.zeros((1, 128, 2, 16))
    with pytest.raises(ValueError, match="multiples"):
        flash_attention(q, k, v, jnp.asarray(0), 1.0, block_q=64, block_k=64, interpret=True)
