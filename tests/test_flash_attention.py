"""Pallas flash-attention kernel vs the XLA reference path (interpret mode
on CPU; the same kernel compiles for TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.ops import causal_attention
from mlx_sharding_tpu.ops.flash_attention import flash_attention


@pytest.mark.parametrize(
    "b,t,s,hq,hkv,dk,offset",
    [
        (1, 128, 256, 4, 4, 64, 0),  # plain prefill from empty cache
        (1, 128, 256, 8, 2, 64, 64),  # GQA + continuation chunk at offset
        (2, 256, 256, 4, 2, 32, 0),  # batch, full-capacity prompt
    ],
)
def test_flash_matches_xla(b, t, s, hq, hkv, dk, offset):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, t, hq, dk)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, hkv, dk)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, hkv, dk)), jnp.float32)
    scale = dk**-0.5
    ref = causal_attention(q, k, v, jnp.asarray(offset), scale)
    got = flash_attention(
        q, k, v, jnp.asarray(offset), scale, block_q=64, block_k=64, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_flash_rejects_ragged_blocks():
    q = jnp.zeros((1, 100, 2, 16))
    k = jnp.zeros((1, 128, 2, 16))
    v = jnp.zeros((1, 128, 2, 16))
    with pytest.raises(ValueError, match="multiples"):
        flash_attention(q, k, v, jnp.asarray(0), 1.0, block_q=64, block_k=64, interpret=True)


@pytest.mark.parametrize(
    "b,t,s,hq,hkv,dk,dv,offset",
    [
        # DeepSeek MLA full mode: dk = qk_nope+qk_rope = 192, dv = 128
        (1, 128, 256, 8, 8, 192, 128, 0),
        # DeepSeek MLA compressed mode: MQA over one latent head,
        # dk = rank+rope = 576, "values" are the rank slice (512)
        (1, 128, 128, 16, 1, 576, 512, 0),
        (1, 128, 256, 8, 8, 192, 128, 96),  # continuation at offset
    ],
)
def test_flash_mla_head_dims(b, t, s, hq, hkv, dk, dv, offset):
    """VERDICT r1 item 7: the kernel must serve DeepSeek's 64-aligned (not
    128-aligned) head dims."""
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(b, t, hq, dk)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, hkv, dk)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, hkv, dv)), jnp.float32)
    scale = dk**-0.5
    ref = causal_attention(q, k, v, jnp.asarray(offset), scale)
    got = flash_attention(
        q, k, v, jnp.asarray(offset), scale, block_q=64, block_k=64, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize(
    "s,hq,hkv,dk,offset",
    [(256, 8, 2, 64, 17), (256, 16, 1, 576, 40), (128, 4, 4, 192, 127)],
)
def test_flash_decode_step(s, hq, hkv, dk, offset):
    """T=1 decode variant: one query row against a long cache, offset mid-
    buffer — positions beyond the offset must contribute nothing."""
    rng = np.random.default_rng(2)
    dv = 512 if dk == 576 else dk
    q = jnp.asarray(rng.normal(size=(1, 1, hq, dk)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, s, hkv, dk)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, s, hkv, dv)), jnp.float32)
    scale = dk**-0.5
    ref = causal_attention(q, k, v, jnp.asarray(offset), scale)
    got = flash_attention(
        q, k, v, jnp.asarray(offset), scale, block_k=64, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_eligibility_gates(monkeypatch):
    from mlx_sharding_tpu.ops.attention import _flash_eligible

    q192 = jnp.zeros((1, 128, 8, 192))
    k192 = jnp.zeros((1, 256, 8, 192))
    v128 = jnp.zeros((1, 256, 8, 128))
    qd = jnp.zeros((1, 1, 8, 192))

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert _flash_eligible(q192, k192, v128, None, None, None)
    # softcap/window stay on XLA
    assert not _flash_eligible(q192, k192, v128, 30.0, None, None)
    assert not _flash_eligible(q192, k192, v128, None, 4096, None)
    # decode is opt-in until measured on hardware
    assert not _flash_eligible(qd, k192, v128, None, None, None)
    monkeypatch.setenv("MST_FLASH_DECODE", "1")
    assert _flash_eligible(qd, k192, v128, None, None, None)
    monkeypatch.setenv("MST_FLASH", "0")
    assert not _flash_eligible(q192, k192, v128, None, None, None)
