"""Chained (device-placed, per-stage-program) pipeline parity — including
the heterogeneous DeepSeek-V2 case the fused SPMD engine can't express."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlx_sharding_tpu.config import LlamaConfig
from mlx_sharding_tpu.generate import Generator
from mlx_sharding_tpu.models.llama import LlamaModel
from mlx_sharding_tpu.parallel.chained import ChainedPipeline, load_chained_pipeline

TINY = dict(
    vocab_size=256,
    hidden_size=32,
    intermediate_size=64,
    num_hidden_layers=6,
    num_attention_heads=4,
    num_key_value_heads=2,
)


def _stage(cfg_kw, params_full, start, end):
    cfg = LlamaConfig(**{**TINY, "start_layer": start, "end_layer": end})
    model = LlamaModel(cfg)
    lay = {k: v[start:end] for k, v in params_full["layers"].items()}
    p = {"layers": lay}
    if cfg.needs_embed:
        p["embed"] = params_full["embed"]
    if cfg.needs_head:
        p["final_norm"] = params_full["final_norm"]
        p["lm_head"] = params_full["lm_head"]
    return model, p


@pytest.mark.slow  # three-stage sweep — the two-stage chain keeps the quick signal
def test_uneven_three_stage_chain_matches_single_device():
    cfg = LlamaConfig(**TINY)
    full = LlamaModel(cfg)
    params = full.init_params(jax.random.PRNGKey(0), jnp.float32)
    ref_gen = Generator(full, params, max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8)
    prompt = [3, 1, 4, 1, 5]
    ref = [t for t, _ in ref_gen.generate_step(prompt, max_tokens=10)]

    # uneven split 1/2/3 — impossible in the fused SPMD engine
    stages = [_stage(TINY, params, 0, 1), _stage(TINY, params, 1, 3), _stage(TINY, params, 3, 6)]
    chain = ChainedPipeline(
        [m for m, _ in stages], [p for _, p in stages],
        max_seq=64, cache_dtype=jnp.float32, prefill_chunk=8,
    )
    got = [t for t, _ in chain.generate_step(prompt, max_tokens=10)]
    assert got == ref


def test_chained_deepseek_two_stage(tmp_path):
    """BASELINE config #1 shape: DeepSeek-V2 split into two uneven stages
    where stage 0 holds the dense prefix."""
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")
    from tests.test_deepseek_v2 import TINY_HF

    torch.manual_seed(21)
    hf = transformers.DeepseekV2ForCausalLM(transformers.DeepseekV2Config(**TINY_HF))
    hf.eval()
    hf.save_pretrained(tmp_path, safe_serialization=True)

    chain = load_chained_pipeline(
        str(tmp_path), [(0, 1), (1, 4)],
        dtype=jnp.float32, max_seq=32, cache_dtype=jnp.float32, prefill_chunk=8,
    )
    prompt = [2, 45, 99, 3]
    got = [t for t, _ in chain.generate_step(prompt, max_tokens=6)]

    # reference continuation via HF greedy
    import torch as _t

    ids = _t.tensor([prompt])
    with _t.no_grad():
        out = hf.generate(
            ids, max_new_tokens=6, do_sample=False, use_cache=True,
            pad_token_id=0,
        )
    assert got == out[0, len(prompt):].tolist()


def test_chained_validates_bounds():
    cfg = LlamaConfig(**TINY)
    full = LlamaModel(cfg)
    params = full.init_params(jax.random.PRNGKey(0), jnp.float32)
    m1, p1 = _stage(TINY, params, 1, 6)  # doesn't start at 0
    with pytest.raises(ValueError, match="start at layer 0"):
        ChainedPipeline([m1], [p1])
