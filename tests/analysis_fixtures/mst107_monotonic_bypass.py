"""MST107 (monotonic-bypass form): a class carries an injectable clock but
its deadline arithmetic reads time.monotonic() directly — the injected
source is silently bypassed, so virtual-clock tests diverge from prod."""
import time


class LeaseTable:
    def __init__(self, clock=time.monotonic):
        self.clock = clock

    def expired(self, deadline: float) -> bool:
        return time.monotonic() > deadline
