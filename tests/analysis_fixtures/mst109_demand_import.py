"""MST109: a spilled KV block's host pages uploaded inside a tick-hot
function — the demand-paged resume stall. The stage belongs in the
(non-hot) wake/admission policy pass via KVPageBlock.prefetch()."""
import jax


# mst: hot-path
def resume_in_tick(cache, tier, req):
    blk = tier.take(req)
    staged = jax.device_put(blk.k_pages)
    return cache, staged
