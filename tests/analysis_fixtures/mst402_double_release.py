"""MST402: the exactly-once release contract, broken on one path."""


def demote(store, owner, digests, pages, urgent):
    lease = store.register(owner, digests, pages, digests, 64)
    if urgent:
        lease.release()
    lease.release()
