"""MST103: data-dependent array shape at a jitted call site."""
import jax
import jax.numpy as jnp

prog = jax.jit(lambda x: x + 1)


def run(tokens):
    return prog(jnp.zeros((len(tokens),), jnp.float32))
