"""MST504: queue get while holding the lock the tick loop also takes."""
import queue
import threading


class Feeder:
    def __init__(self):
        self._lock = threading.Lock()
        self._work_q = queue.Queue()
        self._thread = None
        self.pending = 0

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="continuous-batcher", daemon=True
        )
        self._thread.start()

    def take(self):
        with self._lock:
            return self._work_q.get()

    def _loop(self):
        with self._lock:
            self.pending += 1
