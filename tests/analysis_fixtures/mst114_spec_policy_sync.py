"""MST114: a draft proposal reading a device value mid-round."""


# mst: spec-hot
def propose_window(tracker_ewma, last_count):
    accepted = last_count.item()  # drains the dispatch pipe per round
    return 4 if accepted > 2 else 2
