"""MST201: guarded attribute read with no lock held in a public method."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def incr(self):
        with self._lock:
            self._count += 1

    def snapshot(self):
        return self._count
