"""MST112 fixture: span construction on a tick-hot path outside the
tracing no-op guard — the `tr.add` on line 11 runs its marshalling on
every decode block even with --trace off."""
import time


# mst: hot-path
def _decode_once(req):
    tr = req._trace
    _work(req)
    tr.add("decode_tick", 0.0, time.perf_counter())
    if tr is not None:
        tr.point("guarded")  # clean: behind the no-op check


def _work(req):
    pass
