"""MST105: dense dequantized weight materialized in a decode-hot path."""


def dequantize(q, scales, biases):
    return q  # stand-in for ops.quant.dequantize


# mst: decode-hot
def decode_linear(x, w):
    full = dequantize(w["q"], w["scales"], w["biases"])
    return x @ full.T
