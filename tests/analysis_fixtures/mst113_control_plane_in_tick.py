"""MST113: a blocking control-plane collective inside a tick-hot
function — a cross-host rendezvous completes when the slowest host
arrives (or at the plane timeout when one never does), wedging every
live slot's decode; run it on the transport thread and let the tick
read the gossiped snapshot."""


# mst: hot-path
def tick_with_rendezvous(plane, hdr, blob, out):
    headers, blobs = plane.pod_exchange(hdr, blob)
    out.append(headers)
