"""MST501: attribute written from two thread roles with no lock at all."""
import threading


class Pump:
    def __init__(self):
        self.level = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="continuous-batcher", daemon=True
        )
        self._thread.start()

    def set_level(self, n):
        self.level = n

    def _loop(self):
        while True:
            self.level += 1
