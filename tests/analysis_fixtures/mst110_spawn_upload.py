"""MST110: the full param tree re-placed on device inside a spawn-hot
replica factory — every autoscaler spawn pays a checkpoint upload and a
second W of HBM. The upload belongs in the WeightStore builder; the
factory should alias the resident tree through store.acquire()."""
import jax


# mst: spawn-hot
def spawn_with_upload(model, params, shardings, mesh):
    resident = jax.device_put(params, shardings)
    return model.bind(resident, mesh)
