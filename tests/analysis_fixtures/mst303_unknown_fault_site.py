"""MST303: a typo'd fault-injection site can never be armed."""
from mlx_sharding_tpu.testing.faults import inject


def tick():
    inject("scheduler.tik")
