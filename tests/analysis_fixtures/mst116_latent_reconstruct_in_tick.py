"""MST116: dense latent reconstruction inside a tick-hot function —
reconstruct_block() materializes the full per-head pages from rank-r
latents (a host-numpy up-projection over every page of every layer),
stalling every live slot's decode behind one block's matmul; reconstruct
in prefetch's overlapped stage or the consumer's import path instead."""


# mst: hot-path
def tick_with_latent_reconstruct(codec, block):
    pages = codec.reconstruct_block(block)
    return pages
