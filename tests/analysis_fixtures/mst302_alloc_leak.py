"""MST302: pool allocation leaks when a later raise exits early."""


class Pages:
    def __init__(self):
        self._free_pages = list(range(8))

    def take(self, count):
        page = self._free_pages.pop()
        if count > 8:
            raise ValueError("request too large")
        return page
