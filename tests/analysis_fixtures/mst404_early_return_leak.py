"""MST404: the release exists, but an early-return arm skips it."""


def maybe_admit(store, owner, digests, pages, fast_path):
    lease = store.register(owner, digests, pages, digests, 64)
    if fast_path:
        return None  # forgot the lease on this arm
    lease.release()
    return True
