"""MST108: a KV page-block migration call inside a tick-hot function —
an export gathers a whole page chain per request; park the request on
the tick and migrate from a non-hot helper or the flusher thread."""


# mst: hot-path
def handoff_in_tick(cache, pages, out):
    blk = export_block(cache, pages)
    out.put(blk)
