"""MST503: a live dict mutated by the tick thread, returned bare."""
import threading


class Stats:
    def __init__(self):
        self._counts = {}
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="continuous-batcher", daemon=True
        )
        self._thread.start()

    def counts(self):
        return self._counts

    def _loop(self):
        self._counts["ticks"] = self._counts.get("ticks", 0) + 1
