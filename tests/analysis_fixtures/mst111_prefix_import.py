"""MST111: a prefix-store host block uploaded inside a tick-hot function —
the store-served admission stall. The stage belongs in the (non-hot)
waiting-queue prefetch pass via KVPageBlock.prefetch()."""
import jax.numpy as jnp


# mst: hot-path
def admit_in_tick(cache, store, digests):
    block = store.host_block(digests[-1])
    staged = jnp.asarray(block)
    return cache, staged
