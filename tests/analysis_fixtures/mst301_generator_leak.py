"""MST301: resource-acquiring generator with an unprotected yield."""


def stream(pool):
    ticket = pool.acquire()
    for _ in range(4):
        yield ticket
