"""MST101: wall-clock read inside jit-traced code freezes at trace time."""
import time

import jax


def _step(x):
    return x * time.time()


step = jax.jit(_step)
