"""MST104: a second blocking device_get inside one tick-hot function."""
import jax


# mst: hot-path
def harvest_tick(outs, prev):
    toks = jax.device_get(outs)  # mst: allow(MST102): THE tick sync
    hist = jax.device_get(prev)  # mst: allow(MST102): also reaches the host
    return toks, hist
