"""MST401: a lease leaks when an exception unwinds past its acquire."""


def admit(store, owner, digests, pages):
    lease = store.register(owner, digests, pages, digests, 64)
    broadcast(pages)  # may raise: the lease never reaches release()
    lease.release()


def broadcast(pages):
    raise RuntimeError("table write failed")
