"""MST403: releasing a handle whose ownership was already handed off."""


def handoff(store, owner, digests, pages, registry):
    lease = store.register(owner, digests, pages, digests, 64)
    registry["lease"] = lease  # ownership transferred to the registry
    lease.release()  # not ours to release any more
