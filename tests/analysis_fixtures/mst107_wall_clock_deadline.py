"""MST107: wall-clock time.time() in deadline arithmetic — NTP steps/slew
make the deadline fire early or never; deadlines must be monotonic."""
import time


def remaining_budget(deadline: float) -> float:
    return deadline - time.time()
