"""MST002: a suppression whose finding no longer fires is dead weight."""


def snapshot(counter):
    # mst: allow(MST201): bound once in __init__, never reassigned
    return counter
