"""MST502: every write locked, but the role locksets never intersect."""
import threading


class Gauge:
    def __init__(self):
        self._fast_lock = threading.Lock()
        self._slow_lock = threading.Lock()
        self.total = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="continuous-batcher", daemon=True
        )
        self._thread.start()

    def add(self, n):
        with self._fast_lock:
            self.total += n

    def _loop(self):
        with self._slow_lock:
            self.total += 1
