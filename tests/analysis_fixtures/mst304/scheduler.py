"""MST304: a scheduler.py that lost its inject("scheduler.tick") hook."""


def tick():
    return 1
