"""MST106: an exported KV page block pulled synchronously inside a
tick-hot function — the device→host copy belongs on the spill tier's
flusher thread, not the tick."""
import jax


# mst: hot-path
def preempt_in_tick(cache, pages, tier):
    blk = export_block(cache, pages)  # mst: allow(MST108): MST106's setup
    # mst: allow(MST102): the sync under test here is MST106's block pull
    host = jax.device_get(blk)
    tier.put(host)
