"""MST202: read under the lock, mutate under a later separate acquisition."""
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def put_if_absent(self, key, value):
        with self._lock:
            present = key in self._items
        if not present:
            with self._lock:
                self._items[key] = value
