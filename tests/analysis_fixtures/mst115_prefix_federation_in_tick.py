"""MST115: a pod prefix-federation consult inside a tick-hot function —
fetch() blocks on a cross-host blob transfer bounded only by its
timeout, stalling every live slot's decode behind a peer; start it from
the waiting-queue pass on its own daemon thread and let admission read
the per-request flag."""


# mst: hot-path
def tick_with_federation_fetch(store, digest):
    if store.federation.fetch(digest):
        return True
    return False
