"""MST102: blocking device sync inside an annotated hot path."""
import numpy as np


# mst: hot-path
def decode_tick(token_buf):
    return np.asarray(token_buf)
