"""MST001: a suppression without a reason is itself a finding."""
import time


def stamp():
    # mst: allow(MST101)
    return time.time()
